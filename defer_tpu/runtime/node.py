"""Standalone stage-node processes: the multi-process MPMD chain.

Reference parity: the reference's compute node is a separate process on
another machine that receives its partition, then serves the chain forever —
recv activation, predict, relay to its successor (reference
src/node.py:80-108, boot at src/node.py:110-127).  The last node relays back
to the dispatcher (reference src/dispatcher.py:51-55).

The TPU-native redesign keeps the topology but none of the machinery:

* The partition arrives as a *compiled artifact* — StableHLO + weights
  (``utils/export.py``) loaded with zero model code — not Keras JSON
  rebuilt layer by layer (src/node.py:31-37).
* One typed framed connection per hop (``transport/framed.py``) instead of
  three fixed ports; the hop codec (raw / lzb / blockfloat) is the ZFP+LZ4
  analogue and is *symmetric* (the reference's decode sides are buggy,
  SURVEY.md §3.5).
* Readiness is connect-with-retry, not 5-second poll loops
  (src/node.py:33,96), and shutdown is an in-band END frame that cascades
  down the chain, not process kill.

The SPMD mesh engine (``runtime/spmd.py``) is the primary execution model;
this chain exists for the reference's one topology it doesn't cover —
stages as separate processes/hosts with a network between them.
"""

from __future__ import annotations

import collections
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Sequence

import numpy as np

from ..obs import REGISTRY, LatencyHistogram, new_span_id, tracer
from ..obs.report import ObsReporter, WatermarkSplit
from ..transport.channel import AsyncReceiver, AsyncSender, _sampled
from ..transport.ici import IciSender
from ..transport.framed import (K_ACK, K_BYTES, K_CTRL, K_END, K_TENSOR,
                                K_TENSOR_SEQ, configure_socket,
                                connect_retry, recv_expect, recv_frame,
                                send_ack, send_ctrl, send_end, send_frame)
from ..transport.branch import BranchJoin, BroadcastSender
from ..transport.replay import ACK_EVERY, ReplayFanOut
from ..transport.replicate import FanInMerge, FanOutSender


#: guards lazy creation of per-node watermark splitters (``__new__``-
#: built test stubs have no __init__ to create one in)
_WM_LOCK = threading.Lock()

#: serve()-loop sentinel a ``shutdown`` control command enqueues: a
#: persistent node returns its accumulated stream total NOW
_SHUTDOWN = object()

#: fan-in dedup window under failover: how far behind the merge head a
#: replayed duplicate may land and still be absorbed silently.  Bounds
#: the fan-out's retained window (ack lag + reorder capacity) with an
#: order of magnitude of slack — beyond it, a duplicate is a protocol
#: bug and raises exactly as in strict mode.
_REPLAY_DEDUP_WINDOW = 4096


def _connect_retry(host: str, port: int, timeout_s: float = 30.0
                   ) -> socket.socket:
    """Connect, retrying while the peer boots (replaces the reference's
    sleep-5 polling rendezvous, src/node.py:95-96).  The policy lives in
    :func:`transport.framed.connect_retry`; this alias keeps the
    historical call sites (and test monkeypatch points)."""
    return connect_retry(host, port, timeout_s)


def _parse_hostport(s: str, default_host: str = "127.0.0.1"
                    ) -> tuple[str, int]:
    host, _, port = s.rpartition(":")
    return (host or default_host), int(port)


def _parse_hops(s: str) -> list[tuple[str, int]]:
    """``host:port[,host:port...]`` -> list of (host, port).  More than
    one entry means the downstream stage is replicated: the sender fans
    out round-robin with sequence numbers (docs/TRANSPORT.md)."""
    return [_parse_hostport(p) for p in s.split(",") if p]


class StageNode:
    """One compute node of a process chain: recv -> stage fn -> relay.

    ``python -m defer_tpu node --listen :5000`` boots an EMPTY node that
    receives its stage artifact in-band over the control handshake —
    completing parity with the reference node, which also boots with
    nothing and gets its model over the wire (src/node.py:20-55).
    ``--artifact stage_k.zip --next host:5000`` pre-loads from a local
    file instead (the r3/r4 behavior, kept for pre-provisioned hosts).

    Replication (docs/TRANSPORT.md): ``--next`` may name R comma-
    separated replicas of the downstream stage — frames then fan out
    round-robin with sequence numbers.  ``--fan-in R`` declares R
    sequence-stamped upstream connections, merged through a bounded
    reorder buffer that releases frames strictly in order.  ``--replica
    N`` labels this process's spans/stats as replica N of its stage.
    """

    #: class-level defaults so instances built via ``__new__`` (tests)
    #: still serve; the overlapped loop keeps ``inflight`` device
    #: dispatches un-synced and ``rx_depth``/``tx_depth`` decoded frames
    #: of queue slack per side
    overlap: bool = True
    rx_depth: int = 8
    tx_depth: int = 8
    inflight: int = 2
    fan_in: int = 1
    replica: int | None = None
    #: branched stage graphs (docs/TRANSPORT.md): ``fan_mode="broadcast"``
    #: sends every frame to EVERY downstream hop (parallel branches all
    #: read the fork tensor) instead of round-robin replica fan-out;
    #: ``branch`` labels this node's path through a fork/join region
    #: (spans/stats become ``stageK.bJ``, and the outbound stream_begin
    #: carries the path so the join can slot this connection); ``join_in
    #: >= 2`` makes this node the region's join — P labeled upstream
    #: connections merge through a (path, seq) reorder buffer and the
    #: multi-input stage program runs on all P parts per sequence
    fan_mode: str = "rr"
    branch: int | None = None
    join_in: int = 0
    #: bench-only simulated accelerator seconds per frame (serialized in
    #: the compute loop, sleeping — not spinning — so concurrent stage
    #: processes on a small host still overlap like real devices would;
    #: how the DAG smoke makes branch compute delay-bound on 1 core)
    infer_delay_s: float = 0.0
    next_hops: list[tuple[str, int]] | None = None
    #: outbound transport-tier policy (docs/TRANSPORT.md): "auto" walks
    #: the tier ladder on the downstream dial — ici (same process +
    #: same mesh, device-resident jax.Arrays) over local (same process,
    #: host ndarray by reference) over shm (same host, shared-memory
    #: ring) over tcp — via tier_probe handshakes that silently degrade
    #: when a rung's proof fails; "ici"/"local"/"shm" pin that single
    #: rung's offer; "tcp" never probes — the status-quo wire path
    tier: str = "tcp"
    #: jax device index this node's stage program is pinned to (the
    #: deployment half of the ici tier: upstream device_puts each
    #: activation here, the program consumes it device-resident); None
    #: = the backend default placement
    device: int | None = None
    #: answer inbound tier probes (False = refuse every offer: the hop
    #: degrades to tcp with the sender's fallback counter bumped)
    tier_accept: bool = True
    #: negotiated tiers, for stats/obs ("local"/"shm"/"tcp"; None = no
    #: data path yet)
    tier_out: str | None = None
    tier_in: str | None = None
    #: outbound hops that WANTED a colocated tier but degraded to tcp —
    #: the per-hop twin of the process-global
    #: ``transport.tier_fallback`` counter (a shared count cannot tell a
    #: degraded hop from a never-offered one)
    tier_fallbacks: int = 0
    #: waterfall sampling period carried by the trace context (0 = every
    #: frame records spans, N >= 1 = only wire-seq multiples of N)
    trace_sample_every: int = 0
    #: seq-replay failover substrate (docs/ROBUSTNESS.md): fan-out hops
    #: retain sent frames until the downstream fan-in's cumulative
    #: ``replay_ack`` and HEAL dead replica channels (redial + replay);
    #: replica hops relay acks upstream; fan-in hops ack, dedup replay
    #: overlaps, and tolerate a replica's mid-stream EOF for one redial
    #: grace period
    failover: bool = False
    #: keep serving across stream segments: serve() accumulates per-
    #: stream tensor counts and returns only on a ``shutdown`` control
    #: command — the node half of a zero-downtime live replan
    #: (quiesce -> redeploy -> resume, docs/ROBUSTNESS.md)
    persist: bool = False
    #: redial grace a fan-in allows a dead upstream before poisoning
    #: the merge (the chain supervisor's respawn must beat this)
    failover_grace_s: float = 30.0
    #: live fan-in data connections (the ack plane's targets); class
    #: default covers ``__new__``-built stubs
    _fanin_conns: list | None = None
    #: bumped per fan-in data-path registration — a respawned replica's
    #: dial-in inside the grace period cancels the delayed poisoning
    _fanin_epoch: int = 0
    #: live data-path channels (set once a connection proves to be the
    #: stream) — what obs_push reads queue depths/watermarks from
    _live_rx = None
    _live_tx = None
    #: branch-join reorder buffer (class default covers ``__new__``-
    #: built test stubs)
    _join: BranchJoin | None = None
    #: per-NODE infer histogram (None on ``__new__``-built stubs): the
    #: registry's ``node.infer_s`` is process-wide, which in-process
    #: thread chains share across nodes — this instance copy keeps
    #: stats/obs_push attribution per node everywhere
    infer_hist: LatencyHistogram | None = None
    #: per-NODE host-sync histogram: seconds spent materializing stage
    #: outputs to host memory (``np.asarray`` — the D2H half of the
    #: round-trip every non-ici hop pays; an ici hop records ZERO
    #: samples here, which is the observable proof the round-trip is
    #: gone).  Instance copy for the same attribution reason as
    #: ``infer_hist``; ``node.host_sync_s`` is the registry twin.
    host_sync_hist: LatencyHistogram | None = None
    #: per-NODE phase histograms (docs/OBSERVABILITY.md §Profiling) —
    #: the X-ray of the opaque ``infer`` interval: ``disp_hist`` times
    #: the jit call RETURNING (host-side dispatch cost; jax queues the
    #: compute and returns), ``queue_hist`` times the frame's residency
    #: in the async in-flight window (dispatch return -> its drain
    #: turn), ``dev_hist`` times ``block_until_ready`` (device
    #: compute).  Together with ``host_sync_hist`` the four phases tile
    #: the frame: dispatch + queue + device + host_sync ≈ infer
    #: (scripts/profile_smoke.py asserts the sum).  Registry twins:
    #: ``node.dispatch_s`` / ``node.queue_s`` / ``node.device_s``.
    disp_hist: LatencyHistogram | None = None
    queue_hist: LatencyHistogram | None = None
    dev_hist: LatencyHistogram | None = None
    #: active profile_start session (obs/profile.py); None between
    #: sessions — the double-start refusal's state
    _profile = None
    #: per-subscriber watermark splitter (class default covers
    #: ``__new__``-built stubs; created lazily under ``_WM_LOCK``)
    _wm_split: WatermarkSplit | None = None
    #: analytic capacity of the deployed stage, shipped by the
    #: dispatcher in the deploy message (``flops`` / ``bytes_moved`` at
    #: the deploy batch) — what stats/obs_push MFU accounting divides
    #: by.  None until a deploy carries them (a standalone node without
    #: a dispatcher reports no MFU rather than a fabricated one).
    stage_flops: float | None = None
    stage_bytes_moved: float | None = None
    #: cached chip peak (bytes are cheap; the jax probe is not).
    #: 0.0 = probed and unknown (MFU stays None — utils/hw.py policy:
    #: never fabricate MFU against a guessed peak); None = not probed.
    _peak_flops_s: float | None = None

    def __init__(self, artifact: str | None, listen: str,
                 next_hop: str | None, *, codec: str = "raw",
                 overlap: bool = True, rx_depth: int = 8,
                 tx_depth: int = 8, inflight: int = 2,
                 fan_in: int = 1, replica: int | None = None,
                 fan_mode: str = "rr", branch: int | None = None,
                 join_in: int = 0, infer_delay_s: float = 0.0,
                 tier: str = "tcp", tier_accept: bool = True,
                 device: int | None = None, failover: bool = False,
                 persist: bool = False):
        # bind before the (slow: jax import + StableHLO deserialize)
        # artifact load so upstream connect-retries land as soon as the
        # process exists
        host, port = _parse_hostport(listen, "0.0.0.0")
        self._srv = socket.create_server((host, port))
        self.address = self._srv.getsockname()
        self.prog = None
        if artifact is not None:
            from ..utils.export import load_stage_program
            self.prog = load_stage_program(artifact)
        self.next_hops = _parse_hops(next_hop) if next_hop else None
        self.codec = codec
        self.overlap = overlap
        self.rx_depth = rx_depth
        self.tx_depth = tx_depth
        self.inflight = max(1, inflight)
        self.fan_in = max(1, fan_in)
        self.replica = replica
        if fan_mode not in ("rr", "broadcast"):
            raise ValueError(f"fan_mode must be rr|broadcast, "
                             f"got {fan_mode!r}")
        self.fan_mode = fan_mode
        self.branch = None if branch is None else int(branch)
        self.join_in = max(0, int(join_in))
        if self.join_in == 1:
            raise ValueError("join_in must be 0 or >= 2 (a single-path "
                             "join is a plain unicast hop)")
        if self.join_in >= 2 and self.fan_in > 1:
            raise ValueError("a node cannot be both a branch join and a "
                             "replica fan-in (the two merges own "
                             "different sequence namespaces)")
        self.infer_delay_s = max(0.0, float(infer_delay_s))
        if tier not in ("tcp", "auto", "local", "shm", "ici"):
            raise ValueError(f"tier must be tcp|auto|local|shm|ici, "
                             f"got {tier!r}")
        self.tier = tier
        self.tier_accept = tier_accept
        self.tier_out = None
        self.tier_in = None
        self.tier_fallbacks = 0
        self.device = None
        if device is not None:
            self.set_device(int(device))
        self._check_tier_pin()
        self.processed = 0    # tensors relayed, lifetime
        self.reweights = 0    # weights-only re-pushes accepted
        #: trace-context K_CTRL received from upstream, held until this
        #: node opens its downstream connection so the context cascades
        #: hop by hop through the whole chain
        self._pending_trace: dict | None = None
        #: fan-in state: the reorder merge shared by the upstream reader
        #: connections and the single compute loop (lazy, lock-guarded)
        self._merge: FanInMerge | None = None
        self._merge_lock = threading.Lock()
        self.failover = bool(failover)
        self.persist = bool(persist)
        self._fanin_conns = None
        self._fanin_epoch = 0
        #: branch-join state: the (path, seq) reorder buffer shared by
        #: the P labeled upstream readers and one compute loop
        self._join: BranchJoin | None = None
        self._done_q = None   # serve()'s completion queue (set per serve)
        self._live_rx = None
        self._live_tx = None
        self.infer_hist = LatencyHistogram()
        self.host_sync_hist = LatencyHistogram()
        self.disp_hist = LatencyHistogram()
        self.queue_hist = LatencyHistogram()
        self.dev_hist = LatencyHistogram()
        self._profile = None
        #: live obs_push reporter threads (one per subscription)
        self._reporters: list[ObsReporter] = []

    @property
    def manifest(self):
        return None if self.prog is None else self.prog.manifest

    @property
    def next_hop(self) -> tuple[str, int] | None:
        """First downstream hop (back-compat accessor; ``next_hops``
        holds the full replica list)."""
        return self.next_hops[0] if self.next_hops else None

    @next_hop.setter
    def next_hop(self, value: tuple[str, int] | None) -> None:
        self.next_hops = None if value is None else [value]

    def _span_label(self) -> str:
        """Span/track prefix for this node's rx/tx/infer telemetry;
        replicas get a ``stageK.rN`` prefix and branch-path nodes a
        ``stageK.bJ`` one, so traces/stats show which parallel path a
        row belongs to instead of a flattened index."""
        m = self.manifest
        base = (f"stage{m['index']}" if m is not None
                else f"node{self.address[1]}")
        if self.replica is not None:
            return f"{base}.r{self.replica}"
        if self.branch is not None:
            return f"{base}.b{self.branch}"
        return base

    def _check_tier_pin(self) -> None:
        """Reject an explicit colocated-tier pin (``shm``/``ici``/
        ``local``) on a node whose hop rides the ordered fan machinery
        (replica into a fan-in merge, labeled branch into a join,
        fan-out next hops) — those paths are wire-framed by design, so
        :meth:`_make_tx` would silently skip the offer and run full
        codec + TCP under a tier claim with ``tier_fallbacks`` still 0.
        Mirrors the chain-level ``hop_tiers`` adjacency guard; ``auto``
        stays allowed (riding tcp there is policy, not degradation)."""
        if self.tier not in ("shm", "ici", "local"):
            return
        role = ("replica" if self.replica is not None
                else "branch" if self.branch is not None
                else "fan-out" if self.next_hops
                and len(self.next_hops) > 1 else None)
        if role is not None:
            raise ValueError(
                f"tier {self.tier!r} pinned on a {role} node; fan paths "
                f"ride tcp (drop the replicas/branching or the tier pin)")

    def set_device(self, device: int) -> None:
        """Pin this node's stage program to jax device index ``device``
        (``jax.devices()[device]``): outputs stay resident there, and
        an upstream ici hop device_puts each activation onto it before
        the program runs.  Applied to an already-loaded program
        immediately; an in-band deploy applies it at load."""
        import jax
        devs = jax.devices()
        if not 0 <= device < len(devs):
            raise ValueError(
                f"device {device} out of range: this process has "
                f"{len(devs)} jax device(s) (force a bigger host mesh "
                f"with --xla_force_host_platform_device_count)")
        self.device = device
        if self.prog is not None:
            self.prog.place(devs[device])

    def _jax_device(self):
        """The pinned jax device object, or None."""
        if self.device is None:
            return None
        import jax
        return jax.devices()[self.device]

    def _host_sync(self, y, seq=None, t0=None):
        """Materialize one stage output to host memory (``np.asarray``
        — the D2H sync every non-device-resident hop pays), timed into
        the per-node ``host_sync_hist`` + the registry twin and
        recorded as a ``stageK.host_sync`` span.  Device-resident (ici)
        hops never call this, so their zero sample count is the
        observable proof the host round-trip is gone.

        ``t0`` (the previous phase's end timestamp, when given) chains
        the phase windows end-to-start so the X-ray tiles the frame —
        a fresh clock read per phase would leak each site's own
        recording overhead into unaccounted gaps between phases.
        Returns ``(out, t_end)``; the loops close the ``infer``
        interval at ``t_end`` for the same reason."""
        # finish the (async-dispatched) device compute FIRST — timed
        # as the DEVICE phase: this histogram prices only the host
        # materialization the planner's host_sync term models; folding
        # compute wait into it would mis-calibrate host_sync_bw_s by
        # orders of magnitude
        t0 = self._device_wait(y, seq=seq, t0=t0)
        out = np.asarray(y)
        t_end = time.perf_counter()
        dt = t_end - t0
        REGISTRY.histogram("node.host_sync_s").record(dt)
        if self.host_sync_hist is not None:
            self.host_sync_hist.record(dt)
        tr = tracer()
        if tr.enabled and _sampled(self.trace_sample_every, seq):
            tr.record(f"{self._span_label()}.host_sync", t0, dt,
                      {} if seq is None else {"seq": seq})
        # (out, phase end): the caller closes the infer interval at
        # t_end, not a fresh clock read — otherwise THIS site's own
        # recording cost (worst with every-frame spans) leaks into
        # infer but no phase, and the tiling invariant drifts on
        # microsecond-scale stages
        return out, t_end

    def _dispatch(self, *xs, seq=None):
        """Run the stage program and time the DISPATCH phase — the jit
        call returning, i.e. host-side tracing/queueing cost only (jax
        dispatches asynchronously; the compute itself lands in the
        DEVICE phase at sync time).  Returns ``(t0, y)`` with ``t0``
        the dispatch start, which stays the anchor the loops measure
        the issue-to-materialize ``infer`` interval from.  A dispatch
        p50 near the infer p50 means the frame is HOST-bound — the
        MPK/persistent-program evidence this plane exists to surface.

        Returns ``(t0, t_end, y)``: ``t0`` stays the anchor the loops
        measure the issue-to-materialize ``infer`` interval from, and
        ``t_end`` seeds the QUEUE phase (:meth:`_queue_wait`) so the
        four phases tile the interval exactly."""
        t0 = time.perf_counter()
        y = self.prog(*xs)
        t_end = time.perf_counter()
        dt = t_end - t0
        REGISTRY.histogram("node.dispatch_s").record(dt)
        if self.disp_hist is not None:
            self.disp_hist.record(dt)
        tr = tracer()
        if tr.enabled and _sampled(self.trace_sample_every, seq):
            tr.record(f"{self._span_label()}.dispatch", t0, dt,
                      {} if seq is None else {"seq": seq})
        return t0, t_end, y

    def _queue_wait(self, t_end, seq=None):
        """Time from the dispatch returning to this frame's drain turn,
        recorded as the QUEUE phase — the frame's residency in the
        async in-flight window (``pending``) while OLDER frames sync
        and newer ones dispatch.  This is the overlap actually working:
        a large queue share on a non-bottleneck stage is hidden
        latency, not lost time.  The serial loop records it too (it is
        ~0 there), so dispatch + queue + device + host_sync tiles the
        ``infer`` interval on every loop and the profile plane's
        phase-sum invariant holds everywhere.  Returns the phase's end
        timestamp — pass it as the next phase's ``t0`` so the windows
        chain without leaking recording overhead between them."""
        t_now = time.perf_counter()
        dt = t_now - t_end
        REGISTRY.histogram("node.queue_s").record(dt)
        if self.queue_hist is not None:
            self.queue_hist.record(dt)
        tr = tracer()
        if tr.enabled and _sampled(self.trace_sample_every, seq):
            tr.record(f"{self._span_label()}.queue", t_end, dt,
                      {} if seq is None else {"seq": seq})
        return t_now

    def _device_wait(self, y, seq=None, t0=None):
        """``block_until_ready`` timed as the DEVICE phase: device
        compute plus the queueing of whatever in-flight window sits
        ahead of this frame.  No-op on plain host arrays.  Both host
        hops (via :meth:`_host_sync`) and device-resident ici hops
        (directly) pay this, so the DEV column is comparable across
        tiers while host_sync keeps its ici-hops-record-zero proof.

        ``t0`` chains from the previous phase's end (see
        :meth:`_host_sync`); returns THIS phase's end timestamp (its
        start when the array needs no sync) for the next window."""
        sync = getattr(y, "block_until_ready", None)
        if sync is None:
            return t0 if t0 is not None else time.perf_counter()
        if t0 is None:
            t0 = time.perf_counter()
        sync()
        t_end = time.perf_counter()
        dt = t_end - t0
        REGISTRY.histogram("node.device_s").record(dt)
        if self.dev_hist is not None:
            self.dev_hist.record(dt)
        tr = tracer()
        if tr.enabled and _sampled(self.trace_sample_every, seq):
            tr.record(f"{self._span_label()}.device", t0, dt,
                      {} if seq is None else {"seq": seq})
        return t_end

    def _make_tx(self, connect_timeout_s: float):
        """Open the downstream connection(s): one :class:`AsyncSender`,
        or a :class:`FanOutSender` round-robining across a replicated
        downstream stage (announced with a ``stream_begin`` control
        frame so even a replica that ends up with zero frames knows it
        is on the data path).

        With ``tier="auto"`` a single (non-fan) hop walks the tier
        ladder (``transport.shm.offer_tier_ladder``, shared with the
        dispatcher's edges): first the colocated fast path (same
        process, zero copies), then the shared-memory tier (same host,
        payload through a shm ring with the socket demoted to a
        doorbell); ``tier="shm"`` offers only the shm rung.  Any
        rung granted keeps the socket open as the hop's lifetime
        anchor; all refused, the hop degrades to the status-quo wire
        path with this hop's fallback counted once.  Fan-out and
        replica dial-backs never probe — the ordered fan machinery is
        wire-framed by design."""
        if not self.next_hops:
            raise ValueError("no next hop configured")
        socks = [_connect_retry(*h, timeout_s=connect_timeout_s)
                 for h in self.next_hops]
        if len(socks) == 1:
            tx = None
            if self.tier != "tcp" and self.replica is None \
                    and self.branch is None:
                # branch-path hops never probe: the join end is wire-
                # framed by design (ordered (path, seq) merge)
                from ..obs.events import emit as emit_event
                from ..transport.shm import offer_tier_ladder
                self.tier_out, tx, fell_back = offer_tier_ladder(
                    socks[0], tier=self.tier, depth=self.tx_depth,
                    hop=self._span_label(), device=self._jax_device())
                if fell_back:
                    self.tier_fallbacks += 1
                emit_event("tier", hop=self._span_label(),
                           tier=self.tier_out, wanted=self.tier,
                           fallback=bool(fell_back))
            if tx is None:
                self.tier_out = "tcp"
                tx = AsyncSender(socks[0], depth=self.tx_depth,
                                 codec=self.codec,
                                 gauge="node.tx_queue_depth",
                                 span=self._span_label,
                                 hist="node.tx_s")
            if self.branch is not None:
                # announce this connection's join path BEFORE any frame
                # so the downstream join can slot it (harmless to a
                # non-join downstream, which ignores the label)
                tx.send_ctrl({"cmd": "stream_begin",
                              "path": self.branch})
        elif self.fan_mode == "broadcast":
            # branched stage graph: every parallel branch receives every
            # frame, stamped with one shared sequence number; channel i
            # is path i of the region (docs/TRANSPORT.md)
            self.tier_out = "tcp"
            tx = BroadcastSender(socks, depth=self.tx_depth,
                                 codec=self.codec,
                                 gauge="node.tx_queue_depth",
                                 span=self._span_label,
                                 hist="node.tx_s")
        else:
            self.tier_out = "tcp"
            if self.failover:
                # seq-replay fan-out (docs/ROBUSTNESS.md): retain each
                # frame until the downstream fan-in's cumulative ack,
                # heal a dead replica channel by redialing its address
                # (the chain supervisor respawns it on the same port)
                # and replaying the unacked window
                tx = ReplayFanOut(socks, self.next_hops,
                                  depth=self.tx_depth,
                                  codec=self.codec,
                                  gauge="node.tx_queue_depth",
                                  span=self._span_label,
                                  hist="node.tx_s",
                                  redial_timeout_s=connect_timeout_s)
            else:
                tx = FanOutSender(socks, depth=self.tx_depth,
                                  codec=self.codec,
                                  gauge="node.tx_queue_depth",
                                  span=self._span_label,
                                  hist="node.tx_s")
            tx.send_ctrl({"cmd": "stream_begin"})
        tx.sample_every = self.trace_sample_every
        self._live_tx = tx
        if self._pending_trace is not None:
            # cascade the dispatcher's trace context down the chain
            # (broadcast on fan-out) ahead of the first relayed tensor
            tx.send_ctrl(self._pending_trace)
        return tx, socks

    def _handle_ctrl(self, conn, msg: dict, recv=None) -> bool:
        """One control command; True if the connection should keep serving.

        ``recv`` supplies the follow-up frame of multi-frame commands
        (deploy/reweight blobs); the overlapped loop passes its rx-queue
        getter because the channel's rx thread owns all socket reads.

        deploy:   {"cmd": "deploy", "next": "host:port", "codec": ...}
                  followed by a K_BYTES artifact blob -> load, ACK.
                  The in-band analogue of the reference's weights+arch
                  sockets and \\x06 ACK (src/dispatcher.py:44-65).
        reweight: {"cmd": "reweight"} followed by a K_BYTES npz blob ->
                  swap weights in the already-loaded program, ACK
                  (redeploy without restart; no reference analogue).
        trace:    {"cmd": "trace", "trace_id": ..., "span_id": ...} ->
                  adopt the dispatcher's trace context (spans recorded
                  from here on carry its trace_id and parent under its
                  root span) and cascade the same context downstream when
                  the data connection opens.  One-way: no ACK — it rides
                  the data stream ahead of the first tensor.
        trace_dump: reply with this node's recorded spans as a K_CTRL
                  frame (and drain them) — the dispatcher stitches every
                  stage's spans into one exportable trace.
        clock_probe: reply with this process's tracer-timeline "now"
                  ({"cmd": "clock_probe_reply", "t_us", "echo"}) — one
                  leg of the dispatcher's min-RTT offset estimator
                  (obs/cluster.py).
        clock_adjust: {"cmd": "clock_adjust", "offset_us": d} -> shift
                  the tracer's wall anchor (buffered spans included) so
                  this process's spans land on the dispatcher's
                  timeline; ACKed.
        obs_subscribe: {"cmd": "obs_subscribe", "interval_ms": 250,
                  "spans": bool, "span_limit": N} -> start pushing
                  {"cmd": "obs_push"} telemetry frames back on THIS
                  connection every interval until it closes
                  (obs/report.py; the live-monitoring plane, no new
                  ports).  The subscriber must not send further
                  commands on the connection besides its final END —
                  pushes and replies would interleave mid-frame.
        """
        from ..utils.export import load_stage_program

        def _expect(kind):
            if recv is None:
                return recv_expect(conn, kind)
            got, value = recv()
            if got != kind:
                raise ConnectionError(
                    f"expected frame kind {kind}, got {got}")
            return value

        cmd = msg.get("cmd")
        if cmd == "deploy":
            blob = _expect(K_BYTES)
            self.prog = load_stage_program(blob)
            if msg.get("next"):
                self.next_hops = _parse_hops(msg["next"])
            if msg.get("codec"):
                self.codec = msg["codec"]
            if msg.get("fan_in"):
                self.fan_in = max(1, int(msg["fan_in"]))
            if msg.get("replica") is not None:
                self.replica = int(msg["replica"])
            # branched stage-graph role (docs/TRANSPORT.md): broadcast
            # fork, labeled branch path, or P-path join
            if msg.get("fan"):
                if msg["fan"] not in ("rr", "broadcast"):
                    raise ValueError(f"deploy: fan must be rr|broadcast, "
                                     f"got {msg['fan']!r}")
                self.fan_mode = msg["fan"]
            if msg.get("branch") is not None:
                self.branch = int(msg["branch"])
            if msg.get("join"):
                j = int(msg["join"])
                if j < 2:
                    raise ValueError(f"deploy: join must be >= 2, got {j}")
                if self.fan_in > 1:
                    raise ValueError("deploy: a node cannot be both a "
                                     "branch join and a replica fan-in")
                self.join_in = j
            if msg.get("infer_delay_ms") is not None:
                self.infer_delay_s = max(
                    0.0, float(msg["infer_delay_ms"]) / 1e3)
            # analytic capacity of this stage (dispatcher-computed
            # FLOPs/HBM bytes at the deploy batch): the denominator of
            # the node's live MFU accounting (obs/capacity.py)
            if msg.get("flops") is not None:
                self.stage_flops = float(msg["flops"])
            if msg.get("bytes_moved") is not None:
                self.stage_bytes_moved = float(msg["bytes_moved"])
            if msg.get("tier"):
                # outbound transport-tier policy rides the deploy
                # handshake, like the hop codec
                if msg["tier"] not in ("tcp", "auto", "local", "shm",
                                       "ici"):
                    raise ValueError(
                        f"deploy: tier must be tcp|auto|local|shm|ici, "
                        f"got {msg['tier']!r}")
                self.tier = msg["tier"]
            if msg.get("tier_accept") is not None:
                self.tier_accept = bool(msg["tier_accept"])
            # device residency rides the deploy handshake too: pin the
            # freshly loaded program before any frame arrives — and a
            # node booted with --device keeps its pin across an in-band
            # deploy that doesn't mention one (the program object is
            # new; the old placement must be re-applied to it)
            dev = msg["device"] if msg.get("device") is not None \
                else self.device
            if dev is not None:
                self.set_device(int(dev))
            self._check_tier_pin()
            send_ack(conn)
            return True
        if cmd == "reweight":
            if self.prog is None:
                raise ValueError("reweight before deploy")
            self.prog.reweight(_expect(K_BYTES))
            self.reweights += 1
            send_ack(conn)
            return True
        if cmd == "trace":
            tr = tracer()
            tr.adopt(msg)
            m = self.manifest
            tr.process = (f"stage{m['index']}" if m is not None
                          else f"node:{self.address[1]}")
            self._pending_trace = {k: v for k, v in msg.items()}
            # waterfall sampling rides the trace context: every process
            # of the chain samples the SAME 1-in-N wire sequences
            self.trace_sample_every = int(msg.get("sample_every", 0) or 0)
            for ch in (self._live_rx, self._live_tx):
                if ch is not None:
                    ch.sample_every = self.trace_sample_every
            return True
        if cmd == "clock_probe":
            send_ctrl(conn, {"cmd": "clock_probe_reply",
                             "t_us": tracer().now_us(),
                             "echo": msg.get("echo")})
            return True
        if cmd == "clock_adjust":
            tracer().shift_wall_anchor(int(msg.get("offset_us", 0)))
            REGISTRY.gauge("clock.offset_us").inc(
                float(msg.get("offset_us", 0)))
            send_ack(conn)
            return True
        if cmd == "obs_subscribe":
            rep = ObsReporter(
                self, conn,
                interval_s=float(msg.get("interval_ms", 250.0)) / 1e3,
                spans=bool(msg.get("spans", True)),
                span_limit=int(msg.get("span_limit", 256)))
            self._reporters = [r for r in self._reporters
                               if r.is_alive()] + [rep]
            rep.start()
            return True
        if cmd == "events_since":
            # flight-recorder query (docs/OBSERVABILITY.md): the events
            # emitted in THIS process since the caller's cursor, without
            # draining what obs_push subscribers read incrementally
            from ..obs.events import recorder
            rec = recorder()
            cursor, evs = rec.events_since(
                int(msg.get("cursor", 0)),
                limit=int(msg.get("limit", 512)))
            send_ctrl(conn, {"cmd": "events_reply", "events": evs,
                             "cursor": cursor, "dropped": rec.dropped})
            return True
        if cmd == "trace_dump":
            tr = tracer()
            send_ctrl(conn, {"spans": tr.drain()})
            # the trace is over once collected: stop recording so a node
            # that later serves untraced streams doesn't accumulate spans
            tr.enabled = False
            tr._remote_parent = None
            self._pending_trace = None
            return True
        if cmd == "profile_start":
            # on-demand phase profiling (obs/profile.py): bracket a
            # window; the matching profile_stop replies with the DELTA
            # phase breakdown.  A double start is refused LOUDLY — an
            # error reply, connection kept — because silently restarting
            # would corrupt the first caller's window arithmetic.
            from ..obs.profile import (ProfileSession, memory_watcher,
                                       recompile_watcher)
            if self._profile is not None:
                send_ctrl(conn, {
                    "cmd": "profile_err",
                    "error": "profile session already active on this "
                             "node (profile_stop it first)"})
                return True
            # session start marks warmup done: install the compile
            # listener and arm the one-event-per-episode emitter, prime
            # the memory gauge
            recompile_watcher().install().arm()
            memory_watcher().observe()
            sess = ProfileSession(
                {"dispatch": self.disp_hist, "queue": self.queue_hist,
                 "device": self.dev_hist,
                 "host_sync": self.host_sync_hist,
                 "infer": self.infer_hist},
                processed=lambda: self.processed,
                jax_trace_dir=msg.get("jax_trace_dir") or None)
            started = sess.start()
            self._profile = sess
            send_ctrl(conn, {"cmd": "profile_started",
                             "node": self._span_label(), **started})
            return True
        if cmd == "profile_stop":
            if self._profile is None:
                send_ctrl(conn, {
                    "cmd": "profile_err",
                    "error": "no active profile session on this node "
                             "(profile_start first)"})
                return True
            report = self._profile.stop()
            self._profile = None
            report["node"] = self._span_label()
            mm = self.manifest
            report["stage"] = None if mm is None else mm["index"]
            report["replica"] = self.replica
            send_ctrl(conn, {"cmd": "profile_report", "report": report})
            return True
        if cmd == "stats":
            # chain observability: what this node is and has done — the
            # per-node view the reference never had (SURVEY §5 metrics)
            from ..obs.profile import \
                device_memory_bytes as _dev_mem_bytes
            m = self.manifest
            reg = REGISTRY
            tx_live = self._live_tx
            cap = self._capacity()
            from ..obs.events import recorder as _recorder
            rec = _recorder()
            _, evs = rec.events_since(
                int(msg.get("event_cursor", 0)),
                limit=int(msg.get("event_limit", 256)))
            send_ctrl(conn, {
                "stage": None if m is None else m["index"],
                "name": None if m is None else m["name"],
                "replica": self.replica,
                "branch": self.branch,
                "join": self.join_in,
                "fan_in": self.fan_in,
                "processed": self.processed,
                "reweights": self.reweights,
                "codec": self.codec,
                # negotiated outbound transport tier ("ici"/"local"/
                # "shm"/"tcp"; the configured policy until a data path
                # negotiates) + this hop's degraded-offer count
                "tier": self.tier_out or self.tier,
                "tier_in": self.tier_in,
                "tier_fallbacks": self.tier_fallbacks,
                # device residency: this node's pinned jax device index
                # and — on an ici outbound hop — the cross-device
                # device_put count with the distinct (src, dst) device-
                # id pairs, the stats-level proof a hop moved data
                # between devices without touching the host
                "device": self.device,
                "ici_d2d": (tx_live.d2d
                            if isinstance(tx_live, IciSender) else 0),
                "ici_device_pairs": (sorted(
                    [list(p) for p in tx_live.device_pairs])
                    if isinstance(tx_live, IciSender) else []),
                "next": None if not self.next_hops
                else ",".join(f"{h}:{p}" for h, p in self.next_hops),
                # wire telemetry: this node's process-local transport view
                "tx_frames": reg.counter("transport.tx_frames").value,
                "tx_bytes": reg.counter("transport.tx_bytes").value,
                "rx_frames": reg.counter("transport.rx_frames").value,
                "rx_bytes": reg.counter("transport.rx_bytes").value,
                # per-NODE infer distribution (instance histogram, so
                # in-process thread chains stay attributable per node)
                "infer_latency_s":
                    (self.infer_hist.summary()
                     if self.infer_hist is not None
                     else reg.histogram("node.infer_s").summary()),
                # host-sync distribution: np.asarray materialization
                # seconds per frame — zero COUNT on ici hops (the
                # device-resident proof), calibration input for the
                # planner's host_sync term
                "host_sync_s":
                    (self.host_sync_hist.summary()
                     if self.host_sync_hist is not None
                     else reg.histogram("node.host_sync_s").summary()),
                # the infer X-ray (obs/profile.py): dispatch = the jit
                # call returning (host cost), queue = in-flight window
                # residency, device = block_until_ready — dispatch +
                # queue + device + host_sync tiles the infer interval
                "dispatch_s":
                    (self.disp_hist.summary()
                     if self.disp_hist is not None
                     else reg.histogram("node.dispatch_s").summary()),
                "queue_s":
                    (self.queue_hist.summary()
                     if self.queue_hist is not None
                     else reg.histogram("node.queue_s").summary()),
                "device_s":
                    (self.dev_hist.summary()
                     if self.dev_hist is not None
                     else reg.histogram("node.device_s").summary()),
                # compile/memory telemetry: XLA compilations observed
                # in this process (0 until a profile session or an
                # explicit recompile_watcher().install() hooks the
                # listener) and live device-array bytes (None when jax
                # never loaded here — a deploy-less relay stays cheap)
                "recompiles": reg.counter("jax.compiles").value,
                "mem_bytes": _dev_mem_bytes(),
                "profiling": self._profile is not None,
                # phase timing: per-frame recv+decode / encode+send
                # seconds of the data channels, plus the per-CHANNEL
                # codec-only costs — the live bottleneck estimate's
                # inputs (no blocking waits included)
                "rx_s": reg.histogram("node.rx_s").summary(),
                "tx_s": reg.histogram("node.tx_s").summary(),
                "encode_latency_s":
                    (self._live_tx.enc.summary()
                     if self._live_tx is not None
                     else reg.histogram("codec.encode_s").summary()),
                "decode_latency_s":
                    (self._live_rx.dec.summary()
                     if self._live_rx is not None
                     else reg.histogram("codec.decode_s").summary()),
                # overlap telemetry: queue occupancy of the async channel
                # layer and the un-synced device-dispatch window
                "overlap": self.overlap,
                "rx_queue_depth": reg.gauge("node.rx_queue_depth").value,
                "tx_queue_depth": reg.gauge("node.tx_queue_depth").value,
                "rx_depth": self.rx_depth,
                "tx_depth": self.tx_depth,
                # watermark PEEKS (no reset — obs_push owns the
                # per-interval reset cycle)
                "rx_watermark": self._chan_hi(self._live_rx),
                "tx_watermark": self._chan_hi(self._live_tx),
                "inflight": reg.gauge("node.inflight").value,
                # capacity accounting (obs/capacity.py): analytic stage
                # FLOPs from the deploy message, achieved FLOP/s over
                # the measured infer p50, and MFU against THIS chip's
                # peak (None when the deploy shipped no capacity or the
                # generation has no public peak)
                "flops": self.stage_flops,
                "mfu": cap.get("mfu"),
                "achieved_flops_s": cap.get("achieved_flops_s"),
                # seq-replay substrate (docs/ROBUSTNESS.md): channels
                # healed, frames retained for replay, duplicates the
                # fan-in absorbed inside its dedup window
                "failovers": getattr(tx_live, "failovers", 0),
                "replay_depth": (tx_live.replay_depth()
                                 if hasattr(tx_live, "replay_depth")
                                 else 0),
                "merge_duplicates": (self._merge.duplicates
                                     if self._merge is not None else 0),
                # this process's flight-recorder tail (bounded; obs_push
                # streams the same ring incrementally) — how a teardown-
                # time stats sweep sees the failover/quiesce timeline
                # without a live subscription
                "events": {"dropped": rec.dropped, "events": evs},
            })
            return True
        if cmd == "quiesce":
            # drain to a stable sequence point (docs/ROBUSTNESS.md): the
            # reply comes only once nothing is in flight on this node —
            # the per-stage half of a live replan's safe cutover
            at = msg.get("at_seq")
            processed = self._quiesce(
                None if at is None else int(at),
                float(msg.get("timeout_s", 30.0)))
            from ..obs.events import emit as emit_event
            emit_event("quiesce", hop=self._span_label(),
                       processed=processed)
            send_ctrl(conn, {"cmd": "quiesced", "processed": processed})
            return True
        if cmd == "shutdown":
            # a persistent node exits its serve loop; a one-shot node
            # ACKs harmlessly (its serve returns at stream end anyway)
            send_ack(conn)
            if self._done_q is not None:
                self._done_q.put(_SHUTDOWN)
            return True
        raise ValueError(f"unknown control command {msg!r}")

    def _quiesce(self, at_seq: int | None, timeout_s: float) -> int:
        """Block until this node's data plane is drained and stable:
        ``processed`` past ``at_seq`` (when given) and unchanged across
        consecutive samples, no dispatch in flight, live queues and the
        reorder merge empty.  Returns the stable processed count;
        TimeoutError if the node never settles (frames still arriving —
        the caller quiesced mid-segment instead of at a boundary)."""
        deadline = time.monotonic() + timeout_s
        inflight_g = REGISTRY.gauge("node.inflight")
        last = -1
        while True:
            p = self.processed
            rx, tx = self._live_rx, self._live_tx
            merge = self._merge
            idle = (
                (at_seq is None or p >= at_seq)
                and p == last
                and inflight_g.value == 0
                and (rx is None or rx.qsize() == 0)
                and (tx is None or tx.qsize() == 0)
                and (merge is None or merge.qsize() == 0))
            if idle:
                return p
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"quiesce: node did not stabilize within "
                    f"{timeout_s:.1f}s (processed {p}, at_seq {at_seq})")
            last = p
            time.sleep(0.05)

    # -- live observability (obs_push payloads) -----------------------------

    def _capacity(self) -> dict:
        """Live MFU accounting for stats/obs_push: the deploy message's
        analytic stage FLOPs against this node's own measured infer p50
        and ITS OWN chip peak.  Empty when no deploy shipped capacity;
        ``mfu`` is None — never a number — when the chip generation has
        no public peak (utils/hw.py: callers must not fabricate MFU
        against a guessed peak)."""
        if self.stage_flops is None:
            return {}
        if self._peak_flops_s is None:
            from ..utils import hw
            gen = "unknown"
            try:
                import jax
                gen = hw.identify_chip(jax.devices()[0])
            except Exception:  # noqa: BLE001 — no backend: no peak
                pass
            self._peak_flops_s = hw.peak_flops(gen)
        hist = self.infer_hist
        p50 = hist.quantile(0.5) if hist is not None and hist.count \
            else 0.0
        from ..obs.capacity import achieved_mfu
        mfu = achieved_mfu(self.stage_flops, p50,
                           self._peak_flops_s or 0.0)
        return {
            "flops": self.stage_flops,
            "bytes_moved": self.stage_bytes_moved,
            "achieved_flops_s": (self.stage_flops / p50
                                 if p50 > 0 else None),
            "mfu": mfu,
        }

    @staticmethod
    def _chan_hi(chan) -> int:
        """Peek a channel's occupancy watermark without resetting it."""
        if chan is None:
            return 0
        try:
            return max(int(chan.hi), chan.qsize())
        except (AttributeError, TypeError):
            return 0

    def _wm(self) -> WatermarkSplit:
        with _WM_LOCK:
            if self._wm_split is None:
                self._wm_split = WatermarkSplit()
            return self._wm_split

    def obs_register(self, sid: int) -> None:
        """Register a push subscriber with the watermark splitter (one
        per :class:`ObsReporter`; see ``WatermarkSplit``)."""
        self._wm().register(sid)

    def obs_unregister(self, sid: int) -> None:
        self._wm().unregister(sid)

    def obs_snapshot(self, *, cursor: int = 0, include_spans: bool = True,
                     span_limit: int = 256,
                     subscriber: int | None = None,
                     event_cursor: int = 0, event_limit: int = 128
                     ) -> tuple[dict, int, int]:
        """One ``obs_push`` payload: identity, lifetime counters, queue
        depths + per-interval watermarks (reset on read), cumulative
        latency summaries, the flight recorder's events since
        ``event_cursor`` (obs/events.py — how node events reach the
        cluster-merged log), and — when tracing is live — the spans
        recorded since ``cursor`` (without draining what ``trace_dump``
        collects at stream end).  Called by :class:`ObsReporter` on its
        own thread; everything read here is either an attribute or a
        GIL-atomic registry instrument, so the hot path never blocks on
        the reporter.

        Watermarks are reset-on-read at the CHANNEL, but split per
        subscriber here (``subscriber`` = the reporter's id,
        :class:`~defer_tpu.obs.report.WatermarkSplit`): every
        registered subscription sees the true peak since ITS OWN last
        push, so the serve front door's shedding loop and a human
        ``monitor`` can watch the same chain without corrupting each
        other's readings (the PR 5 single-subscriber caveat, fixed)."""
        m = self.manifest
        reg = REGISTRY
        rx, tx = self._live_rx, self._live_tx
        payload = {
            "node": {"stage": None if m is None else m["index"],
                     "name": None if m is None else m["name"],
                     "replica": self.replica, "branch": self.branch,
                     "join": self.join_in, "fan_in": self.fan_in,
                     "port": self.address[1], "codec": self.codec,
                     "tier": self.tier_out or self.tier,
                     "tier_in": self.tier_in,
                     "tier_fallbacks": self.tier_fallbacks,
                     "device": self.device},
            "processed": self.processed,
            "reweights": self.reweights,
            "counters": {
                "tx_frames": reg.counter("transport.tx_frames").value,
                "tx_bytes": reg.counter("transport.tx_bytes").value,
                "rx_frames": reg.counter("transport.rx_frames").value,
                "rx_bytes": reg.counter("transport.rx_bytes").value,
            },
            "queues": {
                "rx_depth": self.rx_depth, "tx_depth": self.tx_depth,
                "rx": rx.qsize() if rx is not None else 0,
                "tx": tx.qsize() if tx is not None else 0,
                "rx_hi": self._wm().take(subscriber, "rx", rx),
                "tx_hi": self._wm().take(subscriber, "tx", tx),
                "inflight": reg.gauge("node.inflight").value,
                "merge": (self._merge.qsize()
                          if self._merge is not None
                          else self._join.qsize()
                          if self._join is not None else 0),
                # retained-frame memory of a failover fan-out (the
                # monitor's replay-window gauge, docs/ROBUSTNESS.md)
                "replay": (tx.replay_depth()
                           if hasattr(tx, "replay_depth") else 0),
            },
            "latency": {
                # per-node / per-channel instruments where they exist
                # (correct attribution even when in-process nodes share
                # the registry); process-wide registry as the fallback
                "infer_s": (self.infer_hist.summary()
                            if self.infer_hist is not None
                            else reg.histogram("node.infer_s").summary()),
                "host_sync_s": (self.host_sync_hist.summary()
                                if self.host_sync_hist is not None
                                else reg.histogram(
                                    "node.host_sync_s").summary()),
                # phase X-ray (obs/profile.py): the monitor's DISP/DEV
                # columns next to HS50
                "dispatch_s": (self.disp_hist.summary()
                               if self.disp_hist is not None
                               else reg.histogram(
                                   "node.dispatch_s").summary()),
                "queue_s": (self.queue_hist.summary()
                            if self.queue_hist is not None
                            else reg.histogram(
                                "node.queue_s").summary()),
                "device_s": (self.dev_hist.summary()
                             if self.dev_hist is not None
                             else reg.histogram(
                                 "node.device_s").summary()),
                "rx_s": reg.histogram("node.rx_s").summary(),
                "tx_s": reg.histogram("node.tx_s").summary(),
                "encode_s": (tx.enc.summary() if tx is not None
                             else reg.histogram(
                                 "codec.encode_s").summary()),
                "decode_s": (rx.dec.summary() if rx is not None
                             else reg.histogram(
                                 "codec.decode_s").summary()),
            },
            # live MFU accounting (obs/capacity.py): {} until a deploy
            # ships the stage's analytic FLOPs; mfu None without an
            # honest chip peak
            "capacity": self._capacity(),
        }
        # compile/memory telemetry (obs/profile.py): observe() updates
        # the device.mem_bytes gauge AND runs the mem_pressure
        # threshold check — push cadence, never the frame hot path
        from ..obs.profile import memory_watcher
        payload["recompiles"] = reg.counter("jax.compiles").value
        payload["mem_bytes"] = memory_watcher().observe()
        tr = tracer()
        trace_doc: dict = {"dropped": tr.dropped}
        if include_spans and tr.enabled:
            cursor, spans = tr.spans_since(cursor, limit=span_limit)
            trace_doc["spans"] = spans
        payload["trace"] = trace_doc
        from ..obs.events import recorder
        rec = recorder()
        event_cursor, evs = rec.events_since(event_cursor,
                                             limit=event_limit)
        payload["events"] = {"dropped": rec.dropped, "events": evs}
        return payload, cursor, event_cursor

    def serve(self, *, connect_timeout_s: float = 30.0) -> int:
        """Serve control/data connections until a data stream completes.

        Connections are handled CONCURRENTLY (thread per connection — the
        shape of the reference node's 4-thread design, src/node.py:110-124,
        minus the polling): control connections (deploy / reweight, each
        ACKed, ending with the dispatcher's END) may arrive before or
        *during* the upstream data stream, which is relayed through the
        stage function until its END frame.  Returns the number of tensors
        the completed data stream processed.  The END is forwarded
        downstream before closing, so shutdown cascades through the chain
        to the dispatcher's result server.
        """
        import queue as _q
        import threading

        done: _q.Queue = _q.Queue()
        self._done_q = done  # the fan-in compute loop reports here too

        def worker(conn):
            try:
                configure_socket(conn)
                n = self._serve_conn(conn, connect_timeout_s)
                if n is not None:
                    done.put(n)
            except BaseException as e:  # noqa: BLE001 — surfaced below
                done.put(e)
            finally:
                conn.close()

        total = 0
        self._srv.settimeout(0.25)
        try:
            while True:
                try:
                    conn, _ = self._srv.accept()
                except TimeoutError:  # socket.timeout is TimeoutError >=3.10
                    conn = None
                if conn is not None:
                    threading.Thread(target=worker, args=(conn,),
                                     daemon=True).start()
                try:
                    r = done.get_nowait()
                except _q.Empty:
                    continue
                if r is _SHUTDOWN:
                    return total
                if isinstance(r, BaseException):
                    raise r
                if not self.persist:
                    return r
                # persistent node: the segment is done, keep serving
                # until a shutdown control command (live replan's
                # quiesce -> redeploy -> resume rides stream segments)
                total += r
        finally:
            self._srv.close()

    def _serve_conn(self, conn, connect_timeout_s: float) -> int | None:
        """One connection: None if it was control-only, else tensor count.

        ``overlap=True`` (default) runs the three-phase overlapped loop
        (:meth:`_serve_conn_overlapped`); ``overlap=False`` keeps the
        strictly serial recv -> infer -> send loop as the measurable
        baseline (``--no-overlap``, ``scripts/chain_overlap_smoke.py``).
        With ``fan_in > 1`` every connection instead feeds the shared
        reorder merge (:meth:`_serve_conn_fanin`) and ONE compute loop
        consumes the merged in-order stream; with ``join_in >= 2`` the
        connections feed the (path, seq) branch join
        (:meth:`_serve_conn_join`) and the compute loop applies the
        multi-input merge program to each complete sequence.
        """
        if self.join_in >= 2:
            return self._serve_conn_join(conn, connect_timeout_s)
        if self.fan_in > 1:
            return self._serve_conn_fanin(conn, connect_timeout_s)
        if self.overlap:
            return self._serve_conn_overlapped(conn, connect_timeout_s)
        return self._serve_conn_serial(conn, connect_timeout_s)

    def _serve_conn_overlapped(self, conn,
                               connect_timeout_s: float) -> int | None:
        """Three-phase overlap: rx thread -> compute loop -> tx thread.

        An :class:`AsyncReceiver` decodes upstream frames into a bounded
        queue while this thread computes, and an :class:`AsyncSender`
        encodes/sends relayed tensors from a bounded queue — so the rx of
        microbatch j+1, the compute of j, and the tx of j-1 run
        concurrently, and per-hop latency tends to max(rx, compute, tx)
        instead of their sum.  The compute loop additionally keeps up to
        ``inflight`` stage dispatches un-synced (JAX async dispatch): the
        host-side ``np.asarray`` sync of output j-1 overlaps the device
        compute of j.  Bounded queues preserve end-to-end backpressure —
        a stuck downstream fills the tx queue, stalls this loop, fills
        the rx queue, and TCP pushes back upstream.

        ``node.infer_s`` here measures issue-to-materialize (device queue
        included), matching what the overlap actually hides.

        Sequence-stamped frames (``K_TENSOR_SEQ`` — this node is a
        replica on a fan-out path) relay their sequence number onto the
        output frame unchanged, so the downstream fan-in can restore
        stream order.
        """
        out_socks = None
        tx = None
        n = 0                   # tensors relayed downstream
        seq = 0                 # tensors received
        streamed = False
        stream_marked = False   # upstream announced this conn as data path
        infer_hist = REGISTRY.histogram("node.infer_s")
        inflight_g = REGISTRY.gauge("node.inflight")
        #: issued-but-unsynced stage outputs, oldest first
        pending: collections.deque = collections.deque()
        # no gauge yet: most connections are short-lived control round
        # trips whose rx channel would clobber the data stream's reading;
        # the gauge is bound once this connection proves to be the stream
        rx = AsyncReceiver(conn, depth=self.rx_depth,
                           span=self._span_label)
        # replica half of the ack plane (docs/ROBUSTNESS.md): forward
        # the downstream fan-in's cumulative replay_acks one hop
        # upstream on this replica's own inbound connection; the lock
        # serializes those writes against the stream-end replay_done
        ack_lock = threading.Lock()
        relay_on = [False]

        def start_relay():
            if relay_on[0] or not (self.failover
                                   and self.replica is not None
                                   and out_socks):
                return
            relay_on[0] = True
            self._start_ack_relay(conn, out_socks[0], ack_lock)

        def drain_one():
            nonlocal n, streamed
            t0, t_end, s, y, relay_seq = pending.popleft()
            inflight_g.dec()
            tq = self._queue_wait(t_end, seq=relay_seq)
            if isinstance(tx, IciSender):
                # device-resident mode: the downstream hop accepts live
                # jax.Arrays, so the output is NEVER materialized to
                # host — only synced (bounding the dispatch window as
                # before).  Zero host_sync samples on this node is the
                # observable proof the round-trip is gone.
                t_done = self._device_wait(y, seq=relay_seq, t0=tq)
            else:
                # host sync of the OLDEST in-flight output
                y, t_done = self._host_sync(y, seq=relay_seq, t0=tq)
            dt = t_done - t0
            infer_hist.record(dt)
            if self.infer_hist is not None:
                self.infer_hist.record(dt)
            tr = tracer()
            if tr.enabled and _sampled(self.trace_sample_every, relay_seq):
                tr.record(
                    f"{self._span_label()}.infer", t0, dt,
                    {"seq": s if relay_seq is None else relay_seq,
                     "stage": self.manifest["index"]})
            self.processed += 1  # before the send: a stats query can
            #   race the relay of the final tensor otherwise
            tx.send(y, seq=relay_seq)
            n += 1
            streamed = True

        import queue as _q

        try:
            while True:
                if pending:
                    # compute-ahead only while input is immediately
                    # available: an idle upstream means the window must
                    # drain NOW, or the stream's tail stalls in the node
                    try:
                        kind, value = rx.get_nowait()
                    except _q.Empty:
                        drain_one()
                        continue
                else:
                    kind, value = rx.get()
                if kind == K_END:
                    while pending:
                        drain_one()
                    if streamed or stream_marked:
                        if tx is None:
                            # marked data path, zero frames (fewer inputs
                            # than replicas): still propagate the stream
                            # shape so the downstream fan-in's END count
                            # and the result server's dial-back hold
                            # (fan senders and branch-path hops already
                            # announced themselves in _make_tx)
                            tx, out_socks = self._make_tx(
                                connect_timeout_s)
                            start_relay()
                            if not isinstance(
                                    tx, (FanOutSender, BroadcastSender,
                                         ReplayFanOut)) \
                                    and self.branch is None:
                                tx.send_ctrl({"cmd": "stream_begin"})
                        # END + join: every relayed frame is on the wire
                        # before the finally block closes the socket
                        tx.close(timeout=connect_timeout_s)
                        if relay_on[0]:
                            # every frame of this replica's segment got
                            # downstream: tell the upstream fan-out the
                            # coming EOF is shutdown, not death
                            try:
                                with ack_lock:
                                    send_ctrl(conn,
                                              {"cmd": "replay_done"})
                            except OSError:
                                pass
                        from ..obs.events import emit as emit_event
                        emit_event("stream_end", hop=self._span_label(),
                                   n=n)
                        return n
                    return None  # control connection closing
                if kind == K_CTRL:
                    if isinstance(value, dict) \
                            and value.get("cmd") == "stream_begin":
                        stream_marked = True
                        continue
                    if isinstance(value, dict) \
                            and value.get("cmd") == "tier_probe":
                        # colocated-tier handshake: an ici/local grant
                        # SWAPS the data path to the offered in-memory
                        # pipe (ici frames stay live jax.Arrays,
                        # device_put onto this node's pinned device by
                        # the sender); a shm grant wraps this socket
                        # channel into a ShmReceiver (descriptors keep
                        # riding the socket as the doorbell, payloads
                        # come out of the mapped ring); refused, the
                        # stream continues on this socket
                        from ..transport.shm import answer_tier_probe
                        self.tier_in, chan = answer_tier_probe(
                            conn, value, accept=self.tier_accept,
                            inner=rx, depth=self.rx_depth,
                            device=self._jax_device())
                        if chan is not None:
                            rx = chan
                            rx.sample_every = self.trace_sample_every
                        continue
                    if isinstance(value, dict) \
                            and value.get("cmd") == "req_meta":
                        # serve-front-door request metadata: cascade
                        # downstream immediately (docs/SERVING.md).
                        # Relayed ahead of the still-in-flight dispatch
                        # window on purpose — a meta may only move
                        # EARLIER relative to its own frame (it is
                        # processed before the frame at every stage),
                        # never later, and the result-hop demux joins
                        # meta to frame by seq; draining the window
                        # here would cut serving traffic's compute-
                        # ahead to one frame
                        stream_marked = True
                        if tx is None:
                            tx, out_socks = self._make_tx(
                                connect_timeout_s)
                            start_relay()
                        tx.send_ctrl(value)
                        continue
                    is_trace = (isinstance(value, dict)
                                and value.get("cmd") == "trace")
                    if is_trace:
                        # relay order: everything received before this
                        # ctrl frame must reach downstream ahead of it
                        while pending:
                            drain_one()
                    self._handle_ctrl(conn, value, recv=rx.get)
                    if is_trace and tx is not None:
                        # downstream already connected (e.g. a second
                        # traced stream on a live chain): cascade the new
                        # context now, not just at connection open
                        tx.send_ctrl(self._pending_trace)
                    continue
                if kind == K_TENSOR_SEQ:
                    relay_seq, value = value
                elif kind == K_TENSOR:
                    relay_seq = None
                else:
                    raise ValueError(f"unexpected frame kind {kind}")
                if self.prog is None:
                    raise ValueError(
                        "data frame before any stage artifact (boot with "
                        "--artifact or deploy in-band first)")
                if tx is None:
                    tx, out_socks = self._make_tx(connect_timeout_s)
                    start_relay()
                if self._live_rx is not rx:
                    # first tensor on this channel (tx may already be
                    # open from a req_meta cascade): bind the live
                    # telemetry to the channel the stream actually rides
                    rx.bind_gauge("node.rx_queue_depth")
                    rx.bind_hist("node.rx_s")
                    rx.sample_every = self.trace_sample_every
                    self._live_rx = rx
                    from ..obs.events import emit as emit_event
                    emit_event("stream_begin", hop=self._span_label())
                want = tuple(self.manifest["in_shape"])
                if tuple(value.shape[1:]) != want:
                    raise ValueError(
                        f"stage {self.manifest['index']} expects sample "
                        f"shape {want}, got {tuple(value.shape[1:])}")
                if self.infer_delay_s:
                    time.sleep(self.infer_delay_s)  # bench-only device
                t0, t_end, y_disp = self._dispatch(value, seq=relay_seq)
                pending.append((t0, t_end, seq, y_disp, relay_seq))
                seq += 1
                inflight_g.inc()
                while len(pending) >= self.inflight:
                    drain_one()
        except Exception as e:  # noqa: BLE001 — see below
            if streamed:
                raise  # upstream died / corrupted mid-stream: loud
            # a connection that never became the data stream must not be
            # able to kill a serving node: port scanners and malformed
            # control peers are logged and dropped.  The remote side still
            # fails loudly — its recv gets a cut connection, no ACK/END.
            print(f"node: dropped connection before streaming: {e!r}",
                  file=sys.stderr, flush=True)
            return None
        finally:
            # reconcile the ADDITIVE gauges: an abandoned stream's
            # queued frames / un-synced dispatches are never consumed,
            # and must not inflate the shared readings forever
            if self._live_rx is rx:
                self._live_rx = None
            rx.release_gauge()
            if pending:
                inflight_g.dec(len(pending))
            if tx is not None and hasattr(tx, "detach"):
                # local-tier tx: a stream abandoned without its END must
                # fail the downstream consumer like a cut socket would
                tx.detach()
            if out_socks is not None:
                for s in out_socks:
                    s.close()

    def _serve_conn_serial(self, conn, connect_timeout_s: float) -> int | None:
        """The pre-overlap serial loop: per tensor, rx + decode, compute
        with an immediate host sync, encode + tx — phases pay their sum.
        Kept as the baseline the overlap speedup is measured against."""
        out = None
        n = 0
        streamed = False
        stream_marked = False
        infer_hist = REGISTRY.histogram("node.infer_s")
        try:
            while True:
                kind, value = recv_frame(conn)
                if kind == K_END:
                    if streamed or stream_marked:
                        if out is None:
                            if self.next_hop is None:
                                raise ValueError("no next hop configured")
                            out = _connect_retry(*self.next_hop,
                                                 timeout_s=connect_timeout_s)
                            send_ctrl(out, {"cmd": "stream_begin"})
                        send_end(out)
                        return n
                    return None  # control connection closing
                if kind == K_CTRL:
                    if isinstance(value, dict) \
                            and value.get("cmd") == "stream_begin":
                        stream_marked = True
                        continue
                    if isinstance(value, dict) \
                            and value.get("cmd") == "tier_probe":
                        # the serial baseline loop is the measurable
                        # pure-wire reference: always refuse the fast
                        # path (the offering hop degrades to tcp)
                        from ..transport.local import answer_probe
                        answer_probe(conn, value, accept=False)
                        self.tier_in = "tcp"
                        continue
                    if isinstance(value, dict) \
                            and value.get("cmd") == "req_meta":
                        # serve request metadata: cascade downstream in
                        # stream order (the serial loop is already
                        # strictly ordered — no window to drain)
                        stream_marked = True
                        if out is None:
                            if self.next_hop is None:
                                raise ValueError("no next hop configured")
                            out = _connect_retry(
                                *self.next_hop,
                                timeout_s=connect_timeout_s)
                            if self._pending_trace is not None:
                                send_ctrl(out, self._pending_trace)
                        send_ctrl(out, value)
                        continue
                    self._handle_ctrl(conn, value)
                    if (isinstance(value, dict)
                            and value.get("cmd") == "trace"
                            and out is not None):
                        # downstream already connected (e.g. a second
                        # traced stream on a live chain): cascade the new
                        # context now, not just at connection open
                        send_ctrl(out, self._pending_trace)
                    continue
                if kind == K_TENSOR_SEQ:
                    relay_seq, value = value
                elif kind == K_TENSOR:
                    relay_seq = None
                else:
                    raise ValueError(f"unexpected frame kind {kind}")
                if self.prog is None:
                    raise ValueError(
                        "data frame before any stage artifact (boot with "
                        "--artifact or deploy in-band first)")
                if out is None:
                    if self.next_hop is None:
                        raise ValueError("no next hop configured")
                    if self.next_hops and len(self.next_hops) > 1:
                        raise ValueError(
                            "fan-out requires the overlapped node loop "
                            "(drop --no-overlap)")
                    out = _connect_retry(*self.next_hop,
                                         timeout_s=connect_timeout_s)
                    if self._pending_trace is not None:
                        # cascade the dispatcher's trace context down the
                        # chain ahead of the first relayed tensor
                        send_ctrl(out, self._pending_trace)
                want = tuple(self.manifest["in_shape"])
                if tuple(value.shape[1:]) != want:
                    raise ValueError(
                        f"stage {self.manifest['index']} expects sample "
                        f"shape {want}, got {tuple(value.shape[1:])}")
                if self.infer_delay_s:
                    time.sleep(self.infer_delay_s)  # bench-only device
                t0, t_end, y = self._dispatch(value, seq=relay_seq)
                tq = self._queue_wait(t_end, seq=relay_seq)
                y, t_done = self._host_sync(y, seq=relay_seq, t0=tq)
                dt = t_done - t0
                infer_hist.record(dt)
                if self.infer_hist is not None:
                    self.infer_hist.record(dt)
                tr = tracer()
                if tr.enabled and _sampled(self.trace_sample_every,
                                           relay_seq):
                    tr.record(
                        f"{self._span_label()}.infer", t0, dt,
                        {"seq": n if relay_seq is None else relay_seq,
                         "stage": self.manifest["index"]})
                self.processed += 1  # before the send: a stats query can
                #   race the relay of the final tensor otherwise
                send_frame(out, y, codec=self.codec, seq=relay_seq)
                n += 1
                streamed = True
        except Exception as e:  # noqa: BLE001 — see below
            if streamed:
                raise  # upstream died / corrupted mid-stream: loud
            print(f"node: dropped connection before streaming: {e!r}",
                  file=sys.stderr, flush=True)
            return None
        finally:
            if out is not None:
                out.close()

    # -- seq-replay ack plane (docs/ROBUSTNESS.md) ---------------------------

    def _start_ack_relay(self, up_conn, down_sock, lock) -> None:
        """Replica half of the ack plane: read the downstream fan-in's
        cumulative ``replay_ack`` control frames off the data socket's
        reverse direction and forward each one hop upstream on this
        replica's own inbound connection — the fan-out's replay window
        drains end to end without a dedicated ack port.  ``lock``
        serializes the upstream writes against the stream-end
        ``replay_done``; the thread dies silently with either socket."""

        def relay():
            try:
                while True:
                    kind, value = recv_frame(down_sock)
                    if kind == K_END:
                        return
                    if kind == K_CTRL and isinstance(value, dict) \
                            and value.get("cmd") == "replay_ack":
                        with lock:
                            send_ctrl(up_conn, value)
            except (OSError, ConnectionError, ValueError):
                return

        threading.Thread(target=relay, daemon=True,
                         name="node-ack-relay").start()

    def _fanin_ack(self, merge) -> None:
        """Fan-in half of the ack plane: one cumulative ``replay_ack``
        (every seq below it merged in order) on each live upstream
        connection.  A connection that fails the write is dropped from
        the ack set — its reader thread notices the death itself."""
        with self._merge_lock:
            conns = list(self._fanin_conns or ())
        upto = merge.next_seq
        for c in conns:
            try:
                send_ctrl(c, {"cmd": "replay_ack", "seq": upto})
            except OSError:
                self._fanin_forget(c)

    def _fanin_forget(self, conn) -> None:
        with self._merge_lock:
            if self._fanin_conns and conn in self._fanin_conns:
                self._fanin_conns.remove(conn)

    def _fanin_grace(self, merge, exc: BaseException) -> None:
        """Poison ``merge`` with ``exc`` after the redial grace UNLESS
        a fresh upstream registers in the meantime (the respawned
        replica's dial-in bumps ``_fanin_epoch``) or the segment
        completes — failover tolerance with a bounded hang."""
        with self._merge_lock:
            epoch = self._fanin_epoch

        def watch():
            deadline = time.monotonic() + self.failover_grace_s
            while time.monotonic() < deadline:
                with self._merge_lock:
                    if self._fanin_epoch != epoch \
                            or self._merge is not merge:
                        return
                time.sleep(0.1)
            with self._merge_lock:
                expired = (self._merge is merge
                           and self._fanin_epoch == epoch)
            if expired:
                merge.fail(exc)

        threading.Thread(target=watch, daemon=True,
                         name="node-failover-grace").start()

    # -- fan-in (this node merges R replicated upstreams) --------------------

    def _serve_conn_fanin(self, conn, connect_timeout_s: float) -> None:
        """One upstream connection of a fan-in node: a reader loop that
        decodes frames on THIS thread (R connections = R parallel
        decoders) and feeds sequence-stamped tensors into the shared
        reorder merge.  Control connections (deploy / stats / reweight)
        are served inline exactly as before.  Always returns ``None`` —
        the merged compute loop (:meth:`_merge_compute`) is the one
        producer of the stream's tensor count."""
        registered = False
        merge = None
        try:
            while True:
                kind, value = recv_frame(conn)
                if kind == K_END:
                    if registered:
                        self._fanin_forget(conn)
                        merge.end()
                    return None
                if kind == K_CTRL:
                    if isinstance(value, dict) \
                            and value.get("cmd") == "stream_begin":
                        # the upstream fan-out marks every replica path,
                        # so even a zero-frame upstream is counted in the
                        # merge's END bookkeeping
                        if not registered:
                            registered = True
                            merge = self._ensure_merge_loop(
                                connect_timeout_s, conn=conn)
                        continue
                    if isinstance(value, dict) \
                            and value.get("cmd") == "tier_probe":
                        # fan paths are wire-framed by design (ordered
                        # seq merge): refuse, the offer degrades to tcp
                        from ..transport.local import answer_probe
                        answer_probe(conn, value, accept=False)
                        continue
                    self._handle_ctrl(conn, value)
                    if registered and isinstance(value, dict) \
                            and value.get("cmd") == "trace":
                        # a trace context arriving MID-STREAM (second
                        # traced stream on a live chain) must still
                        # cascade past an already-open downstream
                        # connection: ride it through the merge so the
                        # compute loop re-sends it (duplicates across
                        # the R paths are harmless — adoption is
                        # idempotent and the dispatcher skips them)
                        merge.put_ctrl(dict(self._pending_trace))
                    continue
                if kind == K_TENSOR:
                    raise ValueError(
                        "fan-in node received an unsequenced tensor "
                        "frame — the upstream must fan out with "
                        "sequence numbers (K_TENSOR_SEQ)")
                if kind != K_TENSOR_SEQ:
                    raise ValueError(f"unexpected frame kind {kind}")
                seq, arr = value
                if not registered:
                    registered = True
                    merge = self._ensure_merge_loop(connect_timeout_s,
                                                    conn=conn)
                t0 = time.perf_counter()
                merge.put(seq, arr)
                tr = tracer()
                if tr.enabled:
                    tr.record(f"{self._span_label()}.merge_wait", t0,
                              time.perf_counter() - t0, {"seq": seq})
        except Exception as e:  # noqa: BLE001 — policy matches the
            # single-upstream loops: a registered data path fails loudly
            # (and poisons the merge so the compute loop fails too); a
            # connection that never streamed is logged and dropped
            if registered:
                if self.failover and isinstance(e, (ConnectionError,
                                                    OSError)):
                    # a replica died mid-stream (docs/ROBUSTNESS.md
                    # failover timeline): tolerate for one redial
                    # grace — the healed fan-out replays the dead
                    # path's unacked frames through the respawned
                    # replica's NEW connection; only an unfilled grace
                    # poisons the merge with the original error
                    from ..obs.events import emit as emit_event
                    emit_event("replica_lost", hop=self._span_label(),
                               error=repr(e))
                    self._fanin_forget(conn)
                    self._fanin_grace(merge, e)
                    return None
                merge.fail(e)
                raise
            print(f"node: dropped connection before streaming: {e!r}",
                  file=sys.stderr, flush=True)
            return None

    def _ensure_merge_loop(self, connect_timeout_s: float,
                           conn=None) -> FanInMerge:
        """Create the shared reorder merge and its single compute thread
        the first time an upstream turns out to be a data path; under
        failover, ``conn`` joins the ack set and bumps the registration
        epoch (a respawned replica's dial-in cancels the grace timer).
        Returns the segment's merge — readers hold it locally so a
        persistent node's segment reset can't yank it mid-use."""
        with self._merge_lock:
            if self.failover and conn is not None:
                if self._fanin_conns is None:
                    self._fanin_conns = []
                self._fanin_conns.append(conn)
                self._fanin_epoch += 1
            if self._merge is None:
                # capacity: every upstream gets rx_depth frames of
                # reorder slack before backpressure parks its reader
                # thread; the dedup window absorbs failover replay
                # overlaps (transport/replicate.py, docs/ROBUSTNESS.md)
                self._merge = FanInMerge(
                    self.fan_in,
                    capacity=max(self.fan_in,
                                 self.fan_in * self.rx_depth),
                    replay_window=(_REPLAY_DEDUP_WINDOW
                                   if self.failover else 0))
                t = threading.Thread(
                    target=self._merge_loop, args=(connect_timeout_s,),
                    daemon=True, name="node-merge-compute")
                t.start()
            return self._merge

    def _merge_loop(self, connect_timeout_s: float) -> None:
        done = self._done_q
        try:
            n = self._merge_compute(connect_timeout_s)
            with self._merge_lock:
                # segment complete: a persistent node's next stream
                # builds a fresh merge (and a fresh ack set)
                self._merge = None
                self._fanin_conns = None
            done.put(n)
        except BaseException as e:  # noqa: BLE001 — surfaced via serve()
            self._merge.fail(e)  # wake readers parked in put()
            done.put(e)

    def _merge_compute(self, connect_timeout_s: float) -> int:
        """The fan-in node's compute loop: consume the merged in-order
        stream, keep up to ``inflight`` dispatches un-synced (draining
        greedily whenever the merge has no in-order frame ready), relay
        downstream.  Same shape as :meth:`_serve_conn_overlapped`, with
        the reorder merge in place of the single rx channel."""
        import queue as _q

        tx = None
        out_socks = None
        n = 0
        seq = 0
        infer_hist = REGISTRY.histogram("node.infer_s")
        inflight_g = REGISTRY.gauge("node.inflight")
        merge_g = REGISTRY.gauge("node.merge_depth")
        pending: collections.deque = collections.deque()

        def drain_one():
            nonlocal n
            t0, t_end, s, y = pending.popleft()
            inflight_g.dec()
            tq = self._queue_wait(t_end)
            if isinstance(tx, IciSender):
                # the merge node's OUTBOUND hop can legitimately win
                # ici (only its inbound fan is wire-framed): keep the
                # output device-resident, zero host_sync samples
                t_done = self._device_wait(y, t0=tq)
            else:
                y, t_done = self._host_sync(y, t0=tq)
            dt = t_done - t0
            infer_hist.record(dt)
            if self.infer_hist is not None:
                self.infer_hist.record(dt)
            tr = tracer()
            if tr.enabled:
                tr.record(f"{self._span_label()}.infer", t0, dt,
                          {"seq": s, "stage": self.manifest["index"]})
            self.processed += 1
            tx.send(y)
            n += 1

        merge = self._merge
        try:
            while True:
                if pending:
                    try:
                        kind, value = merge.get_nowait()
                    except _q.Empty:
                        drain_one()
                        continue
                else:
                    kind, value = merge.get()
                merge_g.v = merge.qsize()
                if kind == K_END:
                    while pending:
                        drain_one()
                    if self.failover:
                        # final cumulative ack: release the upstream
                        # fan-out's whole retained window before the
                        # END cascades (best effort — a replica that
                        # already exited just misses one write)
                        self._fanin_ack(merge)
                    if tx is None:
                        # all upstreams were zero-frame paths: still
                        # propagate the stream downstream (see the
                        # overlapped loop's marked-but-empty branch)
                        tx, out_socks = self._make_tx(connect_timeout_s)
                        if not isinstance(
                                tx, (FanOutSender, BroadcastSender)) \
                                and self.branch is None:
                            tx.send_ctrl({"cmd": "stream_begin"})
                    tx.close(timeout=connect_timeout_s)
                    return n
                if kind == K_CTRL:
                    # the readers handled the command (trace adoption);
                    # what rides through the merge is the cascade copy
                    # for downstream — forward it if tx is already open
                    # (at open, _make_tx sends _pending_trace itself)
                    if tx is not None and value is not None:
                        tx.send_ctrl(value)
                    continue
                if self.prog is None:
                    raise ValueError(
                        "data frame before any stage artifact (boot with "
                        "--artifact or deploy in-band first)")
                if tx is None:
                    tx, out_socks = self._make_tx(connect_timeout_s)
                want = tuple(self.manifest["in_shape"])
                if tuple(value.shape[1:]) != want:
                    raise ValueError(
                        f"stage {self.manifest['index']} expects sample "
                        f"shape {want}, got {tuple(value.shape[1:])}")
                if self.infer_delay_s:
                    time.sleep(self.infer_delay_s)  # bench-only device
                t0, t_end, y_disp = self._dispatch(value)
                pending.append((t0, t_end, seq, y_disp))
                seq += 1
                inflight_g.inc()
                if self.failover and seq % ACK_EVERY == 0:
                    # cumulative ack cadence: every merged seq below
                    # merge.next_seq is in order here — the upstream
                    # fan-out can release its retained frames
                    self._fanin_ack(merge)
                while len(pending) >= self.inflight:
                    drain_one()
        finally:
            if pending:
                # reconcile: dispatches abandoned by a failed stream
                # must not inflate the shared inflight gauge forever
                inflight_g.dec(len(pending))
            if out_socks is not None:
                for s in out_socks:
                    s.close()

    # -- branch join (this node merges P labeled branch paths) ---------------

    def _serve_conn_join(self, conn, connect_timeout_s: float) -> None:
        """One upstream connection of a join node: a reader loop that
        decodes frames on THIS thread (P connections = P parallel
        decoders) and deposits sequence-stamped tensors into the shared
        (path, seq) join buffer under the path its ``stream_begin``
        announced.  Control connections (deploy / stats / trace) are
        served inline exactly as on every other loop.  Always returns
        ``None`` — the join compute loop (:meth:`_join_compute`) is the
        one producer of the stream's tensor count."""
        path: int | None = None
        try:
            while True:
                kind, value = recv_frame(conn)
                if kind == K_END:
                    if path is not None:
                        self._join.end(path)
                    return None
                if kind == K_CTRL:
                    if isinstance(value, dict) \
                            and value.get("cmd") == "stream_begin":
                        p = value.get("path")
                        if path is not None:
                            continue  # duplicate marker (zero-frame
                            # paths re-announce at END time): keep slot
                        if p is None:
                            raise ValueError(
                                "join upstream announced a stream with "
                                "no path label — every hop into a join "
                                "must ride a labeled branch path")
                        path = int(p)
                        self._ensure_join_loop(connect_timeout_s)
                        self._join.attach(path)
                        continue
                    if isinstance(value, dict) \
                            and value.get("cmd") == "tier_probe":
                        # join paths are wire-framed by design (ordered
                        # (path, seq) merge): refuse, the offer degrades
                        from ..transport.local import answer_probe
                        answer_probe(conn, value, accept=False)
                        continue
                    if isinstance(value, dict) \
                            and value.get("cmd") == "req_meta":
                        raise ValueError(
                            "request-scoped metadata cannot cross a "
                            "branch join (P paths would reorder it); "
                            "serve over a linear chain")
                    self._handle_ctrl(conn, value)
                    if path is not None and isinstance(value, dict) \
                            and value.get("cmd") == "trace":
                        # mid-stream trace context must still cascade
                        # past an already-open downstream connection;
                        # duplicates across the P paths are harmless
                        # (adoption is idempotent)
                        self._join.put_ctrl(dict(self._pending_trace))
                    continue
                if kind == K_TENSOR:
                    raise ValueError(
                        "join node received an unsequenced tensor frame "
                        "— branch hops carry the fork's shared sequence "
                        "stamp (K_TENSOR_SEQ)")
                if kind != K_TENSOR_SEQ:
                    raise ValueError(f"unexpected frame kind {kind}")
                seq, arr = value
                if path is None:
                    raise ValueError(
                        "tensor before stream_begin on a join path — "
                        "the upstream must announce its path first")
                self._join.put(path, seq, arr)
        except Exception as e:  # noqa: BLE001 — policy matches the
            # fan-in loop: a registered branch path fails loudly (and
            # poisons the join so the compute loop fails too); a
            # connection that never streamed is logged and dropped
            if path is not None:
                self._join.fail(e)
                raise
            print(f"node: dropped connection before streaming: {e!r}",
                  file=sys.stderr, flush=True)
            return None

    def _ensure_join_loop(self, connect_timeout_s: float) -> None:
        """Create the shared (path, seq) buffer and its single compute
        thread the first time a branch path announces itself."""
        with self._merge_lock:
            if self._join is not None:
                return
            self._join = BranchJoin(
                self.join_in,
                capacity=max(2, self.rx_depth))
            t = threading.Thread(
                target=self._join_loop, args=(connect_timeout_s,),
                daemon=True, name="node-join-compute")
            t.start()

    def _join_loop(self, connect_timeout_s: float) -> None:
        done = self._done_q
        try:
            done.put(self._join_compute(connect_timeout_s))
        except BaseException as e:  # noqa: BLE001 — surfaced via serve()
            self._join.fail(e)  # wake readers parked in put()
            done.put(e)

    def _join_compute(self, connect_timeout_s: float) -> int:
        """The join node's compute loop: consume complete (all P paths)
        sequences strictly in order, run the multi-input merge program,
        relay downstream with the sequence stamp preserved.  Same shape
        as :meth:`_merge_compute`, with the (path, seq) join in place of
        the round-robin merge and ``prog(*parts)`` in place of
        ``prog(x)``."""
        import queue as _q

        tx = None
        out_socks = None
        n = 0
        infer_hist = REGISTRY.histogram("node.infer_s")
        inflight_g = REGISTRY.gauge("node.inflight")
        join_g = REGISTRY.gauge("node.merge_depth")
        pending: collections.deque = collections.deque()

        def drain_one():
            nonlocal n
            t0, t_end, s, y = pending.popleft()
            inflight_g.dec()
            tq = self._queue_wait(t_end, seq=s)
            if isinstance(tx, IciSender):
                # a join node's outbound hop can win ici too — only
                # the P inbound paths are wire-framed
                t_done = self._device_wait(y, seq=s, t0=tq)
            else:
                y, t_done = self._host_sync(y, seq=s, t0=tq)
            dt = t_done - t0
            infer_hist.record(dt)
            if self.infer_hist is not None:
                self.infer_hist.record(dt)
            tr = tracer()
            if tr.enabled:
                tr.record(f"{self._span_label()}.infer", t0, dt,
                          {"seq": s, "stage": self.manifest["index"]})
            self.processed += 1
            tx.send(y, seq=s)  # relay the region's stamp downstream
            n += 1

        def want_shapes() -> list[tuple]:
            m = self.manifest
            if m.get("in_shapes"):
                return [tuple(s) for s in m["in_shapes"]]
            return [tuple(m["in_shape"])] * self.join_in

        try:
            while True:
                if pending:
                    try:
                        kind, value = self._join.get_nowait()
                    except _q.Empty:
                        drain_one()
                        continue
                else:
                    kind, value = self._join.get()
                join_g.v = self._join.qsize()
                if kind == K_END:
                    while pending:
                        drain_one()
                    if tx is None:
                        tx, out_socks = self._make_tx(connect_timeout_s)
                        if not isinstance(
                                tx, (FanOutSender, BroadcastSender)) \
                                and self.branch is None:
                            tx.send_ctrl({"cmd": "stream_begin"})
                    tx.close(timeout=connect_timeout_s)
                    return n
                if kind == K_CTRL:
                    # the readers handled the command (trace adoption);
                    # what rides through the join is the cascade copy
                    if tx is not None and value is not None:
                        tx.send_ctrl(value)
                    continue
                seq, parts = value
                if self.prog is None:
                    raise ValueError(
                        "data frame before any stage artifact (boot with "
                        "--artifact or deploy in-band first)")
                if tx is None:
                    tx, out_socks = self._make_tx(connect_timeout_s)
                for p, (part, want) in enumerate(
                        zip(parts, want_shapes())):
                    if tuple(part.shape[1:]) != want:
                        raise ValueError(
                            f"join stage {self.manifest['index']} path "
                            f"{p} expects sample shape {want}, got "
                            f"{tuple(part.shape[1:])}")
                if self.infer_delay_s:
                    time.sleep(self.infer_delay_s)
                t0, t_end, y_disp = self._dispatch(*parts, seq=seq)
                pending.append((t0, t_end, seq, y_disp))
                inflight_g.inc()
                while len(pending) >= self.inflight:
                    drain_one()
        finally:
            if pending:
                inflight_g.dec(len(pending))
            if out_socks is not None:
                for s in out_socks:
                    s.close()


class ChainDispatcher:
    """Drives a chain of stage-node processes from one controller.

    Opens the result server (the reference dispatcher's own port 5000 role,
    src/dispatcher.py:95-105), streams inputs to node 0, and yields results
    in order.  Strictly in-flight-window'd so the chain stays full without
    unbounded buffering.
    """

    #: the ONE timeout default; also covers partially-constructed
    #: instances (tests build via __new__ around socketpairs) — as do the
    #: channel defaults below
    timeout_s: float = 180.0
    tx_depth: int = 8
    rx_depth: int = 8
    result_fan_in: int = 1
    #: outbound tier policy for the dispatcher -> stage-0 hop ("auto"
    #: walks the local-over-shm-over-tcp ladder; "shm" offers only the
    #: shared-memory rung; "tcp" never probes) — also gates whether the
    #: result server GRANTS the last node's inbound offer
    tier: str = "tcp"
    tier_accept: bool = True
    #: negotiated tiers for reporting (first hop / result hop)
    tier_out: str | None = None
    tier_in: str | None = None
    #: first-hop offers that degraded to tcp (per-hop fallback twin)
    tier_fallbacks: int = 0
    #: waterfall sampling period (docs/OBSERVABILITY.md): with tracing
    #: enabled and N >= 1, every tensor frame is stamped with its stream
    #: sequence number and only 1-in-N frames record per-frame spans —
    #: in EVERY process of the chain, keyed on the wire seq, so the
    #: sampled frame's rx-wait/infer/tx-wait path stitches end to end
    trace_sample_every: int = 0
    #: class default covers ``__new__``-built instances (tests): the
    #: first ``+=`` then creates the instance attribute
    _stream_seq: int = 0
    _tx_chan = None              # AsyncSender | FanOutSender | None
    _rx_chan: AsyncReceiver | None = None
    _send_socks: list | None = None
    _res_merge: FanInMerge | None = None

    def __init__(self, first_hop: str, *, listen: str = "127.0.0.1:0",
                 codec: str = "raw", window: int = 64,
                 timeout_s: float | None = None,
                 tx_depth: int = 8, rx_depth: int = 8,
                 result_fan_in: int = 1,
                 trace_sample_every: int = 0,
                 tier: str = "tcp", tier_accept: bool | None = None):
        if timeout_s is not None:
            self.timeout_s = timeout_s
        if tier not in ("tcp", "auto", "local", "shm", "ici"):
            raise ValueError(f"tier must be tcp|auto|local|shm|ici, "
                             f"got {tier!r}")
        self.tier = tier
        #: default: grant result-hop offers exactly when this dispatcher
        #: itself plays the colocated game ("--tier tcp" forces a pure
        #: wire chain end to end)
        self.tier_accept = (tier != "tcp") if tier_accept is None \
            else tier_accept
        self.tier_out = None
        self.tier_in = None
        self.tier_fallbacks = 0
        host, port = _parse_hostport(listen)
        self._res_srv = socket.create_server((host, port))
        # a dead chain fails, not hangs
        self._res_srv.settimeout(self.timeout_s)
        self.result_address = self._res_srv.getsockname()
        #: comma-separated list = replicated first stage: the dispatcher
        #: itself fans out round-robin with sequence numbers
        self.first_hop = first_hop
        self.codec = codec
        self.window = window
        self.tx_depth = tx_depth
        self.rx_depth = rx_depth
        #: >1 = replicated LAST stage: R replicas dial the result server
        #: back and the dispatcher merges them in sequence order
        self.result_fan_in = max(1, result_fan_in)
        self.trace_sample_every = max(0, int(trace_sample_every))
        #: wire sequence counter, continuous across stream() calls (a
        #: warm stream and a timed stream must not reuse seq numbers —
        #: sampled spans are keyed by them)
        self._stream_seq = 0
        self._send_sock: socket.socket | None = None
        self._send_socks = None
        self._res_conn: socket.socket | None = None
        self._res_conns: list[socket.socket] = []
        self._tx_chan = None
        self._rx_chan = None
        self._res_merge = None

    def _ensure_connected(self):
        if self._send_sock is None and self._send_socks is None:
            # generous: every node in the chain cold-imports jax first
            socks = [_connect_retry(*h, timeout_s=self.timeout_s)
                     for h in _parse_hops(self.first_hop)]
            if len(socks) == 1:
                self._send_sock = socks[0]
            else:
                self._send_socks = socks
        if self._tx_chan is None:
            # encode + send happen on the channel's tx thread, so the
            # feed loop's np.asarray and the wire overlap (and the END in
            # close() rides the same ordered queue)
            if self._send_socks is not None:
                self.tier_out = "tcp"  # fan-out rides the wire
                self._tx_chan = FanOutSender(self._send_socks,
                                             depth=self.tx_depth,
                                             codec=self.codec,
                                             gauge="chain.tx_queue_depth",
                                             span="chain",
                                             hist="chain.tx_s")
                self._tx_chan.send_ctrl({"cmd": "stream_begin"})
            else:
                if self.tier != "tcp":
                    # tier ladder on the stage-0 hop: local (same
                    # process) over shm (same host) over tcp; a
                    # cross-host node refuses everything and we stay
                    # on tcp with one fallback counted
                    from ..obs.events import emit as emit_event
                    from ..transport.shm import offer_tier_ladder
                    self.tier_out, self._tx_chan, fell_back = \
                        offer_tier_ladder(self._send_sock,
                                          tier=self.tier,
                                          depth=self.tx_depth,
                                          hop="chain")
                    if fell_back:
                        self.tier_fallbacks += 1
                    emit_event("tier", hop="chain",
                               tier=self.tier_out or "tcp",
                               wanted=self.tier,
                               fallback=bool(fell_back))
                if self._tx_chan is None:
                    self.tier_out = "tcp"
                    self._tx_chan = AsyncSender(
                        self._send_sock, depth=self.tx_depth,
                        codec=self.codec,
                        gauge="chain.tx_queue_depth",
                        span="chain", hist="chain.tx_s")
            self._tx_chan.sample_every = self.trace_sample_every
        # the result connection is accepted lazily in _recv_tensor: the
        # last node only dials back once its first tensor arrives, so
        # accepting before sending anything would deadlock the chain

    def stream(self, inputs) -> list[np.ndarray]:
        """Send every input through the chain; return outputs in order.

        FULL-DUPLEX: a sender thread keeps the chain fed (up to
        ``window`` in flight, released as results land) while this thread
        drains results concurrently — a slow stage applies backpressure
        through the window instead of stalling the feed loop mid-send
        (r4 verdict weakness #7).  Encoding happens on the tx channel's
        own thread and result decoding on the rx channel's, so feed,
        encode, the chain itself, and the result drain all overlap with
        bounded in-flight depth.  Per-``get`` timeouts on the result
        channel keep a dead chain failing rather than hanging.

        With tracing enabled (``defer_tpu.obs.enable_tracing``), the call
        injects its trace context as a K_CTRL frame ahead of the first
        tensor; every stage process adopts it, cascades it downstream,
        and parents its per-tensor spans under this stream's root span —
        collect them afterwards with :meth:`collect_trace`.
        """
        self._ensure_connected()
        tr = tracer()
        root_span = None
        t_start = time.perf_counter()
        if tr.enabled:
            # pre-allocate the root span id so remote stages can parent
            # under a span recorded only when the stream completes
            root_span = new_span_id()
            self._tx_chan.send_ctrl(
                {"cmd": "trace", "trace_id": tr.trace_id,
                 "span_id": root_span,
                 "sample_every": self.trace_sample_every})
        # waterfall sampling needs a wire sequence number on every frame
        # (a FanOutSender stamps its own — don't double-stamp)
        stamp_seq = (tr.enabled and self.trace_sample_every > 0
                     and not isinstance(self._tx_chan, FanOutSender))
        outs: list[np.ndarray] = []
        window = threading.Semaphore(self.window)
        sent = [0]
        tx_done = threading.Event()
        rx_failed = threading.Event()
        err: list[BaseException] = []

        def tx():
            try:
                for x in inputs:
                    if rx_failed.is_set():
                        return
                    if not window.acquire(timeout=self.timeout_s):
                        raise TimeoutError(
                            f"chain accepted no result for "
                            f"{self.timeout_s:.0f}s with {self.window} in "
                            f"flight — a stage is stuck")
                    if rx_failed.is_set():
                        return  # woken by the error path, not a result
                    self._tx_chan.send(
                        np.asarray(x),
                        seq=(self._stream_seq + sent[0]) if stamp_seq
                        else None)
                    sent[0] += 1
            except BaseException as e:  # noqa: BLE001 — surfaced below
                err.append(e)
            finally:
                self._stream_seq += sent[0]
                tx_done.set()

        t = threading.Thread(target=tx, daemon=True, name="chain-tx")
        t.start()
        try:
            while True:
                if err:
                    raise err[0]
                if len(outs) < sent[0]:
                    # something is in flight: recv (bounded by the result
                    # socket's timeout).  Never recv otherwise — a recv
                    # with nothing in flight (empty stream, or the final
                    # result landing before tx_done is set) would stall
                    # the full socket timeout for no reason.
                    outs.append(self._recv_tensor())
                    window.release()
                    continue
                if tx_done.is_set():
                    break  # everything sent has been received
                tx_done.wait(0.01)  # sender still working; let it run
        except BaseException:
            rx_failed.set()
            # a sender parked in window.acquire must wake to see the flag;
            # then give it a bounded moment so no trailing frame interleaves
            # with the caller's teardown (close() writes END on this socket)
            window.release(self.window)
            t.join(timeout=5.0)
            raise
        t.join(timeout=self.timeout_s)  # no trailing writes after return
        if err:
            raise err[0]
        if root_span is not None:
            tr.record("chain.stream", t_start,
                      time.perf_counter() - t_start,
                      {"sent": sent[0], "received": len(outs)},
                      span_id=root_span)
        return outs

    @staticmethod
    def _stage_capacity(stage, batch: int) -> dict:
        """The deploy message's capacity fields: the stage's analytic
        FLOPs and HBM bytes at the deploy ``batch``
        (:func:`defer_tpu.obs.capacity.stage_flops_bytes`) — the node
        can then report live MFU against its own chip peak without ever
        seeing the graph.  Empty for stage objects that don't carry
        their graph slice (hand-built test stubs)."""
        graph = getattr(stage, "graph", None)
        names = getattr(stage, "node_names", None)
        if graph is None or not names:
            return {}
        from ..obs.capacity import stage_flops_bytes
        flops, moved = stage_flops_bytes(graph, names, batch=batch)
        return {"flops": flops, "bytes_moved": moved}

    def deploy(self, stages, params, node_addrs: Sequence, *,
               batch: int = 1, result_hop: str | None = None,
               codecs: Sequence[str] | None = None,
               tiers: Sequence[str] | None = None,
               devices: Sequence[int | None] | None = None):
        """Ship each stage's artifact to its node(s) over the control
        channel.

        Serial, in chain order, each ACKed before the next — the in-band
        model distribution of the reference dispatcher
        (src/dispatcher.py:44-65: weights, arch JSON, next-node IP, \\x06
        ACK) collapsed to one control connection per node carrying a
        self-contained StableHLO+weights blob.  Nodes may boot with no
        pre-placed files at all.  ``result_hop`` overrides the address the
        last node relays results to (defaults to this dispatcher's result
        server, reference src/dispatcher.py:51-55).

        Replication: an entry of ``node_addrs`` may itself be a list of
        R addresses — the SAME artifact is deployed to each replica, the
        previous stage's ``next`` becomes the comma-joined replica list
        (fan-out), and the following stage is told ``fan_in=R`` (merge).
        Adjacent replicated stages are rejected — a replica cannot
        restore another fan-out's order.  ``codecs`` (per stage) sets
        each stage's OUTBOUND hop codec; default: this dispatcher's.
        ``tiers`` (per stage, ``auto``/``ici``/``local``/``shm``/
        ``tcp``) sets each stage's OUTBOUND transport-tier policy the
        same way — the deploy-time half of the tier handshake
        (docs/TRANSPORT.md): ``auto`` stages walk the
        ici-over-local-over-shm-over-tcp ladder when they open their
        downstream connection and silently degrade to tcp when no
        rung's proof holds.  ``devices`` (per stage, jax device index
        or None) pins each stage's program to a mesh device — the
        deployment half of the device-resident ici tier.

        Deploying also sweeps ``/dev/shm`` for segments leaked by a
        previous chain whose processes were killed ungracefully
        (``transport.shm.sweep_orphan_segments``).
        """
        from ..transport.shm import sweep_orphan_segments
        from ..utils.export import export_stage_bytes
        sweep_orphan_segments()
        groups = [[a] if isinstance(a, str) else list(a)
                  for a in node_addrs]
        if len(groups) != len(stages):
            raise ValueError(f"{len(stages)} stages but {len(groups)} nodes")
        for i in range(len(groups) - 1):
            if len(groups[i]) > 1 and len(groups[i + 1]) > 1:
                raise ValueError(
                    f"stages {i} and {i + 1} are both replicated; "
                    f"adjacent replication is not supported")
        result_hop = result_hop or \
            f"{self.result_address[0]}:{self.result_address[1]}"
        for i, (stage, addrs) in enumerate(zip(stages, groups)):
            nxt = ",".join(groups[i + 1]) if i + 1 < len(groups) \
                else result_hop
            blob = export_stage_bytes(stage, params, batch=batch)
            capacity = self._stage_capacity(stage, batch)
            for j, addr in enumerate(addrs):
                msg = {"cmd": "deploy", "next": nxt,
                       "codec": codecs[i] if codecs else self.codec,
                       **capacity}
                if tiers:
                    msg["tier"] = tiers[i]
                if devices and devices[i] is not None:
                    # pin stage i's program to a jax device (the
                    # deployment half of the device-resident ici tier)
                    msg["device"] = int(devices[i])
                if i > 0 and len(groups[i - 1]) > 1:
                    msg["fan_in"] = len(groups[i - 1])
                if len(addrs) > 1:
                    msg["replica"] = j
                s = _connect_retry(*_parse_hostport(addr),
                                   timeout_s=self.timeout_s)
                try:
                    send_ctrl(s, msg)
                    send_frame(s, blob)
                    recv_expect(s, K_ACK)
                    send_end(s)
                finally:
                    s.close()

    def deploy_topology(self, topology, stages, params,
                        node_addrs: Sequence[str], *, batch: int = 1,
                        result_hop: str | None = None,
                        stage_delays: dict | None = None):
        """Ship a branched stage graph: one node per topology vertex.

        ``topology`` is a :class:`~defer_tpu.runtime.topology.ChainTopology`
        whose vertices align with ``stages`` (from
        ``topology.stage_specs(graph)``) and ``node_addrs``.  Each deploy
        message carries the vertex's transport role — ``fan`` (broadcast
        fork), ``branch`` (labeled path), ``join`` (P-path merge) — on
        top of the usual next/codec pair; replicas never appear here
        (branch fan machinery and replica fan machinery own different
        sequence namespaces, and mixing them is rejected loudly at the
        node).  ``stage_delays`` (vid -> seconds) installs the bench-only
        simulated device time per vertex."""
        from ..transport.shm import sweep_orphan_segments
        from ..utils.export import export_stage_bytes
        sweep_orphan_segments()
        addrs = list(node_addrs)
        if len(addrs) != len(topology.vertices) or \
                len(stages) != len(topology.vertices):
            raise ValueError(
                f"{len(topology.vertices)} topology vertices need as "
                f"many stages ({len(stages)}) and addresses "
                f"({len(addrs)})")
        result_hop = result_hop or \
            f"{self.result_address[0]}:{self.result_address[1]}"
        for v, stage, addr in zip(topology.vertices, stages, addrs):
            nxt = ",".join(addrs[n] for n in v.next) if v.next \
                else result_hop
            msg = {"cmd": "deploy", "next": nxt,
                   "codec": v.codec or self.codec,
                   **self._stage_capacity(stage, batch)}
            if v.fan == "broadcast":
                msg["fan"] = "broadcast"
            if v.join >= 2:
                msg["join"] = v.join
            if v.branch is not None:
                msg["branch"] = v.branch
            if stage_delays and stage_delays.get(v.vid):
                msg["infer_delay_ms"] = stage_delays[v.vid] * 1e3
            blob = export_stage_bytes(stage, params, batch=batch)
            s = _connect_retry(*_parse_hostport(addr),
                               timeout_s=self.timeout_s)
            try:
                send_ctrl(s, msg)
                send_frame(s, blob)
                recv_expect(s, K_ACK)
                send_end(s)
            finally:
                s.close()

    def reweight(self, stages, params, node_addrs: Sequence[str]):
        """Weights-only re-push: install fresh weights on every node's
        already-loaded stage program — redeploy (e.g. after more training)
        without restarting any process or resending StableHLO."""
        from ..utils.export import stage_weight_leaves, weights_blob
        node_addrs = list(node_addrs)
        if len(node_addrs) != len(stages):
            raise ValueError(
                f"{len(stages)} stages but {len(node_addrs)} nodes")
        for stage, addr in zip(stages, node_addrs):
            s = _connect_retry(*_parse_hostport(addr),
                               timeout_s=self.timeout_s)
            try:
                send_ctrl(s, {"cmd": "reweight"})
                send_frame(s, weights_blob(
                    stage_weight_leaves(stage, params)))
                recv_expect(s, K_ACK)
                send_end(s)
            finally:
                s.close()

    def stats(self, node_addrs: Sequence[str]) -> list[dict]:
        """Per-node chain observability: query every node's stats control
        endpoint (stage identity, tensors processed, reweights, topology)
        — works mid-stream thanks to thread-per-connection nodes."""
        out = []
        for addr in node_addrs:
            s = _connect_retry(*_parse_hostport(addr),
                               timeout_s=self.timeout_s)
            try:
                send_ctrl(s, {"cmd": "stats"})
                out.append(recv_expect(s, K_CTRL))
                send_end(s)
            finally:
                s.close()
        return out

    def _ensure_result_chan(self) -> None:
        """Accept the last node's dial-back and wrap it in the result
        :class:`AsyncReceiver` (idempotent)."""
        if self._res_conn is None:
            self._res_conn, _ = self._res_srv.accept()
            configure_socket(self._res_conn)
        if self._rx_chan is None:
            self._res_conn.settimeout(None)
            self._rx_chan = AsyncReceiver(self._res_conn,
                                          depth=self.rx_depth,
                                          gauge="chain.rx_queue_depth",
                                          span="chain",
                                          hist="chain.rx_s")
            self._rx_chan.sample_every = self.trace_sample_every

    def _result_item(self, *, timeout_s: float | None = None
                     ) -> tuple[int, Any]:
        """One frame off the result hop with the transport handshake
        handled: tier probes are answered (and the channel swapped on a
        grant), trace / stream_begin markers — which the dispatcher
        itself originated — are skipped; everything else is returned to
        the caller."""
        self._ensure_result_chan()
        t = self.timeout_s if timeout_s is None else timeout_s
        while True:
            kind, y = self._rx_chan.get(timeout=t)
            if kind == K_CTRL and isinstance(y, dict):
                cmd = y.get("cmd")
                if cmd == "tier_probe":
                    # the last node offers its fast path on the result
                    # dial-back: an ici/local grant swaps results to
                    # the in-memory pipe (the socket stays as lifetime
                    # anchor; ici frames arrive as live jax.Arrays and
                    # are host-synced HERE, exactly once per frame), a
                    # shm grant wraps the socket channel into a
                    # ShmReceiver (the socket becomes the doorbell)
                    from ..transport.shm import answer_tier_probe
                    self.tier_in, chan = answer_tier_probe(
                        self._res_conn, y, accept=self.tier_accept,
                        inner=self._rx_chan, depth=self.rx_depth)
                    if self.tier_in in ("local", "ici"):
                        old = self._rx_chan
                        self._rx_chan = chan
                        self._rx_chan.sample_every = \
                            self.trace_sample_every
                        self._rx_chan.bind_gauge("chain.rx_queue_depth")
                        old.release_gauge()
                    elif self.tier_in == "shm":
                        # the inner channel stays live (doorbell source)
                        # and keeps its gauge
                        self._rx_chan = chan
                        self._rx_chan.sample_every = \
                            self.trace_sample_every
                    continue
                if cmd in ("trace", "stream_begin"):
                    continue
            if kind in (K_TENSOR, K_TENSOR_SEQ) \
                    and self.tier_in == "ici":
                # the chain's ONE host sync per frame: device-resident
                # results materialize here, at the result edge — every
                # upstream ici hop skipped its np.asarray entirely
                t0 = time.perf_counter()
                if kind == K_TENSOR_SEQ:
                    y = (y[0], np.asarray(y[1]))
                else:
                    y = np.asarray(y)
                REGISTRY.histogram("chain.host_sync_s").record(
                    time.perf_counter() - t0)
            return kind, y

    # -- serve front door: request-scoped duplex stream --------------------

    def begin_trace(self, *, sample_every: int | None = None
                    ) -> str | None:
        """Inject the current trace context into the chain ahead of any
        request-scoped frame — the serving-path twin of what
        :meth:`stream` does per call.  A front door has no stream()
        call, so its backend calls this once at start: every stage
        adopts the trace, cascades it downstream, and samples the SAME
        1-in-N wire seqs (``sample_every`` rides the context exactly
        like ``--trace-sample``).  Returns the pre-allocated root span
        id stage spans parent under, or None when tracing is off."""
        tr = tracer()
        if not tr.enabled:
            return None
        if sample_every is not None:
            self.trace_sample_every = max(0, int(sample_every))
        self._ensure_connected()
        self._tx_chan.sample_every = self.trace_sample_every
        if self._rx_chan is not None:
            self._rx_chan.sample_every = self.trace_sample_every
        root_span = new_span_id()
        self._tx_chan.send_ctrl(
            {"cmd": "trace", "trace_id": tr.trace_id,
             "span_id": root_span,
             "sample_every": self.trace_sample_every})
        return root_span

    def send_request_frame(self, arr: np.ndarray, *, seq: int,
                           meta: dict | None = None) -> None:
        """One request-scoped frame into the chain (docs/SERVING.md):
        the frame is stamped with ``seq`` (wire protocol v2
        ``K_TENSOR_SEQ`` — every stage relays the stamp unchanged, so
        the result hop identifies the frame it answers), optionally
        preceded by a ``req_meta`` K_CTRL frame carrying its
        tenant/request composition, which stage nodes cascade
        downstream ahead of (never behind) the frame it describes.
        Requires a non-replicated chain
        (a fan-out re-stamps sequence numbers and cannot order metadata
        across paths)."""
        self._ensure_connected()
        if isinstance(self._tx_chan, FanOutSender) \
                or self.result_fan_in > 1:
            raise ValueError(
                "request-scoped streaming requires a non-replicated "
                "first/last stage (fan paths re-stamp seq numbers)")
        if meta is not None:
            msg = {"cmd": "req_meta", "seq": int(seq)}
            msg.update(meta)
            self._tx_chan.send_ctrl(msg)
        self._tx_chan.send(np.asarray(arr), seq=int(seq))

    def recv_result(self, *, timeout_s: float | None = None):
        """Next item off the result hop for a request-scoped stream:
        ``("meta", msg)`` for a cascaded ``req_meta`` frame, ``("tensor",
        (seq, arr))`` for a result (``seq`` None on unstamped frames),
        ``("end", None)`` when the chain drained."""
        kind, y = self._result_item(timeout_s=timeout_s)
        if kind == K_CTRL and isinstance(y, dict) \
                and y.get("cmd") == "req_meta":
            return "meta", y
        if kind == K_TENSOR_SEQ:
            return "tensor", (y[0], y[1])
        if kind == K_TENSOR:
            return "tensor", (None, y)
        if kind == K_END:
            return "end", None
        raise ConnectionError(
            f"unexpected frame kind {kind!r} on the result hop")

    def _recv_tensor(self) -> np.ndarray:
        """One in-order result frame; loud protocol check (not an assert:
        ``python -O`` strips asserts, and an early END from a node that died
        mid-stream must raise, not silently mis-drain).

        Results arrive through an :class:`AsyncReceiver`: the decode of
        result j+1 happens on the channel's rx thread while this thread
        hands j back to the caller.  The per-``get`` timeout keeps the
        dead-chain-fails-not-hangs contract; the socket itself stays
        blocking so an idle (but healthy) chain never desyncs mid-frame.

        With ``result_fan_in > 1`` (replicated last stage) the results
        instead come off the sequence-ordered :class:`FanInMerge` over
        the R replica dial-backs.
        """
        if self.result_fan_in > 1:
            return self._recv_tensor_fanin()
        kind, y = self._result_item()
        if kind == K_TENSOR_SEQ:
            # waterfall sampling stamps every frame end to end; the
            # result hop carries the stamp through — strip it here
            return y[1]
        if kind != K_TENSOR:
            raise ConnectionError(
                f"chain returned frame kind {kind!r} while results were "
                f"still in flight (a stage node died and cascaded END?)")
        return y

    def _ensure_result_merge(self) -> FanInMerge:
        """Start the result-side fan-in: a background acceptor takes the
        R replica dial-backs AS THEY COME (a replica that sees its first
        frame late — or only the END — dials late; blocking for all R up
        front would deadlock short streams) and one reader thread per
        connection feeds the sequence-ordered merge."""
        if self._res_merge is not None:
            return self._res_merge
        merge = FanInMerge(
            self.result_fan_in,
            capacity=max(self.result_fan_in,
                         self.result_fan_in * self.rx_depth))
        self._res_merge = merge

        def reader(c):
            try:
                while True:
                    kind, value = recv_frame(c)
                    if kind == K_END:
                        merge.end()
                        return
                    if kind == K_CTRL:
                        if isinstance(value, dict) \
                                and value.get("cmd") == "tier_probe":
                            # replica dial-backs never win the fast path
                            # (the seq merge is wire-framed); refuse so
                            # the prober degrades instead of hanging
                            from ..transport.local import answer_probe
                            answer_probe(c, value, accept=False)
                        continue  # trace / stream_begin: informational
                    if kind != K_TENSOR_SEQ:
                        raise ConnectionError(
                            f"result fan-in got frame kind {kind!r}; "
                            f"replicas must relay sequence-stamped frames")
                    merge.put(*value)
            except BaseException as e:  # noqa: BLE001 — surfaced in get()
                merge.fail(e)

        def acceptor():
            try:
                for _ in range(self.result_fan_in):
                    c, _ = self._res_srv.accept()
                    configure_socket(c)
                    c.settimeout(None)
                    self._res_conns.append(c)
                    threading.Thread(target=reader, args=(c,), daemon=True,
                                     name="chain-result-rx").start()
            except BaseException as e:  # noqa: BLE001 — surfaced in get()
                merge.fail(e)

        threading.Thread(target=acceptor, daemon=True,
                         name="chain-result-accept").start()
        return merge

    def _recv_tensor_fanin(self) -> np.ndarray:
        merge = self._ensure_result_merge()
        kind, y = merge.get(timeout=self.timeout_s)
        while kind == K_CTRL:
            kind, y = merge.get(timeout=self.timeout_s)
        if kind != K_TENSOR:
            raise ConnectionError(
                f"chain returned frame kind {kind!r} while results were "
                f"still in flight (a stage replica died and cascaded "
                f"END?)")
        return y

    def align_clocks(self, node_addrs: Sequence[str], *,
                     rounds: int = 8) -> dict:
        """Clock-align every node's tracer to this process's timeline:
        per node, a min-RTT ping-pong offset estimate over a control
        connection followed by a ``clock_adjust`` shifting the node's
        ``Tracer._wall0_us`` anchor (obs/cluster.py).  Call before
        ``stream`` when exporting cross-process traces, so every
        process's spans land on one coherent Perfetto axis.  Returns
        ``{addr: {"offset_us", "rtt_us", ...}}``."""
        from ..obs.cluster import align_clock
        out = {}
        for addr in node_addrs:
            s = _connect_retry(*_parse_hostport(addr),
                               timeout_s=self.timeout_s)
            try:
                out[addr] = align_clock(s, rounds=rounds)
                send_end(s)
            finally:
                s.close()
        return out

    def watch(self, node_addrs: Sequence[str], *,
              interval_ms: float = 250.0, spans: bool = False,
              align_clocks: bool = False):
        """Subscribe to every node's live obs_push stream: returns a
        :class:`~defer_tpu.obs.cluster.ClusterView` aggregating pushes
        on background reader threads until ``view.close()``.  Works
        mid-stream (thread-per-connection nodes) — this is the push
        plane the ``defer_tpu monitor`` CLI renders."""
        from ..obs.cluster import ClusterView
        view = ClusterView()
        view.connect(node_addrs, interval_ms=interval_ms, spans=spans,
                     align_clocks=align_clocks, timeout_s=self.timeout_s)
        return view

    def collect_trace(self, node_addrs: Sequence[str]) -> int:
        """Fetch and merge every node's recorded spans into this process's
        tracer (``trace_dump`` control round-trip per node) so one export
        holds the stitched dispatcher -> stage0 -> ... -> stageN-1 trace.
        Returns the number of spans ingested.  Call while the nodes are
        still alive — after ``stream`` returns, before ``close``."""
        tr = tracer()
        total = 0
        for addr in node_addrs:
            s = _connect_retry(*_parse_hostport(addr),
                               timeout_s=self.timeout_s)
            try:
                send_ctrl(s, {"cmd": "trace_dump"})
                reply = recv_expect(s, K_CTRL)
                spans = reply.get("spans", [])
                tr.ingest(spans)
                total += len(spans)
                send_end(s)
            finally:
                s.close()
        return total

    def quiesce(self, node_addrs: Sequence, *,
                at_seq: int | None = None,
                timeout_s: float | None = None) -> list[int]:
        """Drain every node to a stable sequence point (the live-replan
        barrier, docs/ROBUSTNESS.md): per node, a ``quiesce`` control
        round-trip that returns only once the node's queues are empty,
        its in-flight window has drained, and its processed count has
        stopped moving (optionally past ``at_seq``).  Returns each
        node's processed count at the quiesce point.  Entries of
        ``node_addrs`` may be replica lists — every replica is
        quiesced."""
        t = self.timeout_s if timeout_s is None else timeout_s
        flat: list[str] = []
        for a in node_addrs:
            flat.extend([a] if isinstance(a, str) else list(a))
        out: list[int] = []
        for addr in flat:
            s = _connect_retry(*_parse_hostport(addr), timeout_s=t)
            try:
                msg: dict = {"cmd": "quiesce", "timeout_s": t}
                if at_seq is not None:
                    msg["at_seq"] = int(at_seq)
                send_ctrl(s, msg)
                reply = recv_expect(s, K_CTRL)
                if not isinstance(reply, dict) \
                        or reply.get("cmd") != "quiesced":
                    raise ConnectionError(
                        f"node {addr} answered quiesce with {reply!r}")
                out.append(int(reply.get("processed", 0)))
                send_end(s)
            finally:
                s.close()
        return out

    def shutdown_nodes(self, node_addrs: Sequence) -> None:
        """Ask persistent nodes (``--persist``) to exit their serve loop
        after the current segment — the graceful half of a live-replan
        teardown (kill-free, so replay buffers and shm segments unwind
        cleanly)."""
        flat: list[str] = []
        for a in node_addrs:
            flat.extend([a] if isinstance(a, str) else list(a))
        for addr in flat:
            s = _connect_retry(*_parse_hostport(addr),
                               timeout_s=self.timeout_s)
            try:
                send_ctrl(s, {"cmd": "shutdown"})
                recv_expect(s, K_ACK)
                send_end(s)
            finally:
                s.close()

    def end_stream(self):
        """Drain the current stream segment (best effort) and drop every
        data-plane connection — but KEEP the result server listening, so
        a follow-up :meth:`stream` opens a fresh segment against nodes
        that persisted across it (``--persist``).  The wire sequence
        counter is NOT reset: seq numbers stay continuous across
        segments, which is what lets a live replan splice byte-identical
        streams (docs/ROBUSTNESS.md).

        The graceful END handshake is wrapped so a chain that already died
        mid-stream can't mask the original failure with a secondary
        BrokenPipe/EOF from the teardown itself."""
        try:
            if self._send_sock is not None or self._send_socks:
                if self._tx_chan is not None:
                    # the END rides the ordered tx queue behind any
                    # trailing frames; close() joins the tx thread so it
                    # is on the wire before we wait for the cascaded echo
                    # (a FanOutSender ENDs every replica channel)
                    self._tx_chan.close(timeout=min(10.0, self.timeout_s))
                elif self._send_sock is not None:
                    send_end(self._send_sock)
                if self.result_fan_in > 1:
                    # drain the merge until all R replica dial-backs have
                    # delivered their END (the acceptor keeps taking late
                    # dial-backs — e.g. a replica whose only frame was
                    # the cascaded END itself)
                    merge = self._ensure_result_merge()
                    while True:
                        kind, _ = merge.get(timeout=self.timeout_s)
                        if kind == K_END:
                            break
                else:
                    if self._res_conn is None:
                        # nothing was ever received: still accept the last
                        # node's dial-back so its cascaded END completes
                        try:
                            self._res_srv.settimeout(
                                min(10.0, self.timeout_s))
                            self._res_conn, _ = self._res_srv.accept()
                            self._res_conn.settimeout(self.timeout_s)
                        except OSError:
                            pass
                    if self._res_conn is not None:
                        # drain any leftover in-flight frames until the
                        # END cascades through
                        while True:
                            if self._rx_chan is not None:
                                kind, v = self._rx_chan.get(
                                    timeout=self.timeout_s)
                            else:
                                kind, v = recv_frame(self._res_conn)
                            if kind == K_CTRL and isinstance(v, dict) \
                                    and v.get("cmd") == "tier_probe":
                                # zero-result stream: the last node's
                                # offer arrives during teardown — refuse
                                # so its END cascades over plain tcp
                                from ..transport.local import answer_probe
                                answer_probe(self._res_conn, v,
                                             accept=False)
                            if kind == K_END:
                                break
        except (OSError, ConnectionError, ValueError, TimeoutError):
            pass  # teardown after failure: keep the root cause
        finally:
            if self._rx_chan is not None:
                # reconcile the additive chain.rx_queue_depth gauge: a
                # teardown after failure can abandon queued results
                self._rx_chan.release_gauge()
            if self._send_sock is not None:
                self._send_sock.close()
            for s in self._send_socks or []:
                s.close()
            if self._res_conn is not None:
                self._res_conn.close()
            for c in getattr(self, "_res_conns", None) or []:
                c.close()
            # reset to pre-connect state: the next stream() segment
            # redials the (possibly re-deployed) chain from scratch
            self._send_sock = None
            self._send_socks = None
            self._tx_chan = None
            self._rx_chan = None
            self._res_conn = None
            self._res_conns = []
            self._res_merge = None
            # tier_out/tier_in stay readable (post-run reporting); the
            # next segment's negotiation overwrites them
            srv = getattr(self, "_res_srv", None)
            if srv is not None:
                try:
                    srv.settimeout(self.timeout_s)
                except OSError:
                    pass  # already closed (end_stream after close)

    def close(self):
        """End the current segment (:meth:`end_stream`) and close the
        result server — the dispatcher is done for good."""
        try:
            self.end_stream()
        finally:
            self._res_srv.close()


def _free_ports(n: int) -> list[int]:
    """Probe n free localhost ports.  Inherently racy (probe-then-close,
    then the children bind): a concurrent process can steal a port in
    the gap.  ``run_chain`` compensates by detecting children that died
    with a bind failure and retrying the whole spawn on fresh ports —
    the race is unavoidable without fd passing, the hang it used to
    cause is not."""
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


#: substrings that identify a child that lost the ``_free_ports`` race
_BIND_RACE_MARKS = ("Address already in use", "EADDRINUSE",
                    "address is already in use")


def _log_tail(lf, limit: int = 2000) -> str:
    try:
        lf.flush()
        lf.seek(0)
        return lf.read()[-limit:]
    except (OSError, ValueError):
        return "<log unavailable>"


def _kill_procs(procs, *, grace_s: float = 5.0) -> None:
    """Terminate every child NOW (SIGTERM, short grace, then SIGKILL) —
    the hardened teardown: a node that died mid-deploy/mid-stream must
    not leave its siblings (or replica processes) running."""
    for pr in procs:
        if pr.poll() is None:
            try:
                pr.terminate()
            except OSError:
                pass
    deadline = time.monotonic() + grace_s
    for pr in procs:
        try:
            pr.wait(timeout=max(0.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            pr.kill()
    for pr in procs:
        try:
            pr.wait(timeout=grace_s)
        except subprocess.TimeoutExpired:
            pass


def _normalize_replicas(replicas, n: int) -> list[int]:
    """``{stage: R}`` -> per-stage replica counts, validated: in range,
    >= 1, and never two adjacent replicated stages (a replica cannot
    restore another fan-out's sequence order)."""
    r_of = [1] * n
    for k, r in (replicas or {}).items():
        k, r = int(k), int(r)
        if not 0 <= k < n:
            raise ValueError(f"replicas: stage {k} out of range 0..{n - 1}")
        if r < 1:
            raise ValueError(f"replicas: stage {k} count {r} must be >= 1")
        r_of[k] = r
    for k in range(n - 1):
        if r_of[k] > 1 and r_of[k + 1] > 1:
            raise ValueError(
                f"replicas: stages {k} and {k + 1} are both replicated; "
                f"adjacent replication is not supported")
    return r_of


def _normalize_hop_tiers(hop_tiers, n: int, r_of: list[int],
                         default: str) -> list[str]:
    """Per-inter-stage-hop tier list, validated: known names, one entry
    per hop, and no colocated (local/device) hop touching a replicated
    stage — the ordered fan machinery is wire-framed by design, so a
    silent tcp downgrade there would belie the caller's topology."""
    if hop_tiers is None:
        # a global default still goes through the adjacency checks: a
        # chain-wide tier="shm" pin with a replicated stage must fail
        # as loudly as the equivalent explicit hop_tiers entry
        tiers = [default] * max(0, n - 1)
    else:
        tiers = [str(t) for t in hop_tiers]
    if len(tiers) != n - 1:
        raise ValueError(f"hop_tiers must have one entry per inter-stage "
                         f"hop ({n - 1}), got {len(tiers)}")
    for k, t in enumerate(tiers):
        if t not in ("tcp", "auto", "local", "shm", "ici", "device"):
            raise ValueError(f"hop_tiers[{k}] = {t!r}; "
                             f"use tcp|auto|local|shm|ici|device")
        if t in ("local", "shm", "ici", "device") \
                and (r_of[k] > 1 or r_of[k + 1] > 1):
            raise ValueError(
                f"hop_tiers[{k}] = {t!r} but stage {k} or {k + 1} is "
                f"replicated; fan paths ride tcp (drop the replicas or "
                f"the colocation)")
    return tiers


def run_chain(stages: Sequence, params: dict[str, Any], inputs,
              *, batch: int = 1, codec: str = "raw",
              artifact_dir: str | None = None,
              env: dict[str, str] | None = None,
              in_band: bool = False, overlap: bool = True,
              rx_depth: int | None = None, tx_depth: int | None = None,
              inflight: int | None = None,
              replicas: dict[int, int] | None = None,
              hop_codecs: Sequence[str] | None = None,
              hop_tiers: Sequence[str] | None = None,
              tier: str = "auto",
              devices: int | None = None,
              device_map: dict[int, int] | None = None,
              stage_delays: Sequence[float] | None = None,
              stats_out: list | None = None,
              spawn_retries: int = 3,
              on_spawn=None,
              trace_sample_every: int = 0,
              plan=None, graph=None,
              report_interval_ms: float = 250.0,
              failover: bool = False,
              journal_dir: str | None = None) -> list[np.ndarray]:
    """Export, spawn one OS process per stage REPLICA, stream, tear down.

    ``failover=True`` arms the seq-replay substrate
    (docs/ROBUSTNESS.md): fan-out stages retain sent frames until the
    downstream merge acks them, replicas relay acks upstream, and a
    supervisor thread respawns any replica process that dies mid-stream
    from its original argv — the healed channel redials, replays the
    unacked window, and the fan-in dedups the overlap, so a ``kill -9``
    of a mid-chain replica yields a byte-identical stream.  Requires
    ``in_band=False`` (the respawn re-boots from command-line artifact
    paths), at least one replicated stage, and every replicated stage
    to be interior (a fan-out above it and a fan-in below it carry the
    replay/ack plane).

    The one-call analogue of the reference's whole deployment procedure
    (start N ``node.py`` processes, run the dispatcher, src/dispatcher.py:
    44-65 + test/test.py) — used by the CLI ``chain`` command and the
    multi-process integration test.

    ``in_band=True`` boots every node EMPTY (no --artifact flag, no shared
    filesystem) and ships each stage artifact over its control connection
    with an ACK handshake — full control-plane parity with the reference.
    ``in_band=False`` pre-exports artifacts to a (shared) directory and
    passes paths on the command line.

    ``replicas`` maps stage index -> R: stage k runs as R data-parallel
    processes fed round-robin with sequence numbers and merged back in
    order downstream (docs/TRANSPORT.md).  The same artifact deploys to
    every replica.  Adjacent stages cannot both be replicated.
    ``hop_codecs`` (len = num stages) sets each stage's OUTBOUND hop
    codec individually (default: ``codec`` everywhere); the dispatcher ->
    stage-0 hop always uses ``codec``.  ``stats_out`` (a list) receives
    every node's ``stats`` reply — per replica, queried before teardown
    (each row carries the hop's negotiated transport ``tier``).

    Transport tiers (docs/TRANSPORT.md): ``hop_tiers`` (len = num
    stages - 1, one entry per INTER-stage hop) classifies each boundary:

    * ``"device"`` — the two stages land on one device: they are FUSED
      into a single jit-compiled stage program before spawn
      (``partition.fuse_stages``), so the hop — frame, queue, process —
      ceases to exist.
    * ``"ici"`` — same process + same mesh: the two stages are
      COLOCATED into one OS process and the hop negotiates the
      DEVICE-RESIDENT channel — live ``jax.Array``s cross with no host
      materialization at all (zero ``host_sync`` samples), and when
      ``device_map`` pins the stages to distinct devices each frame
      pays exactly one device-to-device ``jax.device_put``.
    * ``"local"`` — same process: the two stages are COLOCATED into one
      OS process (the downstream rides the upstream's process as a
      ``--co-stage`` serve thread) and the hop negotiates the
      zero-serialization in-memory channel.  A handshake that fails
      anyway degrades to tcp and bumps ``transport.tier_fallback``.
    * ``"shm"`` — same host, separate OS processes: the hop's payload
      crosses a ``multiprocessing.shared_memory`` ring (one memcpy per
      side, no codec, no socket bytes) while the TCP socket is demoted
      to a per-frame doorbell carrying seq/ctrl/END ordering
      (``transport/shm.py``).  A failed handshake (cross-host peer,
      refusal) degrades to tcp the same way.
    * ``"auto"`` — separate processes; the hop walks the
      ici-over-local-over-shm-over-tcp ladder at connect time, so the
      standard same-host multi-process chain negotiates shm everywhere
      without being asked (and ici on any same-process hop).
    * ``"tcp"`` — the status-quo wire path, no probe.

    Neither side of a ``device``/``local``/``ici``/``shm`` hop may be
    replicated (the ordered fan machinery is wire-framed by design).
    ``tier`` is the policy for the dispatcher-edge hops (dispatcher ->
    stage 0, last stage -> result server) and the default when
    ``hop_tiers`` is omitted: ``"auto"`` (offers that degrade cleanly)
    or ``"tcp"`` (the escape hatch — a pure wire chain end to end).
    ``devices=N`` forces an N-device host mesh in every child
    (``--xla_force_host_platform_device_count``); ``device_map``
    ({stage: device index}) pins each stage's program — the deployment
    half of the ici tier's cross-device transfers.

    Children that exit with an address-in-use bind failure (the
    ``_free_ports`` probe race) are detected and the whole spawn retries
    on fresh ports, up to ``spawn_retries`` attempts; any other child
    death surfaces that node's log tail in the raised error.  On ANY
    failure every remaining child is terminated before the error
    propagates — a mid-deploy crash cannot leak live replica processes.
    ``on_spawn(procs)`` is a test/instrumentation hook called with the
    freshly spawned ``subprocess.Popen`` list of each attempt.

    Live observability (docs/OBSERVABILITY.md): with tracing enabled the
    dispatcher clock-aligns every node before streaming (min-RTT offset
    estimate + ``clock_adjust``), and ``trace_sample_every=N`` switches
    per-frame spans to 1-in-N waterfall sampling keyed on the wire
    sequence number.  ``plan`` (the deployment's solved
    :class:`~defer_tpu.plan.solver.Plan`) together with ``stats_out``
    subscribes a live :class:`~defer_tpu.obs.cluster.ClusterView` to
    every node's obs_push stream (``report_interval_ms`` cadence) and
    appends one extra ``{"obs": ...}`` row to ``stats_out`` carrying the
    live rows, the detected bottleneck stage, and any straggler flags;
    pass ``graph`` too and the row gains a ``replan`` suggestion from
    :func:`defer_tpu.plan.replan.replan` fed with the live measurements.

    ``journal_dir`` arms the black-box flight recorder
    (docs/OBSERVABILITY.md): every child boots with ``--journal-dir``
    so each stage process — and this dispatcher process — spills its
    events/snapshots/spans to a crash-safe on-disk journal under the
    directory, a failover respawn auto-assembles a postmortem bundle
    naming the first fault, and any ``run_chain`` failure does the
    same synchronously before the error propagates.

    ``env`` overrides the child environment.  By default children are
    pinned to the CPU backend: a local chain is a topology demonstration,
    and N child processes racing the parent for a single-client TPU would
    deadlock (this host's tunnel admits exactly one client).  Real
    multi-host deployments run ``python -m defer_tpu node`` per host with
    each host's own accelerator environment instead.
    """
    from ..transport.shm import sweep_orphan_segments
    from ..utils.export import export_pipeline

    # reap /dev/shm segments leaked by a previous chain whose processes
    # were all killed ungracefully (kill -9 skips every unlink path)
    sweep_orphan_segments()
    tmp = None
    if artifact_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="defer_chain_")
        artifact_dir = tmp.name
    try:
        n = len(stages)
        r_of = _normalize_replicas(replicas, n)
        if any(r > 1 for r in r_of) and not overlap:
            raise ValueError(
                "replicas require the overlapped node loop "
                "(drop overlap=False / --no-overlap)")
        if failover:
            if in_band:
                raise ValueError(
                    "failover requires in_band=False: the supervisor "
                    "respawns a dead replica from its original argv, "
                    "which must carry the artifact path")
            if not any(r > 1 for r in r_of):
                raise ValueError(
                    "failover requires at least one replicated stage "
                    "(replicas={k: R}) — an unreplicated stage's death "
                    "has no surviving peer to absorb its slots")
            for k in range(n):
                if r_of[k] > 1 and not 0 < k < n - 1:
                    raise ValueError(
                        f"failover: replicated stage {k} must be "
                        f"interior (0 < k < {n - 1}) — the replay/ack "
                        f"plane needs a fan-out stage above it and a "
                        f"fan-in stage below it")
        if hop_codecs is not None and len(hop_codecs) != n:
            raise ValueError(
                f"hop_codecs must have one entry per stage "
                f"({n}), got {len(hop_codecs)}")
        codec_of = list(hop_codecs) if hop_codecs is not None \
            else [codec] * n
        if stage_delays is not None and len(stage_delays) != n:
            raise ValueError(
                f"stage_delays must have one entry per stage "
                f"({n}), got {len(stage_delays)}")
        delay_of = [float(d) for d in stage_delays] \
            if stage_delays is not None else [0.0] * n
        if tier not in ("tcp", "auto", "shm"):
            # "ici"/"local" are structurally impossible as the CHAIN
            # tier here: it also governs the dispatcher edges, and the
            # dispatcher is always its own process in a spawned chain —
            # the pin would silently run both edges over full codec +
            # TCP under a tier claim (the exact failure mode the
            # no-overlap and fan-role guards reject loudly)
            if tier in ("ici", "local"):
                raise ValueError(
                    f"tier={tier!r} cannot hold on the dispatcher edges "
                    f"of a spawned chain (the dispatcher is a separate "
                    f"process); pin the stage hops with "
                    f"hop_tiers=[{tier!r}, ...] and keep tier='auto'")
            raise ValueError(f"tier must be tcp|auto|shm, got {tier!r}")
        tiers = _normalize_hop_tiers(hop_tiers, n, r_of, tier)
        claimed = [t for t in tiers if t in ("local", "shm", "ici")]
        if not overlap and claimed:
            # the serial baseline loop is pure-wire by design and always
            # refuses tier offers — an EXPLICIT local/shm/ici claim
            # would silently run full codec + TCP under a tier claim,
            # so reject loudly (same rule as replicated colocated
            # hops); "auto" offers still degrade cleanly under
            # --no-overlap
            raise ValueError(
                f"hop_tiers {claimed[0]!r} requires the overlapped node "
                f"loop (drop overlap=False / --no-overlap)")
        device_map = {int(k): int(v)
                      for k, v in (device_map or {}).items()}
        for k, v in device_map.items():
            if not 0 <= k < n:
                raise ValueError(
                    f"device_map: stage {k} out of range 0..{n - 1}")
            if v < 0:
                raise ValueError(
                    f"device_map: stage {k} device {v} must be >= 0")
        if device_map and any(t == "device" for t in tiers):
            # device-tier fusion rewrites stage indices before spawn, so
            # a pre-fusion pin would land on the wrong stage (or vanish)
            # silently — the same loud-miss policy as every other
            # stage-indexed map
            raise ValueError(
                "device_map does not compose with device-tier fusion "
                "(fusion renumbers the stages); fuse first and pin the "
                "post-fusion chain, or drop the 'device' hops")
        if device_map and devices is None:
            # pinning stage programs needs the child host mesh to hold
            # the named devices
            devices = max(device_map.values()) + 1
        if devices is not None:
            bad = [v for v in device_map.values() if v >= devices]
            if bad:
                raise ValueError(
                    f"device_map names device {bad[0]} but the forced "
                    f"host mesh has only {devices} device(s)")
        if any(t == "device" for t in tiers):
            # fuse every device-tier hop: adjacent stages become ONE
            # jit-compiled stage program and the hop ceases to exist
            from ..partition.partitioner import fuse_stages
            stages, groups = fuse_stages(list(stages), tiers)
            r_of = [r_of[g[0]] for g in groups]
            codec_of = [codec_of[g[-1]] for g in groups]
            delay_of = [sum(delay_of[i] for i in g) for g in groups]
            tiers = [tiers[g[-1]] for g in groups[:-1]]
            n = len(stages)
        # colocation groups: maximal runs of stages joined by "local"
        # or "ici" hops share one OS process (co-stage serve threads —
        # both tiers need one address space to hand a live object)
        coloc = [[0]]
        for k in range(n - 1):
            if tiers[k] in ("local", "ici"):
                coloc[-1].append(k + 1)
            else:
                coloc.append([k + 1])
        #: per-stage OUTBOUND tier policy argv: explicit claims pin
        #: that single rung's offer ("local" no longer rides the auto
        #: ladder — auto's top rung is now ici, and a 'local' claim
        #: must negotiate what it claimed); "shm" keeps the stages in
        #: separate OS processes with the payload crossing the shared-
        #: memory ring
        tier_of = [(tiers[k] if tiers[k] in ("auto", "local", "shm",
                                             "ici") else "tcp")
                   for k in range(n - 1)] + [tier]

        child_env = dict(os.environ)
        if env is None:
            env = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                   "XLA_FLAGS": "--xla_force_host_platform_device_count"
                                "=1"}
        child_env.update(env)
        if devices is not None:
            # the forced mesh must hold under a CALLER-supplied env too
            # (a device_map pin against a 1-device child dies at boot)
            from ..utils.compat import host_device_count_flags
            child_env["XLA_FLAGS"] = host_device_count_flags(
                child_env.get("XLA_FLAGS"), devices)

        tuning = [] if overlap else ["--no-overlap"]
        if failover:
            tuning += ["--failover"]
        for flag, v in (("--rx-depth", rx_depth), ("--tx-depth", tx_depth),
                        ("--inflight", inflight)):
            if v is not None:
                tuning += [flag, str(v)]
        paths = None
        if not in_band:
            paths = export_pipeline(stages, params, artifact_dir,
                                    batch=batch)

        started_journal = False
        if journal_dir is not None:
            # the dispatcher is a fleet member too: its events
            # (replica_respawn, watchdog, stream lifecycle) are the
            # forensic spine of a postmortem bundle
            from ..obs.journal import active_journal, start_journal
            if active_journal() is None:
                start_journal(journal_dir, "dispatcher")
                started_journal = True

        last_exc: BaseException | None = None
        try:
            for attempt in range(max(1, spawn_retries)):
                try:
                    return _chain_attempt(
                        stages, params, inputs, batch=batch, codec=codec,
                        codec_of=codec_of, r_of=r_of, paths=paths,
                        in_band=in_band, tuning=tuning,
                        child_env=child_env,
                        artifact_dir=artifact_dir, rx_depth=rx_depth,
                        tx_depth=tx_depth, stats_out=stats_out,
                        on_spawn=on_spawn,
                        trace_sample_every=trace_sample_every,
                        plan=plan, graph=graph,
                        report_interval_ms=report_interval_ms,
                        coloc=coloc, tier_of=tier_of, tier=tier,
                        delay_of=delay_of, device_map=device_map,
                        failover=failover, journal_dir=journal_dir)
                except _BindRace as e:
                    last_exc = e
                    print(f"run_chain: bind race on attempt "
                          f"{attempt + 1} ({e}); retrying on fresh "
                          f"ports", file=sys.stderr, flush=True)
            raise RuntimeError(
                f"chain spawn lost the port race {spawn_retries} times: "
                f"{last_exc}") from last_exc
        except _BindRace:
            raise
        except BaseException as e:
            if journal_dir is not None:
                # the failure IS the postmortem trigger: final-spill
                # this process's journal, then assemble the bundle
                # synchronously — the stage journals are already on
                # disk whether their processes died or were killed
                from ..obs.journal import stop_journal
                from ..obs.postmortem import maybe_autopsy
                if started_journal:
                    stop_journal()
                    started_journal = False
                maybe_autopsy(f"run_chain: {type(e).__name__}: {e}",
                              journal_dir=journal_dir, sync=True,
                              delay_s=0.0)
            raise
        finally:
            if started_journal:
                from ..obs.journal import stop_journal
                stop_journal()
    finally:
        if tmp is not None:
            tmp.cleanup()


class _BindRace(RuntimeError):
    """A chain child lost the ``_free_ports`` probe race (bound port was
    stolen before the child's bind) — the spawn should retry."""


def _await_binds(procs, labels, logs, flat_addrs, *,
                 timeout_s: float = 90.0, proc_of=None) -> None:
    """Block until every child REPORTS its bind (the ``listening on``
    line ``cmd_node`` prints right after ``StageNode`` binds), or
    diagnose the one that died trying: a bind-race death raises
    :class:`_BindRace` (retryable), anything else a ``RuntimeError``
    carrying that node's log tail.  This is what turns the old bare
    180 s connect timeout into a fast, attributed failure.  The log line
    (not a connect probe) is the signal on purpose: a stolen port still
    ACCEPTS connections — from whoever stole it.

    ``proc_of`` maps each ``flat_addrs`` index to its process index
    (default: identity) — a COLOCATED process hosts several stage
    listeners, each printing its own ``listening on <addr>`` line, so
    the wait is per-address, matched on the address itself."""
    deadline = time.monotonic() + timeout_s
    for i, addr in enumerate(flat_addrs):
        p = i if proc_of is None else proc_of[i]
        while True:
            rc = procs[p].poll()
            tail = _log_tail(logs[p], limit=8000)
            # delimited match: cmd_node always prints "... listening on
            # <addr>, next ..." — a bare prefix match would accept port
            # 50001's line while waiting on port 5000
            if f"listening on {addr}," in tail or (
                    proc_of is None and "listening on" in tail):
                break
            if rc is not None and rc != 0:
                if any(m in tail for m in _BIND_RACE_MARKS):
                    raise _BindRace(
                        f"node {labels[i]} lost the port bind race")
                raise RuntimeError(
                    f"chain node {labels[i]} exited rc={rc} during "
                    f"boot: {tail[-2000:]}")
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"chain node {labels[i]} did not bind {addr} "
                    f"within {timeout_s:.0f}s: {tail[-2000:]}")
            time.sleep(0.1)


def _chain_attempt(stages, params, inputs, *, batch, codec, codec_of,
                   r_of, paths, in_band, tuning, child_env, artifact_dir,
                   rx_depth, tx_depth, stats_out, on_spawn,
                   trace_sample_every=0, plan=None, graph=None,
                   report_interval_ms=250.0, coloc=None, tier_of=None,
                   tier="tcp", delay_of=None, device_map=None,
                   failover=False, journal_dir=None):
    """One spawn -> deploy -> stream -> teardown attempt (see
    ``run_chain``).  Raises :class:`_BindRace` when a child died with an
    address-in-use failure; any other failure surfaces the dead node's
    log tail after every remaining child has been terminated.

    ``coloc`` groups stage indices into OS processes (stages joined by
    ``local``-tier hops ride one process: the first member is the
    process's primary node, the rest board as ``--co-stage`` serve
    threads); ``tier_of`` is each stage's outbound tier-policy argv."""
    n = len(stages)
    if coloc is None:
        coloc = [[k] for k in range(n)]
    if tier_of is None:
        tier_of = [tier] * n
    total = sum(r_of)
    ports = _free_ports(total + 1)  # per-replica listen ports + result
    result_port = ports[-1]
    # stage k's replica ports, in spawn order
    addrs: list[list[str]] = []
    p = 0
    for k in range(n):
        addrs.append([f"127.0.0.1:{ports[p + j]}" for j in range(r_of[k])])
        p += r_of[k]

    def stage_label(k: int, j: int) -> str:
        return f"stage{k}" if r_of[k] == 1 else f"stage{k}.r{j}"

    def next_of(k: int) -> str:
        return ",".join(addrs[k + 1]) if k + 1 < n \
            else f"127.0.0.1:{result_port}"

    def flags_for(k: int, j: int) -> list[str]:
        if in_band:
            return []
        flags = ["--artifact", paths[k], "--next", next_of(k),
                 "--codec", codec_of[k], "--tier", tier_of[k]]
        if k > 0 and tier_of[k - 1] != "tcp" and tier_of[k] == "tcp":
            # the INBOUND hop claims a colocated tier but this stage's
            # own outbound policy is tcp: grant inbound offers anyway —
            # acceptance follows the upstream's claim, not this stage's
            # outbound (mixed maps like shm,tcp must not silently
            # degrade hop k-1)
            flags += ["--tier-accept", "1"]
        if k > 0 and r_of[k - 1] > 1:
            flags += ["--fan-in", str(r_of[k - 1])]
        if r_of[k] > 1:
            flags += ["--replica", str(j)]
        if delay_of and delay_of[k]:
            flags += ["--infer-delay-ms", str(delay_of[k] * 1e3)]
        if device_map and device_map.get(k) is not None:
            flags += ["--device", str(device_map[k])]
        if journal_dir is not None:
            flags += ["--journal-dir", journal_dir]
        return flags

    #: spawn units: one OS process each, hosting >= 1 (stage, replica)
    #: members (colocation groups always have replica counts of 1)
    units: list[list[tuple[int, int]]] = []
    for grp in coloc:
        if len(grp) == 1:
            units += [[(grp[0], j)] for j in range(r_of[grp[0]])]
        else:
            units.append([(k, 0) for k in grp])

    def argv_for(unit) -> list[str]:
        k0, j0 = unit[0]
        argv = [sys.executable, "-m", "defer_tpu", "node",
                "--listen", addrs[k0][j0]] + flags_for(k0, j0)
        for k, j in unit[1:]:
            # accept=1 always: every co-stage's INBOUND hop is the
            # local-tier boundary that put it in this process, whatever
            # its own outbound policy says
            spec = f"listen={addrs[k][j]};accept=1"
            if not in_band:
                spec += (f";artifact={paths[k]};next={next_of(k)}"
                         f";codec={codec_of[k]};tier={tier_of[k]}")
            if device_map and device_map.get(k) is not None:
                spec += f";device={device_map[k]}"
            argv += ["--co-stage", spec]
        return argv + tuning

    procs, logs = [], []
    labels: list[str] = []   # per-process labels for diagnostics
    failure: BaseException | None = None
    try:
        for unit in units:
            # log to files, not PIPEs: an undrained pipe fills and
            # deadlocks a chatty child mid-chain
            name = "node_" + "+".join(
                f"{k}" + (f"_r{j}" if r_of[k] > 1 else "")
                for k, j in unit)
            labels.append("+".join(stage_label(k, j) for k, j in unit))
            lf = open(os.path.join(artifact_dir, f"{name}.log"), "w+")
            logs.append(lf)
            procs.append(subprocess.Popen(
                argv_for(unit), env=child_env, stdout=lf,
                stderr=subprocess.STDOUT))
        if on_spawn is not None:
            on_spawn(procs)
        flat, flat_labels, proc_of = [], [], []
        for u, unit in enumerate(units):
            for k, j in unit:
                flat.append(addrs[k][j])
                flat_labels.append(stage_label(k, j))
                proc_of.append(u)
        _await_binds(procs, flat_labels, logs, flat, proc_of=proc_of)

        try:
            disp = ChainDispatcher(",".join(addrs[0]),
                                   listen=f"127.0.0.1:{result_port}",
                                   codec=codec,
                                   # the CLI depth flags tune BOTH ends:
                                   # the nodes (via argv) and the
                                   # dispatcher's own feed/drain channels
                                   tx_depth=tx_depth if tx_depth else 8,
                                   rx_depth=rx_depth if rx_depth else 8,
                                   result_fan_in=r_of[-1],
                                   trace_sample_every=trace_sample_every,
                                   tier=tier)
        except OSError as e:
            import errno
            if getattr(e, "errno", None) == errno.EADDRINUSE \
                    or any(m in str(e) for m in _BIND_RACE_MARKS):
                # the PARENT's result-port bind lost the probe race —
                # just as retryable as a child's
                raise _BindRace(
                    f"dispatcher lost the result-port bind race "
                    f"({e})") from e
            raise
        flat_addrs = flat
        view = None
        try:
            if in_band:
                disp.deploy(stages, params, addrs, batch=batch,
                            codecs=codec_of, tiers=tier_of,
                            devices=[device_map.get(k)
                                     if device_map else None
                                     for k in range(n)])
            if tracer().enabled:
                # one coherent cross-process timeline: correct every
                # node's wall anchor before any stream spans record
                try:
                    disp.align_clocks(flat_addrs)
                except (OSError, ConnectionError) as e:
                    print(f"run_chain: clock alignment failed: {e!r}",
                          file=sys.stderr)
            if plan is not None and stats_out is not None:
                # live observation loop: subscribe to every node's
                # obs_push stream for the duration of the stream
                view = disp.watch(flat_addrs,
                                  interval_ms=report_interval_ms)
            stop_super = threading.Event()
            super_thread = None
            if failover:
                def _supervise():
                    # respawn any dead REPLICA process from its original
                    # argv (same listen port: SO_REUSEADDR lets the
                    # respawn rebind immediately); the upstream replay
                    # fan-out's redial loop bridges the gap and replays
                    # the unacked window once the new process binds.
                    # procs[idx] is REPLACED so the post-stream rc check
                    # judges the respawn, not the corpse.
                    from ..obs.events import emit as emit_event
                    from ..transport.shm import sweep_orphan_segments
                    while not stop_super.wait(0.2):
                        for idx, unit in enumerate(units):
                            rc = procs[idx].poll()
                            if rc is None or rc == 0:
                                continue
                            if len(unit) != 1 or r_of[unit[0][0]] <= 1:
                                return  # not respawnable: let teardown
                                        # surface the death
                            k, j = unit[0]
                            # a kill -9 skipped every unlink path: reap
                            # shm segments before the replacement boots
                            sweep_orphan_segments()
                            procs[idx] = subprocess.Popen(
                                argv_for(unit), env=child_env,
                                stdout=logs[idx],
                                stderr=subprocess.STDOUT)
                            emit_event("replica_respawn", stage=k,
                                       replica=j, addr=addrs[k][j],
                                       rc=rc)
                            print(f"run_chain: respawned "
                                  f"{stage_label(k, j)} (rc={rc})",
                                  file=sys.stderr, flush=True)
                            if journal_dir is not None:
                                # a failover episode auto-emits its
                                # forensics bundle (rate-limited; the
                                # delay lets this respawn event reach
                                # the journals first)
                                from ..obs.postmortem import \
                                    maybe_autopsy
                                maybe_autopsy(
                                    f"failover: respawned "
                                    f"{stage_label(k, j)} rc={rc}",
                                    journal_dir=journal_dir)

                super_thread = threading.Thread(
                    target=_supervise, daemon=True,
                    name="chain-supervisor")
                super_thread.start()
            try:
                outs = disp.stream(inputs)
            finally:
                # stop BEFORE teardown: the END cascade exits every
                # node, and exits must not read as deaths to respawn
                stop_super.set()
                if super_thread is not None:
                    super_thread.join(timeout=5.0)
            if stats_out is not None:
                # per-replica observability, queried while the nodes are
                # still serving (they exit once close() cascades END)
                stats_out.extend(disp.stats(flat_addrs))
            if view is not None:
                from ..obs.cluster import (StragglerDetector,
                                           expected_stage_ms)
                det = StragglerDetector(expected_stage_ms(plan))
                obs = {"rows": view.rows(),
                       "bottleneck": view.bottleneck(),
                       "stragglers": [f.to_json()
                                      for f in det.observe(view)]}
                if graph is not None:
                    try:
                        obs["replan"] = det.suggest(
                            view, graph, plan).to_json()
                    except Exception as e:  # noqa: BLE001 — advisory
                        obs["replan_error"] = repr(e)
                stats_out.append({"obs": obs})
            if tracer().enabled:
                # stitch every stage process's spans into this process's
                # tracer while the nodes are still serving
                try:
                    disp.collect_trace(flat_addrs)
                except (OSError, ConnectionError) as e:
                    print(f"run_chain: trace collection failed: {e!r}",
                          file=sys.stderr)
        except BaseException as e:
            failure = e
            raise
        finally:
            if view is not None:
                view.close()
            if failure is not None:
                # hardened teardown: kill the children FIRST so the
                # dispatcher's drain hits dead sockets (fast) instead of
                # waiting out its timeouts against a wedged chain — and
                # so a mid-deploy crash cannot leak live replicas
                _kill_procs(procs)
            disp.close()
            if failure is None:
                for pr in procs:
                    try:
                        pr.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        pr.kill()
        for i, pr in enumerate(procs):
            if pr.returncode not in (0, None):
                raise RuntimeError(
                    f"chain node {labels[i]} exited rc={pr.returncode}: "
                    f"{_log_tail(logs[i])}")
        return outs
    except _BindRace:
        _kill_procs(procs)
        raise
    except BaseException as e:
        # diagnose: which children died, and why — surfacing each dead
        # node's log tail instead of the dispatcher's bare timeout
        _kill_procs(procs)
        dead = [(labels[i], pr.returncode, _log_tail(logs[i]))
                for i, pr in enumerate(procs)
                if pr.returncode not in (0, None)]
        races = [d for d in dead
                 if any(m in d[2] for m in _BIND_RACE_MARKS)]
        if races and all(d in races for d in dead):
            raise _BindRace(
                f"{[d[0] for d in races]} lost the port bind race") from e
        if dead and not isinstance(e, RuntimeError):
            detail = "; ".join(
                f"node {lbl} rc={rc}: ...{tail[-800:]}"
                for lbl, rc, tail in dead)
            raise RuntimeError(
                f"chain failed ({type(e).__name__}: {e}); dead nodes: "
                f"{detail}") from e
        raise
    finally:
        for lf in logs:
            lf.close()


def run_dag_chain(graph, params, inputs, *, topology, batch: int = 1,
                  codec: str = "raw", artifact_dir: str | None = None,
                  env: dict[str, str] | None = None,
                  rx_depth: int | None = None, tx_depth: int | None = None,
                  inflight: int | None = None,
                  stage_delays: dict | None = None,
                  replicas=None, hop_tiers=None,
                  stats_out: list | None = None,
                  spawn_retries: int = 3, on_spawn=None,
                  trace_sample_every: int = 0) -> "list[np.ndarray]":
    """Spawn a BRANCHED process pipeline — one OS process per topology
    vertex — stream, tear down (the DAG analogue of :func:`run_chain`).

    ``topology`` is a :class:`~defer_tpu.runtime.topology.ChainTopology`
    (typically ``ChainTopology.from_json`` of a ``plan --dag --json``
    document): trunk vertices relay as usual, a fork vertex broadcasts
    every frame to all of its region's paths with a shared sequence
    stamp, branch vertices ride labeled paths, and the join vertex
    merges all P paths per sequence before running the graph's merge op
    (docs/TRANSPORT.md).  Outputs return in order, byte-identical to the
    single-process forward.

    ``stage_delays`` (vertex id -> seconds) installs bench-only
    simulated device time per vertex (``node --infer-delay-ms``) — how
    ``scripts/dag_smoke.py`` expresses branch compute on a small host.

    Replication and colocation tiers do NOT compose with branch
    topologies (the ordered fan machineries own different sequence
    namespaces; every branch hop is wire-framed): ``replicas`` /
    ``hop_tiers`` are rejected loudly rather than silently ignored.
    """
    from ..utils.export import export_stage

    if replicas:
        raise ValueError(
            "replicas do not compose with a branched topology (a branch "
            "hop touching a replicated stage is rejected like any fan "
            "hop); drop the replicas or run a linear chain")
    if hop_tiers:
        raise ValueError(
            "hop_tiers do not compose with a branched topology yet — "
            "every branch fan-out/join hop is wire-framed by design")
    stages = topology.stage_specs(graph)
    tmp = None
    if artifact_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="defer_dag_")
        artifact_dir = tmp.name
    try:
        paths = []
        for v, stage in zip(topology.vertices, stages):
            p = os.path.join(artifact_dir, f"vertex_{v.vid}.zip")
            export_stage(stage, params, p, batch=batch)
            paths.append(p)

        child_env = dict(os.environ)
        if env is None:
            env = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                   "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
        child_env.update(env)
        tuning = []
        for flag, val in (("--rx-depth", rx_depth),
                          ("--tx-depth", tx_depth),
                          ("--inflight", inflight)):
            if val is not None:
                tuning += [flag, str(val)]

        last_exc: BaseException | None = None
        for attempt in range(max(1, spawn_retries)):
            try:
                return _dag_attempt(
                    topology, paths, inputs, codec=codec,
                    child_env=child_env, artifact_dir=artifact_dir,
                    tuning=tuning, rx_depth=rx_depth, tx_depth=tx_depth,
                    stage_delays=stage_delays or {},
                    stats_out=stats_out, on_spawn=on_spawn,
                    trace_sample_every=trace_sample_every)
            except _BindRace as e:
                last_exc = e
                print(f"run_dag_chain: bind race on attempt "
                      f"{attempt + 1} ({e}); retrying on fresh ports",
                      file=sys.stderr, flush=True)
        raise RuntimeError(
            f"dag chain spawn lost the port race {spawn_retries} times: "
            f"{last_exc}") from last_exc
    finally:
        if tmp is not None:
            tmp.cleanup()


def dag_vertex_argv(v, artifact: str, *, addrs, result_addr: str,
                    codec: str = "raw",
                    stage_delays: dict | None = None) -> list[str]:
    """argv for one topology vertex's ``defer_tpu node`` process — the
    single source of truth for the branched deployment shape
    (:func:`run_dag_chain` and ``scripts/dag_smoke.py`` both spawn
    through it, so the bench always measures what ``chain --dag``
    ships)."""
    nxt = ",".join(addrs[n] for n in v.next) if v.next else result_addr
    argv = [sys.executable, "-m", "defer_tpu", "node",
            "--listen", addrs[v.vid], "--artifact", artifact,
            "--next", nxt, "--codec", v.codec or codec,
            "--tier", "tcp"]
    if v.fan == "broadcast":
        argv += ["--fan", "broadcast"]
    if v.branch is not None:
        argv += ["--branch", str(v.branch)]
    if v.join >= 2:
        argv += ["--join", str(v.join)]
    if stage_delays and stage_delays.get(v.vid):
        argv += ["--infer-delay-ms", str(stage_delays[v.vid] * 1e3)]
    return argv


def _dag_attempt(topology, paths, inputs, *, codec, child_env,
                 artifact_dir, tuning, rx_depth, tx_depth, stage_delays,
                 stats_out, on_spawn, trace_sample_every=0):
    """One spawn -> stream -> teardown attempt of a branched topology
    (see :func:`run_dag_chain`); same bind-race/teardown discipline as
    :func:`_chain_attempt`."""
    vs = topology.vertices
    ports = _free_ports(len(vs) + 1)
    result_port = ports[-1]
    addrs = [f"127.0.0.1:{ports[i]}" for i in range(len(vs))]

    def argv_for(v, path):
        return dag_vertex_argv(
            v, path, addrs=addrs,
            result_addr=f"127.0.0.1:{result_port}", codec=codec,
            stage_delays=stage_delays) + tuning

    procs, logs = [], []
    labels = [v.label for v in vs]
    failure: BaseException | None = None
    try:
        for v, path in zip(vs, paths):
            lf = open(os.path.join(artifact_dir,
                                   f"node_{v.label.replace('.', '_')}"
                                   f".log"), "w+")
            logs.append(lf)
            procs.append(subprocess.Popen(
                argv_for(v, path), env=child_env, stdout=lf,
                stderr=subprocess.STDOUT))
        if on_spawn is not None:
            on_spawn(procs)
        # identity proc_of: exact per-address "listening on" matching
        _await_binds(procs, labels, logs, addrs,
                     proc_of=list(range(len(vs))))

        try:
            disp = ChainDispatcher(addrs[0],
                                   listen=f"127.0.0.1:{result_port}",
                                   codec=codec,
                                   tx_depth=tx_depth if tx_depth else 8,
                                   rx_depth=rx_depth if rx_depth else 8,
                                   trace_sample_every=trace_sample_every,
                                   tier="tcp")
        except OSError as e:
            import errno
            if getattr(e, "errno", None) == errno.EADDRINUSE \
                    or any(m in str(e) for m in _BIND_RACE_MARKS):
                raise _BindRace(
                    f"dispatcher lost the result-port bind race "
                    f"({e})") from e
            raise
        try:
            if tracer().enabled:
                try:
                    disp.align_clocks(addrs)
                except (OSError, ConnectionError) as e:
                    print(f"run_dag_chain: clock alignment failed: "
                          f"{e!r}", file=sys.stderr)
            outs = disp.stream(inputs)
            if stats_out is not None:
                stats_out.extend(disp.stats(addrs))
            if tracer().enabled:
                try:
                    disp.collect_trace(addrs)
                except (OSError, ConnectionError) as e:
                    print(f"run_dag_chain: trace collection failed: "
                          f"{e!r}", file=sys.stderr)
        except BaseException as e:
            failure = e
            raise
        finally:
            if failure is not None:
                _kill_procs(procs)
            disp.close()
            if failure is None:
                for pr in procs:
                    try:
                        pr.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        pr.kill()
        for i, pr in enumerate(procs):
            if pr.returncode not in (0, None):
                raise RuntimeError(
                    f"dag node {labels[i]} exited rc={pr.returncode}: "
                    f"{_log_tail(logs[i])}")
        return outs
    except _BindRace:
        _kill_procs(procs)
        raise
    except BaseException as e:
        _kill_procs(procs)
        dead = [(labels[i], pr.returncode, _log_tail(logs[i]))
                for i, pr in enumerate(procs)
                if pr.returncode not in (0, None)]
        races = [d for d in dead
                 if any(m in d[2] for m in _BIND_RACE_MARKS)]
        if races and all(d in races for d in dead):
            raise _BindRace(
                f"{[d[0] for d in races]} lost the port bind race") from e
        if dead and not isinstance(e, RuntimeError):
            detail = "; ".join(
                f"node {lbl} rc={rc}: ...{tail[-800:]}"
                for lbl, rc, tail in dead)
            raise RuntimeError(
                f"dag chain failed ({type(e).__name__}: {e}); dead "
                f"nodes: {detail}") from e
        raise
    finally:
        for lf in logs:
            lf.close()
