"""Standalone stage-node processes: the multi-process MPMD chain.

Reference parity: the reference's compute node is a separate process on
another machine that receives its partition, then serves the chain forever —
recv activation, predict, relay to its successor (reference
src/node.py:80-108, boot at src/node.py:110-127).  The last node relays back
to the dispatcher (reference src/dispatcher.py:51-55).

The TPU-native redesign keeps the topology but none of the machinery:

* The partition arrives as a *compiled artifact* — StableHLO + weights
  (``utils/export.py``) loaded with zero model code — not Keras JSON
  rebuilt layer by layer (src/node.py:31-37).
* One typed framed connection per hop (``transport/framed.py``) instead of
  three fixed ports; the hop codec (raw / lzb / blockfloat) is the ZFP+LZ4
  analogue and is *symmetric* (the reference's decode sides are buggy,
  SURVEY.md §3.5).
* Readiness is connect-with-retry, not 5-second poll loops
  (src/node.py:33,96), and shutdown is an in-band END frame that cascades
  down the chain, not process kill.

The SPMD mesh engine (``runtime/spmd.py``) is the primary execution model;
this chain exists for the reference's one topology it doesn't cover —
stages as separate processes/hosts with a network between them.
"""

from __future__ import annotations

import collections
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Sequence

import numpy as np

from ..obs import REGISTRY, new_span_id, tracer
from ..transport.channel import AsyncReceiver, AsyncSender
from ..transport.framed import (K_ACK, K_BYTES, K_CTRL, K_END, K_TENSOR,
                                configure_socket, recv_expect, recv_frame,
                                send_ack, send_ctrl, send_end, send_frame)


def _connect_retry(host: str, port: int, timeout_s: float = 30.0
                   ) -> socket.socket:
    """Connect, retrying while the peer boots (replaces the reference's
    sleep-5 polling rendezvous, src/node.py:95-96)."""
    deadline = time.monotonic() + timeout_s
    delay = 0.05
    while True:
        try:
            return configure_socket(
                socket.create_connection((host, port), timeout=timeout_s))
        except OSError:
            if time.monotonic() >= deadline:
                raise
            time.sleep(delay)
            delay = min(delay * 2, 1.0)


def _parse_hostport(s: str, default_host: str = "127.0.0.1"
                    ) -> tuple[str, int]:
    host, _, port = s.rpartition(":")
    return (host or default_host), int(port)


class StageNode:
    """One compute node of a process chain: recv -> stage fn -> relay.

    ``python -m defer_tpu node --listen :5000`` boots an EMPTY node that
    receives its stage artifact in-band over the control handshake —
    completing parity with the reference node, which also boots with
    nothing and gets its model over the wire (src/node.py:20-55).
    ``--artifact stage_k.zip --next host:5000`` pre-loads from a local
    file instead (the r3/r4 behavior, kept for pre-provisioned hosts).
    """

    #: class-level defaults so instances built via ``__new__`` (tests)
    #: still serve; the overlapped loop keeps ``inflight`` device
    #: dispatches un-synced and ``rx_depth``/``tx_depth`` decoded frames
    #: of queue slack per side
    overlap: bool = True
    rx_depth: int = 8
    tx_depth: int = 8
    inflight: int = 2

    def __init__(self, artifact: str | None, listen: str,
                 next_hop: str | None, *, codec: str = "raw",
                 overlap: bool = True, rx_depth: int = 8,
                 tx_depth: int = 8, inflight: int = 2):
        # bind before the (slow: jax import + StableHLO deserialize)
        # artifact load so upstream connect-retries land as soon as the
        # process exists
        host, port = _parse_hostport(listen, "0.0.0.0")
        self._srv = socket.create_server((host, port))
        self.address = self._srv.getsockname()
        self.prog = None
        if artifact is not None:
            from ..utils.export import load_stage_program
            self.prog = load_stage_program(artifact)
        self.next_hop = _parse_hostport(next_hop) if next_hop else None
        self.codec = codec
        self.overlap = overlap
        self.rx_depth = rx_depth
        self.tx_depth = tx_depth
        self.inflight = max(1, inflight)
        self.processed = 0    # tensors relayed, lifetime
        self.reweights = 0    # weights-only re-pushes accepted
        #: trace-context K_CTRL received from upstream, held until this
        #: node opens its downstream connection so the context cascades
        #: hop by hop through the whole chain
        self._pending_trace: dict | None = None

    @property
    def manifest(self):
        return None if self.prog is None else self.prog.manifest

    def _span_label(self) -> str:
        """Span/track prefix for this node's rx/tx/infer telemetry."""
        m = self.manifest
        return (f"stage{m['index']}" if m is not None
                else f"node{self.address[1]}")

    def _handle_ctrl(self, conn, msg: dict, recv=None) -> bool:
        """One control command; True if the connection should keep serving.

        ``recv`` supplies the follow-up frame of multi-frame commands
        (deploy/reweight blobs); the overlapped loop passes its rx-queue
        getter because the channel's rx thread owns all socket reads.

        deploy:   {"cmd": "deploy", "next": "host:port", "codec": ...}
                  followed by a K_BYTES artifact blob -> load, ACK.
                  The in-band analogue of the reference's weights+arch
                  sockets and \\x06 ACK (src/dispatcher.py:44-65).
        reweight: {"cmd": "reweight"} followed by a K_BYTES npz blob ->
                  swap weights in the already-loaded program, ACK
                  (redeploy without restart; no reference analogue).
        trace:    {"cmd": "trace", "trace_id": ..., "span_id": ...} ->
                  adopt the dispatcher's trace context (spans recorded
                  from here on carry its trace_id and parent under its
                  root span) and cascade the same context downstream when
                  the data connection opens.  One-way: no ACK — it rides
                  the data stream ahead of the first tensor.
        trace_dump: reply with this node's recorded spans as a K_CTRL
                  frame (and drain them) — the dispatcher stitches every
                  stage's spans into one exportable trace.
        """
        from ..utils.export import load_stage_program

        def _expect(kind):
            if recv is None:
                return recv_expect(conn, kind)
            got, value = recv()
            if got != kind:
                raise ConnectionError(
                    f"expected frame kind {kind}, got {got}")
            return value

        cmd = msg.get("cmd")
        if cmd == "deploy":
            blob = _expect(K_BYTES)
            self.prog = load_stage_program(blob)
            if msg.get("next"):
                self.next_hop = _parse_hostport(msg["next"])
            if msg.get("codec"):
                self.codec = msg["codec"]
            send_ack(conn)
            return True
        if cmd == "reweight":
            if self.prog is None:
                raise ValueError("reweight before deploy")
            self.prog.reweight(_expect(K_BYTES))
            self.reweights += 1
            send_ack(conn)
            return True
        if cmd == "trace":
            tr = tracer()
            tr.adopt(msg)
            m = self.manifest
            tr.process = (f"stage{m['index']}" if m is not None
                          else f"node:{self.address[1]}")
            self._pending_trace = {k: v for k, v in msg.items()}
            return True
        if cmd == "trace_dump":
            tr = tracer()
            send_ctrl(conn, {"spans": tr.drain()})
            # the trace is over once collected: stop recording so a node
            # that later serves untraced streams doesn't accumulate spans
            tr.enabled = False
            tr._remote_parent = None
            self._pending_trace = None
            return True
        if cmd == "stats":
            # chain observability: what this node is and has done — the
            # per-node view the reference never had (SURVEY §5 metrics)
            m = self.manifest
            reg = REGISTRY
            send_ctrl(conn, {
                "stage": None if m is None else m["index"],
                "name": None if m is None else m["name"],
                "processed": self.processed,
                "reweights": self.reweights,
                "codec": self.codec,
                "next": None if self.next_hop is None
                else f"{self.next_hop[0]}:{self.next_hop[1]}",
                # wire telemetry: this node's process-local transport view
                "tx_frames": reg.counter("transport.tx_frames").value,
                "tx_bytes": reg.counter("transport.tx_bytes").value,
                "rx_frames": reg.counter("transport.rx_frames").value,
                "rx_bytes": reg.counter("transport.rx_bytes").value,
                "infer_latency_s":
                    reg.histogram("node.infer_s").summary(),
                # overlap telemetry: queue occupancy of the async channel
                # layer and the un-synced device-dispatch window
                "overlap": self.overlap,
                "rx_queue_depth": reg.gauge("node.rx_queue_depth").value,
                "tx_queue_depth": reg.gauge("node.tx_queue_depth").value,
                "inflight": reg.gauge("node.inflight").value,
            })
            return True
        raise ValueError(f"unknown control command {msg!r}")

    def serve(self, *, connect_timeout_s: float = 30.0) -> int:
        """Serve control/data connections until a data stream completes.

        Connections are handled CONCURRENTLY (thread per connection — the
        shape of the reference node's 4-thread design, src/node.py:110-124,
        minus the polling): control connections (deploy / reweight, each
        ACKed, ending with the dispatcher's END) may arrive before or
        *during* the upstream data stream, which is relayed through the
        stage function until its END frame.  Returns the number of tensors
        the completed data stream processed.  The END is forwarded
        downstream before closing, so shutdown cascades through the chain
        to the dispatcher's result server.
        """
        import queue as _q
        import threading

        done: _q.Queue = _q.Queue()

        def worker(conn):
            try:
                configure_socket(conn)
                n = self._serve_conn(conn, connect_timeout_s)
                if n is not None:
                    done.put(n)
            except BaseException as e:  # noqa: BLE001 — surfaced below
                done.put(e)
            finally:
                conn.close()

        self._srv.settimeout(0.25)
        try:
            while True:
                try:
                    conn, _ = self._srv.accept()
                except TimeoutError:  # socket.timeout is TimeoutError >=3.10
                    conn = None
                if conn is not None:
                    threading.Thread(target=worker, args=(conn,),
                                     daemon=True).start()
                try:
                    r = done.get_nowait()
                except _q.Empty:
                    continue
                if isinstance(r, BaseException):
                    raise r
                return r
        finally:
            self._srv.close()

    def _serve_conn(self, conn, connect_timeout_s: float) -> int | None:
        """One connection: None if it was control-only, else tensor count.

        ``overlap=True`` (default) runs the three-phase overlapped loop
        (:meth:`_serve_conn_overlapped`); ``overlap=False`` keeps the
        strictly serial recv -> infer -> send loop as the measurable
        baseline (``--no-overlap``, ``scripts/chain_overlap_smoke.py``).
        """
        if self.overlap:
            return self._serve_conn_overlapped(conn, connect_timeout_s)
        return self._serve_conn_serial(conn, connect_timeout_s)

    def _serve_conn_overlapped(self, conn,
                               connect_timeout_s: float) -> int | None:
        """Three-phase overlap: rx thread -> compute loop -> tx thread.

        An :class:`AsyncReceiver` decodes upstream frames into a bounded
        queue while this thread computes, and an :class:`AsyncSender`
        encodes/sends relayed tensors from a bounded queue — so the rx of
        microbatch j+1, the compute of j, and the tx of j-1 run
        concurrently, and per-hop latency tends to max(rx, compute, tx)
        instead of their sum.  The compute loop additionally keeps up to
        ``inflight`` stage dispatches un-synced (JAX async dispatch): the
        host-side ``np.asarray`` sync of output j-1 overlaps the device
        compute of j.  Bounded queues preserve end-to-end backpressure —
        a stuck downstream fills the tx queue, stalls this loop, fills
        the rx queue, and TCP pushes back upstream.

        ``node.infer_s`` here measures issue-to-materialize (device queue
        included), matching what the overlap actually hides.
        """
        out = None
        tx = None
        n = 0                   # tensors relayed downstream
        seq = 0                 # tensors received
        streamed = False
        infer_hist = REGISTRY.histogram("node.infer_s")
        inflight_g = REGISTRY.gauge("node.inflight")
        #: issued-but-unsynced stage outputs, oldest first
        pending: collections.deque = collections.deque()
        # no gauge yet: most connections are short-lived control round
        # trips whose rx channel would clobber the data stream's reading;
        # the gauge is bound once this connection proves to be the stream
        rx = AsyncReceiver(conn, depth=self.rx_depth,
                           span=self._span_label)

        def drain_one():
            nonlocal n, streamed
            t0, s, y = pending.popleft()
            inflight_g.v = len(pending)
            y = np.asarray(y)  # host sync of the OLDEST in-flight output
            dt = time.perf_counter() - t0
            infer_hist.record(dt)
            tr = tracer()
            if tr.enabled:
                tr.record(
                    f"stage{self.manifest['index']}.infer", t0, dt,
                    {"seq": s, "stage": self.manifest["index"]})
            self.processed += 1  # before the send: a stats query can
            #   race the relay of the final tensor otherwise
            tx.send(y)
            n += 1
            streamed = True

        import queue as _q

        try:
            while True:
                if pending:
                    # compute-ahead only while input is immediately
                    # available: an idle upstream means the window must
                    # drain NOW, or the stream's tail stalls in the node
                    try:
                        kind, value = rx.get_nowait()
                    except _q.Empty:
                        drain_one()
                        continue
                else:
                    kind, value = rx.get()
                if kind == K_END:
                    while pending:
                        drain_one()
                    if streamed:
                        # END + join: every relayed frame is on the wire
                        # before the finally block closes the socket
                        tx.close(timeout=connect_timeout_s)
                        return n
                    return None  # control connection closing
                if kind == K_CTRL:
                    is_trace = (isinstance(value, dict)
                                and value.get("cmd") == "trace")
                    if is_trace:
                        # relay order: everything received before this
                        # ctrl frame must reach downstream ahead of it
                        while pending:
                            drain_one()
                    self._handle_ctrl(conn, value, recv=rx.get)
                    if is_trace and tx is not None:
                        # downstream already connected (e.g. a second
                        # traced stream on a live chain): cascade the new
                        # context now, not just at connection open
                        tx.send_ctrl(self._pending_trace)
                    continue
                if kind != K_TENSOR:
                    raise ValueError(f"unexpected frame kind {kind}")
                if self.prog is None:
                    raise ValueError(
                        "data frame before any stage artifact (boot with "
                        "--artifact or deploy in-band first)")
                if out is None:
                    if self.next_hop is None:
                        raise ValueError("no next hop configured")
                    out = _connect_retry(*self.next_hop,
                                         timeout_s=connect_timeout_s)
                    rx.bind_gauge("node.rx_queue_depth")
                    tx = AsyncSender(out, depth=self.tx_depth,
                                     codec=self.codec,
                                     gauge="node.tx_queue_depth",
                                     span=self._span_label)
                    if self._pending_trace is not None:
                        # cascade the dispatcher's trace context down the
                        # chain ahead of the first relayed tensor
                        tx.send_ctrl(self._pending_trace)
                want = tuple(self.manifest["in_shape"])
                if tuple(value.shape[1:]) != want:
                    raise ValueError(
                        f"stage {self.manifest['index']} expects sample "
                        f"shape {want}, got {tuple(value.shape[1:])}")
                t0 = time.perf_counter()
                pending.append((t0, seq, self.prog(value)))  # no sync yet
                seq += 1
                inflight_g.v = len(pending)
                while len(pending) >= self.inflight:
                    drain_one()
        except Exception as e:  # noqa: BLE001 — see below
            if streamed:
                raise  # upstream died / corrupted mid-stream: loud
            # a connection that never became the data stream must not be
            # able to kill a serving node: port scanners and malformed
            # control peers are logged and dropped.  The remote side still
            # fails loudly — its recv gets a cut connection, no ACK/END.
            print(f"node: dropped connection before streaming: {e!r}",
                  file=sys.stderr, flush=True)
            return None
        finally:
            if out is not None:
                out.close()

    def _serve_conn_serial(self, conn, connect_timeout_s: float) -> int | None:
        """The pre-overlap serial loop: per tensor, rx + decode, compute
        with an immediate host sync, encode + tx — phases pay their sum.
        Kept as the baseline the overlap speedup is measured against."""
        out = None
        n = 0
        streamed = False
        infer_hist = REGISTRY.histogram("node.infer_s")
        try:
            while True:
                kind, value = recv_frame(conn)
                if kind == K_END:
                    if streamed:
                        send_end(out)
                        return n
                    return None  # control connection closing
                if kind == K_CTRL:
                    self._handle_ctrl(conn, value)
                    if (isinstance(value, dict)
                            and value.get("cmd") == "trace"
                            and out is not None):
                        # downstream already connected (e.g. a second
                        # traced stream on a live chain): cascade the new
                        # context now, not just at connection open
                        send_ctrl(out, self._pending_trace)
                    continue
                if kind != K_TENSOR:
                    raise ValueError(f"unexpected frame kind {kind}")
                if self.prog is None:
                    raise ValueError(
                        "data frame before any stage artifact (boot with "
                        "--artifact or deploy in-band first)")
                if out is None:
                    if self.next_hop is None:
                        raise ValueError("no next hop configured")
                    out = _connect_retry(*self.next_hop,
                                         timeout_s=connect_timeout_s)
                    if self._pending_trace is not None:
                        # cascade the dispatcher's trace context down the
                        # chain ahead of the first relayed tensor
                        send_ctrl(out, self._pending_trace)
                want = tuple(self.manifest["in_shape"])
                if tuple(value.shape[1:]) != want:
                    raise ValueError(
                        f"stage {self.manifest['index']} expects sample "
                        f"shape {want}, got {tuple(value.shape[1:])}")
                t0 = time.perf_counter()
                y = np.asarray(self.prog(value))
                dt = time.perf_counter() - t0
                infer_hist.record(dt)
                tr = tracer()
                if tr.enabled:
                    tr.record(
                        f"stage{self.manifest['index']}.infer", t0, dt,
                        {"seq": n, "stage": self.manifest["index"]})
                self.processed += 1  # before the send: a stats query can
                #   race the relay of the final tensor otherwise
                send_frame(out, y, codec=self.codec)
                n += 1
                streamed = True
        except Exception as e:  # noqa: BLE001 — see below
            if streamed:
                raise  # upstream died / corrupted mid-stream: loud
            print(f"node: dropped connection before streaming: {e!r}",
                  file=sys.stderr, flush=True)
            return None
        finally:
            if out is not None:
                out.close()


class ChainDispatcher:
    """Drives a chain of stage-node processes from one controller.

    Opens the result server (the reference dispatcher's own port 5000 role,
    src/dispatcher.py:95-105), streams inputs to node 0, and yields results
    in order.  Strictly in-flight-window'd so the chain stays full without
    unbounded buffering.
    """

    #: the ONE timeout default; also covers partially-constructed
    #: instances (tests build via __new__ around socketpairs) — as do the
    #: channel defaults below
    timeout_s: float = 180.0
    tx_depth: int = 8
    rx_depth: int = 8
    _tx_chan: AsyncSender | None = None
    _rx_chan: AsyncReceiver | None = None

    def __init__(self, first_hop: str, *, listen: str = "127.0.0.1:0",
                 codec: str = "raw", window: int = 64,
                 timeout_s: float | None = None,
                 tx_depth: int = 8, rx_depth: int = 8):
        if timeout_s is not None:
            self.timeout_s = timeout_s
        host, port = _parse_hostport(listen)
        self._res_srv = socket.create_server((host, port))
        # a dead chain fails, not hangs
        self._res_srv.settimeout(self.timeout_s)
        self.result_address = self._res_srv.getsockname()
        self.first_hop = first_hop
        self.codec = codec
        self.window = window
        self.tx_depth = tx_depth
        self.rx_depth = rx_depth
        self._send_sock: socket.socket | None = None
        self._res_conn: socket.socket | None = None
        self._tx_chan = None
        self._rx_chan = None

    def _ensure_connected(self):
        if self._send_sock is None:
            # generous: every node in the chain cold-imports jax first
            self._send_sock = _connect_retry(
                *_parse_hostport(self.first_hop), timeout_s=self.timeout_s)
        if self._tx_chan is None:
            # encode + send happen on the channel's tx thread, so the
            # feed loop's np.asarray and the wire overlap (and the END in
            # close() rides the same ordered queue)
            self._tx_chan = AsyncSender(self._send_sock,
                                        depth=self.tx_depth,
                                        codec=self.codec,
                                        gauge="chain.tx_queue_depth",
                                        span="chain")
        # the result connection is accepted lazily in _recv_tensor: the
        # last node only dials back once its first tensor arrives, so
        # accepting before sending anything would deadlock the chain

    def stream(self, inputs) -> list[np.ndarray]:
        """Send every input through the chain; return outputs in order.

        FULL-DUPLEX: a sender thread keeps the chain fed (up to
        ``window`` in flight, released as results land) while this thread
        drains results concurrently — a slow stage applies backpressure
        through the window instead of stalling the feed loop mid-send
        (r4 verdict weakness #7).  Encoding happens on the tx channel's
        own thread and result decoding on the rx channel's, so feed,
        encode, the chain itself, and the result drain all overlap with
        bounded in-flight depth.  Per-``get`` timeouts on the result
        channel keep a dead chain failing rather than hanging.

        With tracing enabled (``defer_tpu.obs.enable_tracing``), the call
        injects its trace context as a K_CTRL frame ahead of the first
        tensor; every stage process adopts it, cascades it downstream,
        and parents its per-tensor spans under this stream's root span —
        collect them afterwards with :meth:`collect_trace`.
        """
        self._ensure_connected()
        tr = tracer()
        root_span = None
        t_start = time.perf_counter()
        if tr.enabled:
            # pre-allocate the root span id so remote stages can parent
            # under a span recorded only when the stream completes
            root_span = new_span_id()
            self._tx_chan.send_ctrl(
                {"cmd": "trace", "trace_id": tr.trace_id,
                 "span_id": root_span})
        outs: list[np.ndarray] = []
        window = threading.Semaphore(self.window)
        sent = [0]
        tx_done = threading.Event()
        rx_failed = threading.Event()
        err: list[BaseException] = []

        def tx():
            try:
                for x in inputs:
                    if rx_failed.is_set():
                        return
                    if not window.acquire(timeout=self.timeout_s):
                        raise TimeoutError(
                            f"chain accepted no result for "
                            f"{self.timeout_s:.0f}s with {self.window} in "
                            f"flight — a stage is stuck")
                    if rx_failed.is_set():
                        return  # woken by the error path, not a result
                    self._tx_chan.send(np.asarray(x))
                    sent[0] += 1
            except BaseException as e:  # noqa: BLE001 — surfaced below
                err.append(e)
            finally:
                tx_done.set()

        t = threading.Thread(target=tx, daemon=True, name="chain-tx")
        t.start()
        try:
            while True:
                if err:
                    raise err[0]
                if len(outs) < sent[0]:
                    # something is in flight: recv (bounded by the result
                    # socket's timeout).  Never recv otherwise — a recv
                    # with nothing in flight (empty stream, or the final
                    # result landing before tx_done is set) would stall
                    # the full socket timeout for no reason.
                    outs.append(self._recv_tensor())
                    window.release()
                    continue
                if tx_done.is_set():
                    break  # everything sent has been received
                tx_done.wait(0.01)  # sender still working; let it run
        except BaseException:
            rx_failed.set()
            # a sender parked in window.acquire must wake to see the flag;
            # then give it a bounded moment so no trailing frame interleaves
            # with the caller's teardown (close() writes END on this socket)
            window.release(self.window)
            t.join(timeout=5.0)
            raise
        t.join(timeout=self.timeout_s)  # no trailing writes after return
        if err:
            raise err[0]
        if root_span is not None:
            tr.record("chain.stream", t_start,
                      time.perf_counter() - t_start,
                      {"sent": sent[0], "received": len(outs)},
                      span_id=root_span)
        return outs

    def deploy(self, stages, params, node_addrs: Sequence[str], *,
               batch: int = 1, result_hop: str | None = None):
        """Ship each stage's artifact to its node over the control channel.

        Serial, in chain order, each ACKed before the next — the in-band
        model distribution of the reference dispatcher
        (src/dispatcher.py:44-65: weights, arch JSON, next-node IP, \\x06
        ACK) collapsed to one control connection per node carrying a
        self-contained StableHLO+weights blob.  Nodes may boot with no
        pre-placed files at all.  ``result_hop`` overrides the address the
        last node relays results to (defaults to this dispatcher's result
        server, reference src/dispatcher.py:51-55).
        """
        from ..utils.export import export_stage_bytes
        addrs = list(node_addrs)
        if len(addrs) != len(stages):
            raise ValueError(f"{len(stages)} stages but {len(addrs)} nodes")
        result_hop = result_hop or \
            f"{self.result_address[0]}:{self.result_address[1]}"
        for i, (stage, addr) in enumerate(zip(stages, addrs)):
            nxt = addrs[i + 1] if i + 1 < len(addrs) else result_hop
            blob = export_stage_bytes(stage, params, batch=batch)
            s = _connect_retry(*_parse_hostport(addr),
                               timeout_s=self.timeout_s)
            try:
                send_ctrl(s, {"cmd": "deploy", "next": nxt,
                              "codec": self.codec})
                send_frame(s, blob)
                recv_expect(s, K_ACK)
                send_end(s)
            finally:
                s.close()

    def reweight(self, stages, params, node_addrs: Sequence[str]):
        """Weights-only re-push: install fresh weights on every node's
        already-loaded stage program — redeploy (e.g. after more training)
        without restarting any process or resending StableHLO."""
        from ..utils.export import stage_weight_leaves, weights_blob
        node_addrs = list(node_addrs)
        if len(node_addrs) != len(stages):
            raise ValueError(
                f"{len(stages)} stages but {len(node_addrs)} nodes")
        for stage, addr in zip(stages, node_addrs):
            s = _connect_retry(*_parse_hostport(addr),
                               timeout_s=self.timeout_s)
            try:
                send_ctrl(s, {"cmd": "reweight"})
                send_frame(s, weights_blob(
                    stage_weight_leaves(stage, params)))
                recv_expect(s, K_ACK)
                send_end(s)
            finally:
                s.close()

    def stats(self, node_addrs: Sequence[str]) -> list[dict]:
        """Per-node chain observability: query every node's stats control
        endpoint (stage identity, tensors processed, reweights, topology)
        — works mid-stream thanks to thread-per-connection nodes."""
        out = []
        for addr in node_addrs:
            s = _connect_retry(*_parse_hostport(addr),
                               timeout_s=self.timeout_s)
            try:
                send_ctrl(s, {"cmd": "stats"})
                out.append(recv_expect(s, K_CTRL))
                send_end(s)
            finally:
                s.close()
        return out

    def _recv_tensor(self) -> np.ndarray:
        """One in-order result frame; loud protocol check (not an assert:
        ``python -O`` strips asserts, and an early END from a node that died
        mid-stream must raise, not silently mis-drain).

        Results arrive through an :class:`AsyncReceiver`: the decode of
        result j+1 happens on the channel's rx thread while this thread
        hands j back to the caller.  The per-``get`` timeout keeps the
        dead-chain-fails-not-hangs contract; the socket itself stays
        blocking so an idle (but healthy) chain never desyncs mid-frame.
        """
        if self._res_conn is None:
            self._res_conn, _ = self._res_srv.accept()
            configure_socket(self._res_conn)
        if self._rx_chan is None:
            self._res_conn.settimeout(None)
            self._rx_chan = AsyncReceiver(self._res_conn,
                                          depth=self.rx_depth,
                                          gauge="chain.rx_queue_depth",
                                          span="chain")
        kind, y = self._rx_chan.get(timeout=self.timeout_s)
        while kind == K_CTRL and isinstance(y, dict) \
                and y.get("cmd") == "trace":
            # the last node cascaded the trace context to the result hop;
            # informational — the dispatcher originated it
            kind, y = self._rx_chan.get(timeout=self.timeout_s)
        if kind != K_TENSOR:
            raise ConnectionError(
                f"chain returned frame kind {kind!r} while results were "
                f"still in flight (a stage node died and cascaded END?)")
        return y

    def collect_trace(self, node_addrs: Sequence[str]) -> int:
        """Fetch and merge every node's recorded spans into this process's
        tracer (``trace_dump`` control round-trip per node) so one export
        holds the stitched dispatcher -> stage0 -> ... -> stageN-1 trace.
        Returns the number of spans ingested.  Call while the nodes are
        still alive — after ``stream`` returns, before ``close``."""
        tr = tracer()
        total = 0
        for addr in node_addrs:
            s = _connect_retry(*_parse_hostport(addr),
                               timeout_s=self.timeout_s)
            try:
                send_ctrl(s, {"cmd": "trace_dump"})
                reply = recv_expect(s, K_CTRL)
                spans = reply.get("spans", [])
                tr.ingest(spans)
                total += len(spans)
                send_end(s)
            finally:
                s.close()
        return total

    def close(self):
        """Drain the chain (best effort) and close every socket.

        The graceful END handshake is wrapped so a chain that already died
        mid-stream can't mask the original failure with a secondary
        BrokenPipe/EOF from the teardown itself."""
        try:
            if self._send_sock is not None:
                if self._tx_chan is not None:
                    # the END rides the ordered tx queue behind any
                    # trailing frames; close() joins the tx thread so it
                    # is on the wire before we wait for the cascaded echo
                    self._tx_chan.close(timeout=min(10.0, self.timeout_s))
                else:
                    send_end(self._send_sock)
                if self._res_conn is None:
                    # nothing was ever received: still accept the last
                    # node's dial-back so its cascaded END completes
                    try:
                        self._res_srv.settimeout(min(10.0, self.timeout_s))
                        self._res_conn, _ = self._res_srv.accept()
                        self._res_conn.settimeout(self.timeout_s)
                    except OSError:
                        pass
                if self._res_conn is not None:
                    # drain any leftover in-flight frames until the END
                    # cascades through
                    while True:
                        if self._rx_chan is not None:
                            kind, _ = self._rx_chan.get(
                                timeout=self.timeout_s)
                        else:
                            kind, _ = recv_frame(self._res_conn)
                        if kind == K_END:
                            break
        except (OSError, ConnectionError, ValueError):
            pass  # teardown after failure: keep the root cause
        finally:
            if self._send_sock is not None:
                self._send_sock.close()
            if self._res_conn is not None:
                self._res_conn.close()
            self._res_srv.close()


def _free_ports(n: int) -> list[int]:
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def run_chain(stages: Sequence, params: dict[str, Any], inputs,
              *, batch: int = 1, codec: str = "raw",
              artifact_dir: str | None = None,
              env: dict[str, str] | None = None,
              in_band: bool = False, overlap: bool = True,
              rx_depth: int | None = None, tx_depth: int | None = None,
              inflight: int | None = None) -> list[np.ndarray]:
    """Export, spawn one OS process per stage, stream, and tear down.

    The one-call analogue of the reference's whole deployment procedure
    (start N ``node.py`` processes, run the dispatcher, src/dispatcher.py:
    44-65 + test/test.py) — used by the CLI ``chain`` command and the
    multi-process integration test.

    ``in_band=True`` boots every node EMPTY (no --artifact flag, no shared
    filesystem) and ships each stage artifact over its control connection
    with an ACK handshake — full control-plane parity with the reference.
    ``in_band=False`` pre-exports artifacts to a (shared) directory and
    passes paths on the command line.

    ``env`` overrides the child environment.  By default children are
    pinned to the CPU backend: a local chain is a topology demonstration,
    and N child processes racing the parent for a single-client TPU would
    deadlock (this host's tunnel admits exactly one client).  Real
    multi-host deployments run ``python -m defer_tpu node`` per host with
    each host's own accelerator environment instead.
    """
    from ..utils.export import export_pipeline

    tmp = None
    if artifact_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="defer_chain_")
        artifact_dir = tmp.name
    logs: list = []
    try:
        n = len(stages)
        ports = _free_ports(n + 1)  # node listen ports + result port
        result_port = ports[-1]

        child_env = dict(os.environ)
        if env is None:
            env = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                   "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
        child_env.update(env)

        tuning = [] if overlap else ["--no-overlap"]
        for flag, v in (("--rx-depth", rx_depth), ("--tx-depth", tx_depth),
                        ("--inflight", inflight)):
            if v is not None:
                tuning += [flag, str(v)]
        if in_band:
            argv_for = lambda i: [  # noqa: E731 — tiny per-node argv
                sys.executable, "-m", "defer_tpu", "node",
                "--listen", f"127.0.0.1:{ports[i]}"] + tuning
        else:
            paths = export_pipeline(stages, params, artifact_dir,
                                    batch=batch)
            argv_for = lambda i: [  # noqa: E731
                sys.executable, "-m", "defer_tpu", "node",
                "--artifact", paths[i],
                "--listen", f"127.0.0.1:{ports[i]}",
                "--next", (f"127.0.0.1:{ports[i + 1]}" if i + 1 < n
                           else f"127.0.0.1:{result_port}"),
                "--codec", codec] + tuning

        procs = []
        for i in range(n):
            # log to files, not PIPEs: an undrained pipe fills and
            # deadlocks a chatty child mid-chain
            lf = open(os.path.join(artifact_dir, f"node_{i}.log"), "w+")
            logs.append(lf)
            procs.append(subprocess.Popen(
                argv_for(i), env=child_env, stdout=lf,
                stderr=subprocess.STDOUT))

        disp = ChainDispatcher(f"127.0.0.1:{ports[0]}",
                               listen=f"127.0.0.1:{result_port}",
                               codec=codec,
                               # the CLI depth flags tune BOTH ends: the
                               # nodes (via argv) and the dispatcher's own
                               # feed/drain channels
                               tx_depth=tx_depth if tx_depth else 8,
                               rx_depth=rx_depth if rx_depth else 8)
        try:
            if in_band:
                disp.deploy(stages, params,
                            [f"127.0.0.1:{p}" for p in ports[:-1]],
                            batch=batch)
            outs = disp.stream(inputs)
            if tracer().enabled:
                # stitch every stage process's spans into this process's
                # tracer while the nodes are still serving (they exit
                # once close() cascades the END)
                try:
                    disp.collect_trace(
                        [f"127.0.0.1:{p}" for p in ports[:-1]])
                except (OSError, ConnectionError) as e:
                    print(f"run_chain: trace collection failed: {e!r}",
                          file=sys.stderr)
        finally:
            disp.close()
            for pr in procs:
                try:
                    pr.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pr.kill()
        for i, pr in enumerate(procs):
            if pr.returncode not in (0, None):
                logs[i].seek(0)
                raise RuntimeError(
                    f"stage node {i} exited rc={pr.returncode}: "
                    f"{logs[i].read()[-2000:]}")
        return outs
    finally:
        for lf in logs:
            lf.close()
        if tmp is not None:
            tmp.cleanup()
