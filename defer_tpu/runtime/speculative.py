"""Speculative decoding over the pipelined verification forward.

A small DRAFT causal LM proposes ``gamma`` tokens per round; the TARGET
model verifies the whole block in ONE pipelined full-sequence forward
(``Defer.logits`` — length-bucketed, compiled once per power-of-two
bucket) and accepts the longest matching greedy prefix plus its own
correction token.  Greedy speculative decoding is TOKEN-EXACT: the
output equals target-only greedy decoding by construction, regardless of
the draft's quality — the draft only changes how many target forwards
are spent, never what is produced.

Design notes for this engine:

* Verification is the pipeline's natural shape — one wide full-sequence
  forward per round instead of per-token decode steps, exactly the
  program the SPMD pipeline is best at (MXU-dense, no per-token host
  round trips).  On the tunnel-attached chip this also pays the ~64 ms
  dispatch sync once per BLOCK of tokens instead of once per token.
* Draft proposals run through the same bucketed-forward machinery on the
  draft graph (a recompute per proposed token).  A draft this small is
  cheap; a KV-cached draft would only sharpen the win.
* Per-sequence acceptance is ragged; bookkeeping lives host-side in
  numpy while every device forward stays batched and fixed-shape
  (sequences are right-padded to the round's bucket).

No reference analogue (reference is CNN-only); this extends the
generation engine family (runtime/decode.py).
"""

from __future__ import annotations

from typing import Any

import numpy as np


def speculative_generate(
    defer,
    target_graph, target_params: dict[str, Any],
    draft_graph, draft_params: dict[str, Any],
    prompt_ids, max_new_tokens: int,
    *,
    gamma: int = 4,
    eos_id: int | None = None,
    num_stages: int | None = None,
    draft_num_stages: int | None = None,
    cut_points=None,
    draft_cut_points=None,
    return_stats: bool = False,
):
    """Greedy speculative decoding; token-exact vs target-only greedy.

    ``prompt_ids``: [B, plen] ints (B a multiple of the deployment's
    microbatch).  Returns [B, plen + max_new_tokens] (positions after an
    ``eos_id`` hit are filled with ``eos_id``), plus a stats dict when
    ``return_stats`` (acceptance rate, rounds, forward counts).
    """
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    ids = np.asarray(prompt_ids)
    if ids.ndim != 2:
        raise ValueError("prompt_ids must be [B, plen]")
    b, plen = ids.shape
    t_total = plen + max_new_tokens
    t_model = target_graph.input_spec.shape[0]
    if t_total > t_model:
        raise ValueError(
            f"prompt {plen} + {max_new_tokens} new exceeds the target's "
            f"sequence length {t_model}")
    if draft_graph.input_spec.shape[0] < t_total:
        raise ValueError(
            f"draft sequence length {draft_graph.input_spec.shape[0]} "
            f"< {t_total}")

    # out[i, :lens[i]] is valid; done[i] freezes a sequence at EOS
    out = np.zeros((b, t_total), np.int64)
    out[:, :plen] = ids
    lens = np.full(b, plen)
    done = np.zeros(b, bool)
    stats = {"rounds": 0, "target_forwards": 0, "draft_forwards": 0,
             "proposed": 0, "accepted": 0}

    def greedy_next(graph, params, length, n_stages, cp):
        """argmax logits at each sequence's position length-1 .. (batched
        full-sequence forward at the max live length)."""
        logits = defer.logits(graph, params, out[:, :length],
                              num_stages=n_stages, cut_points=cp)
        return np.argmax(logits, axis=-1)  # [B, length, ] -> argmax ids

    while not done.all() and (lens < t_total).any():
        stats["rounds"] += 1
        # --- draft proposes up to gamma tokens past each live sequence
        # (rows at the length cap simply stop proposing; clamping the
        # whole block by the most-advanced row would collapse the other
        # rows' speculation to one token per round)
        base = lens.copy()
        for _ in range(gamma):
            if (done | (lens >= t_total)).all():
                break
            cur = int(lens[~done].max())
            am = greedy_next(draft_graph, draft_params, cur,
                             draft_num_stages, draft_cut_points)
            stats["draft_forwards"] += 1
            for i in range(b):
                if done[i] or lens[i] >= t_total:
                    continue
                out[i, lens[i]] = am[i, lens[i] - 1]
                lens[i] += 1
        # --- target verifies the whole block in ONE pipelined forward
        cur = int(lens[~done].max())
        tm = greedy_next(target_graph, target_params, cur, num_stages,
                         cut_points)
        stats["target_forwards"] += 1
        for i in range(b):
            if done[i]:
                continue
            n_prop = int(lens[i] - base[i])
            stats["proposed"] += n_prop
            acc = 0
            pos = int(base[i])
            # accept drafted tokens while they equal the target's greedy
            # choice given the (verified) prefix before them
            while acc < n_prop and out[i, pos] == tm[i, pos - 1]:
                acc += 1
                pos += 1
            stats["accepted"] += acc
            # first mismatch is REPLACED by the target's own token; full
            # acceptance earns the bonus token from the same forward
            if pos < t_total:
                out[i, pos] = tm[i, pos - 1]
                pos += 1
            lens[i] = pos
            out[i, pos:] = 0  # drop rejected draft tail
            if eos_id is not None:
                hits = np.where(out[i, plen:pos] == eos_id)[0]
                if hits.size:
                    stop = plen + int(hits[0]) + 1
                    out[i, stop:] = eos_id
                    lens[i] = t_total
                    done[i] = True
        lens = np.minimum(lens, t_total)

    if return_stats:
        stats["accept_rate"] = (stats["accepted"] / stats["proposed"]
                                if stats["proposed"] else 0.0)
        return out, stats
    return out
