"""SPMD pipeline engine: the TPU-native heart of the framework.

What the reference does with a chain of TCP-connected hosts — each node
receives an activation, runs ``model.predict`` on its partition, compresses
and relays to its successor (reference src/node.py:80-108), with the
dispatcher feeding node 0 and receiving from node N-1
(src/dispatcher.py:85-105) — this engine does inside a single jit-compiled
SPMD program over a ``stage`` mesh axis:

  * Each device holds exactly its stage's weights (sharded flat buffer, no
    runtime weight shipping — replaces the control plane of
    src/dispatcher.py:44-65).
  * Per pipeline step every device runs its stage via ``lax.switch`` on its
    stage index, then ``lax.ppermute``s its activation to its successor over
    ICI — the TPU-native "send to next node" (src/node.py:108).  The wrap
    link (stage N-1 → stage 0) is the reference's "last node points back at
    the dispatcher" (src/dispatcher.py:51-55).
  * ``lax.scan`` fuses many steps into one XLA program, so the whole
    streaming loop (recv → decompress → queue → predict → compress → send,
    reference §3.3) collapses to compute + collective with zero host-side
    tensor serialization.
  * Activations cross stages in one homogeneous padded buffer so the single
    program covers heterogeneous stage shapes; buffer dtype bfloat16 is the
    TPU-idiomatic analogue of the reference's lossy ZFP wire compression.

Schedule: inference (GPipe-style fill/drain-free streaming): at step t device
0 starts microbatch t, device k computes microbatch t-k, device N-1 emits
microbatch t-N+1.  After N-1 warmup steps every device is busy every step —
DEFER's "all stages process different in-flight inputs concurrently"
(SURVEY.md §0), with the in-flight window = pipeline depth.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..graph.ir import ShapeSpec
from ..obs import tracer
from ..parallel.mesh import DATA_AXIS, MODEL_AXIS, STAGE_AXIS, pipeline_mesh
from ..partition.stage import StageSpec, buffer_footprint
from ..utils.compat import shard_map
from ..utils.metrics import PipelineMetrics
from ..utils.xla_opts import ring_jit_kwargs
from . import flatbuf


class SpmdPipeline:
    """Inference pipeline over the ``stage`` axis of a device mesh.

    Usage::

        stages = partition(graph, cut_points)
        pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(len(stages)))
        outputs = pipe.run(inputs)          # [M, B, ...] -> [M, B, ...]

    or streaming: ``reset()`` / ``push(chunk, n_real)`` / ``flush()``.
    """

    def __init__(
        self,
        stages: Sequence[StageSpec],
        params: dict[str, Any],
        *,
        mesh: Mesh | None = None,
        microbatch: int = 1,
        chunk: int = 16,
        buffer_dtype=jnp.float32,
        compute_dtype=None,
        wire: str = "buffer",
        master_weights: bool = False,
    ):
        self.stages = list(stages)
        self.num_stages = n = len(self.stages)
        self.mesh = mesh if mesh is not None else pipeline_mesh(n)
        if self.mesh.shape[STAGE_AXIS] != n:
            raise ValueError(
                f"mesh stage axis is {self.mesh.shape[STAGE_AXIS]} but "
                f"pipeline has {n} stages")
        self.data_parallel = self.mesh.shape.get(DATA_AXIS, 1)
        self.tensor_parallel = tp = self.mesh.shape.get(MODEL_AXIS, 1)
        if microbatch % self.data_parallel:
            raise ValueError("microbatch must divide by data_parallel")
        self.microbatch = microbatch
        self.chunk = chunk
        self.buffer_dtype = jnp.dtype(buffer_dtype)
        self.compute_dtype = jnp.dtype(compute_dtype) if compute_dtype else None

        # --- weights: one flat vector per stage (per TP rank when the
        # mesh has a "model" axis), padded & stacked to [N, (tp,) Pmax] and
        # sharded over (stage[, model]).  Each device materializes only its
        # own stage's — and, under TP, its own rank's — parameters.  The
        # buffer is stored in ``compute_dtype`` when set (bf16 deployments
        # hold bf16 weights in HBM — half the footprint, no per-step
        # recast inside the branch); float32 otherwise.
        # ``master_weights=True`` keeps the buffer f32 regardless and casts
        # to compute_dtype inside each stage branch — the mixed-precision
        # training recipe (optimizer updates land in full precision; XLA
        # fuses the per-step downcast into the stage program).
        self.master_weights = bool(master_weights)
        self.weight_dtype = wdt = np.dtype(
            self.compute_dtype
            if self.compute_dtype is not None and not self.master_weights
            else np.float32)
        self._wmeta: list[list[tuple[int, int, tuple[int, ...], Any]]] = []
        self._wtreedef = []
        #: per stage, per leaf: True when the leaf is REPLICATED across tp
        #: ranks (its shard shape equals the full leaf's shape) — the
        #: trainer needs this to sum tied-copy gradients across ranks
        self._wreplicated: list[list[bool]] = []
        self._wspec = P(STAGE_AXIS, MODEL_AXIS, None) if tp > 1 \
            else P(STAGE_AXIS, None)
        self._w = jax.device_put(self._pack_wbuf(params, init=True),
                                 NamedSharding(self.mesh, self._wspec))

        # --- homogeneous activation buffer sizing (shared geometry
        # helper: under wire="int8" the buffer pads to the quant block
        # size so hops block-quantize cleanly in HBM)
        if wire not in ("buffer", "int8"):
            raise ValueError(f"wire must be 'buffer' or 'int8', got {wire!r}")
        self.wire = wire
        self._in_sizes = [s.in_spec.size for s in self.stages]
        self._out_sizes = [s.out_spec.size for s in self.stages]
        self._footprint = buffer_footprint(
            self.stages, microbatch=microbatch,
            itemsize=self.buffer_dtype.itemsize, wire=wire)
        self.buf_elems = self._footprint["buf_elems"]
        self.in_spec: ShapeSpec = self.stages[0].in_spec
        self.out_spec: ShapeSpec = self.stages[-1].out_spec

        self._branches = [self._make_branch(k) for k in range(n)]
        self._chunk_fn = self._build_chunk_fn()

        self._act_sharding = NamedSharding(
            self.mesh, P(STAGE_AXIS, DATA_AXIS, None)
            if self.data_parallel > 1 else P(STAGE_AXIS, None, None))
        self._xs_sharding = NamedSharding(
            self.mesh, P(None, DATA_AXIS, None)
            if self.data_parallel > 1 else P(None, None, None))

        if (jnp.issubdtype(self.in_spec.dtype, jnp.integer)
                and self.buffer_dtype != jnp.float32):
            raise ValueError(
                "integer model inputs (e.g. token ids) require "
                "buffer_dtype=float32: ids above 256 are not exactly "
                f"representable in {self.buffer_dtype.name}")

        self.metrics = PipelineMetrics(
            num_stages=n, microbatch=microbatch, buffer_elems=self.buf_elems,
            buffer_bytes_per_hop=self._footprint["bytes_per_hop"])
        # telemetry: publish this deployment into the process registry
        # (scalar counters + push/stage histograms + derived per-hop
        # bytes-on-wire — the ICI-side wire accounting)
        self.metrics.bind()
        self._flush_zeros = None  # lazy device-resident bubble block
        self.reset()

    # ------------------------------------------------------------------
    # program construction
    # ------------------------------------------------------------------

    def _to_wire(self, leaf: np.ndarray, stage_name: str) -> np.ndarray:
        """Cast one param leaf into the flat weight buffer's dtype.

        Float leaves simply cast (lossy to bf16 is the deployment's choice).
        Integer/bool leaves are only accepted when they round-trip exactly
        through the buffer dtype — the reference ships raw per-dtype arrays
        (src/dispatcher.py:67-80) so it never has this hazard; the flat
        homogeneous buffer does, and silently corrupted int params (e.g.
        embedding ids) would be far worse than a loud error here.
        """
        wdt = self.weight_dtype
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf.astype(wdt)
        cast = leaf.astype(wdt)
        if not np.array_equal(cast.astype(leaf.dtype), leaf):
            raise ValueError(
                f"stage {stage_name!r} has a non-float param leaf "
                f"(dtype {leaf.dtype}) whose values do not survive the "
                f"{wdt} weight buffer; use compute_dtype=None (float32 "
                f"buffer, exact for |int| < 2**24) or keep such leaves "
                f"out of the flat buffer")
        return cast

    def _pack_wbuf(self, params, *, init: bool = False) -> np.ndarray:
        """Pack ``params`` into the [N, (tp,) Pmax] flat weight buffer.

        ``init=True`` (constructor) records per-stage leaf meta/treedefs;
        ``init=False`` (reweight) validates the new leaves against the
        recorded layout — same shapes or a loud error.
        """
        tp = self.tensor_parallel
        n = self.num_stages
        wdt = self.weight_dtype
        flats: list[list[np.ndarray]] = []  # [stage][tp_rank]
        for k, s in enumerate(self.stages):
            rank_flats = []
            full_shapes = None
            if tp > 1:
                full_shapes = [np.shape(l) for l in
                               jax.tree.flatten(s.select_params(params))[0]]
            for r in range(tp):
                shard = (s.tp_shard_params(params, tp, r) if tp > 1
                         else s.select_params(params))
                leaves, treedef = jax.tree.flatten(shard)
                if r == 0:
                    if init:
                        self._wmeta.append(flatbuf.leaf_meta(leaves))
                        self._wtreedef.append(treedef)
                        self._wreplicated.append(
                            [np.shape(l) == fs for l, fs
                             in zip(leaves, full_shapes)]
                            if full_shapes is not None
                            else [True] * len(leaves))
                    else:
                        # the compiled branches unflatten with the INIT-
                        # recorded treedef/shapes/dtypes: all three must
                        # match or the program would serve garbage
                        flatbuf.check_layout(
                            leaves, treedef, self._wmeta[k],
                            self._wtreedef[k], f"reweight: stage {s.name!r}")
                rank_flats.append(flatbuf.pack_leaves(
                    leaves, wdt,
                    cast_fn=lambda a, _nm=s.name: self._to_wire(a, _nm)))
            flats.append(rank_flats)
        if tp > 1:
            rows = [f for rf in flats for f in rf]
            return flatbuf.stack_rows(rows, wdt).reshape(n, tp, -1)
        return flatbuf.stack_rows([rf[0] for rf in flats], wdt)

    def reweight(self, params) -> None:
        """Install fresh weights into the live pipeline — no recompile.

        The SPMD analogue of the chain's weights-only re-push
        (``ChainDispatcher.reweight``): the new params (same graph, same
        leaf shapes) are packed into a fresh flat buffer and placed with
        the existing sharding; the compiled chunk program is reused as-is.
        Microbatches still inside the pipe run their REMAINING stages
        under the new weights (mixed-generation execution) — call
        ``flush()`` first when a clean cut matters.
        """
        wbuf = self._pack_wbuf(params, init=False)
        if wbuf.shape != self._w.shape:
            raise ValueError(
                f"reweight: packed buffer {wbuf.shape} != deployed "
                f"{self._w.shape} (stage boundaries changed?)")
        self._w = jax.device_put(
            wbuf, NamedSharding(self.mesh, self._wspec))

    def _make_branch(self, k: int):
        stage = self.stages[k]
        meta = self._wmeta[k]
        treedef = self._wtreedef[k]
        in_sz, out_sz = self._in_sizes[k], self._out_sizes[k]
        in_shape, in_dtype = stage.in_spec.shape, stage.in_spec.dtype
        pad = self.buf_elems - out_sz
        cd = self.compute_dtype
        x_dtype = (cd if cd is not None and jnp.issubdtype(in_dtype, jnp.floating)
                   else in_dtype)

        tp = self.tensor_parallel

        def leaf_dtype(dtype):
            # under compute_dtype, float leaves cast to the compute dtype
            # (a no-op when the buffer already stores it; the per-step
            # downcast under master_weights — fused by XLA); otherwise
            # every leaf restores its exact original dtype
            if cd is not None and jnp.issubdtype(dtype, jnp.floating):
                return cd
            return dtype

        def branch(w_local, a_local):
            p = flatbuf.unpack_leaves(w_local, meta, treedef, leaf_dtype)
            b = a_local.shape[0]
            x = a_local[:, :in_sz].reshape((b,) + in_shape).astype(x_dtype)
            y = stage.fn(p, x, tp_axis=MODEL_AXIS if tp > 1 else None, tp=tp)
            y = y.reshape(b, out_sz).astype(self.buffer_dtype)
            if pad:
                y = jnp.pad(y, ((0, 0), (0, pad)))
            return y

        return branch

    def _build_chunk_fn(self):
        n = self.num_stages
        perm = [(k, (k + 1) % n) for k in range(n)]
        branches = self._branches
        has_dp = self.data_parallel > 1
        has_tp = self.tensor_parallel > 1

        int8_wire = self.wire == "int8"
        if int8_wire:
            from ..ops.quant import quantized_ring_hop
        buffer_dtype = self.buffer_dtype
        out_sz_last = self._out_sizes[-1]

        def device_chunk(w, a0, xs):
            # local shapes: w [1, (1,) Pmax], a0 [1, Blocal, L],
            # xs [T, Blocal, L]
            w_l = w[0, 0] if has_tp else w[0]
            idx = lax.axis_index(STAGE_AXIS)

            def body(a, x):
                # inject fresh input at stage 0 (the dispatcher feeding node
                # 0, reference src/dispatcher.py:90-93), compute my stage,
                # relay to successor over ICI (src/node.py:103-108)
                a = jnp.where(idx == 0, x, a)
                y = lax.switch(idx, branches, w_l, a)
                if int8_wire:
                    # quantize the hop in HBM: ICI carries ~1 byte/value
                    # (the ZFP-wire analogue, SURVEY.md §2.2)
                    y_next = quantized_ring_hop(y, STAGE_AXIS, perm,
                                                buffer_dtype)
                else:
                    y_next = lax.ppermute(y, STAGE_AXIS, perm)
                # per-step output: only the slice the dispatcher reads —
                # what stage N-1 just delivered to device 0 (reference
                # src/dispatcher.py:102-105).  Emitting the whole buffer
                # here made XLA stack [T, B, buf_elems] per device (~100 MB
                # of dead stores per ResNet50 chunk) when only device 0's
                # first out_sz_last columns are ever read.
                return y_next, lax.slice_in_dim(y_next, 0, out_sz_last, axis=1)

            a_t, outs = lax.scan(body, a0[0], xs)
            return a_t[None], outs[None]

        bspec = P(STAGE_AXIS, DATA_AXIS, None) if has_dp \
            else P(STAGE_AXIS, None, None)
        xspec = P(None, DATA_AXIS, None) if has_dp else P(None, None, None)
        ospec = P(STAGE_AXIS, None, DATA_AXIS, None) if has_dp \
            else P(STAGE_AXIS, None, None, None)

        fn = shard_map(
            device_chunk, mesh=self.mesh,
            in_specs=(self._wspec, bspec, xspec),
            out_specs=(bspec, ospec),
            check_vma=False,
        )
        return jax.jit(fn, donate_argnums=(1,),
                       **ring_jit_kwargs(self.mesh.devices))

    # ------------------------------------------------------------------
    # streaming interface
    # ------------------------------------------------------------------

    def reset(self):
        """Empty the pipe (all stages hold bubbles)."""
        self._a = jax.device_put(
            jnp.zeros((self.num_stages, self.microbatch, self.buf_elems),
                      self.buffer_dtype), self._act_sharding)
        self._step = 0
        self._fed = 0
        self._real: collections.deque[bool] = collections.deque()
        self._emitted = 0

    def _flatten_inputs(self, xs, staged: bool = False) -> jax.Array:
        if (isinstance(xs, jax.Array) and xs.ndim == 3
                and xs.shape[1:] == (self.microbatch, self.buf_elems)
                and xs.dtype == self.buffer_dtype):
            return xs  # already staged via stage_inputs()
        if staged:
            # host block already in transfer-buffer layout (e.g. drained
            # from the native staging ring): one straight device copy.
            # Opt-in only — a mis-shaped user input that coincidentally
            # matched [C, microbatch, buf_elems] must NOT skip validation.
            xs = np.asarray(xs)
            if xs.ndim != 3 or xs.shape[1:] != (self.microbatch,
                                                self.buf_elems):
                raise ValueError(
                    f"staged block must be [C, {self.microbatch}, "
                    f"{self.buf_elems}], got {xs.shape}")
            return jax.device_put(xs.astype(self.buffer_dtype, copy=False),
                                  self._xs_sharding)
        c = xs.shape[0]
        flat = np.asarray(xs, np.float32).reshape(c, self.microbatch, -1)
        if flat.shape[-1] != self._in_sizes[0]:
            raise ValueError(
                f"input sample size {flat.shape[-1]} != stage-0 input "
                f"size {self._in_sizes[0]}")
        buf = np.zeros((c, self.microbatch, self.buf_elems), np.float32)
        buf[..., : flat.shape[-1]] = flat
        return jax.device_put(buf.astype(self.buffer_dtype),
                              self._xs_sharding)

    def stage_inputs(self, xs: np.ndarray) -> jax.Array:
        """Pre-stage a [C, microbatch, *in_shape] host block on device.

        ``push`` accepts the result directly, skipping the host flatten +
        transfer on the hot path — the analogue of the single-device
        baseline keeping its input resident (reference test/local_infer.py
        reuses one device tensor per predict call)."""
        return self._flatten_inputs(np.asarray(xs))

    def push(self, xs: np.ndarray, n_real: int | None = None, *,
             staged: bool = False, raw: bool = False):
        """Advance the pipe by ``xs.shape[0]`` steps, feeding ``xs``.

        ``xs``: [C, microbatch, *in_shape] host array, or a device block
        from ``stage_inputs``.  ``n_real`` marks how many leading entries
        are real inputs (the rest are bubble padding).  ``staged=True``
        declares a host block already in transfer-buffer layout
        ``[C, microbatch, buf_elems]`` (e.g. drained from the native
        staging ring) — the explicit opt-in for skipping per-sample size
        validation.  Returns the list of completed output microbatches
        (jax arrays of shape [microbatch, *out_shape]), in feed order.

        ``raw=True`` returns ``(slab, real_mask)`` instead: one lazy device
        array ``[n_completed, microbatch, out_size]`` of every microbatch
        that completed this chunk (bubbles included) plus a bool mask of
        which entries are real.  One device slice per chunk instead of one
        per step — the hot-path drain for benchmarks and bulk serving.
        """
        c = xs.shape[0]
        if n_real is None:
            n_real = c
        xs_dev = self._flatten_inputs(xs, staged=staged)
        t0 = time.perf_counter()
        self._a, outs = self._chunk_fn(self._w, self._a, xs_dev)
        self.metrics.chunk_calls += 1
        self.metrics.steps += c
        self._real.extend([True] * n_real + [False] * (c - n_real))
        self._fed += c

        ready = self._collect(outs, c, raw=raw)
        dt = time.perf_counter() - t0
        self.metrics.wall_s += dt
        self.metrics.push_latency.record(dt)
        tr = tracer()
        if tr.enabled:
            tr.record("spmd.push", t0, dt, {"chunk": c, "n_real": n_real})
        return ready

    def _collect(self, outs, c: int, raw: bool = False):
        """Map step outputs back to microbatch indices and drop bubbles."""
        n = self.num_stages
        out_shape = (self.microbatch,) + self.out_spec.shape
        # outs[0] is device-0's [T, B, out_sz_last] slice: what arrived at
        # "the dispatcher" each step (reference src/dispatcher.py:102-105);
        # the scan body already cropped it to the final stage's output size
        outs0 = outs[0]
        # steps j in this chunk completing a microbatch m = _step+j-(n-1)
        # with 0 <= m < _fed form one contiguous local range [j0, j1)
        j0 = max(0, (n - 1) - self._step)
        j1 = min(c, self._fed + (n - 1) - self._step)
        cnt = max(0, j1 - j0)
        if cnt:  # outputs complete strictly in feed order
            assert self._step + j0 - (n - 1) == self._emitted, \
                (self._step, j0, n, self._emitted)
        self._step += c

        if raw:
            mask = np.empty(cnt, bool)
            for i in range(cnt):
                mask[i] = self._real.popleft()
            self._emitted += cnt
            self.metrics.inferences += int(mask.sum()) * self.microbatch
            slab = outs0[j0:j1] if cnt else None  # lazy: ONE device slice
            return slab, mask

        emitted = []
        for j in range(j0, j1):
            is_real = self._real.popleft()
            self._emitted += 1
            if is_real:
                self.metrics.inferences += self.microbatch
                emitted.append(outs0[j].reshape(out_shape))
        return emitted

    def _bubble_block(self) -> jax.Array:
        """Cached device-resident all-bubble [chunk, ...] input block."""
        if self._flush_zeros is None:
            self._flush_zeros = self.stage_inputs(
                np.zeros((self.chunk, self.microbatch) + self.in_spec.shape,
                         np.float32))
        return self._flush_zeros

    def warmup(self):
        """Compile-and-run the exact full-chunk program that will serve
        traffic, on bubbles, leaving the pipe empty.

        The one probe recipe shared by ``Defer.health_check`` and the
        dispatcher's preflight — and it seeds the same cached bubble block
        ``flush`` drains with, so no extra host transfer."""
        self.reset()
        self.push(self._bubble_block(), n_real=0)
        self.reset()

    def flush(self):
        """Drain the pipe: run bubble steps until every fed microbatch has
        emerged (the fill/drain of the classic pipeline schedule).

        Always pushes full-chunk bubble blocks (cached, device-resident) so
        draining reuses the already-compiled [chunk, ...] program — a
        partial-size push would trigger a fresh XLA compile."""
        emitted = []
        target = self._fed  # overshoot bubbles beyond this are just ignored
        block = self._bubble_block()
        while self._emitted < target:
            emitted.extend(self.push(block, n_real=0))
        return emitted

    # ------------------------------------------------------------------
    # batch convenience
    # ------------------------------------------------------------------

    def run(self, inputs: np.ndarray) -> np.ndarray:
        """Feed [M, microbatch, *in_shape]; return [M, microbatch, *out]."""
        inputs = np.asarray(inputs)
        m = inputs.shape[0]
        if inputs.shape[1] != self.microbatch:
            raise ValueError(
                f"inputs microbatch dim {inputs.shape[1]} != {self.microbatch}")
        self.reset()
        outs = []
        for lo in range(0, m, self.chunk):
            hi = min(lo + self.chunk, m)
            block = inputs[lo:hi]
            n_real = hi - lo
            if n_real < self.chunk:
                pad = np.zeros((self.chunk - n_real,) + block.shape[1:],
                               block.dtype)
                block = np.concatenate([block, pad], 0)
            outs.extend(self.push(block, n_real=n_real))
        outs.extend(self.flush())
        assert len(outs) == m, (len(outs), m)
        arr = jnp.stack(outs)
        return np.asarray(jax.device_get(arr), np.float32)

    def __call__(self, inputs: np.ndarray) -> np.ndarray:
        return self.run(inputs)

    # ------------------------------------------------------------------
    # diagnostics
    # ------------------------------------------------------------------

    @property
    def hop_utilization(self) -> list[float]:
        """Fraction of the homogeneous ``buf_elems`` hop buffer each
        stage->successor boundary actually carries (hop k = stage k's
        output; the last entry is the wrap link back to "the dispatcher").
        The padded-buffer waste diagnostic: every ``ppermute`` hop and
        every ``xs`` transfer pays ``buf_elems`` regardless."""
        return list(self._footprint["hop_utilization"])

    def stage_latencies(self, params: dict[str, Any] | None = None,
                        iters: int = 10):
        """Per-stage device latency (seconds) of the *deployed* program.

        Times each stage's compiled branch — the same function the pipeline
        scan dispatches — so the numbers reflect the deployment's compute
        dtype, weight-buffer storage dtype, and (under TP) the Megatron
        sharding, not a pristine f32 re-jit.  ``params`` is accepted for
        backward compatibility but unused: the branch reads the pipeline's
        own staged weight buffer.
        """
        del params  # weights come from the deployed buffer
        lats = []
        tp = self.tensor_parallel
        tp_mesh = None
        if tp > 1:
            # submesh of the model axis: the tp devices hosting stage 0's
            # ranks (any stage's rank group is equivalent for timing)
            ax = list(self.mesh.axis_names)
            devs = self.mesh.devices
            sl = tuple(slice(None) if a == MODEL_AXIS else slice(0, 1)
                       for a in ax)
            tp_devs = devs[sl].reshape((tp,))
            tp_mesh = Mesh(tp_devs, (MODEL_AXIS,))
        for k in range(self.num_stages):
            branch = self._branches[k]
            a = jnp.zeros((self.microbatch, self.buf_elems),
                          self.buffer_dtype)
            # slice this stage's row on device — no full-buffer host
            # round-trip (the buffer is the whole model's weights)
            if tp_mesh is not None:
                w_k = jax.device_put(
                    self._w[k], NamedSharding(tp_mesh, P(MODEL_AXIS, None)))
                fn = jax.jit(shard_map(
                    lambda w, a: branch(w[0], a), mesh=tp_mesh,
                    in_specs=(P(MODEL_AXIS, None), P(None, None)),
                    out_specs=P(None, None), check_vma=False))
            else:
                w_k = self._w[k]  # [Pmax]
                fn = jax.jit(branch)
            fn(w_k, a).block_until_ready()  # compile
            t0 = time.perf_counter()
            for _ in range(iters):
                y = fn(w_k, a)
            y.block_until_ready()
            lat = (time.perf_counter() - t0) / iters
            lats.append(lat)
            self.metrics.record_stage_latency(k, lat)
            tr = tracer()
            if tr.enabled:
                tr.record(f"stage{k}:{self.stages[k].name}", t0,
                          time.perf_counter() - t0,
                          {"stage": k, "mean_latency_s": lat,
                           "iters": iters})
        self.metrics.stage_latency_s = lats
        return lats
