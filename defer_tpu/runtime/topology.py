"""First-class chain topology: the process graph a deployment spawns.

Until the DAG work, ``deploy``/``run_chain`` wired the process topology
implicitly — a list of stages was a chain, replica lists fanned out, and
that was the whole vocabulary.  A :class:`ChainTopology` makes the shape
explicit: a validated DAG of stage VERTICES, each naming its slice of
the layer graph, its downstream vertices, and its transport role
(unicast relay, per-seq broadcast fork, or all-paths join).  The DAG
planner emits one (``plan/dag.py``, the plan JSON's ``topology`` field),
``ChainDispatcher.deploy_topology`` ships it, and ``run_dag_chain``
spawns it — the same object end to end, so a plan file IS a deployable
topology.

Schema (``to_json`` / ``from_json``, documented in docs/PLANNER.md)::

    {"format": "defer_tpu.topology.v1",
     "vertices": [
       {"id": 0, "nodes": [...], "inputs": ["input"],
        "output": "stem_pool2", "next": [1, 2], "fan": "broadcast",
        "join": 0, "branch": null, "codec": "raw"},
       ...]}

Invariants ``validate`` enforces: exactly one entry (the dispatcher
feeds it) and one exit (it dials the result server); edges topological
(``next`` ids strictly increase — vertex order is a topo order);
``fan="broadcast"`` iff a vertex has several downstreams (round-robin
replica fan-out is the LINEAR deploy path's business, not a topology
vertex's); every join's in-degree equals its ``join`` count with
distinct path labels 0..P-1; and join/broadcast never mix with
replication — the ordered fan machinery owns the wire there.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

TOPOLOGY_FORMAT = "defer_tpu.topology.v1"


@dataclasses.dataclass(frozen=True)
class TopoVertex:
    """One deployed stage of a branched (or linear) pipeline."""

    vid: int
    nodes: tuple[str, ...]        #: layer-graph nodes this stage evaluates
    inputs: tuple[str, ...]       #: seed boundary tensors (P for a join)
    output: str                   #: boundary tensor this stage emits
    next: tuple[int, ...]         #: downstream vertex ids; () = result hop
    fan: str = "unicast"          #: "unicast" | "broadcast"
    join: int = 0                 #: >= 2: merge this many labeled paths
    branch: int | None = None     #: path index inside a fork/join region
    codec: str = "raw"            #: outbound hop codec

    @property
    def label(self) -> str:
        """Span/stats label: ``stageK`` or ``stageK.bJ`` for a branch
        vertex (docs/OBSERVABILITY.md)."""
        base = f"stage{self.vid}"
        return base if self.branch is None else f"{base}.b{self.branch}"

    def to_json(self) -> dict:
        return {"id": self.vid, "nodes": list(self.nodes),
                "inputs": list(self.inputs), "output": self.output,
                "next": list(self.next), "fan": self.fan,
                "join": self.join, "branch": self.branch,
                "codec": self.codec}


class ChainTopology:
    """A validated stage-graph deployment plan (see module docstring)."""

    def __init__(self, vertices: Sequence[TopoVertex]):
        self.vertices = list(vertices)
        self.validate()

    def __len__(self) -> int:
        return len(self.vertices)

    def __iter__(self):
        return iter(self.vertices)

    @property
    def entry(self) -> TopoVertex:
        return self.vertices[0]

    @property
    def exit(self) -> TopoVertex:
        return self.vertices[-1]

    def upstreams(self, vid: int) -> list[TopoVertex]:
        return [v for v in self.vertices if vid in v.next]

    def path_of_edge(self, up: TopoVertex, vid: int) -> int | None:
        """The join-path label an edge ``up -> vid`` carries: the
        upstream's own branch index, or — for a direct fork->join edge
        (an empty branch / residual skip) — its position in the fork's
        broadcast list."""
        if up.branch is not None:
            return up.branch
        if up.fan == "broadcast":
            return up.next.index(vid)
        return None

    def validate(self) -> None:
        vs = self.vertices
        if not vs:
            raise ValueError("topology has no vertices")
        ids = [v.vid for v in vs]
        if ids != list(range(len(vs))):
            raise ValueError(f"vertex ids must be 0..{len(vs) - 1} in "
                             f"order, got {ids}")
        exits = [v for v in vs if not v.next]
        if len(exits) != 1 or exits[0] is not vs[-1]:
            raise ValueError("topology needs exactly one exit vertex "
                             "(empty `next`), and it must come last")
        indeg = {v.vid: 0 for v in vs}
        for v in vs:
            if v.fan not in ("unicast", "broadcast"):
                raise ValueError(f"vertex {v.vid}: fan must be "
                                 f"unicast|broadcast, got {v.fan!r}")
            if (len(v.next) > 1) != (v.fan == "broadcast"):
                raise ValueError(
                    f"vertex {v.vid}: {len(v.next)} downstreams with "
                    f"fan={v.fan!r} — broadcast exactly when fanning to "
                    f"parallel branches")
            for n in v.next:
                if not (v.vid < n < len(vs)):
                    raise ValueError(f"vertex {v.vid}: next {n} is not a "
                                     f"later vertex id")
                indeg[n] += 1
        entries = [v for v in vs if indeg[v.vid] == 0]
        if len(entries) != 1 or entries[0] is not vs[0]:
            raise ValueError("topology needs exactly one entry vertex "
                             "(no upstreams), and it must come first")
        for v in vs:
            if v.join >= 2:
                if len(v.inputs) != v.join:
                    raise ValueError(
                        f"join vertex {v.vid} merges {v.join} paths but "
                        f"seeds {len(v.inputs)} inputs")
                labels = []
                for u in self.upstreams(v.vid):
                    p = self.path_of_edge(u, v.vid)
                    if p is None:
                        raise ValueError(
                            f"join vertex {v.vid}: upstream vertex "
                            f"{u.vid} carries no path label — join "
                            f"inputs must arrive from a branch member "
                            f"or a broadcast fork")
                    labels.append(p)
                paths = sorted(labels)
                if paths != list(range(v.join)):
                    raise ValueError(
                        f"join vertex {v.vid} needs one labeled upstream "
                        f"per path 0..{v.join - 1}, got {paths}")
            elif indeg[v.vid] > 1:
                raise ValueError(f"vertex {v.vid} has {indeg[v.vid]} "
                                 f"upstreams but join={v.join}")

    # -- mutation (live replan, docs/ROBUSTNESS.md) -------------------------

    def update(self, vid: int, **changes) -> TopoVertex:
        """Mutate one vertex in place (``dataclasses.replace`` on the
        frozen vertex, swapped into the list) and revalidate the whole
        graph.  A change that breaks an invariant is ROLLED BACK before
        the ``ValueError`` propagates — a topology object is never left
        observably invalid, because a live replan hands it straight to
        ``deploy_topology``."""
        if not 0 <= vid < len(self.vertices):
            raise ValueError(f"no vertex {vid} in {self!r}")
        old = self.vertices[vid]
        new = dataclasses.replace(old, **changes)
        self.vertices[vid] = new
        try:
            self.validate()
        except ValueError:
            self.vertices[vid] = old
            raise
        return new

    def move_boundary(self, vid: int, *, nodes, output: str,
                      downstream_nodes, downstream_inputs) -> None:
        """Shift the cut between vertex ``vid`` and ``vid + 1``: the
        upstream vertex now evaluates ``nodes`` and emits ``output``;
        the downstream evaluates ``downstream_nodes`` seeded by
        ``downstream_inputs``.  This is the replanner's one move —
        migrating layer-graph nodes across an adjacent boundary —
        expressed as a single atomic topology edit."""
        if vid + 1 >= len(self.vertices):
            raise ValueError(f"vertex {vid} has no downstream boundary")
        up_old, dn_old = self.vertices[vid], self.vertices[vid + 1]
        self.vertices[vid] = dataclasses.replace(
            up_old, nodes=tuple(nodes), output=output)
        self.vertices[vid + 1] = dataclasses.replace(
            dn_old, nodes=tuple(downstream_nodes),
            inputs=tuple(downstream_inputs))
        try:
            self.validate()
        except ValueError:
            self.vertices[vid] = up_old
            self.vertices[vid + 1] = dn_old
            raise

    def diff(self, other: "ChainTopology") -> dict:
        """Structural delta ``self -> other``: which vertex ids changed,
        appeared, or vanished.  A live replan redeploys EXACTLY
        ``changed + added`` — untouched stages keep their loaded
        artifact across the cutover."""
        mine = {v.vid: v.to_json() for v in self.vertices}
        theirs = {v.vid: v.to_json() for v in other.vertices}
        return {
            "changed": sorted(vid for vid in mine.keys() & theirs.keys()
                              if mine[vid] != theirs[vid]),
            "added": sorted(theirs.keys() - mine.keys()),
            "removed": sorted(mine.keys() - theirs.keys()),
        }

    def copy(self) -> "ChainTopology":
        """Deep-enough copy: vertices are frozen, the list is fresh —
        mutate the copy, diff against the original."""
        return ChainTopology(list(self.vertices))

    # -- (de)serialization --------------------------------------------------

    def to_json(self) -> dict:
        return {"format": TOPOLOGY_FORMAT,
                "vertices": [v.to_json() for v in self.vertices]}

    @classmethod
    def from_json(cls, doc: dict) -> "ChainTopology":
        """Accepts a bare topology dict, a DAG plan's ``to_json``, or a
        whole ``plan --dag --json`` document."""
        doc = doc.get("plan", doc)
        doc = doc.get("topology", doc)
        if doc.get("format") != TOPOLOGY_FORMAT:
            raise ValueError(f"not a {TOPOLOGY_FORMAT} document "
                             f"(format={doc.get('format')!r})")
        vs = [TopoVertex(vid=int(d["id"]), nodes=tuple(d["nodes"]),
                         inputs=tuple(d["inputs"]), output=d["output"],
                         next=tuple(int(n) for n in d["next"]),
                         fan=d.get("fan", "unicast"),
                         join=int(d.get("join", 0)),
                         branch=(None if d.get("branch") is None
                                 else int(d["branch"])),
                         codec=d.get("codec", "raw"))
              for d in doc["vertices"]]
        return cls(vs)

    @classmethod
    def linear(cls, stages, *, codecs: Sequence[str] | None = None
               ) -> "ChainTopology":
        """The chain special case: every ``StageSpec`` a unicast vertex —
        what ``run_chain``'s implicit wiring has always meant, now as a
        first-class object."""
        vs = []
        n = len(stages)
        for i, s in enumerate(stages):
            vs.append(TopoVertex(
                vid=i, nodes=tuple(s.node_names),
                inputs=(s.input_name,), output=s.output_name,
                next=(i + 1,) if i + 1 < n else (),
                codec=codecs[i] if codecs else "raw"))
        return cls(vs)

    # -- stage building -----------------------------------------------------

    def stage_specs(self, graph) -> list:
        """One ``StageSpec``/``JoinStageSpec`` per vertex (vertex order)
        — what ``deploy_topology``/``run_dag_chain`` export and ship."""
        from ..partition.partitioner import stage_specs_for_vertices
        return stage_specs_for_vertices(graph, self.vertices)

    def __repr__(self):
        joins = sum(1 for v in self.vertices if v.join >= 2)
        forks = sum(1 for v in self.vertices if v.fan == "broadcast")
        return (f"ChainTopology({len(self.vertices)} vertices, "
                f"{forks} forks, {joins} joins)")
