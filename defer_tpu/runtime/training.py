"""Pipeline-parallel training over the SPMD inference engine.

The reference is inference-only (SURVEY.md §5: nothing to checkpoint,
weights shipped once — reference src/dispatcher.py:57).  This module goes
beyond parity: the same ``shard_map`` + ``lax.switch`` + ``lax.ppermute``
+ ``lax.scan`` chunk program the inference pipeline runs is simply
*differentiated* — JAX transposes the ``ppermute`` ring into the reverse
ring for the backward pass, so one ``jax.value_and_grad`` yields GPipe-style
pipeline-parallel training with zero bespoke backward scheduling:

  * forward: microbatch t enters stage 0 at step t; stage k computes
    microbatch t-k; losses accrue on device 0 as completed microbatches
    arrive (steps n-1 .. n-1+M-1);
  * backward: the transposed scan runs the ring in reverse — exactly the
    1F1B wavefront, scheduled by XLA rather than by hand;
  * weights and their gradients live in the SAME [N, Pmax] stage-sharded
    flat buffer the inference engine uses, so any elementwise optax
    optimizer applies shard-local with no resharding.

Memory: the scan body is wrapped in ``jax.checkpoint`` so the backward
rematerializes each step's stage compute instead of storing every
intermediate — the standard TPU trade of FLOPs for HBM.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.mesh import DATA_AXIS, MODEL_AXIS, STAGE_AXIS
from ..utils.compat import pcast_varying, shard_map
from .spmd import SpmdPipeline


class PipelineTrainer:
    """Train a model through an :class:`SpmdPipeline` deployment.

    ``loss_fn(logits, targets) -> scalar`` is applied per microbatch (it
    sees ``[microbatch/dp, *out_shape]`` logits per data-parallel shard),
    SUMMED over the chunk's completed microbatches, and AVERAGED across
    dp shards — so a mean-over-batch loss keeps per-sample scaling
    regardless of the dp factor.  ``optimizer`` is any optax-style
    gradient transformation; it runs directly on the stage-sharded flat
    weight buffer in one jitted fused update.

    Supports pp, pp x dp, and pp x tp meshes (the Megatron in-stage psums
    transpose correctly under autodiff).  ``wire="int8"`` pipelines train
    with a straight-through estimator on the quantized hop: the forward
    is exactly the deployment's quantized wire, the backward treats
    dequant∘quant as identity (cotangents still ride the reverse ring).
    """

    def __init__(self, pipe: SpmdPipeline, loss_fn: Callable,
                 optimizer=None):
        self.pipe = pipe
        self.loss_fn = loss_fn
        if optimizer is None:
            import optax
            optimizer = optax.sgd(1e-2)
        self.optimizer = optimizer
        #: compiled value_and_grad programs, keyed by the targets' rank
        #: (the target sharding spec must match ys's rank)
        self._loss_grad_cache: dict[int, Any] = {}
        self.opt_state = None  # lazily init'd on device from pipe._w
        self._fix_tp_grads = None
        if pipe.tensor_parallel > 1:
            # tied-copy gradient correction: a REPLICATED leaf exists once
            # per tp rank in the weight buffer, and value_and_grad hands
            # each copy only its own rank's partial (scaled 1/tp by the
            # loss pmean) — the correct tied-weight gradient is the SUM of
            # the copies' grads.  Sharded leaves are rank-owned: untouched.
            n, pmax = pipe._w.shape[0], pipe._w.shape[-1]
            rep = np.zeros((n, 1, pmax), bool)
            for k, (meta, flags) in enumerate(zip(pipe._wmeta,
                                                  pipe._wreplicated)):
                for (off, size, _shape, _dt), is_rep in zip(meta, flags):
                    if is_rep:
                        rep[k, 0, off: off + size] = True
            rep = jnp.asarray(rep)

            @jax.jit
            def fix(g):
                return jnp.where(rep, g.sum(axis=1, keepdims=True), g)

            self._fix_tp_grads = fix
        self._a0 = None        # cached sharded all-zeros activation block
        # one fused program per optimizer step instead of eager per-op
        # dispatches over the full weight buffer
        import optax

        @jax.jit
        def _apply(grads, opt_state, w):
            updates, opt_state = self.optimizer.update(grads, opt_state, w)
            return optax.apply_updates(w, updates), opt_state

        self._apply_updates = _apply

    # -- program construction ---------------------------------------------

    def _loss_grad(self, ys_ndim: int):
        if ys_ndim not in self._loss_grad_cache:
            self._loss_grad_cache[ys_ndim] = self._build_loss_grad(ys_ndim)
        return self._loss_grad_cache[ys_ndim]

    def _build_loss_grad(self, ys_ndim: int):
        pipe = self.pipe
        n = pipe.num_stages
        perm = [(k, (k + 1) % n) for k in range(n)]
        branches = pipe._branches
        has_dp = pipe.data_parallel > 1
        out_sz = pipe._out_sizes[-1]
        out_shape = pipe.out_spec.shape
        mb_local = pipe.microbatch // pipe.data_parallel
        loss_fn = self.loss_fn

        has_tp = pipe.tensor_parallel > 1

        if pipe.wire == "int8":
            # quantized hop with a straight-through estimator: forward
            # block-quantizes exactly like inference (the deployment being
            # trained IS the deployment that serves), backward treats
            # dequant∘quant as identity while still transposing the ring
            from ..ops.quant import quantized_ring_hop
            inv_perm = [(k, (k - 1) % n) for k in range(n)]
            buffer_dtype = pipe.buffer_dtype

            @jax.custom_vjp
            def hop(y):
                return quantized_ring_hop(y, STAGE_AXIS, perm,
                                          buffer_dtype)

            def _hop_fwd(y):
                return hop(y), None

            def _hop_bwd(_, g):
                return (lax.ppermute(g, STAGE_AXIS, inv_perm),)

            hop.defvjp(_hop_fwd, _hop_bwd)
        else:
            def hop(y):
                return lax.ppermute(y, STAGE_AXIS, perm)

        def device_chunk(w, a0, xs, ys, mask):
            # local: w [1, (1,) Pmax], a0 [1, B, L], xs [T, B, L],
            # ys [T, B, *target], mask [T].  Under tp each model rank runs
            # its own stage ring on its weight shard; in-stage psums make
            # activations (and hence the loss) replicated across ranks,
            # and their transposes route each rank's shard gradient — so
            # the same differentiation covers pp x tp x dp.
            w_l = w[0, 0] if has_tp else w[0]
            idx = lax.axis_index(STAGE_AXIS)

            @jax.checkpoint
            def body(a, xym):
                x, y, m = xym
                a = jnp.where(idx == 0, x, a)
                yhat = lax.switch(idx, branches, w_l, a)
                y_next = hop(yhat)
                # what arrived back at "the dispatcher" this step: a
                # completed microbatch (only device 0's copy is real).
                # Bubble steps are masked with where, not multiply: a
                # loss_fn that is non-finite on the zero padding must not
                # poison the chunk (nan * 0 == nan)
                out = lax.slice_in_dim(y_next, 0, out_sz, axis=1)
                step_loss = jnp.where(
                    m > 0,
                    loss_fn(out.reshape((mb_local,) + out_shape), y), 0.0)
                return y_next, step_loss

            a_init = a0[0]
            if has_tp:
                # the tp-rank rings produce replicated values the VMA
                # system types as model-varying; match the carry type
                a_init = pcast_varying(a_init, (MODEL_AXIS,))
            _a_t, losses = lax.scan(body, a_init, (xs, ys, mask))
            total = jnp.where(idx == 0, losses.sum(), 0.0)
            # replicate the scalar so every shard returns the same loss;
            # pmean over dp so a mean-over-batch loss_fn keeps per-sample
            # scaling regardless of the dp factor (moving to a wider dp
            # mesh must not silently scale the effective learning rate)
            total = lax.psum(total, STAGE_AXIS)
            if has_dp:
                total = lax.pmean(total, DATA_AXIS)
            if has_tp:
                # numerically identity (ranks hold the same loss); types
                # the scalar back to model-invariant for out_specs P()
                total = lax.pmean(total, MODEL_AXIS)
            return total

        bspec = P(STAGE_AXIS, DATA_AXIS, None) if has_dp \
            else P(STAGE_AXIS, None, None)
        xspec = P(None, DATA_AXIS, None) if has_dp else P(None, None, None)
        # ys is [T, microbatch, *target...]: shard the microbatch axis
        # under dp, replicate everything else, matched to ys's rank
        yspec = P(None, DATA_AXIS if has_dp else None,
                  *([None] * (ys_ndim - 2)))
        # NOTE check_vma=True (unlike the inference engine): replication
        # tracking is what makes the TRANSPOSE of the in-stage Megatron
        # psums correct — with it off, a replicated cotangent re-enters
        # psum and every tp-rank gradient double-counts
        fn = shard_map(
            device_chunk, mesh=pipe.mesh,
            in_specs=(pipe._wspec, bspec, xspec, yspec, P(None)),
            out_specs=P(),
            check_vma=True,
        )
        return jax.jit(jax.value_and_grad(fn))

    # -- stepping ----------------------------------------------------------

    def _schedule(self, xs: np.ndarray, ys: np.ndarray):
        """Lay out one self-contained chunk: M real inputs then n-1 bubble
        steps so every microbatch's loss lands inside the chunk."""
        pipe = self.pipe
        n = pipe.num_stages
        m = xs.shape[0]
        t = m + n - 1
        xs_full = np.zeros((t,) + xs.shape[1:], np.float32)
        xs_full[:m] = xs
        xs_dev = pipe._flatten_inputs(xs_full)
        ys_full = np.zeros((t,) + ys.shape[1:], ys.dtype)
        ys_full[n - 1: n - 1 + m] = ys  # target for mb j at step j+n-1
        mask = np.zeros((t,), np.float32)
        mask[n - 1: n - 1 + m] = 1.0
        return xs_dev, jnp.asarray(ys_full), jnp.asarray(mask)

    def loss_and_grad(self, xs: np.ndarray, ys: np.ndarray):
        """Summed loss + weight-buffer gradient for one chunk.

        ``xs``: [M, microbatch, *in_shape]; ``ys``: [M, microbatch, ...]
        targets (whatever ``loss_fn`` consumes).
        """
        pipe = self.pipe
        xs_dev, ys_dev, mask = self._schedule(np.asarray(xs),
                                              np.asarray(ys))
        if self._a0 is None:
            self._a0 = jax.device_put(
                jnp.zeros((pipe.num_stages, pipe.microbatch,
                           pipe.buf_elems), pipe.buffer_dtype),
                pipe._act_sharding)
        loss, grads = self._loss_grad(ys_dev.ndim)(pipe._w, self._a0,
                                                   xs_dev, ys_dev, mask)
        if self._fix_tp_grads is not None:
            grads = self._fix_tp_grads(grads)
        return loss, grads

    def step(self, xs: np.ndarray, ys: np.ndarray) -> float:
        """One optimizer step over a chunk; returns the summed loss."""
        loss, grads = self.loss_and_grad(xs, ys)
        if self.opt_state is None:
            self.opt_state = self.optimizer.init(self.pipe._w)
        self.pipe._w, self.opt_state = self._apply_updates(
            grads, self.opt_state, self.pipe._w)
        return float(loss)

    def accumulate_step(self, batches) -> float:
        """One optimizer step over SEVERAL chunks (gradient accumulation).

        ``batches`` iterates ``(xs, ys)`` chunk pairs; gradients stay in
        the stage-sharded buffer layout and sum on device (one lazy add
        per chunk, no host round trips), then a single optimizer update
        applies.  The effective batch is the sum of the chunks' — the
        standard recipe when the target batch exceeds what one chunk's
        in-flight window should hold.  Returns the summed loss.
        """
        total_loss = None  # device scalar until the end: no per-chunk sync
        acc = None
        for xs, ys in batches:
            loss, grads = self.loss_and_grad(xs, ys)
            total_loss = loss if total_loss is None else total_loss + loss
            acc = grads if acc is None else jax.tree.map(
                jnp.add, acc, grads)
        if acc is None:
            raise ValueError("accumulate_step needs at least one batch")
        if self.opt_state is None:
            self.opt_state = self.optimizer.init(self.pipe._w)
        self.pipe._w, self.opt_state = self._apply_updates(
            acc, self.opt_state, self.pipe._w)
        return float(total_loss)

    # -- interop ------------------------------------------------------------

    def trained_params(self) -> dict[str, Any]:
        """The deployment's CURRENT weights as a standard graph parameter
        pytree (the inverse of the buffer staging) — restore-anywhere
        interop with ``utils.checkpoint`` / fresh deployments.  Leaves
        come back in their original dtypes.  Under tensor parallelism the
        per-rank shards are reassembled op-by-op (``Op.tp_unshard``, the
        inverse of the Megatron column/row splits)."""
        pipe = self.pipe
        tp = pipe.tensor_parallel
        w = np.asarray(pipe._w)
        params: dict[str, Any] = {}
        for k, s in enumerate(pipe.stages):
            def unpack(row):
                return [row[off: off + size].reshape(shape).astype(dtype)
                        for off, size, shape, dtype in pipe._wmeta[k]]
            if tp > 1:
                rank_params = [
                    jax.tree.unflatten(pipe._wtreedef[k], unpack(w[k, r]))
                    for r in range(tp)]
                params.update(s.tp_unshard_params(rank_params))
            else:
                params.update(jax.tree.unflatten(pipe._wtreedef[k],
                                                 unpack(w[k])))
        return params

    def save_checkpoint(self, path: str):
        """Persist the training state (weight buffer + optimizer state)."""
        from ..utils.checkpoint import save_params
        if self.opt_state is None:
            # pre-first-step save must still restore: write the same
            # opt/s* keys load_checkpoint's template will demand
            self.opt_state = self.optimizer.init(self.pipe._w)
        flat, _ = jax.tree.flatten(self.opt_state)
        save_params(path, {
            "w": {"buffer": np.asarray(self.pipe._w)},
            "opt": {f"s{i}": np.asarray(l) for i, l in enumerate(flat)},
        })

    def load_checkpoint(self, path: str):
        """Restore training state saved by :meth:`save_checkpoint` into
        this deployment (same partition/mesh/optimizer)."""
        from ..utils.checkpoint import load_params
        pipe = self.pipe
        if self.opt_state is None:
            self.opt_state = self.optimizer.init(pipe._w)
        flat, treedef = jax.tree.flatten(self.opt_state)
        tpl = {"w": {"buffer": np.zeros(pipe._w.shape, pipe._w.dtype)},
               "opt": {f"s{i}": np.zeros(np.shape(l), np.asarray(l).dtype)
                       for i, l in enumerate(flat)}}
        state = load_params(path, tpl)
        sharding = NamedSharding(pipe.mesh, pipe._wspec)
        pipe._w = jax.device_put(state["w"]["buffer"], sharding)
        restored = []
        for i, l in enumerate(flat):
            arr = state["opt"][f"s{i}"]
            restored.append(
                jax.device_put(arr, sharding) if np.shape(arr) == pipe._w.shape
                else jnp.asarray(arr))
        self.opt_state = jax.tree.unflatten(treedef, restored)

    def stage_grads(self, grads) -> list[dict[str, Any]]:
        """Unflatten a weight-buffer gradient back into per-stage pytrees
        (host side; for inspection/tests/checkpointing).  Under tp the
        buffer holds per-rank shards whose reassembly is op-specific;
        inspect the raw [N, tp, Pmax] gradient directly instead."""
        pipe = self.pipe
        if pipe.tensor_parallel > 1:
            raise NotImplementedError(
                "stage_grads reassembly under tensor parallelism; "
                "read the sharded gradient buffer directly")
        out = []
        g = np.asarray(grads)
        for k, meta in enumerate(pipe._wmeta):
            leaves = [g[k, off: off + size].reshape(shape).astype(np.float32)
                      for off, size, shape, _dtype in meta]
            out.append(jax.tree.unflatten(pipe._wtreedef[k], leaves))
        return out
