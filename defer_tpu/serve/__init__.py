"""Serving front door: multi-tenant admission, continuous batching, and
SLO-aware shedding over one deployed chain (docs/SERVING.md).

The dispatcher streams exactly one client's inputs through the chain;
this package is the layer that turns that single stream into a *service*:

* :mod:`admission` — per-tenant weighted-fair queuing with priorities
  and SLO-aware load shedding (reject at admission when the predicted
  queueing delay blows the request's deadline).
* :mod:`batcher` — continuous batching: coalesce admitted samples
  across tenants into dynamic microbatches up to a per-stage latency
  budget taken from the planner's cost model.
* :mod:`frontdoor` — the TCP front door: many concurrent client
  streams multiplexed onto one deployed chain (tenant + request ids
  ride K_CTRL ``req_meta`` frames through the chain and are
  demultiplexed on the result hop), per-tenant telemetry.
* :mod:`engine` — continuous-batching autoregressive decode
  (``models/gpt.py`` graphs): per-request KV state rides through the
  pipeline stages, requests join and leave the batch between decode
  steps.
* :mod:`client` — the framed-protocol client and an open-loop load
  generator driven by :mod:`arrivals` traces.
"""

from .admission import (AdmissionController, ShedDecision, TenantConfig,
                        WeightedFairQueue)
from .arrivals import poisson_trace
from .batcher import BatchFormer, max_batch_within_budget
from .client import LoadGenerator, ServeClient
from .engine import ContinuousBatchEngine, DecodeRequest
from .frontdoor import ServeFrontDoor

__all__ = [
    "AdmissionController", "BatchFormer", "ContinuousBatchEngine",
    "DecodeRequest", "LoadGenerator", "ServeClient", "ServeFrontDoor",
    "ShedDecision", "TenantConfig", "WeightedFairQueue",
    "max_batch_within_budget", "poisson_trace",
]
