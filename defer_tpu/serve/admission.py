"""Admission control: per-tenant weighted-fair queuing, priorities, and
SLO-aware load shedding.

The front door admits work in *units* (one unit = one sample, or one
decode request) into per-tenant FIFO queues and drains them through a
:class:`WeightedFairQueue`: strict priority between levels, start-time
fair queuing (SFQ — the classic virtual-clock WFQ approximation) within
a level, so a greedy tenant flooding its queue cannot starve a neighbor
beyond its weight share.

Shedding happens AT ADMISSION: the controller predicts this unit's
completion time from the current backlog and a live per-unit service
estimate (front-door-measured EWMA by default, a
:class:`~defer_tpu.obs.cluster.ClusterView` service estimate or planner
figure when wired — docs/SERVING.md) and rejects when the prediction
blows the request's deadline.  A rejected client gets a ``shed`` control
frame with a ``retry_after_ms`` hint instead of silently-late results —
bounded queues and honest p99s instead of collapse under overload.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Any, Callable

from ..obs import REGISTRY
from ..obs.events import emit as emit_event


@dataclasses.dataclass
class TenantConfig:
    """Fairness/SLO knobs of one tenant (docs/SERVING.md)."""

    name: str
    weight: float = 1.0         #: WFQ share within the priority level
    priority: int = 0           #: strict level; higher preempts lower
    deadline_ms: float | None = None  #: per-unit completion SLO
    max_queued: int = 4096      #: hard per-tenant backlog cap

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"tenant {self.name}: weight must be > 0")
        if self.max_queued < 1:
            raise ValueError(f"tenant {self.name}: max_queued must be >= 1")


@dataclasses.dataclass
class ShedDecision:
    """Outcome of one admission attempt."""

    admitted: bool
    predicted_s: float = 0.0    #: predicted completion latency if admitted
    reason: str = ""            #: "deadline" | "backlog" | "" (admitted)
    retry_after_s: float = 0.0  #: hint: when the backlog should admit

    def to_json(self) -> dict:
        return {"admitted": self.admitted,
                "predicted_ms": round(self.predicted_s * 1e3, 3),
                "reason": self.reason,
                "retry_after_ms": round(self.retry_after_s * 1e3, 3)}


class _TenantQueue:
    __slots__ = ("cfg", "items", "finish_tag")

    def __init__(self, cfg: TenantConfig):
        self.cfg = cfg
        self.items: collections.deque = collections.deque()
        #: SFQ finish tag of this tenant's last-enqueued unit
        self.finish_tag = 0.0


class WeightedFairQueue:
    """Strict-priority levels, start-time fair queuing within a level.

    Every unit costs 1 virtual unit over its tenant's weight; within a
    priority level the unit with the smallest start tag drains first,
    and the level's virtual clock follows the served tags — the textbook
    SFQ bound: over any backlogged interval two tenants' served counts
    differ from their weight ratio by at most one unit each.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._ready = threading.Condition(self._lock)
        self._levels: dict[int, dict[str, _TenantQueue]] = {}
        self._vclock: dict[int, float] = {}
        self._size = 0

    def configure(self, cfg: TenantConfig) -> None:
        with self._lock:
            # a re-configure that changes priority MOVES the tenant's
            # queue (items included) to the new level — leaving it
            # registered in the old level would silently ignore the
            # repriority and double-count a later drop
            tq = None
            for prio, level in list(self._levels.items()):
                old = level.get(cfg.name)
                if old is not None:
                    tq = old
                    if prio != cfg.priority:
                        del level[cfg.name]
                        # the old level's virtual clock means nothing
                        # in the new level
                        tq.finish_tag = self._vclock.get(cfg.priority,
                                                         0.0)
                    break
            if tq is None:
                tq = _TenantQueue(cfg)
            tq.cfg = cfg
            self._levels.setdefault(cfg.priority, {})[cfg.name] = tq

    def qsize(self, tenant: str | None = None) -> int:
        with self._lock:
            if tenant is None:
                return self._size
            return sum(len(tq.items) for level in self._levels.values()
                       for name, tq in level.items() if name == tenant)

    def push(self, tenant: str, item: Any) -> None:
        """Enqueue one unit for ``tenant`` (configure() it first)."""
        with self._lock:
            for level in self._levels.values():
                tq = level.get(tenant)
                if tq is not None:
                    v = self._vclock.setdefault(tq.cfg.priority, 0.0)
                    start = max(v, tq.finish_tag)
                    tq.finish_tag = start + 1.0 / tq.cfg.weight
                    tq.items.append((start, item))
                    self._size += 1
                    self._ready.notify()
                    return
        raise KeyError(f"unknown tenant {tenant!r} (configure first)")

    def pop(self, timeout: float | None = 0.0) -> tuple[str, Any] | None:
        """Dequeue the next unit by priority-then-fair-share.

        ``timeout=0`` (default) never blocks; ``timeout=None`` blocks
        until a unit arrives; a positive timeout waits at most that
        long.  Returns ``None`` when nothing arrived."""
        with self._lock:
            if self._size == 0 and timeout != 0.0:
                self._ready.wait_for(lambda: self._size > 0,
                                     timeout=timeout)
            for prio in sorted(self._levels, reverse=True):
                level = self._levels[prio]
                best: _TenantQueue | None = None
                for tq in level.values():
                    if tq.items and (best is None
                                     or tq.items[0][0] < best.items[0][0]):
                        best = tq
                if best is not None:
                    start, item = best.items.popleft()
                    self._vclock[prio] = max(self._vclock.get(prio, 0.0),
                                             start)
                    self._size -= 1
                    return best.cfg.name, item
        return None

    def drop_tenant(self, tenant: str) -> int:
        """Discard every queued unit of ``tenant`` (client disconnect);
        returns the number dropped.  The tenant stays configured."""
        dropped = 0
        with self._lock:
            for level in self._levels.values():
                tq = level.get(tenant)
                if tq is not None:
                    n = len(tq.items)
                    tq.items.clear()
                    dropped += n
                    self._size -= n
        return dropped


class AdmissionController:
    """SLO-aware admission over a :class:`WeightedFairQueue`.

    ``service_s`` supplies the live per-unit service estimate (seconds a
    unit occupies the chain once scheduled, batch amortization already
    divided out).  The default estimator is the front door's measured
    EWMA (:meth:`observe_service`); wire :meth:`bind_cluster_view` to
    override it with the live
    :class:`~defer_tpu.obs.cluster.ClusterView` bottleneck estimate, or
    seed it from the planner's ``stage_effective_ms`` before any
    traffic has been measured.
    """

    def __init__(self, *, service_s: Callable[[], float] | None = None,
                 seed_service_s: float = 0.0, ewma: float = 0.25):
        self.queue = WeightedFairQueue()
        self._tenants: dict[str, TenantConfig] = {}
        self._lock = threading.Lock()
        self._service_s = service_s
        self._ewma_alpha = ewma
        self._ewma_s = max(0.0, seed_service_s)
        self._view = None
        self._view_width = 1
        #: units admitted but not yet completed (queued + in flight)
        self.inflight = 0
        self._qdelay = REGISTRY.histogram("serve.queue_delay_s")
        self._shed_total = REGISTRY.counter("serve.shed")
        self._admit_total = REGISTRY.counter("serve.admitted")

    # -- tenants -----------------------------------------------------------

    def configure(self, cfg: TenantConfig) -> None:
        with self._lock:
            self._tenants[cfg.name] = cfg
        self.queue.configure(cfg)
        # instantiate the per-tenant instruments up front so a tenant
        # that only ever gets shed still shows up in stats
        for c in ("admitted", "shed", "completed", "slo_measured",
                  "slo_ok"):
            REGISTRY.counter(f"serve.tenant.{cfg.name}.{c}")
        REGISTRY.histogram(f"serve.tenant.{cfg.name}.queue_delay_s")

    def tenant(self, name: str) -> TenantConfig:
        with self._lock:
            cfg = self._tenants.get(name)
        if cfg is None:
            raise KeyError(f"unknown tenant {name!r}")
        return cfg

    # -- live service estimate --------------------------------------------

    def observe_service(self, per_unit_s: float) -> None:
        """Fold one measured per-unit service time into the EWMA."""
        if per_unit_s <= 0:
            return
        with self._lock:
            a = self._ewma_alpha
            self._ewma_s = per_unit_s if self._ewma_s <= 0 \
                else (1 - a) * self._ewma_s + a * per_unit_s

    def bind_cluster_view(self, view, *, batch_width: int = 1) -> None:
        """Use ``view.stage_effective_ms()`` (the live bottleneck-stage
        estimate) as the service source: per-unit seconds = the slowest
        stage's per-frame ms over the batch width it serves."""
        with self._lock:
            self._view = view
            self._view_width = max(1, batch_width)

    def service_estimate_s(self) -> float:
        """Current per-unit service estimate, best source first."""
        if self._service_s is not None:
            return max(0.0, float(self._service_s()))
        with self._lock:
            view, width, ewma = self._view, self._view_width, self._ewma_s
        if view is not None:
            try:
                eff = view.stage_effective_ms()
            except Exception:  # noqa: BLE001 — live view died: fall back
                eff = None
            if eff:
                ms = max(eff.values())
                if ms > 0:
                    return ms / 1e3 / width
        return ewma

    # -- admission ---------------------------------------------------------

    def admit(self, tenant: str, item: Any, *,
              deadline_s: float | None = None,
              now: float | None = None) -> ShedDecision:
        """Admit one unit into ``tenant``'s queue, or shed it.

        Predicted completion = (units already admitted and not yet
        completed) x per-unit service + this unit's own service.  An
        explicit ``deadline_s`` overrides the tenant's configured
        ``deadline_ms``.  Sheds also fire on the per-tenant backlog cap
        regardless of SLO (an unbounded queue is never correct)."""
        del now  # reserved for tests that want a frozen clock
        cfg = self.tenant(tenant)
        if deadline_s is None and cfg.deadline_ms is not None:
            deadline_s = cfg.deadline_ms / 1e3
        unit_s = self.service_estimate_s()
        with self._lock:
            backlog = self.inflight
        predicted = (backlog + 1) * unit_s
        if self.queue.qsize(tenant) >= cfg.max_queued:
            dec = ShedDecision(False, predicted, "backlog",
                               retry_after_s=max(unit_s, 0.001))
        elif deadline_s is not None and unit_s > 0 \
                and predicted > deadline_s:
            # retry once enough backlog has drained that the SAME
            # prediction would fit the deadline
            excess = predicted - deadline_s
            dec = ShedDecision(False, predicted, "deadline",
                               retry_after_s=excess)
        else:
            dec = ShedDecision(True, predicted)
        t_cfg = cfg.name
        rid = getattr(item, "rid", None)
        if rid is None:
            rid = getattr(item, "request_id", None)
        if dec.admitted:
            with self._lock:
                self.inflight += 1
            self.queue.push(tenant, item)
            self._admit_total.n += 1
            REGISTRY.counter(f"serve.tenant.{t_cfg}.admitted").n += 1
            emit_event("admit", tenant=t_cfg, rid=rid,
                       backlog=backlog + 1)
        else:
            self._shed_total.n += 1
            REGISTRY.counter(f"serve.tenant.{t_cfg}.shed").n += 1
            emit_event("shed", tenant=t_cfg, rid=rid,
                       reason=dec.reason,
                       predicted_ms=round(dec.predicted_s * 1e3, 3),
                       retry_after_ms=round(dec.retry_after_s * 1e3, 3))
        return dec

    def complete(self, tenant: str, *, queued_at: float | None = None,
                 units: int = 1) -> None:
        """Mark ``units`` of ``tenant`` complete (result delivered or the
        unit was dropped with its client); records queue-delay when the
        admission timestamp is supplied."""
        with self._lock:
            self.inflight = max(0, self.inflight - units)
        REGISTRY.counter(f"serve.tenant.{tenant}.completed").n += units
        if queued_at is not None:
            dt = max(0.0, time.monotonic() - queued_at)
            self._qdelay.record(dt)
            REGISTRY.histogram(
                f"serve.tenant.{tenant}.queue_delay_s").record(dt)

    def record_slo(self, tenant: str, e2e_s: float) -> None:
        """Score one DELIVERED unit against its tenant's deadline —
        the per-tenant SLO-attainment fraction ``monitor --serve``
        renders.  Units dropped with a dead client are never scored
        (they have no delivery latency), so attainment measures what
        tenants actually experienced."""
        try:
            cfg = self.tenant(tenant)
        except KeyError:
            return
        if cfg.deadline_ms is None:
            return
        REGISTRY.counter(f"serve.tenant.{tenant}.slo_measured").n += 1
        if e2e_s * 1e3 <= cfg.deadline_ms:
            REGISTRY.counter(f"serve.tenant.{tenant}.slo_ok").n += 1

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """Per-tenant serving stats (the front door's ``stats`` reply)."""
        with self._lock:
            tenants = dict(self._tenants)
            inflight = self.inflight
        rows = {}
        for name, cfg in sorted(tenants.items()):
            measured = REGISTRY.counter(
                f"serve.tenant.{name}.slo_measured").value
            ok = REGISTRY.counter(f"serve.tenant.{name}.slo_ok").value
            rows[name] = {
                "weight": cfg.weight, "priority": cfg.priority,
                "deadline_ms": cfg.deadline_ms,
                "queued": self.queue.qsize(name),
                "admitted": REGISTRY.counter(
                    f"serve.tenant.{name}.admitted").value,
                "shed": REGISTRY.counter(
                    f"serve.tenant.{name}.shed").value,
                "completed": REGISTRY.counter(
                    f"serve.tenant.{name}.completed").value,
                "queue_delay_s": REGISTRY.histogram(
                    f"serve.tenant.{name}.queue_delay_s").summary(),
                # fraction of delivered units inside deadline_ms (None
                # until a deadline tenant has deliveries to score)
                "slo_attainment": (round(ok / measured, 4)
                                   if measured else None),
                "slo_measured": measured,
            }
        return {"tenants": rows, "inflight": inflight,
                "queued": self.queue.qsize(),
                "service_estimate_ms": round(
                    self.service_estimate_s() * 1e3, 4),
                "admitted": self._admit_total.value,
                "shed": self._shed_total.value,
                "queue_delay_s": self._qdelay.summary()}
