"""Deterministic open-loop arrival traces: seeded Poisson + burst phases.

Closed-loop load generators (send, wait, send again) cannot measure
queueing behavior — the generator slows down exactly when the system
does, hiding the p99 the user would have seen.  An OPEN-loop trace fixes
the arrival times up front (exponential inter-arrivals from a seeded
RNG), so a serving benchmark pays real queueing delay under overload and
its p99 means something (``benchmarks/run.py`` ``serving_frontdoor``
row, ``scripts/serve_smoke.py``).

Burst phases multiply the base rate over declared windows — the
"2x-overload burst" of the shedding acceptance test is
``bursts=[(t0, t1, 2.0)]``.
"""

from __future__ import annotations

import numpy as np


def poisson_trace(rate_hz: float, duration_s: float, *, seed: int = 0,
                  bursts: list[tuple[float, float, float]] | None = None,
                  max_events: int = 100_000) -> list[float]:
    """Arrival offsets (seconds, sorted, within ``[0, duration_s)``).

    Exponential inter-arrivals at ``rate_hz``, thinned/boosted by burst
    phases via the standard time-rescaling construction: draw a
    unit-rate Poisson process in *integrated-intensity* time and map
    each event back through the (piecewise-constant) rate function, so
    the same seed yields the same trace regardless of how bursts are
    arranged, and events inside a ``(t0, t1, mult)`` window arrive
    ``mult`` times as fast.
    """
    if rate_hz <= 0 or duration_s <= 0:
        return []
    bursts = sorted(bursts or [])
    for t0, t1, mult in bursts:
        if t1 <= t0 or mult <= 0:
            raise ValueError(f"bad burst phase ({t0}, {t1}, {mult})")

    def rate_at(t: float) -> float:
        for t0, t1, mult in bursts:
            if t0 <= t < t1:
                return rate_hz * mult
        return rate_hz

    rng = np.random.default_rng(seed)
    out: list[float] = []
    t = 0.0
    while len(out) < max_events:
        # integrated-intensity step: advance through the piecewise-
        # constant rate until the unit-exponential budget is spent
        budget = float(rng.exponential())
        while True:
            r = rate_at(t)
            # next rate-change boundary after t (or the horizon)
            nxt = duration_s
            for t0, t1, _ in bursts:
                for edge in (t0, t1):
                    if t < edge < nxt:
                        nxt = edge
            span = (nxt - t) * r
            if budget <= span:
                t += budget / r
                break
            budget -= span
            t = nxt
            if t >= duration_s:
                return out
        if t >= duration_s:
            return out
        out.append(t)
    return out
