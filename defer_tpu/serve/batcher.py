"""Continuous batching: coalesce admitted units across tenants into
dynamic microbatches up to a per-stage latency budget.

The chain's stage programs are compiled at a fixed frame batch ``W``
(``deploy(batch=W)``), so a formed microbatch always ships exactly ``W``
rows — what varies frame to frame is the COMPOSITION: however many
admitted units are waiting (from any mix of tenants, in weighted-fair
order) ride the next frame, and the rest of the rows are zero padding.
Under light load a unit never waits for company (latency-optimal
singles); under heavy load frames fill and the per-frame cost amortizes
over W units (throughput-optimal).  This is the fixed-width slot form of
continuous batching, and it is what keeps per-request outputs
byte-identical to a solo run: every frame executes the SAME compiled
program, and stage programs are row-independent, so a row's bytes do not
depend on who shares its frame.

``W`` itself comes from the planner:
:func:`~defer_tpu.plan.cost.max_batch_within_budget` picks the largest
width whose slowest stage stays inside the configured per-stage latency
budget (``defer_tpu serve --budget-ms``).
"""

from __future__ import annotations

import time
from typing import Any

from ..plan.cost import max_batch_within_budget  # noqa: F401  (re-export)
from .admission import WeightedFairQueue


class BatchFormer:
    """Forms dynamic microbatches from a :class:`WeightedFairQueue`.

    ``gather_s`` bounds how long a PARTIALLY filled frame waits for
    company after its first unit arrived (0 = never wait: whatever is
    queued right now forms the frame).  Waiting trades first-unit
    latency for fill — with a delay-bound chain the default of 0 is
    right (the pipeline itself provides the batching window: units
    arriving while a frame is in flight batch into the next one).
    """

    def __init__(self, queue: WeightedFairQueue, width: int, *,
                 gather_s: float = 0.0):
        if width < 1:
            raise ValueError(f"width must be >= 1, got {width}")
        self.queue = queue
        self.width = width
        self.gather_s = max(0.0, gather_s)

    def form(self, *, timeout: float | None = 0.25
             ) -> list[tuple[str, Any]]:
        """Collect up to ``width`` (tenant, unit) pairs in weighted-fair
        order: block up to ``timeout`` for the first unit, then drain
        greedily (plus the optional ``gather_s`` fill window).  Returns
        ``[]`` when nothing arrived."""
        first = self.queue.pop(timeout=timeout)
        if first is None:
            return []
        _stamp_popped(first)
        out = [first]
        deadline = time.monotonic() + self.gather_s if self.gather_s \
            else None
        while len(out) < self.width:
            nxt = self.queue.pop(timeout=0.0)
            if nxt is not None:
                _stamp_popped(nxt)
                out.append(nxt)
                continue
            if deadline is None or time.monotonic() >= deadline:
                break
            nxt = self.queue.pop(
                timeout=max(0.0, deadline - time.monotonic()))
            if nxt is None:
                break
            _stamp_popped(nxt)
            out.append(nxt)
        return out


def _stamp_popped(entry) -> None:
    """Stamp the popped unit with the instant it left the admission
    queue — the admission/gather boundary of per-request latency
    attribution (``obs/attrib.py``).  Best-effort: units without the
    slot (foreign test objects) simply go unstamped."""
    try:
        entry[1].popped_at = time.perf_counter()
    except (AttributeError, TypeError, IndexError):
        pass
