"""Serve-protocol client and the open-loop load generator.

:class:`ServeClient` speaks the front door's framed protocol
(docs/SERVING.md): hello -> samples -> END, with results and shed
notices collected on a background reader keyed by the client's own
sample numbers.  :class:`LoadGenerator` drives one client from a
deterministic arrival trace (:mod:`~defer_tpu.serve.arrivals`)
OPEN-LOOP: samples go out at their scheduled instants whether or not
earlier ones completed, so measured p99 includes real queueing delay —
the number closed-loop benchmarking structurally cannot see.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..transport.framed import (K_CTRL, K_END, K_TENSOR_SEQ,
                                connect_retry, recv_frame, send_ctrl,
                                send_end, send_frame)


class ServeClient:
    """One tenant stream against a :class:`ServeFrontDoor`."""

    def __init__(self, host: str, port: int, tenant: str = "default", *,
                 weight: float = 1.0, priority: int = 0,
                 deadline_ms: float | None = None,
                 timeout_s: float = 120.0, **extra_hello):
        self._sock = connect_retry(host, port, timeout_s)
        self.tenant = tenant
        self.timeout_s = timeout_s
        send_ctrl(self._sock, {"cmd": "hello", "tenant": tenant,
                               "weight": weight, "priority": priority,
                               "deadline_ms": deadline_ms, **extra_hello})
        kind, msg = recv_frame(self._sock)
        if kind != K_CTRL or msg.get("cmd") != "welcome":
            raise ConnectionError(f"expected welcome, got {kind}/{msg}")
        self.welcome = msg
        #: seq -> ("ok", ndarray, t_recv) | ("shed", msg, t_recv)
        self.results: dict[int, tuple] = {}
        self.sent_at: dict[int, float] = {}
        self._seq = 0
        self._done = threading.Event()
        self._err: list[BaseException] = []
        self._lock = threading.Lock()
        self._rx = threading.Thread(target=self._reader, daemon=True,
                                    name="serve-client-rx")
        self._rx.start()

    def _reader(self) -> None:
        try:
            while True:
                kind, value = recv_frame(self._sock)
                now = time.monotonic()
                if kind == K_END:
                    self._done.set()
                    return
                if kind == K_TENSOR_SEQ:
                    seq, arr = value
                    with self._lock:
                        self.results[int(seq)] = ("ok", arr, now)
                elif kind == K_CTRL and isinstance(value, dict) \
                        and value.get("cmd") == "shed":
                    with self._lock:
                        self.results[int(value["seq"])] = \
                            ("shed", value, now)
                else:
                    raise ConnectionError(
                        f"unexpected reply frame {kind!r}")
        except BaseException as e:  # noqa: BLE001 — surfaced in finish()
            self._err.append(e)
            self._done.set()

    def submit(self, sample: np.ndarray) -> int:
        """Send one sample (tensor mode) / prompt (decode mode);
        returns its sequence number."""
        seq = self._seq
        self._seq += 1
        self.sent_at[seq] = time.monotonic()
        send_frame(self._sock, np.asarray(sample))
        return seq

    def finish(self, *, close: bool = True) -> dict[int, tuple]:
        """END the stream, wait for every admitted sample to resolve,
        return ``{seq: outcome}``."""
        send_end(self._sock)
        if not self._done.wait(self.timeout_s):
            raise TimeoutError(
                f"front door did not drain within {self.timeout_s:.0f}s")
        if self._err:
            raise self._err[0]
        if close:
            self._sock.close()
        return dict(self.results)

    def abort(self) -> None:
        """Cut the connection without an END (the disconnect tests)."""
        self._sock.close()

    def stream(self, samples) -> list:
        """Submit everything, finish, and return outcomes in send order."""
        seqs = [self.submit(s) for s in samples]
        results = self.finish()
        return [results.get(q) for q in seqs]


def fetch_stats(host: str, port: int, *, timeout_s: float = 30.0) -> dict:
    """One observer stats round-trip against a front door."""
    sock = connect_retry(host, port, timeout_s)
    try:
        send_ctrl(sock, {"cmd": "stats"})
        kind, msg = recv_frame(sock)
        if kind != K_CTRL or msg.get("cmd") != "stats_reply":
            raise ConnectionError(f"expected stats_reply, got {kind}")
        send_end(sock)
        return msg
    finally:
        sock.close()


def fetch_events(host: str, port: int, *, cursor: int = 0,
                 limit: int = 512, timeout_s: float = 30.0) -> dict:
    """One flight-recorder round-trip against a front door: the door
    process's events since ``cursor`` (``{"events", "cursor",
    "dropped"}`` — pass the returned cursor back for the next
    incremental batch).  The serving twin of a stage node's
    ``{"cmd": "events_since"}`` control query."""
    sock = connect_retry(host, port, timeout_s)
    try:
        send_ctrl(sock, {"cmd": "events_since", "cursor": int(cursor),
                         "limit": int(limit)})
        kind, msg = recv_frame(sock)
        if kind != K_CTRL or msg.get("cmd") != "events_reply":
            raise ConnectionError(f"expected events_reply, got {kind}")
        send_end(sock)
        return msg
    finally:
        sock.close()


def _quantile(xs: list[float], q: float) -> float:
    if not xs:
        return 0.0
    ys = sorted(xs)
    i = min(len(ys) - 1, max(0, int(round(q * (len(ys) - 1)))))
    return ys[i]


class LoadGenerator:
    """Open-loop playback of an arrival trace through one client.

    ``samples`` may be shorter than the trace (cycled).  The sender
    honors the schedule even when the service lags — arrivals are not
    gated on completions — so the summary's p99 is the latency a real
    user at that arrival instant would have seen (admitted requests
    only; sheds are counted separately, with their own rate)."""

    def __init__(self, client: ServeClient, samples, offsets_s):
        self.client = client
        self.samples = list(samples)
        self.offsets = list(offsets_s)

    def run(self) -> dict:
        c = self.client
        t0 = time.monotonic()
        seqs = []
        for i, off in enumerate(self.offsets):
            lag = t0 + off - time.monotonic()
            if lag > 0:
                time.sleep(lag)
            seqs.append(c.submit(self.samples[i % len(self.samples)]))
        results = c.finish()
        wall = time.monotonic() - t0
        lat_ok, shed = [], 0
        for q in seqs:
            out = results.get(q)
            if out is None:
                continue
            if out[0] == "ok":
                lat_ok.append(out[2] - c.sent_at[q])
            else:
                shed += 1
        return {
            "tenant": c.tenant,
            "offered": len(seqs),
            "completed": len(lat_ok),
            "shed": shed,
            "shed_rate": round(shed / max(1, len(seqs)), 4),
            "wall_s": round(wall, 4),
            "throughput_per_s": round(len(lat_ok) / max(wall, 1e-9), 3),
            "latency_p50_ms": round(_quantile(lat_ok, 0.50) * 1e3, 3),
            "latency_p95_ms": round(_quantile(lat_ok, 0.95) * 1e3, 3),
            "latency_p99_ms": round(_quantile(lat_ok, 0.99) * 1e3, 3),
            "latency_max_ms": round(max(lat_ok, default=0.0) * 1e3, 3),
        }
