"""Continuous-batching autoregressive decode: per-request KV state rides
through the pipeline stages; requests join and leave between steps.

:class:`~defer_tpu.runtime.decode.PipelinedDecoder` decodes one CLOSED
batch: every sequence enters together, decodes in lockstep, and exits
together — a serving system driving it would pay head-of-line blocking
(a 512-token request holds a 5-token request's slot hostage) and refill
bubbles (the whole batch must drain before new prompts enter).  This
engine is continuous batching proper:

* The batch is ``width`` SLOTS.  Each slot holds one request's state —
  its prompt, its position, and its OWN KV cache rows in every stage's
  cache (``[blocks, width, kv_heads, max_len, head_dim]`` per stage, the
  stage-sharded layout of ``runtime/decode.py`` with the group axis
  replaced by a slot axis).
* Between any two decode steps, finished requests leave (slot freed,
  tokens delivered) and waiting requests join (slot claimed, position
  0); the step program itself never changes — one compiled program per
  width serves every batch composition.
* A step is one token per active slot: teacher-forced from the prompt
  while ``pos < prompt_len`` (prefill at decode rate — a joining
  request needs no separate prefill program), sampled past it.  Every
  row's computation is vmapped single-row decode against its own cache
  at its own position, so a row's output bytes are INDEPENDENT of who
  shares the batch — per-request outputs are byte-identical to the
  request run alone, the correctness bar continuous batching must meet.
* Sampling keys are ``fold_in(request_seed, position)`` per row —
  deterministic per request regardless of batch composition or join
  step.

The stage structure mirrors the deployed chain's partition (same
``_split_blocks`` assignment), so the planner's per-stage latency budget
(``plan.cost.stage_ms_at_batch``) prices this engine's step the same way
it prices a chain frame.  Execution here is in-process (one jitted step
over the stage-structured state); carrying the per-slot caches through
OS-process stage nodes needs stateful stage artifacts — the documented
next step (docs/SERVING.md), not this PR.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable

import numpy as np

import jax
import jax.numpy as jnp

from ..graph.ir import LayerGraph
from ..models.gpt import CausalTransformerBlock, GptEmbedding
from ..obs import REGISTRY
from ..obs.events import emit as emit_event
from ..runtime.decode import _sample_ids, _split_blocks


@dataclasses.dataclass
class DecodeRequest:
    """One admitted generation request."""

    prompt: np.ndarray                 #: [prompt_len] int token ids
    max_new_tokens: int
    tenant: str = "default"
    request_id: int = 0
    seed: int = 0
    temperature: float = 0.0
    #: called with the finished [prompt_len + new] int64 ids (or None on
    #: cancellation) from the engine's step thread
    on_done: Callable[[Any], None] | None = None
    queued_at: float = 0.0
    #: set by the front door when the client disconnects while this
    #: request is still queued — the engine loop must not join it
    cancelled: bool = False

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError("prompt must contain at least one token")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")


class _Slot:
    __slots__ = ("req", "pos", "out", "last_id", "cancelled")

    def __init__(self, req: DecodeRequest):
        self.req = req
        self.pos = 0               #: next position to feed
        self.out: list[int] = []   #: generated ids
        self.last_id = 0           #: last sampled id (input past prompt)
        self.cancelled = False


class ContinuousBatchEngine:
    """Step-wise decoder over ``width`` request slots.

    The engine is PASSIVE: callers (the front door's decode loop, or a
    test) drive it with :meth:`join` / :meth:`cancel` between calls to
    :meth:`step`.  All three must be called from one scheduling thread
    (the slot table is not locked against concurrent mutation; the
    front door owns that thread)."""

    def __init__(self, graph: LayerGraph, params: dict[str, Any], *,
                 num_stages: int, width: int,
                 max_len: int | None = None, top_k: int | None = None):
        if width < 1:
            raise ValueError(f"width must be >= 1, got {width}")
        nodes = graph.nodes
        for req in ("embeddings", "final_ln", "lm_head"):
            if req not in nodes:
                raise ValueError(
                    f"decode engine needs the gpt() node contract; "
                    f"missing {req!r} (models/gpt.py)")
        self.graph = graph
        self.params = jax.tree.map(jnp.asarray, params)
        self.width = width
        self.num_stages = num_stages
        self.embed_op: GptEmbedding = nodes["embeddings"].op
        self.max_len = max_len or self.embed_op.max_len
        if self.max_len > self.embed_op.max_len:
            raise ValueError(
                f"max_len {self.max_len} exceeds the positional table "
                f"({self.embed_op.max_len})")
        block_names = [nm for nm in graph.topo_order
                       if nm.startswith("block_")]
        for nm in block_names:
            if not isinstance(nodes[nm].op, CausalTransformerBlock):
                raise TypeError(f"{nm} is not a CausalTransformerBlock")
        assign = _split_blocks(len(block_names), num_stages)
        #: the chain-partition structure: stage s owns these blocks (and
        #: their slice of every slot's KV state)
        self.stage_blocks = [[block_names[i] for i in idxs]
                             for idxs in assign]
        blk0 = nodes[block_names[0]].op
        self.d_model = nodes[block_names[0]].out_spec.shape[-1]
        self.kv_heads = blk0.kv_heads
        self.head_dim = self.d_model // blk0.num_heads
        self.top_k = top_k

        self._slots: list[_Slot | None] = [None] * width
        self._caches = self._init_caches()
        self._step_fns: dict[bool, Any] = {}
        self.steps = 0
        self._step_hist = REGISTRY.histogram("serve.decode.step_s")
        self._tok_count = REGISTRY.counter("serve.decode.tokens")
        # per-step phase decomposition (obs/profile.py ENGINE_PHASES):
        # gather (host build of the per-slot rows / teacher-forcing),
        # dispatch (the jit step call returning), device
        # (block_until_ready — the fused step program: blocks, lm_head,
        # sampling AND the KV write all live here; splitting those
        # needs jax.profiler), sync (np.asarray of the sampled ids),
        # delivery (per-slot bookkeeping + on_done).  step_s stays the
        # dispatch→materialize total the serve stats already report.
        self._phase_hists = {
            name: REGISTRY.histogram(f"serve.decode.{name}_s")
            for name in ("gather", "dispatch", "device", "sync",
                         "delivery")}

    # -- state -------------------------------------------------------------

    def _init_caches(self):
        w, kv, ml, hd = (self.width, self.kv_heads, self.max_len,
                         self.head_dim)
        return [{"k": jnp.zeros((len(blks), w, kv, ml, hd), jnp.float32),
                 "v": jnp.zeros((len(blks), w, kv, ml, hd), jnp.float32)}
                for blks in self.stage_blocks]

    def free_slots(self) -> int:
        return sum(1 for s in self._slots if s is None)

    def active(self) -> int:
        return self.width - self.free_slots()

    def join(self, req: DecodeRequest) -> bool:
        """Claim a free slot for ``req``; False when the batch is full.
        The request's KV rows start clean by construction: position p's
        cache row is written before any later position reads it, so a
        recycled slot needs no cache zeroing."""
        if req.prompt.size + req.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {req.prompt.size} + {req.max_new_tokens} new "
                f"tokens exceeds max_len={self.max_len}")
        for i, s in enumerate(self._slots):
            if s is None:
                self._slots[i] = _Slot(req)
                return True
        return False

    def cancel(self, req: DecodeRequest) -> bool:
        """Free ``req``'s slot immediately (client disconnected).  The
        slot is reusable at the next join; other slots' rows are
        untouched (row-independent step), so a mid-decode cancellation
        cannot perturb anyone else's output."""
        for i, s in enumerate(self._slots):
            if s is not None and s.req is req:
                s.cancelled = True
                self._slots[i] = None
                if req.on_done is not None:
                    req.on_done(None)
                return True
        return False

    # -- the step program --------------------------------------------------

    def _build_step(self, sample: bool):
        nodes = self.graph.nodes
        embed = self.embed_op
        stage_ops = [[nodes[nm].op for nm in blks]
                     for blks in self.stage_blocks]
        stage_names = self.stage_blocks
        final_ln = nodes["final_ln"].op
        lm_head = nodes["lm_head"].op
        top_k = self.top_k

        def step(params, caches, ids, pos, seeds, temps):
            safe = jnp.clip(pos, 0, self.max_len - 1)
            x = (params["embeddings"]["wte"][ids]
                 + params["embeddings"]["wpe"][safe]).astype(jnp.float32)
            out_caches = []
            # ride the stage partition: stage s applies its blocks
            # against its slice of every slot's KV state
            for s, (ops, names) in enumerate(zip(stage_ops, stage_names)):
                ks, vs = caches[s]["k"], caches[s]["v"]
                for l, (op, nm) in enumerate(zip(ops, names)):
                    p_blk = params[nm]

                    def row(x_r, k_r, v_r, pos_r, _op=op, _p=p_blk):
                        y, k2, v2 = _op.decode(_p, x_r[None], k_r[None],
                                               v_r[None], pos_r)
                        return y[0], k2[0], v2[0]

                    x, k_l, v_l = jax.vmap(row)(x, ks[l], vs[l], safe)
                    ks = ks.at[l].set(k_l)
                    vs = vs.at[l].set(v_l)
                out_caches.append({"k": ks, "v": vs})
            h = final_ln.apply(params["final_ln"], x)
            logits = lm_head.apply(params["lm_head"],
                                   h).astype(jnp.float32)
            if sample:
                def row_sample(lg, seed_r, pos_r, temp_r):
                    key = jax.random.fold_in(
                        jax.random.PRNGKey(seed_r), pos_r)
                    return _sample_ids(lg[None], temp_r, top_k, key)[0]
                sampled = jax.vmap(row_sample)(logits, seeds, safe, temps)
                ids_out = jnp.where(temps > 0, sampled,
                                    jnp.argmax(logits, axis=-1))
            else:
                ids_out = jnp.argmax(logits, axis=-1)
            return ids_out.astype(jnp.int32), out_caches

        return jax.jit(step, donate_argnums=(1,))

    def _step_fn(self, sample: bool):
        fn = self._step_fns.get(sample)
        if fn is None:
            fn = self._step_fns[sample] = self._build_step(sample)
        return fn

    # -- one decode step ---------------------------------------------------

    def step(self) -> list[tuple[DecodeRequest, np.ndarray]]:
        """Advance every active slot one token; returns requests that
        FINISHED this step as ``(request, [plen + new] ids)`` (their
        slots are already free).  No-op (empty list) with no active
        slots."""
        live = [(i, s) for i, s in enumerate(self._slots) if s is not None]
        if not live:
            return []
        ph = self._phase_hists
        t_gather = time.perf_counter()
        w = self.width
        ids = np.zeros(w, np.int32)
        pos = np.zeros(w, np.int32)
        seeds = np.zeros(w, np.uint32)
        temps = np.zeros(w, np.float32)
        sample = False
        for i, s in live:
            plen = s.req.prompt.size
            ids[i] = s.req.prompt[s.pos] if s.pos < plen else s.last_id
            pos[i] = s.pos
            seeds[i] = s.req.seed & 0xFFFFFFFF
            temps[i] = s.req.temperature
            sample = sample or s.req.temperature > 0
        t0 = time.perf_counter()
        ph["gather"].record(t0 - t_gather)
        next_ids, self._caches = self._step_fn(sample)(
            self.params, self._caches, jnp.asarray(ids), jnp.asarray(pos),
            jnp.asarray(seeds), jnp.asarray(temps))
        t_disp = time.perf_counter()
        ph["dispatch"].record(t_disp - t0)
        sync = getattr(next_ids, "block_until_ready", None)
        if sync is not None:
            sync()
        t_dev = time.perf_counter()
        ph["device"].record(t_dev - t_disp)
        next_ids = np.asarray(next_ids)
        t_sync = time.perf_counter()
        ph["sync"].record(t_sync - t_dev)
        dt = t_sync - t0
        self._step_hist.record(dt)
        self.steps += 1
        done: list[tuple[DecodeRequest, np.ndarray]] = []
        for i, s in live:
            plen = s.req.prompt.size
            tok = int(next_ids[i])
            # the step consumed position s.pos; the token it produced
            # sits at position s.pos + 1, generated iff past the prompt
            if s.pos + 1 >= plen:
                s.out.append(tok)
                s.last_id = tok
                self._tok_count.n += 1
            s.pos += 1
            if len(s.out) >= s.req.max_new_tokens:
                result = np.concatenate(
                    [s.req.prompt.astype(np.int64),
                     np.asarray(s.out, np.int64)])
                self._slots[i] = None
                done.append((s.req, result))
                if s.req.on_done is not None:
                    s.req.on_done(result)
        ph["delivery"].record(time.perf_counter() - t_sync)
        return done

    # -- convenience (tests, sequential baselines) -------------------------

    def run_all(self, requests, *, joiner=None, max_steps: int = 100_000
                ) -> dict[int, np.ndarray]:
        """Drive the engine until every request finished: join waiting
        requests whenever slots free up (continuous batching), step
        until drained.  ``joiner(engine, pending)`` can override join
        order/timing (tests use it to stagger joins).  Returns
        ``{request_id: ids}``."""
        pending = list(requests)
        results: dict[int, np.ndarray] = {}

        def default_joiner(eng, queue):
            while queue and eng.free_slots():
                if not eng.join(queue[0]):
                    break
                queue.pop(0)

        join = joiner or default_joiner
        for _ in range(max_steps):
            join(self, pending)
            if not pending and self.active() == 0:
                return results
            for req, ids in self.step():
                results[req.request_id] = ids
        raise RuntimeError(f"run_all did not drain in {max_steps} steps")


class EngineLoop(threading.Thread):
    """The front door's decode scheduling thread: joins admitted
    requests from a :class:`~defer_tpu.serve.batcher.BatchFormer` into
    free slots between steps, steps while anything is active, parks on
    the queue otherwise."""

    def __init__(self, engine: ContinuousBatchEngine, former,
                 on_service=None):
        super().__init__(daemon=True, name="serve-decode-loop")
        self.engine = engine
        self.former = former
        self._halt = threading.Event()
        self.error: BaseException | None = None
        #: called with (per-unit seconds, units) after each step — feeds
        #: the admission controller's live service EWMA
        self._on_service = on_service
        #: cancellations queued from OTHER threads (client reader saw a
        #: disconnect); applied between steps on THIS thread — the slot
        #: table has exactly one mutating thread
        self._cancel_q: list = []
        self._cancel_lock = threading.Lock()

    def stop(self) -> None:
        self._halt.set()

    def request_cancel(self, req) -> None:
        """Thread-safe: free ``req``'s slot at the next step boundary."""
        with self._cancel_lock:
            self._cancel_q.append(req)

    def _apply_cancels(self) -> None:
        with self._cancel_lock:
            cancels, self._cancel_q = self._cancel_q, []
        for req in cancels:
            if self.engine.cancel(req):
                emit_event("decode_cancel", rid=req.request_id,
                           tenant=req.tenant)

    def run(self) -> None:
        eng = self.engine
        try:
            while not self._halt.is_set():
                self._apply_cancels()
                free = eng.free_slots()
                queue = self.former.queue
                for j in range(free):
                    # park on the queue only when idle; with work in
                    # flight just sweep whatever is already waiting
                    timeout = 0.05 if eng.active() == 0 and j == 0 else 0.0
                    item = queue.pop(timeout=timeout)
                    if item is None:
                        break
                    # this loop pops the admission queue directly (no
                    # BatchFormer.form), so the attribution boundary is
                    # stamped here
                    from .batcher import _stamp_popped
                    _stamp_popped(item)
                    if getattr(item[1], "cancelled", False):
                        continue  # client left while it queued
                    if eng.join(item[1]):
                        emit_event("decode_join",
                                   rid=item[1].request_id,
                                   tenant=item[1].tenant,
                                   step=eng.steps)
                if eng.active() == 0:
                    continue
                t0 = time.perf_counter()
                n = eng.active()
                eng.step()
                if self._on_service is not None and n > 0:
                    self._on_service((time.perf_counter() - t0) / n, n)
        except BaseException as e:  # noqa: BLE001 — surfaced by the door
            self.error = e
