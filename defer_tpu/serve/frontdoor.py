"""The serving front door: many concurrent client streams multiplexed
onto one deployed chain (or one continuous-batching decode engine).

Topology (docs/SERVING.md)::

    clients --hello/samples--> [admission: WFQ + SLO shed]
                                    |
                              [batch former]         (tensor mode)
                                    |  W-row frames + req_meta K_CTRL
                              ChainDispatcher -> stage0 -> ... -> stageN
                                    |                             |
                              [demux on the result hop] <---------+
                                    |  per-row, keyed by the cascaded
                                    v  req_meta composition
                               owning client (K_TENSOR_SEQ, seq =
                               the client's own sample number)

Decode mode replaces the chain with a
:class:`~defer_tpu.serve.engine.ContinuousBatchEngine`: each admitted
unit is a whole generation request whose KV state rides the engine's
pipeline stages, joining/leaving the batch between decode steps.

Client wire protocol (framed, ``transport/framed.py``): one K_CTRL
``hello`` (tenant identity + fairness/SLO knobs), then one K_TENSOR per
sample (tensor mode: one ``in_shape`` sample; decode mode: one 1-D
prompt), then K_END.  Replies: per-sample ``K_TENSOR_SEQ`` stamped with
the CLIENT's own sample number (results may complete out of submission
order; the stamp is the join key), or a ``shed`` K_CTRL carrying the
admission prediction and a retry hint; K_END echoes after the client's
END once every admitted sample resolved.  A connection whose first
frame is ``{"cmd": "stats"}`` is an observer, not a tenant: it gets the
per-tenant serving stats reply (the ``monitor --serve`` column source).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any, Sequence

import numpy as np

from ..obs import REGISTRY, tracer
from ..obs.attrib import DoorAttribution
from ..obs.events import emit as emit_event
from ..obs.events import recorder
from ..transport.channel import _sampled
from ..transport.framed import (K_CTRL, K_END, K_TENSOR, configure_socket,
                                recv_frame, send_ctrl, send_end, send_frame)
from .admission import AdmissionController, TenantConfig
from .batcher import BatchFormer
from .engine import ContinuousBatchEngine, DecodeRequest, EngineLoop


class _Client:
    """One accepted tenant connection."""

    __slots__ = ("conn", "tenant", "wlock", "state", "alive", "draining",
                 "outstanding", "decode_kw", "requests")

    def __init__(self, conn, tenant: str):
        self.conn = conn
        self.tenant = tenant
        self.wlock = threading.Lock()   # serializes reply writes
        self.state = threading.Lock()   # guards the fields below
        self.alive = True
        self.draining = False
        self.outstanding = 0            # admitted, result not yet sent
        self.decode_kw: dict = {}
        #: live decode requests (for cancellation on disconnect)
        self.requests: list = []


class _Unit:
    """One admitted sample (tensor mode)."""

    __slots__ = ("client", "seq", "rid", "sample", "queued_at",
                 "queued_pc", "popped_at", "submitted_at", "demuxed_at",
                 "sampled_seq", "settled")

    def __init__(self, client: _Client, seq: int, rid: int,
                 sample: np.ndarray):
        self.client = client
        self.seq = seq          #: the client's own sample number
        self.rid = rid          #: door-global request id (demux key)
        self.sample = sample
        #: admission-slot settlement token (guarded by client.state):
        #: delivery and the backend-lost shed sweep can both reach a
        #: unit — whichever flips this settles the slot, the other
        #: backs off
        self.settled = False
        self.queued_at = time.monotonic()
        #: the same instant on the tracer/attribution clock
        #: (perf_counter) — plus the downstream waypoints the batch
        #: former / backend stamp: popped from the admission queue,
        #: frame submitted into the chain, frame back off the demux.
        #: Together they tile the unit's timeline for the always-on
        #: door attribution buckets (obs/attrib.py)
        self.queued_pc = time.perf_counter()
        self.popped_at: float | None = None
        self.submitted_at: float | None = None
        self.demuxed_at: float | None = None
        #: frame wire seq when this request was trace-sampled (the
        #: join key to the chain's stageK spans), else None
        self.sampled_seq: int | None = None


class ChainBackend:
    """Tensor-mode backend: formed microbatches ride one deployed chain.

    ``dispatcher`` is a connected
    :class:`~defer_tpu.runtime.node.ChainDispatcher` whose stage
    programs were exported at frame batch ``width``.  Every formed
    frame is exactly ``width`` rows (queued units + zero padding),
    preceded by its ``req_meta`` composition frame; the demux thread
    attributes result rows by the metadata that CASCADED THROUGH THE
    CHAIN, not by local bookkeeping — a chain that reorders or drops a
    metadata frame fails loudly instead of mixing tenants' bytes.
    ``window`` bounds frames in flight inside the chain; everything
    beyond it waits in the admission queue where shed predictions can
    see it.
    """

    def __init__(self, dispatcher, width: int, in_shape: Sequence[int], *,
                 window: int = 8, trace_sample_every: int = 0):
        self.disp = dispatcher
        self.width = int(width)
        self.in_shape = tuple(in_shape)
        #: request-scoped waterfall sampling (docs/OBSERVABILITY.md):
        #: with tracing enabled, 1-in-N FRAMES — and therefore whole
        #: requests, every unit of a sampled frame — record spans end
        #: to end, keyed on the frame's wire seq that already rides
        #: the chain (the same mechanism as ``chain --trace-sample``,
        #: now composed with serving); 0 = every frame
        self.trace_sample_every = max(0, int(trace_sample_every))
        self._window = threading.Semaphore(max(1, window))
        self._next_seq = 0
        self._pending: dict[int, dict[int, _Unit]] = {}
        self._metas: dict[int, dict] = {}
        self._lock = threading.Lock()
        self._last_done = 0.0
        #: True when, at the LAST completion, another frame was already
        #: in flight — only then is the next completion gap evidence of
        #: service rate rather than of an idle lull (an idle gap folded
        #: into the EWMA would shed deadline tenants forever after a
        #: traffic pause: no admissions -> no completions -> no decay)
        self._prev_busy = False
        self._frames = REGISTRY.counter("serve.frames")
        self._samples = REGISTRY.counter("serve.samples")
        self.on_deliver = None       # set by the door
        self.on_service = None       # set by the door
        self._halt = threading.Event()
        self._rx: threading.Thread | None = None
        self.error: BaseException | None = None

    def start(self) -> None:
        # trace composition happens BEFORE the demux reader exists:
        # begin_trace cascades the trace context (and the shared
        # sample_every) down the chain ahead of any request frame, so
        # every stage samples the SAME 1-in-N wire seqs the door does
        if tracer().enabled:
            self.disp.begin_trace(sample_every=self.trace_sample_every)
        self._rx = threading.Thread(target=self._demux, daemon=True,
                                    name="serve-chain-demux")
        self._rx.start()

    def submit(self, entries: list[tuple[str, _Unit]]) -> None:
        """Ship one formed microbatch (<= width units)."""
        live = [u for _, u in entries
                if u.client.alive or u.client.draining]
        # a unit whose client died while queued is dropped here — its
        # admission slot must still be released
        for _, u in entries:
            if u not in live and self.on_deliver is not None:
                self.on_deliver(u, None)
        if not live:
            return
        frame = np.zeros((self.width,) + self.in_shape, np.float32)
        slots = []
        for row, u in enumerate(live):
            frame[row] = u.sample
            slots.append([u.client.tenant, u.rid, u.seq, row])
        self._window.acquire()
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            self._pending[seq] = {u.rid: u for u in live}
        now = time.perf_counter()
        tr = tracer()
        if tr.enabled and _sampled(self.trace_sample_every, seq):
            # a sampled FRAME samples every request riding it: the
            # admission-wait and gather spans land on the same timeline
            # (and under the same trace) as the chain's stageK spans
            first_pop = min((u.popped_at for u in live
                             if u.popped_at is not None), default=now)
            tr.record("serve.gather", first_pop,
                      max(now - first_pop, 0.0),
                      {"seq": seq, "n": len(live)})
            for u in live:
                u.sampled_seq = seq
                pop = u.popped_at if u.popped_at is not None else now
                tr.record("serve.admission_wait", u.queued_pc,
                          max(pop - u.queued_pc, 0.0),
                          {"rid": u.rid, "tenant": u.client.tenant,
                           "seq": seq})
        for u in live:
            u.submitted_at = now
        self.disp.send_request_frame(
            frame, seq=seq, meta={"slots": slots, "t": time.monotonic()})
        self._frames.n += 1
        self._samples.n += len(live)

    def _demux(self) -> None:
        try:
            while not self._halt.is_set():
                try:
                    kind, value = self.disp.recv_result(timeout_s=1.0)
                except TimeoutError:
                    continue
                if kind == "meta":
                    self._metas[int(value["seq"])] = value
                    continue
                if kind == "end":
                    return
                seq, arr = value
                if seq is None:
                    raise ConnectionError(
                        "result frame arrived unstamped; the chain must "
                        "relay request-scoped sequence numbers")
                meta = self._metas.pop(seq, None)
                if meta is None:
                    raise ConnectionError(
                        f"result frame seq={seq} arrived without its "
                        f"req_meta — the chain dropped or reordered "
                        f"request metadata")
                with self._lock:
                    units = self._pending.pop(seq)
                    still_busy = bool(self._pending)
                now = time.monotonic()
                # live per-unit service estimate from the completion
                # RATE (amortized chain throughput), not end-to-end
                # latency: the pipeline overlaps frames, so the gap
                # between completions is what bounds capacity.  Only
                # back-to-back gaps count (_prev_busy): a gap spanning
                # an idle lull measures the lull, not the service.
                gap = now - self._last_done if self._last_done else None
                self._last_done = now
                n_live = len(meta["slots"])
                if self.on_service is not None and gap is not None \
                        and n_live and self._prev_busy:
                    self.on_service(max(1e-6, gap) / n_live, n_live)
                self._prev_busy = still_busy
                arr = np.asarray(arr)
                now_pc = time.perf_counter()
                for tenant, rid, cseq, row in meta["slots"]:
                    unit = units.pop(rid, None)
                    if unit is None:
                        raise ConnectionError(
                            f"req_meta names unknown request {rid} "
                            f"(tenant {tenant}, frame {seq})")
                    if unit.seq != cseq or unit.client.tenant != tenant:
                        raise ConnectionError(
                            f"req_meta/unit mismatch on frame {seq}: "
                            f"{tenant}/{rid}/{cseq}")
                    unit.demuxed_at = now_pc
                    if self.on_deliver is not None:
                        self.on_deliver(unit, arr[row])
                self._window.release()
        except BaseException as e:  # noqa: BLE001 — surfaced by the door
            if not self._halt.is_set():
                self.error = e

    def halt_demux(self) -> None:
        """Stop the demux reader and wait it out — the backend-lost
        settlement sweep must not race a late delivery for the same
        admission slot."""
        self._halt.set()
        if self._rx is not None:
            self._rx.join(timeout=10.0)

    def drain_pending(self) -> list[_Unit]:
        """Pop every in-flight unit (submitted into the chain, result
        never demuxed) and release their window slots.  Call with the
        demux halted; the units' admission slots are the caller's to
        settle."""
        with self._lock:
            frames = list(self._pending.values())
            self._pending.clear()
            self._metas.clear()
        units = [u for frame in frames for u in frame.values()]
        for _ in frames:
            self._window.release()
        return units

    def close(self) -> None:
        # stop the demux reader BEFORE the dispatcher's drain: both read
        # the result channel, and a demux thread still racing would eat
        # the cascaded K_END and leave close() waiting out its timeout
        self.halt_demux()
        try:
            self.disp.close()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass


class ServeFrontDoor:
    """The multi-tenant admission server (``defer_tpu serve``).

    Tensor mode: pass a :class:`ChainBackend`.  Decode mode: pass a
    :class:`~defer_tpu.serve.engine.ContinuousBatchEngine` as
    ``engine``.  ``tenants`` pre-configures known tenants; unknown
    tenants are auto-configured from their hello (weight/priority/
    deadline knobs are client-supplied then — a real deployment would
    pin them server-side).
    """

    def __init__(self, *, listen: str = "127.0.0.1:0",
                 backend: ChainBackend | None = None,
                 engine: ContinuousBatchEngine | None = None,
                 tenants: Sequence[TenantConfig] = (),
                 seed_service_s: float = 0.0,
                 decode_defaults: dict | None = None,
                 gather_s: float = 0.0):
        if (backend is None) == (engine is None):
            raise ValueError("pass exactly one of backend= / engine=")
        host, _, port = listen.rpartition(":")
        self._srv = socket.create_server((host or "127.0.0.1", int(port)))
        self.address = self._srv.getsockname()
        self.mode = "decode" if engine is not None else "tensor"
        self.admission = AdmissionController(seed_service_s=seed_service_s)
        for cfg in tenants:
            self.admission.configure(cfg)
        self.backend = backend
        self.engine = engine
        self.width = engine.width if engine is not None else backend.width
        self.former = BatchFormer(self.admission.queue, self.width,
                                  gather_s=gather_s)
        self.decode_defaults = dict(decode_defaults or {})
        #: always-on per-tenant latency-attribution buckets (admission /
        #: gather / chain / result edge — docs/OBSERVABILITY.md); rides
        #: the stats reply for ``monitor --serve``
        self.attrib = DoorAttribution()
        self._clients: list[_Client] = []
        self._lock = threading.Lock()
        self._halt = threading.Event()
        self._threads: list[threading.Thread] = []
        self._next_rid = 0
        self._engine_loop: EngineLoop | None = None
        self.error: BaseException | None = None
        #: set once the chain backend died and its in-flight units were
        #: shed/settled — the door then sheds new samples at ingest
        #: (reason "backend_lost") instead of queueing into a dead chain
        self._backend_dead = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ServeFrontDoor":
        if self.backend is not None:
            self.backend.on_deliver = self._deliver
            self.backend.on_service = \
                lambda s, n: self.admission.observe_service(s)
            self.backend.start()
            t = threading.Thread(target=self._form_loop, daemon=True,
                                 name="serve-batch-former")
            t.start()
            self._threads.append(t)
        else:
            # decode: per-unit service = per-token step time x a typical
            # generation length, so shed predictions price whole requests
            typ = float(self.decode_defaults.get("max_new_tokens", 16))

            def on_service(per_tok_s, _n):
                self.admission.observe_service(per_tok_s * typ)

            self._engine_loop = EngineLoop(self.engine, self.former,
                                           on_service=on_service)
            self._engine_loop.start()
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="serve-accept")
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        self._halt.set()
        try:
            self._srv.close()
        except OSError:
            pass
        if self._engine_loop is not None:
            self._engine_loop.stop()
            self._engine_loop.join(timeout=10.0)
        if self.backend is not None:
            self.backend.close()
        with self._lock:
            clients = list(self._clients)
        for c in clients:
            self._finish_client(c, send_eos=False)

    def healthcheck(self) -> None:
        """Raise the first UNHANDLED backend/loop error (tests poll
        this).  A chain-backend death the form loop already settled
        (every affected tenant shed with ``retry_after_ms``, slots
        released — :meth:`_backend_lost`) is degraded-but-honest
        service, not a health failure: the door keeps answering, and
        ``stats()['pressure']['backend_lost']`` carries the state."""
        for src in (self, self._engine_loop):
            err = getattr(src, "error", None)
            if err is not None:
                raise err
        if self.backend is not None and self.backend.error is not None \
                and not self._backend_dead:
            raise self.backend.error

    # -- tenant connections ------------------------------------------------

    def _accept_loop(self) -> None:
        self._srv.settimeout(0.25)
        while not self._halt.is_set():
            try:
                conn, _ = self._srv.accept()
            except TimeoutError:
                continue
            except OSError:
                return
            configure_socket(conn)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True, name="serve-client")
            t.start()

    def _serve_conn(self, conn) -> None:
        """One connection: observer (stats) or tenant stream."""
        client: _Client | None = None
        try:
            kind, value = recv_frame(conn)
            if kind != K_CTRL or not isinstance(value, dict):
                raise ConnectionError("first frame must be a hello/stats "
                                      "control frame")
            if value.get("cmd") in ("stats", "events_since"):
                # observer connection: stats / flight-recorder queries
                # per request until END
                while True:
                    if value.get("cmd") == "events_since":
                        rec = recorder()
                        cursor, evs = rec.events_since(
                            int(value.get("cursor", 0)),
                            limit=int(value.get("limit", 512)))
                        send_ctrl(conn, {"cmd": "events_reply",
                                         "events": evs,
                                         "cursor": cursor,
                                         "dropped": rec.dropped})
                    else:
                        send_ctrl(conn, {"cmd": "stats_reply",
                                         **self.stats()})
                    kind, value = recv_frame(conn)
                    if kind == K_END:
                        return
                    if kind != K_CTRL or value.get("cmd") not in \
                            ("stats", "events_since"):
                        raise ConnectionError(
                            "observer connections speak stats/"
                            "events_since/END only")
            if value.get("cmd") != "hello":
                raise ConnectionError(f"expected hello, got {value!r}")
            client = self._handle_hello(conn, value)
            self._reader(client)
        except Exception as e:  # noqa: BLE001 — connection-fatal
            if client is not None:
                self._disconnect(client, e)
            else:
                conn.close()

    def _handle_hello(self, conn, msg: dict) -> _Client:
        tenant = str(msg.get("tenant") or "default")
        try:
            cfg = self.admission.tenant(tenant)
        except KeyError:
            cfg = TenantConfig(
                name=tenant,
                weight=float(msg.get("weight", 1.0)),
                priority=int(msg.get("priority", 0)),
                deadline_ms=msg.get("deadline_ms"),
                max_queued=int(msg.get("max_queued", 4096)))
            self.admission.configure(cfg)
        client = _Client(conn, tenant)
        if self.mode == "decode":
            kw = dict(self.decode_defaults)
            for k in ("max_new_tokens", "temperature", "seed"):
                if msg.get(k) is not None:
                    kw[k] = msg[k]
            kw.setdefault("max_new_tokens", 16)
            client.decode_kw = kw
        emit_event("client_open", tenant=tenant, mode=self.mode)
        with self._lock:
            self._clients.append(client)
        send_ctrl(conn, {"cmd": "welcome", "mode": self.mode,
                         "width": self.width, "tenant": tenant,
                         "deadline_ms": cfg.deadline_ms})
        return client

    def _reader(self, client: _Client) -> None:
        """The per-client ingest loop: admit or shed each sample."""
        seq = 0
        while True:
            kind, value = recv_frame(client.conn)
            if kind == K_END:
                with client.state:
                    client.draining = True
                self._maybe_drained(client)
                return
            if kind == K_CTRL and isinstance(value, dict) \
                    and value.get("cmd") == "stats":
                with client.wlock:
                    send_ctrl(client.conn,
                              {"cmd": "stats_reply", **self.stats()})
                continue
            if kind != K_TENSOR:
                raise ConnectionError(
                    f"unexpected frame kind {kind!r} on a tenant stream")
            with self._lock:
                rid = self._next_rid
                self._next_rid += 1
            if self.mode == "decode":
                unit: Any = self._make_decode_request(client, seq, rid,
                                                      value)
            else:
                if self._backend_dead:
                    # the chain is gone: shed at ingest with the same
                    # retry contract the settlement sweep used — never
                    # admit into a queue nothing drains
                    with client.wlock:
                        send_ctrl(client.conn, {
                            "cmd": "shed", "seq": seq, "admitted": False,
                            "predicted_ms": 0.0, "reason": "backend_lost",
                            "retry_after_ms": round(max(
                                0.05, self.admission.service_estimate_s())
                                * 1e3, 3)})
                    seq += 1
                    continue
                sample = np.asarray(value, np.float32)
                if sample.shape != self.backend.in_shape:
                    sample = sample.reshape(self.backend.in_shape)
                unit = _Unit(client, seq, rid, sample)
            # ownership/outstanding BEFORE admit: admit() publishes the
            # unit to the scheduler, and a fast engine could complete it
            # before a post-admit append — the delivery path settles
            # only units it finds owned
            with client.state:
                client.outstanding += 1
                if self.mode == "decode":
                    client.requests.append(unit)
            decision = self.admission.admit(client.tenant, unit)
            if not decision.admitted:
                with client.state:
                    client.outstanding -= 1
                    if self.mode == "decode" \
                            and unit in client.requests:
                        client.requests.remove(unit)
                with client.wlock:
                    send_ctrl(client.conn,
                              {"cmd": "shed", "seq": seq,
                               **decision.to_json()})
            seq += 1

    def _make_decode_request(self, client: _Client, seq: int, rid: int,
                             value) -> DecodeRequest:
        prompt = np.asarray(value).reshape(-1).astype(np.int32)
        kw = client.decode_kw
        max_new = int(kw.get("max_new_tokens", 16))
        if prompt.size + max_new > self.engine.max_len:
            # reject on the CLIENT's connection, not inside the engine
            # loop — one oversized request must not kill the service
            raise ConnectionError(
                f"prompt {prompt.size} + {max_new} new tokens exceeds "
                f"the engine's max_len={self.engine.max_len}")
        req = DecodeRequest(
            prompt=prompt,
            max_new_tokens=max_new,
            tenant=client.tenant, request_id=rid,
            seed=int(kw.get("seed", 0)),
            temperature=float(kw.get("temperature", 0.0)))
        req.queued_at = time.monotonic()
        req.queued_pc = time.perf_counter()  # attribution clock twin
        req.popped_at = None

        def on_done(tokens, _c=client, _s=seq, _r=req):
            self._deliver_decode(_c, _s, _r, tokens)

        req.on_done = on_done
        return req

    # -- delivery ----------------------------------------------------------

    def _deliver(self, unit: _Unit, row: np.ndarray | None) -> None:
        """Tensor-mode result: route one row back to its owner (row is
        None when the unit was dropped with its dead client)."""
        client = unit.client
        with client.state:
            # settle exactly once: the backend-lost sweep and a late
            # delivery can both reach a unit — the settled flag is the
            # ownership token (the decode path's client.requests twin)
            if unit.settled:
                return
            unit.settled = True
            client.outstanding -= 1
            alive = client.alive
        self.admission.complete(client.tenant, queued_at=unit.queued_at)
        if row is not None and alive:
            try:
                with client.wlock:
                    send_frame(client.conn, np.asarray(row),
                               seq=unit.seq)
            except OSError as e:
                self._disconnect(client, e)
                return
            done = time.perf_counter()
            # always-on attribution + SLO scoring: the four stamped
            # waypoints tile this unit's timeline exactly
            self.admission.record_slo(client.tenant,
                                      done - unit.queued_pc)
            self.attrib.record(
                client.tenant, queued=unit.queued_pc,
                popped=unit.popped_at if unit.popped_at is not None
                else unit.queued_pc,
                submitted=unit.submitted_at
                if unit.submitted_at is not None else unit.queued_pc,
                demuxed=unit.demuxed_at
                if unit.demuxed_at is not None else done,
                delivered=done)
            tr = tracer()
            if tr.enabled and unit.sampled_seq is not None:
                # the sampled request's result edge + root span close
                # the trace: demux receipt -> client bytes written,
                # then admitted -> delivered as the e2e envelope every
                # child bucket telescopes inside
                t_dx = unit.demuxed_at if unit.demuxed_at is not None \
                    else done
                tr.record("serve.deliver", t_dx, max(done - t_dx, 0.0),
                          {"rid": unit.rid, "tenant": client.tenant,
                           "seq": unit.sampled_seq})
                tr.record("serve.request", unit.queued_pc,
                          max(done - unit.queued_pc, 0.0),
                          {"rid": unit.rid, "tenant": client.tenant,
                           "seq": unit.sampled_seq,
                           "client_seq": unit.seq})
        self._maybe_drained(client)

    def _deliver_decode(self, client: _Client, seq: int,
                        req: DecodeRequest, tokens) -> None:
        # settle exactly once: membership in client.requests is the
        # ownership token — a disconnect racing the engine's on_done
        # (both threads can reach here for the same request) must not
        # double-count admission.complete / the tenant counters
        with client.state:
            owned = req in client.requests
            if owned:
                client.requests.remove(req)
                client.outstanding -= 1
            alive = client.alive
        if not owned:
            return  # _disconnect already settled this request
        self.admission.complete(client.tenant, queued_at=req.queued_at)
        if tokens is not None and alive:
            try:
                with client.wlock:
                    send_frame(client.conn,
                               np.asarray(tokens, np.int64), seq=seq)
            except OSError as e:
                self._disconnect(client, e)
                return
            done = time.perf_counter()
            queued_pc = getattr(req, "queued_pc", done)
            popped = getattr(req, "popped_at", None)
            if popped is None:
                popped = done
            # decode buckets: admission = queue wait, chain = the
            # engine's whole-request residency (its pipeline stages are
            # in-process; no per-stage frame path to decompose)
            self.admission.record_slo(client.tenant, done - queued_pc)
            self.attrib.record(client.tenant, queued=queued_pc,
                               popped=popped, submitted=popped,
                               demuxed=done, delivered=done)
        self._maybe_drained(client)

    def _maybe_drained(self, client: _Client) -> None:
        with client.state:
            done = (client.draining and client.outstanding == 0
                    and client.alive)
        if done:
            self._finish_client(client, send_eos=True)

    def _finish_client(self, client: _Client, *, send_eos: bool) -> None:
        with client.state:
            if not client.alive:
                return
            client.alive = False
        emit_event("client_close", tenant=client.tenant,
                   clean=bool(send_eos))
        try:
            if send_eos:
                with client.wlock:
                    send_end(client.conn)
        except OSError:
            pass
        client.conn.close()
        with self._lock:
            if client in self._clients:
                self._clients.remove(client)

    def _disconnect(self, client: _Client, err: BaseException) -> None:
        """A client died mid-stream: cancel its in-flight decode
        requests (their KV slots free at the next step boundary),
        leave everyone else untouched."""
        del err
        self._finish_client(client, send_eos=False)
        if self.mode == "decode":
            with client.state:
                live = list(client.requests)
                client.requests.clear()
            for req in live:
                req.on_done = None  # the client is gone
                req.cancelled = True  # still-queued: never join
                if self._engine_loop is not None:
                    self._engine_loop.request_cancel(req)
                self.admission.complete(client.tenant,
                                        queued_at=req.queued_at)
        # queued-but-unsubmitted tensor units drain through
        # ChainBackend.submit's dead-client drop

    # -- the tensor-mode forming loop --------------------------------------

    def _form_loop(self) -> None:
        try:
            while not self._halt.is_set():
                entries = self.former.form(timeout=0.25)
                err = self.backend.error
                if err is None and entries:
                    try:
                        self.backend.submit(entries)
                        entries = []
                    except BaseException as e:  # noqa: BLE001
                        # a dead chain surfaces as a send failure here
                        # before the demux notices EOF; either way the
                        # settlement sweep below owns the cleanup
                        err = e
                if err is not None:
                    if not self._halt.is_set():
                        self._backend_lost(err, entries)
                    return
                self.healthcheck()
        except BaseException as e:  # noqa: BLE001
            if not self._halt.is_set():
                self.error = e

    def _backend_lost(self, err: BaseException,
                      entries: list[tuple[str, _Unit]]) -> None:
        """The chain backend died mid-request: settle EVERY affected
        admission slot exactly once and shed the owning tenants with a
        ``retry_after_ms`` hint, instead of failing the healthcheck and
        leaving in-flight clients hanging (docs/ROBUSTNESS.md).

        Affected units live in three mutually exclusive places —
        formed-but-unsubmitted (``entries``), submitted into the dead
        chain (the backend's pending frames), and still queued in
        admission; the per-unit ``settled`` token makes the sweep safe
        against any delivery that raced the demux shutdown."""
        # stop late deliveries FIRST: settlement must not race the demux
        self.backend.halt_demux()
        self._backend_dead = True
        units = [u for _, u in entries]
        units += self.backend.drain_pending()
        while True:
            nxt = self.admission.queue.pop(timeout=0.0)
            if nxt is None:
                break
            units.append(nxt[1])
        # one honest retry hint for the whole incident: the time to
        # redeploy a chain dwarfs per-unit service, so hint the larger
        retry_s = max(0.05, self.admission.service_estimate_s()
                      * max(1, len(units)))
        shed = 0
        for u in units:
            if self._shed_unit(u, retry_s):
                shed += 1
        emit_event("backend_lost", error=type(err).__name__, shed=shed)
        # backend loss is the serve plane's first-class failure: emit
        # the forensics bundle (no-op unless this process journals)
        from ..obs.postmortem import maybe_autopsy
        maybe_autopsy(f"backend_lost: {type(err).__name__}")

    def _shed_unit(self, unit: _Unit, retry_s: float) -> bool:
        """Settle one in-flight unit as shed (backend lost): release its
        admission slot, tell its client to retry.  Returns False when a
        racing delivery already settled it."""
        client = unit.client
        with client.state:
            if unit.settled:
                return False
            unit.settled = True
            client.outstanding -= 1
            alive = client.alive
        self.admission.complete(client.tenant, queued_at=unit.queued_at)
        REGISTRY.counter(f"serve.tenant.{client.tenant}.shed").n += 1
        REGISTRY.counter("serve.shed").n += 1
        if alive:
            try:
                with client.wlock:
                    send_ctrl(client.conn, {
                        "cmd": "shed", "seq": unit.seq, "admitted": False,
                        "predicted_ms": 0.0, "reason": "backend_lost",
                        "retry_after_ms": round(retry_s * 1e3, 3)})
            except OSError as e:
                self._disconnect(client, e)
                return True
        self._maybe_drained(client)
        return True

    # -- observability -----------------------------------------------------

    def pressure(self) -> dict:
        """Admission-pressure snapshot: the serving-side input to the
        replanner's scale decision (docs/ROBUSTNESS.md).  A monitor loop
        combines ``drain_eta_ms`` (how long the current backlog takes at
        the live service estimate) with the straggler detector's
        :meth:`~defer_tpu.obs.cluster.StragglerDetector.suggest` — a
        bursty arrival trace shows up here as backlog long before it
        shows up in any per-stage latency histogram, which is what lets
        queue depth drive a cutover instead of merely describing one."""
        queued = self.admission.queue.qsize()
        inflight = self.admission.inflight
        unit_s = self.admission.service_estimate_s()
        return {
            "queued": queued,
            "inflight": inflight,
            # frames of work outstanding at the deployed width
            "backlog_frames": -(-inflight // max(1, self.width)),
            "drain_eta_ms": round(inflight * unit_s * 1e3, 3),
            "service_estimate_ms": round(unit_s * 1e3, 4),
            "width": self.width,
            "backend_lost": self._backend_dead,
        }

    def stats(self) -> dict:
        doc = {"mode": self.mode, "width": self.width,
               "pressure": self.pressure(),
               "frames": REGISTRY.counter("serve.frames").value,
               "samples": REGISTRY.counter("serve.samples").value,
               # per-tenant latency-attribution buckets (ms summaries)
               # + the flight recorder's loss counter, so a monitor can
               # see both what the p99 is made of and whether the event
               # log under it is complete
               "attribution": self.attrib.summary(),
               "events_dropped": recorder().dropped,
               **self.admission.stats()}
        if self.engine is not None:
            doc["decode"] = {
                "active": self.engine.active(),
                "free_slots": self.engine.free_slots(),
                "steps": self.engine.steps,
                "tokens": REGISTRY.counter(
                    "serve.decode.tokens").value,
                "step_s": REGISTRY.histogram(
                    "serve.decode.step_s").summary(),
            }
        return doc
