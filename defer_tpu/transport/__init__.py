from .framed import (K_BYTES, K_END, K_TENSOR, TensorClient, TensorServer,
                     recv_frame, send_end, send_frame)
