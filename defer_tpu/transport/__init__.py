from .channel import AsyncReceiver, AsyncSender, ChannelError
from .framed import (K_BYTES, K_END, K_TENSOR, K_TENSOR_SEQ, TensorClient,
                     TensorServer, configure_socket, recv_frame, send_end,
                     send_frame)
from .local import (LocalPipe, LocalReceiver, LocalSender, grant_local,
                    offer_local, record_fallback)
from .shm import (ShmReceiver, ShmRing, ShmSender, grant_shm, offer_shm,
                  offer_tier_ladder, sweep_orphan_segments)
from .branch import BranchJoin, BroadcastSender
from .replicate import FanInMerge, FanOutSender
