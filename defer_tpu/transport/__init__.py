from .channel import AsyncReceiver, AsyncSender, ChannelError
from .framed import (K_BYTES, K_END, K_TENSOR, TensorClient, TensorServer,
                     configure_socket, recv_frame, send_end, send_frame)
