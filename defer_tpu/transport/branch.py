"""Per-seq broadcast fan-out / all-paths join for branched stage graphs.

Stage replication (``transport/replicate.py``) splits a stream ACROSS R
identical replicas: frame ``i`` goes to ONE replica and the fan-in
restores round-robin order.  A branched stage graph needs the other fan:
EVERY branch computes on EVERY frame (an inception block's four branches
all read the block input; a branched MoE's experts all read the token
batch), and the join needs ALL P branch outputs of sequence ``s``
before it can run the graph's merge op.  The two halves here:

* :class:`BroadcastSender` — sends each tensor frame to ALL P branch
  channels, stamped with one shared sequence number (``K_TENSOR_SEQ``).
  Each channel's ``stream_begin`` control frame carries its PATH label,
  so the downstream join can attribute every connection (including a
  direct fork->join channel standing in for an empty residual branch)
  to its merge-input slot.  Backpressure holds per path: one stalled
  branch fills its bounded channel queue and parks the producer.

* :class:`BranchJoin` — a bounded reorder buffer keyed on ``(path,
  seq)`` (vs the replica fan-in's round-robin ``seq``): reader threads
  (one per inbound branch connection) deposit each path's frame for
  ``s``; the consumer parks until all P paths delivered ``s``, then
  receives ``(seq, [x_path0, ..., x_pathP-1])`` strictly in sequence
  order — the argument list the join stage's merge program applies.
  The reorder-buffer discipline is FanInMerge's: a full buffer parks
  readers EXCEPT for deposits completing the consumer's next needed
  seq (liveness), duplicate/stale ``(path, seq)`` deposits raise, and
  an END requires all P paths to end with no incomplete seq buffered —
  a branch that died mid-stream can never be silently papered over.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Sequence

from ..obs import LatencyHistogram
from .channel import AsyncSender
from .framed import K_CTRL, K_END, K_TENSOR_SEQ

__all__ = ["BranchJoin", "BroadcastSender"]


class BranchJoin:
    """Bounded ``(path, seq)`` reorder buffer merging P branch paths.

    Reader threads call :meth:`attach` (once per path) then :meth:`put`
    / :meth:`put_ctrl` / :meth:`end` / :meth:`fail`; one consumer calls
    :meth:`get` and receives ``(kind, value)`` tuples: control frames
    first, then ``(K_TENSOR_SEQ, (seq, [parts...]))`` strictly in
    sequence order with ``parts`` in path order, then ``(K_END, None)``
    once every path ended and the buffer drained.
    """

    def __init__(self, paths: int, *, capacity: int = 32):
        if paths < 2:
            raise ValueError(f"paths must be >= 2, got {paths}")
        if capacity < 2:
            raise ValueError(f"capacity must be >= 2, got {capacity}")
        self.paths = paths
        self.capacity = capacity          # distinct buffered seqs
        self._slots: dict[int, list] = {}  # seq -> [part per path]
        self._have: dict[int, int] = {}    # seq -> parts present
        self._ctrl: list[dict] = []
        self._next = 0
        self._attached: set[int] = set()
        self._ended: set[int] = set()
        self._err: BaseException | None = None
        self._cv = threading.Condition()

    # -- producer side (one reader thread per branch connection) ------------

    def _check_path(self, path: int) -> None:
        if not 0 <= path < self.paths:
            raise ValueError(f"path {path} out of range 0..{self.paths - 1}")

    def attach(self, path: int) -> None:
        """Claim ``path`` for one upstream connection; a second
        connection claiming the same path raises (two branches cannot
        share a merge-input slot)."""
        with self._cv:
            self._check_path(path)
            if path in self._attached:
                raise ConnectionError(
                    f"two upstreams claimed join path {path}")
            self._attached.add(path)

    def put(self, path: int, seq: int, value,
            timeout: float | None = None) -> None:
        """Deposit path ``path``'s frame for sequence ``seq``.  Blocks
        while ``capacity`` distinct seqs are buffered UNLESS the deposit
        lands in an existing slot or opens the consumer's next needed
        seq (liveness: the frame everyone is waiting on is always
        admitted).  Duplicate ``(path, seq)`` or stale ``seq`` raise."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            self._check_path(path)
            while True:
                if self._err is not None:
                    raise self._err
                if seq < self._next:
                    raise ValueError(
                        f"stale sequence {seq} on path {path} "
                        f"(next expected {self._next})")
                slot = self._slots.get(seq)
                if slot is not None and slot[path] is not None:
                    raise ValueError(
                        f"duplicate frame for (path {path}, seq {seq})")
                if slot is not None or seq == self._next \
                        or len(self._slots) < self.capacity:
                    if slot is None:
                        slot = self._slots[seq] = [None] * self.paths
                        self._have[seq] = 0
                    slot[path] = value
                    self._have[seq] += 1
                    self._cv.notify_all()
                    return
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"join buffer full ({self.capacity} seqs) for "
                        f"{timeout:.1f}s waiting on seq {self._next}")
                self._cv.wait(0.05)

    def put_ctrl(self, msg: dict) -> None:
        """Queue a control frame — delivered ahead of buffered tensors
        (control rides ahead of data, the single-path convention)."""
        with self._cv:
            self._ctrl.append(msg)
            self._cv.notify_all()

    def end(self, path: int) -> None:
        """Path ``path`` delivered its END frame (exactly once)."""
        with self._cv:
            self._check_path(path)
            if path in self._ended:
                self._err = ConnectionError(
                    f"two END frames on join path {path}")
            self._ended.add(path)
            self._cv.notify_all()

    def fail(self, exc: BaseException) -> None:
        """A branch reader died: surface ``exc`` to everyone parked."""
        with self._cv:
            if self._err is None:
                self._err = exc
            self._cv.notify_all()

    # -- consumer side -------------------------------------------------------

    def _pop_locked(self):
        if self._ctrl:
            return K_CTRL, self._ctrl.pop(0)
        if self._have.get(self._next, 0) == self.paths:
            seq = self._next
            parts = self._slots.pop(seq)
            del self._have[seq]
            self._next += 1
            self._cv.notify_all()  # wake readers parked on a full buffer
            return K_TENSOR_SEQ, (seq, parts)
        if self._err is not None:
            raise self._err
        if len(self._ended) >= self.paths:
            if self._slots:
                missing = {
                    s: [p for p, v in enumerate(self._slots[s])
                        if v is None]
                    for s in sorted(self._slots)[:4]}
                raise ConnectionError(
                    f"all {self.paths} branch paths ended with the join "
                    f"incomplete: waiting on seq {self._next}, missing "
                    f"(seq -> paths) {missing}")
            return K_END, None
        return None

    def get(self, timeout: float | None = None) -> tuple:
        """Next in-order item (see class docstring); TimeoutError past
        ``timeout`` (None = wait forever), re-raises reader failures."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                got = self._pop_locked()
                if got is not None:
                    return got
                if deadline is not None and time.monotonic() > deadline:
                    have = self._have.get(self._next, 0)
                    raise TimeoutError(
                        f"no complete join frame within {timeout:.1f}s "
                        f"(seq {self._next} has {have}/{self.paths} "
                        f"paths, {len(self._slots)} seqs buffered)")
                self._cv.wait(0.05)

    def get_nowait(self) -> tuple:
        """Non-blocking :meth:`get`; raises ``queue.Empty`` while the
        next seq is incomplete (the consumer's cue to drain its compute
        window)."""
        with self._cv:
            got = self._pop_locked()
        if got is None:
            raise queue.Empty
        return got

    def qsize(self) -> int:
        with self._cv:
            return len(self._slots)


class BroadcastSender:
    """Every frame to every branch: the fork side of a stage graph.

    Presents the :class:`AsyncSender` surface over P of them, like
    :class:`~defer_tpu.transport.replicate.FanOutSender` — but where the
    replica fan round-robins, a broadcast DUPLICATES: tensor ``i`` goes
    to ALL channels stamped with sequence ``i`` (a caller-supplied seq
    is ignored — the fork begins a fresh sequence segment), and each
    channel is announced with ``{"cmd": "stream_begin", "path": p}`` so
    the join end of the region can map connections to merge-input slots.
    Control and END frames broadcast as well (each branch needs the
    trace context; the join counts one END per path).
    """

    def __init__(self, socks: Sequence, *, depth: int = 8,
                 codec: str = "raw", gauge: str | None = None, span=None,
                 hist: str | None = None,
                 paths: Sequence[int] | None = None):
        if len(socks) < 2:
            raise ValueError("BroadcastSender needs >= 2 channels "
                             "(a single path is a plain unicast hop)")
        self._chans = [AsyncSender(s, depth=depth, codec=codec,
                                   gauge=gauge, span=span, hist=hist)
                       for s in socks]
        self.paths = list(paths) if paths is not None \
            else list(range(len(socks)))
        if len(self.paths) != len(self._chans):
            raise ValueError(f"{len(self._chans)} channels but "
                             f"{len(self.paths)} path labels")
        self._n = 0
        self.depth = depth
        for p, ch in zip(self.paths, self._chans):
            ch.send_ctrl({"cmd": "stream_begin", "path": int(p)})

    @property
    def width(self) -> int:
        return len(self._chans)

    @property
    def sample_every(self) -> int:
        return self._chans[0].sample_every

    @sample_every.setter
    def sample_every(self, n: int) -> None:
        for ch in self._chans:
            ch.sample_every = n

    def take_watermark(self) -> int:
        return max(ch.take_watermark() for ch in self._chans)

    @property
    def hi(self) -> int:
        return max(ch.hi for ch in self._chans)

    @property
    def enc(self) -> LatencyHistogram:
        h = LatencyHistogram()
        for ch in self._chans:
            h.merge(ch.enc)
        return h

    def send(self, arr, *, seq: int | None = None) -> None:
        # every channel's encode thread reads the SAME (read-only)
        # ndarray concurrently; the shared stamp is what lets the join
        # pair the P copies back up
        for ch in self._chans:
            ch.send(arr, seq=self._n)
        self._n += 1

    def send_ctrl(self, msg: dict) -> None:
        for ch in self._chans:
            ch.send_ctrl(msg)

    def send_end(self) -> None:
        for ch in self._chans:
            ch.send_end()

    def close(self, timeout: float | None = None) -> None:
        """END every channel, then join them all; the first failure is
        raised after every channel got its close attempt."""
        first: BaseException | None = None
        for ch in self._chans:
            try:
                ch.close(timeout=timeout)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                if first is None:
                    first = e
        if first is not None:
            raise first

    def flush(self, timeout: float | None = None) -> None:
        for ch in self._chans:
            ch.flush(timeout=timeout)

    def qsize(self) -> int:
        return sum(ch.qsize() for ch in self._chans)
