"""Async double-buffered transport channels: overlap rx, compute, and tx.

A serial stage loop pays rx + decode + compute + encode + tx per tensor,
so per-hop latency is the *sum* of the phases.  The paper's pipeline claim
(+53% ResNet50 throughput at 8 nodes) needs every node to process
microbatch *j* while receiving *j+1* and relaying *j-1* — per-hop cost is
then the *max* of the phases.  This module supplies the two halves of that
overlap for any framed socket:

* :class:`AsyncReceiver` — a daemon thread that reads *and decodes* frames
  into a bounded queue.  A full queue parks the thread in ``put``, which
  stops its reads; TCP flow control then pushes back on the upstream
  sender, so backpressure is preserved end to end with at most
  ``depth`` decoded frames of slack.
* :class:`AsyncSender` — a bounded queue drained by a daemon thread that
  *encodes and sends*.  A full queue blocks the producer (``send``), so a
  slow wire stalls the compute loop after ``depth`` frames, never later.

Both sides surface worker-thread failures on the caller's thread: the
receiver's ``get`` re-raises the exact exception that killed the rx
thread; the sender's next ``send``/``flush`` raises :class:`ChannelError`
chained to the tx thread's failure (and the dead thread drains the queue
so a producer parked in ``send`` always wakes).

Telemetry: pass ``gauge="node.rx_queue_depth"`` to publish the queue's
occupancy as a registry gauge, and ``span=<name or callable>`` to record a
``<name>.rx`` / ``<name>.tx`` span per frame when the process tracer is
enabled — the Perfetto view of rx/compute/tx actually overlapping.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable

from ..obs import REGISTRY, tracer
from .framed import K_END, recv_frame, send_ctrl, send_end, send_frame

#: rx-queue sentinel: the thread died, ``err`` holds why
_ERR = object()
#: tx-queue item kinds
_TENSOR, _CTRL, _END, _FLUSH, _TENSOR_SEQ = 0, 1, 2, 3, 4


class ChannelError(ConnectionError):
    """A channel worker thread died; the original failure is ``__cause__``."""


def _resolve_label(span) -> Callable[[], str] | None:
    if span is None:
        return None
    return span if callable(span) else (lambda: span)


class AsyncReceiver:
    """Daemon rx thread: recv + decode into a bounded in-order queue.

    The thread exits after delivering a ``K_END`` frame (the stream is
    over) or on error.  ``get`` never hangs past its timeout and re-raises
    the rx thread's failure once the queue is drained.
    """

    def __init__(self, sock, *, depth: int = 8, gauge: str | None = None,
                 span=None):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._sock = sock
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._gauge = REGISTRY.gauge(gauge) if gauge else None
        self._span = _resolve_label(span)
        self.err: BaseException | None = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="channel-rx")
        self._thread.start()

    def bind_gauge(self, name: str) -> None:
        """Start publishing queue occupancy under ``name`` — for callers
        that only later learn this connection is worth monitoring (a node
        binds its gauge once a connection becomes THE data stream, so
        short-lived control connections never clobber the reading)."""
        self._gauge = REGISTRY.gauge(name)

    def _run(self):
        n = 0
        try:
            while True:
                t0 = time.perf_counter()
                kind, value = recv_frame(self._sock)
                tr = tracer()
                if tr.enabled and self._span is not None:
                    tr.record(f"{self._span()}.rx", t0,
                              time.perf_counter() - t0, {"seq": n})
                n += 1
                self._q.put((kind, value))
                if self._gauge is not None:
                    self._gauge.v = self._q.qsize()
                if kind == K_END:
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised in get()
            self.err = e
            try:
                self._q.put_nowait(_ERR)
            except queue.Full:
                pass  # get() checks err once the queue drains

    def get(self, timeout: float | None = None) -> tuple:
        """Next (kind, value) in arrival order; re-raises the rx thread's
        failure, raises TimeoutError past ``timeout`` (None = forever)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                item = self._q.get(timeout=0.05)
            except queue.Empty:
                if self.err is not None and self._q.empty():
                    raise self.err
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"no frame within {timeout:.1f}s")
                continue
            return self._unwrap(item)

    def get_nowait(self) -> tuple:
        """Non-blocking :meth:`get`; raises ``queue.Empty`` when no frame
        is ready (the consumer's cue to spend the idle time elsewhere,
        e.g. draining its compute window)."""
        try:
            item = self._q.get_nowait()
        except queue.Empty:
            if self.err is not None:
                raise self.err from None
            raise
        return self._unwrap(item)

    def _unwrap(self, item) -> tuple:
        if self._gauge is not None:
            self._gauge.v = self._q.qsize()
        if item is _ERR:
            raise self.err
        return item

    def qsize(self) -> int:
        return self._q.qsize()


class AsyncSender:
    """Bounded tx queue drained by a daemon encode+send thread.

    ``send``/``send_ctrl``/``send_end`` enqueue in call order; a full
    queue blocks the caller (bounded in-flight depth).  After the tx
    thread dies, every subsequent call raises :class:`ChannelError` and
    the queue is drained so a parked producer always wakes.
    """

    def __init__(self, sock, *, depth: int = 8, codec: str = "raw",
                 gauge: str | None = None, span=None):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._sock = sock
        self.codec = codec
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._gauge = REGISTRY.gauge(gauge) if gauge else None
        self._span = _resolve_label(span)
        self.err: BaseException | None = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="channel-tx")
        self._thread.start()

    # -- producer side -----------------------------------------------------

    def send(self, arr, *, seq: int | None = None) -> None:
        """Enqueue one tensor frame (encode + send happen on the tx
        thread, under this sender's codec).  ``seq`` stamps the frame
        with a stream sequence number (``K_TENSOR_SEQ``) so a downstream
        fan-in can restore order across parallel replica paths."""
        if seq is None:
            self._put((_TENSOR, arr))
        else:
            self._put((_TENSOR_SEQ, (seq, arr)))

    def send_ctrl(self, msg: dict) -> None:
        self._put((_CTRL, msg))

    def send_end(self) -> None:
        """Enqueue the END frame; the tx thread exits after sending it."""
        self._put((_END, None))

    def close(self, timeout: float | None = None) -> None:
        """Send END (after everything already queued) and wait for the tx
        thread to put it on the wire and exit — the caller may close the
        socket afterwards without racing a buffered frame."""
        self.send_end()
        self._thread.join(timeout)
        if self.err is not None:
            raise ChannelError("transport tx thread died") from self.err
        if self._thread.is_alive():
            raise TimeoutError(f"tx queue did not drain in {timeout:.1f}s")

    def flush(self, timeout: float | None = None) -> None:
        """Block until everything enqueued so far is on the wire (or raise
        the tx thread's failure / TimeoutError)."""
        ev = threading.Event()
        self._put((_FLUSH, ev))
        deadline = None if timeout is None else time.monotonic() + timeout
        while not ev.wait(0.05):
            if self.err is not None:
                raise ChannelError("transport tx thread died") from self.err
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"tx queue did not drain in {timeout:.1f}s")
        if self.err is not None:
            raise ChannelError("transport tx thread died") from self.err

    def _put(self, item) -> None:
        while True:
            if self.err is not None:
                raise ChannelError("transport tx thread died") from self.err
            try:
                self._q.put(item, timeout=0.05)
            except queue.Full:
                continue
            if self._gauge is not None:
                self._gauge.v = self._q.qsize()
            return

    def qsize(self) -> int:
        return self._q.qsize()

    # -- tx thread ----------------------------------------------------------

    def _run(self):
        n = 0
        try:
            while True:
                kind, v = self._q.get()
                if self._gauge is not None:
                    self._gauge.v = self._q.qsize()
                if kind == _FLUSH:
                    v.set()
                    continue
                t0 = time.perf_counter()
                if kind == _TENSOR:
                    send_frame(self._sock, v, codec=self.codec)
                elif kind == _TENSOR_SEQ:
                    send_frame(self._sock, v[1], codec=self.codec,
                               seq=v[0])
                elif kind == _CTRL:
                    send_ctrl(self._sock, v)
                else:
                    send_end(self._sock)
                tr = tracer()
                if tr.enabled and self._span is not None \
                        and kind in (_TENSOR, _TENSOR_SEQ):
                    tr.record(f"{self._span()}.tx", t0,
                              time.perf_counter() - t0,
                              {"seq": v[0] if kind == _TENSOR_SEQ else n})
                n += 1
                if kind == _END:
                    # release any flush marker enqueued after the END so
                    # a racing flush() can never hang on a dead thread
                    while True:
                        try:
                            k2, v2 = self._q.get_nowait()
                        except queue.Empty:
                            return
                        if k2 == _FLUSH:
                            v2.set()
        except BaseException as e:  # noqa: BLE001 — surfaced in _put/flush
            self.err = e
            # wake any parked producer and release pending flush waiters;
            # items still queued are dropped (the wire is dead anyway)
            while True:
                try:
                    kind, v = self._q.get_nowait()
                except queue.Empty:
                    return
                if kind == _FLUSH:
                    v.set()  # flush re-checks err after the event fires
