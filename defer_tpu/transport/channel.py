"""Async double-buffered transport channels: overlap rx, compute, and tx.

A serial stage loop pays rx + decode + compute + encode + tx per tensor,
so per-hop latency is the *sum* of the phases.  The paper's pipeline claim
(+53% ResNet50 throughput at 8 nodes) needs every node to process
microbatch *j* while receiving *j+1* and relaying *j-1* — per-hop cost is
then the *max* of the phases.  This module supplies the two halves of that
overlap for any framed socket:

* :class:`AsyncReceiver` — a daemon thread that reads *and decodes* frames
  into a bounded queue.  A full queue parks the thread in ``put``, which
  stops its reads; TCP flow control then pushes back on the upstream
  sender, so backpressure is preserved end to end with at most
  ``depth`` decoded frames of slack.
* :class:`AsyncSender` — a bounded queue drained by a daemon thread that
  *encodes and sends*.  A full queue blocks the producer (``send``), so a
  slow wire stalls the compute loop after ``depth`` frames, never later.

Both sides surface worker-thread failures on the caller's thread: the
receiver's ``get`` re-raises the exact exception that killed the rx
thread; the sender's next ``send``/``flush`` raises :class:`ChannelError`
chained to the tx thread's failure (and the dead thread drains the queue
so a producer parked in ``send`` always wakes).

Telemetry: pass ``gauge="node.rx_queue_depth"`` to publish the queue's
occupancy as a registry gauge (ADDITIVE ``inc``/``dec`` updates, so
several channels sharing a name report their total; ``take_watermark``
returns the per-interval peak), ``hist="node.rx_s"`` to record per-frame
recv+decode / encode+send seconds, and ``span=<name or callable>`` to
record a ``<name>.rx`` / ``<name>.tx`` span per frame when the process
tracer is enabled — the Perfetto view of rx/compute/tx actually
overlapping.  Setting ``sample_every = N`` switches per-frame spans to
1-in-N waterfall sampling keyed on the wire sequence number, adding
``.rx_wait`` / ``.tx_wait`` queue-time spans for the sampled frames
(docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable

from ..obs import REGISTRY, LatencyHistogram, tracer
from .framed import (K_END, K_TENSOR, K_TENSOR_SEQ, recv_frame, send_ctrl,
                     send_end, send_frame)

#: rx-queue sentinel: the thread died, ``err`` holds why
_ERR = object()
#: tx-queue item kinds
_TENSOR, _CTRL, _END, _FLUSH, _TENSOR_SEQ = 0, 1, 2, 3, 4


class ChannelError(ConnectionError):
    """A channel worker thread died; the original failure is ``__cause__``."""


def _resolve_label(span) -> Callable[[], str] | None:
    if span is None:
        return None
    return span if callable(span) else (lambda: span)


def _sampled(sample_every: int, seq: int | None) -> bool:
    """Waterfall sampling predicate: ``sample_every <= 0`` keeps the
    pre-sampling behavior (every frame records its span); ``N >= 1``
    records only frames whose WIRE sequence number is a multiple of N —
    the same 1-in-N frames in every process of the chain, so the sampled
    frame's full rx-wait/infer/tx-wait path stitches into one waterfall
    (docs/OBSERVABILITY.md).  Frames without a wire seq are not sampled.
    """
    if sample_every <= 0:
        return True
    return seq is not None and seq % sample_every == 0


class AsyncReceiver:
    """Daemon rx thread: recv + decode into a bounded in-order queue.

    The thread exits after delivering a ``K_END`` frame (the stream is
    over) or on error.  ``get`` never hangs past its timeout and re-raises
    the rx thread's failure once the queue is drained.
    """

    #: waterfall sampling period for per-frame spans (0 = every frame);
    #: set by the owner when the trace context carries ``sample_every``
    sample_every: int = 0

    def __init__(self, sock, *, depth: int = 8, gauge: str | None = None,
                 span=None, hist: str | None = None):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._sock = sock
        self.depth = depth
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._gauge = REGISTRY.gauge(gauge) if gauge else None
        self._span = _resolve_label(span)
        #: registry histogram of recv+decode seconds per tensor frame
        #: (always-on; the live bottleneck estimate reads it)
        self._hist = REGISTRY.histogram(hist) if hist else None
        #: per-CHANNEL decode seconds (codec work only, no blocking recv
        #: wait) — the live bottleneck estimate's per-node attribution
        #: even when several in-process nodes share the registry
        self.dec = LatencyHistogram()
        #: high watermark of queue occupancy since take_watermark()
        self.hi = 0
        self.err: BaseException | None = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="channel-rx")
        self._thread.start()

    def bind_gauge(self, name: str) -> None:
        """Start publishing queue occupancy under ``name`` — for callers
        that only later learn this connection is worth monitoring (a node
        binds its gauge once a connection becomes THE data stream, so
        short-lived control connections never clobber the reading).
        Gauge updates are ADDITIVE (``inc``/``dec``) so several channels
        sharing one name report their total; binding syncs the current
        occupancy in (±1 transient if the rx thread races the bind)."""
        g = REGISTRY.gauge(name)
        g.inc(self._q.qsize())
        self._gauge = g

    def bind_hist(self, name: str) -> None:
        """Start recording per-frame recv+decode seconds under ``name``
        (bound with the gauge once a connection proves to be the data
        stream)."""
        self._hist = REGISTRY.histogram(name)

    def take_watermark(self) -> int:
        """Max queue occupancy since the previous call (the per-interval
        depth watermark an obs_push reports)."""
        h = max(self.hi, self._q.qsize())
        self.hi = self._q.qsize()
        return h

    def release_gauge(self) -> None:
        """Return this channel's remaining contribution to its shared
        ADDITIVE gauge and unbind: a stream abandoned mid-flight leaves
        queued frames nobody will ever dequeue, and without this the
        gauge would carry the dead stream's depth forever (the old
        absolute-set updates self-corrected; additive ones must
        reconcile).  ±1 transient if the rx thread races the unbind."""
        g, self._gauge = self._gauge, None
        if g is not None:
            g.dec(self._q.qsize())

    def _run(self):
        n = 0
        try:
            while True:
                t0 = time.perf_counter()
                kind, value = recv_frame(self._sock,
                                         on_decode=self.dec.record)
                dt = time.perf_counter() - t0
                if kind in (K_TENSOR, K_TENSOR_SEQ):
                    if self._hist is not None:
                        self._hist.record(dt)
                    tr = tracer()
                    if tr.enabled and self._span is not None:
                        seq = value[0] if kind == K_TENSOR_SEQ else None
                        if _sampled(self.sample_every, seq):
                            tr.record(f"{self._span()}.rx", t0, dt,
                                      {"seq": n if seq is None else seq})
                n += 1
                self._q.put((kind, value, time.perf_counter()))
                if self._gauge is not None:
                    self._gauge.inc()
                q = self._q.qsize()
                if q > self.hi:
                    self.hi = q
                if kind == K_END:
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised in get()
            self.err = e
            try:
                self._q.put_nowait(_ERR)
            except queue.Full:
                pass  # get() checks err once the queue drains

    def get(self, timeout: float | None = None) -> tuple:
        """Next (kind, value) in arrival order; re-raises the rx thread's
        failure, raises TimeoutError past ``timeout`` (None = forever)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                item = self._q.get(timeout=0.05)
            except queue.Empty:
                if self.err is not None and self._q.empty():
                    raise self.err
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"no frame within {timeout:.1f}s")
                continue
            return self._unwrap(item)

    def get_nowait(self) -> tuple:
        """Non-blocking :meth:`get`; raises ``queue.Empty`` when no frame
        is ready (the consumer's cue to spend the idle time elsewhere,
        e.g. draining its compute window)."""
        try:
            item = self._q.get_nowait()
        except queue.Empty:
            if self.err is not None:
                raise self.err from None
            raise
        return self._unwrap(item)

    def _unwrap(self, item) -> tuple:
        if item is _ERR:
            raise self.err
        if self._gauge is not None:
            self._gauge.dec()
        kind, value, t_enq = item
        if self._span is not None and self.sample_every > 0:
            # waterfall sampling: how long the sampled frame waited in
            # the rx queue before the compute loop took it
            tr = tracer()
            seq = value[0] if kind == K_TENSOR_SEQ else None
            if tr.enabled and _sampled(self.sample_every, seq):
                now = time.perf_counter()
                tr.record(f"{self._span()}.rx_wait", t_enq, now - t_enq,
                          {"seq": seq})
        return kind, value

    def qsize(self) -> int:
        return self._q.qsize()


class AsyncSender:
    """Bounded tx queue drained by a daemon encode+send thread.

    ``send``/``send_ctrl``/``send_end`` enqueue in call order; a full
    queue blocks the caller (bounded in-flight depth).  After the tx
    thread dies, every subsequent call raises :class:`ChannelError` and
    the queue is drained so a parked producer always wakes.
    """

    #: waterfall sampling period for per-frame spans (0 = every frame)
    sample_every: int = 0

    def __init__(self, sock, *, depth: int = 8, codec: str = "raw",
                 gauge: str | None = None, span=None,
                 hist: str | None = None):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._sock = sock
        self.codec = codec
        self.depth = depth
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._gauge = REGISTRY.gauge(gauge) if gauge else None
        self._span = _resolve_label(span)
        #: registry histogram of encode+send seconds per tensor frame
        self._hist = REGISTRY.histogram(hist) if hist else None
        #: per-CHANNEL encode seconds (codec work only) — see
        #: ``AsyncReceiver.dec``
        self.enc = LatencyHistogram()
        #: high watermark of queue occupancy since take_watermark()
        self.hi = 0
        self.err: BaseException | None = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="channel-tx")
        self._thread.start()

    def take_watermark(self) -> int:
        """Max queue occupancy since the previous call."""
        h = max(self.hi, self._q.qsize())
        self.hi = self._q.qsize()
        return h

    # -- producer side -----------------------------------------------------

    def send(self, arr, *, seq: int | None = None) -> None:
        """Enqueue one tensor frame (encode + send happen on the tx
        thread, under this sender's codec).  ``seq`` stamps the frame
        with a stream sequence number (``K_TENSOR_SEQ``) so a downstream
        fan-in can restore order across parallel replica paths."""
        if seq is None:
            self._put((_TENSOR, arr))
        else:
            self._put((_TENSOR_SEQ, (seq, arr)))

    def send_ctrl(self, msg: dict) -> None:
        self._put((_CTRL, msg))

    def send_end(self) -> None:
        """Enqueue the END frame; the tx thread exits after sending it."""
        self._put((_END, None))

    def close(self, timeout: float | None = None) -> None:
        """Send END (after everything already queued) and wait for the tx
        thread to put it on the wire and exit — the caller may close the
        socket afterwards without racing a buffered frame."""
        self.send_end()
        self._thread.join(timeout)
        if self.err is not None:
            raise ChannelError("transport tx thread died") from self.err
        if self._thread.is_alive():
            raise TimeoutError(f"tx queue did not drain in {timeout:.1f}s")

    def flush(self, timeout: float | None = None) -> None:
        """Block until everything enqueued so far is on the wire (or raise
        the tx thread's failure / TimeoutError)."""
        ev = threading.Event()
        self._put((_FLUSH, ev))
        deadline = None if timeout is None else time.monotonic() + timeout
        while not ev.wait(0.05):
            if self.err is not None:
                raise ChannelError("transport tx thread died") from self.err
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"tx queue did not drain in {timeout:.1f}s")
        if self.err is not None:
            raise ChannelError("transport tx thread died") from self.err

    def _put(self, item) -> None:
        while True:
            if self.err is not None:
                raise ChannelError("transport tx thread died") from self.err
            try:
                self._q.put(item + (time.perf_counter(),), timeout=0.05)
            except queue.Full:
                continue
            if self._gauge is not None:
                self._gauge.inc()
            q = self._q.qsize()
            if q > self.hi:
                self.hi = q
            return

    def qsize(self) -> int:
        return self._q.qsize()

    # -- tx thread ----------------------------------------------------------

    def _run(self):
        n = 0
        try:
            while True:
                kind, v, t_enq = self._q.get()
                if self._gauge is not None:
                    self._gauge.dec()
                if kind == _FLUSH:
                    v.set()
                    continue
                t0 = time.perf_counter()
                if kind == _TENSOR:
                    send_frame(self._sock, v, codec=self.codec,
                               on_encode=self.enc.record)
                elif kind == _TENSOR_SEQ:
                    send_frame(self._sock, v[1], codec=self.codec,
                               seq=v[0], on_encode=self.enc.record)
                elif kind == _CTRL:
                    send_ctrl(self._sock, v)
                else:
                    send_end(self._sock)
                if kind in (_TENSOR, _TENSOR_SEQ):
                    dt = time.perf_counter() - t0
                    if self._hist is not None:
                        self._hist.record(dt)
                    tr = tracer()
                    if tr.enabled and self._span is not None:
                        seq = v[0] if kind == _TENSOR_SEQ else None
                        if _sampled(self.sample_every, seq):
                            label = self._span()
                            if self.sample_every > 0:
                                # waterfall sampling: queue wait before
                                # the frame reached the wire
                                tr.record(f"{label}.tx_wait", t_enq,
                                          t0 - t_enq, {"seq": seq})
                            tr.record(f"{label}.tx", t0, dt,
                                      {"seq": n if seq is None else seq})
                n += 1
                if kind == _END:
                    # release any flush marker enqueued after the END so
                    # a racing flush() can never hang on a dead thread
                    while True:
                        try:
                            k2, v2, _ = self._q.get_nowait()
                        except queue.Empty:
                            return
                        if self._gauge is not None:
                            self._gauge.dec()
                        if k2 == _FLUSH:
                            v2.set()
        except BaseException as e:  # noqa: BLE001 — surfaced in _put/flush
            self.err = e
            # wake any parked producer and release pending flush waiters;
            # items still queued are dropped (the wire is dead anyway)
            while True:
                try:
                    kind, v, _ = self._q.get_nowait()
                except queue.Empty:
                    return
                if self._gauge is not None:
                    self._gauge.dec()
                if kind == _FLUSH:
                    v.set()  # flush re-checks err after the event fires
