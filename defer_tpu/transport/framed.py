"""Framed stream transport for the host/DCN edge.

The reference's entire distributed backend is a hand-rolled framed TCP
protocol: 8-byte big-endian length prefix, fixed-size chunking, non-blocking
sockets parked on select() (reference src/node_state.py:43-101).  In the TPU
design that role is played by ICI/DCN collectives *inside* the pod; this
module exists for the edge the collectives don't cover — a remote client
streaming inference inputs to (and results from) the pipeline host.

Design differences from the reference, on purpose:
  * Blocking sockets + memoryview scatter/gather writes instead of
    non-blocking + select-spin: simpler, same throughput, no EAGAIN loops.
  * One connection carries typed frames (header with kind/shape/dtype/codec)
    instead of three fixed single-purpose ports (5000/5001/5002,
    reference src/node.py:17).
  * Codec is negotiated per frame (raw / blockfloat+lzb), not hardwired,
    and encode/decode are symmetric (the reference's decode sides are
    asymmetric — SURVEY.md §3.5).
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Any

import numpy as np

from ..codec import BlockFloatCodec, Codec, LosslessCodec, PipelineCodec, RawCodec
from ..obs import REGISTRY


def _env_int(name: str) -> int:
    v = os.environ.get(name, "")
    return int(v) if v else 0


#: default kernel socket buffer sizes for data sockets (bytes; 0 = leave
#: the kernel default).  Overridable per process via environment or the
#: ``--sock-buf`` CLI flag; big cross-host hops with high bandwidth-delay
#: product want these raised well past the Linux default.
SOCK_SNDBUF = _env_int("DEFER_SOCK_SNDBUF")
SOCK_RCVBUF = _env_int("DEFER_SOCK_RCVBUF")


def default_sock_buf(max_frame_bytes: int, *, floor: int = 1 << 16,
                     ceil: int = 1 << 23) -> int:
    """SO_SNDBUF/SO_RCVBUF sized to a chain's fattest boundary frame.

    Two frames of headroom (one draining into the kernel while the next
    encodes), clamped to [64 KiB, 8 MiB]: below the floor small-tensor
    chains would lose to syscall churn, above the ceiling a 100 MB
    activation should flow-control rather than buffer whole in the
    kernel.  Callers derive ``max_frame_bytes`` from the partition's
    boundary specs (``graph.analysis.max_activation_bytes``) instead of
    guessing a flat constant.
    """
    return max(floor, min(ceil, 2 * int(max_frame_bytes)))


def configure_socket(sock: socket.socket, *, nodelay: bool = True,
                     sndbuf: int | None = None,
                     rcvbuf: int | None = None) -> socket.socket:
    """Tune a data socket: TCP_NODELAY plus optional SO_SNDBUF/SO_RCVBUF.

    Every frame here is a complete message the peer is waiting on —
    small K_CTRL/K_ACK/K_END frames under Nagle + delayed ACK add up to
    ~40 ms stalls per handshake on localhost chains, so NODELAY is the
    default on every data socket.  Non-TCP sockets (AF_UNIX socketpairs
    in tests) are left untouched, and non-socket transports entirely —
    the in-memory channel objects of the ``local`` tier
    (``transport/local.py``) have no kernel buffers to size, so every
    tuning step (NODELAY, SO_SNDBUF/SO_RCVBUF, the ``default_sock_buf``
    clamp) is skipped rather than raising on them.
    """
    if not isinstance(sock, socket.socket):
        return sock  # non-TCP tier (local pipe end / test double)
    if sndbuf is None:
        sndbuf = SOCK_SNDBUF
    if rcvbuf is None:
        rcvbuf = SOCK_RCVBUF
    try:
        if nodelay:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass  # not TCP (e.g. AF_UNIX)
    try:
        if sndbuf:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, sndbuf)
        if rcvbuf:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, rcvbuf)
    except OSError:
        pass
    return sock


def connect_retry(host: str, port: int, timeout_s: float = 30.0,
                  *, base_delay_s: float = 0.05,
                  max_delay_s: float = 1.0) -> socket.socket:
    """Connect to a peer that may still be booting: exponential backoff
    with full jitter (50 ms envelope doubling to 1 s) capped by the
    ``timeout_s`` deadline, returning a :func:`configure_socket`-tuned
    connection.  The one retry policy for every control/data dial in
    the chain (stage nodes, dispatcher, monitor subscriptions, failover
    re-dials).  Jitter matters on the failover path: R replica channels
    re-dialing a respawned process on a fixed cadence would arrive in
    lockstep bursts.  Every failed attempt emits a ``redial`` flight-
    recorder event, so ``monitor --events`` attributes exactly how a
    failover re-dial converged (docs/ROBUSTNESS.md)."""
    import random

    deadline = time.monotonic() + timeout_s
    envelope = base_delay_s
    attempt = 0
    while True:
        try:
            # per-attempt connect timeout is bounded by the remaining
            # deadline, so the LAST attempt cannot overshoot the cap
            budget = max(0.001, min(timeout_s,
                                    deadline - time.monotonic()))
            return configure_socket(
                socket.create_connection((host, port), timeout=budget))
        except OSError as e:
            attempt += 1
            now = time.monotonic()
            if now >= deadline:
                raise
            # full jitter: uniform over the exponential envelope,
            # clipped to what the deadline still allows
            delay = min(random.uniform(0.0, envelope), deadline - now)
            from ..obs.events import emit as _emit
            _emit("redial", addr=f"{host}:{port}", attempt=attempt,
                  delay_ms=round(delay * 1e3, 3),
                  error=type(e).__name__)
            time.sleep(delay)
            envelope = min(envelope * 2, max_delay_s)

#: frame kinds
K_TENSOR = 1
K_BYTES = 2
K_END = 3
K_CTRL = 4   # JSON control message (deploy/reweight handshake)
K_ACK = 5    # the reference's 1-byte \x06 ACK (src/node.py:42), framed
K_TENSOR_SEQ = 6  # v2: K_TENSOR + a u64 sequence number after the header

#: wire protocol version.  v2 adds K_TENSOR_SEQ: a tensor frame carrying
#: a monotonically increasing stream sequence number (u64, big-endian,
#: between the fixed header and the codec name) so frames that travel
#: parallel paths — data-parallel stage replicas — can be merged back
#: into strict stream order at the fan-in (docs/TRANSPORT.md).  v1
#: receivers reject kind 6 loudly; every other frame kind is unchanged.
PROTOCOL_VERSION = 2

_CODECS: dict[str, Codec] = {}
#: creation lock: ``TensorClient.infer_stream`` decodes on a receiver
#: thread while the sender encodes — both may fault the same codec in.
#: Reads stay lock-free (dict get under the GIL); only misses lock.
_CODECS_LOCK = threading.Lock()

# wire telemetry: per-hop frame/byte counters plus codec encode/decode
# latency histograms, all in the process registry.  Plain attribute
# increments on the hot path; a snapshot is only paid when exported.
_TX_FRAMES = REGISTRY.counter("transport.tx_frames")
_TX_BYTES = REGISTRY.counter("transport.tx_bytes")
_RX_FRAMES = REGISTRY.counter("transport.rx_frames")
_RX_BYTES = REGISTRY.counter("transport.rx_bytes")
_ENC_HIST = REGISTRY.histogram("codec.encode_s")
_DEC_HIST = REGISTRY.histogram("codec.decode_s")


class _SleepCodec(Codec):
    """Test/bench-only wrapper: a real codec plus a fixed per-side delay.

    ``sleep<ms>+<codec>`` models per-hop phases a CPU-bound localhost
    chain cannot express (accelerator compute, NIC serialization): the
    sleep occupies wall time without occupying the CPU, which is exactly
    the resource profile the rx/compute/tx overlap is built for.  The
    wire payload is byte-identical to the wrapped codec's.  Used by
    ``scripts/chain_overlap_smoke.py``; never pick it for deployments.

    ``esleep<ms>+<codec>`` / ``dsleep<ms>+<codec>`` delay only the
    encode / only the decode side — the one-sided variants let a bench
    place the modeled non-CPU time on a *specific* process of a chain
    (``scripts/replication_smoke.py`` makes one stage the bottleneck by
    paying ``dsleep`` on its inbound hop and ``esleep`` on its outbound
    hop, so the delay lands in the replicated stage's processes only).
    """

    name = "sleep"

    def __init__(self, delay_s: float, inner: Codec, *,
                 enc: bool = True, dec: bool = True):
        self._delay_s = delay_s
        self._inner = inner
        self._enc = enc
        self._dec = dec

    def encode(self, arr):
        if self._enc:
            time.sleep(self._delay_s)
        return self._inner.encode(arr)

    def decode(self, data, shape, dtype):
        if self._dec:
            time.sleep(self._delay_s)
        return self._inner.decode(data, shape, dtype)


def _make_codec(name: str) -> Codec:
    if name == "raw":
        return RawCodec()
    if name == "lzb":
        return LosslessCodec()
    if name.startswith("bf"):
        return PipelineCodec(bits=int(name[2:]))
    if name.startswith("sleep"):
        head, _, inner = name.partition("+")
        return _SleepCodec(float(head[5:]) / 1e3, _make_codec(inner or "raw"))
    if name.startswith("esleep") or name.startswith("dsleep"):
        head, _, inner = name.partition("+")
        return _SleepCodec(float(head[6:]) / 1e3, _make_codec(inner or "raw"),
                           enc=name[0] == "e", dec=name[0] == "d")
    raise ValueError(f"unknown codec {name!r}")


def _codec(name: str) -> Codec:
    c = _CODECS.get(name)
    if c is not None:
        return c
    with _CODECS_LOCK:
        c = _CODECS.get(name)
        if c is None:
            c = _CODECS[name] = _make_codec(name)
    return c


# header: kind u8 | codec len u8 | dtype len u8 | ndim u8 | payload len u64
_HDR = struct.Struct(">BBBBQ")
MAX_FRAME = 1 << 34  # 16 GiB sanity bound


def wire_dtype(dtype) -> str:
    """The dtype string a frame (or shm doorbell descriptor) ships:
    numpy's ``.str`` for builtin dtypes, the registered NAME (e.g.
    ``bfloat16``) for extension dtypes whose ``.str`` is an opaque void
    alias (``<V2``) that would decode as raw bytes on the far end."""
    s = dtype.str
    if np.dtype(s) != dtype:
        return dtype.name
    return s


def dtype_from_wire(s: str) -> np.dtype:
    """Inverse of :func:`wire_dtype`.  Extension-dtype NAMES only
    resolve once ml_dtypes has registered them — import it on demand
    so a consumer that never imported jax still decodes bf16."""
    try:
        return np.dtype(s)
    except TypeError:
        import ml_dtypes  # noqa: F401 — import registers the dtypes
        return np.dtype(s)


def _sendv(sock: socket.socket, *parts) -> None:
    """Scatter-gather sendall (``sendmsg``/writev): the frame goes out as
    one syscall per kernel-buffer fill with NO concatenation copy of the
    payload — the old ``hdr + cname + meta + payload`` built a second
    multi-megabyte buffer per activation frame."""
    sendmsg = getattr(sock, "sendmsg", None)
    if sendmsg is None:  # platform without sendmsg: one copy, one sendall
        sock.sendall(b"".join(bytes(p) for p in parts))
        return
    views = [memoryview(p).cast("B") for p in parts if len(p)]
    while views:
        n = sendmsg(views)
        while views and n >= len(views[0]):
            n -= len(views[0])
            del views[0]
        if n:
            views[0] = views[0][n:]


def send_frame(sock: socket.socket, arr_or_bytes, *, codec: str = "raw",
               seq: int | None = None, on_encode=None):
    """Send one typed frame (tensor or raw bytes).

    ``seq`` (tensor frames only) stamps the frame with a u64 stream
    sequence number (kind ``K_TENSOR_SEQ``, protocol v2) so a fan-in
    downstream of data-parallel replicas can restore stream order.
    ``on_encode(dt_s)`` is called with the encode seconds of a tensor
    frame — per-CHANNEL cost attribution (the process-wide
    ``codec.encode_s`` histogram records regardless)."""
    if isinstance(arr_or_bytes, (bytes, bytearray, memoryview)):
        kind, payload = K_BYTES, arr_or_bytes  # scatter-gather: no copy
        meta = b""
        cname = b"raw"
        ndim = 0
    else:
        arr = np.asarray(arr_or_bytes)
        kind = K_TENSOR if seq is None else K_TENSOR_SEQ
        t0 = time.perf_counter()
        if codec == "raw":
            # zero-copy: the payload is a view of the array's own buffer
            # (ascontiguousarray is a no-op for the usual contiguous case)
            try:
                payload = memoryview(np.ascontiguousarray(arr)).cast("B")
            except (TypeError, ValueError):  # 0-d / exotic dtypes
                payload = _codec(codec).encode(arr)
        else:
            payload = _codec(codec).encode(arr)
        dt = time.perf_counter() - t0
        _ENC_HIST.record(dt)
        if on_encode is not None:
            on_encode(dt)
        cname = codec.encode()
        dt = wire_dtype(arr.dtype).encode()
        meta = dt + b"".join(struct.pack(">Q", s) for s in arr.shape)
        ndim = arr.ndim
    dt_len = len(meta) - 8 * ndim if kind != K_BYTES else 0
    plen = payload.nbytes if isinstance(payload, memoryview) else len(payload)
    hdr = _HDR.pack(kind, len(cname), dt_len, ndim, plen)
    # v2: the sequence number rides between the fixed header and the
    # codec name, so every later field keeps its v1 offset relative to it
    pre = struct.pack(">Q", seq) if kind == K_TENSOR_SEQ else b""
    _sendv(sock, hdr + pre + cname + meta, payload)
    _TX_FRAMES.n += 1
    _TX_BYTES.n += _HDR.size + len(pre) + len(cname) + len(meta) + plen


def send_end(sock: socket.socket):
    sock.sendall(_HDR.pack(K_END, 0, 0, 0, 0))


def send_ctrl(sock: socket.socket, msg: dict):
    """Send one JSON control frame (the control-plane channel: deploy,
    reweight — reference src/dispatcher.py:58-63's arch+topology send)."""
    import json as _json
    payload = _json.dumps(msg).encode()
    sock.sendall(_HDR.pack(K_CTRL, 0, 0, 0, len(payload)) + payload)


def send_ack(sock: socket.socket):
    sock.sendall(_HDR.pack(K_ACK, 0, 0, 0, 0))


def recv_expect(sock: socket.socket, kind: int) -> Any:
    """Receive one frame and demand its kind — loud handshake errors."""
    got, value = recv_frame(sock)
    if got != kind:
        raise ConnectionError(f"expected frame kind {kind}, got {got} "
                              f"({value if got == K_CTRL else ''})")
    return value


def _recv_into(sock: socket.socket, n: int) -> bytearray:
    """Read exactly ``n`` bytes into a fresh buffer — returned as the
    bytearray itself, NOT a ``bytes(buf)`` copy: tensor payloads go
    straight to ``np.frombuffer``/codec decode over this buffer."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed mid-frame")
        got += r
    return buf


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    return bytes(_recv_into(sock, n))


def recv_frame(sock: socket.socket, *, on_decode=None) -> tuple[int, Any]:
    """Receive one frame -> (kind, payload).  Tensor frames are decoded to
    ndarrays; K_END returns (K_END, None); K_TENSOR_SEQ (protocol v2)
    returns (K_TENSOR_SEQ, (seq, ndarray)).  ``on_decode(dt_s)`` is
    called with the decode seconds of a tensor frame — per-CHANNEL cost
    attribution, excluding the blocking recv wait (the process-wide
    ``codec.decode_s`` histogram records regardless)."""
    kind, clen, dlen, ndim, plen = _HDR.unpack(_recv_into(sock, _HDR.size))
    _RX_FRAMES.n += 1
    _RX_BYTES.n += _HDR.size + clen + dlen + 8 * ndim + plen
    if kind == K_END:
        return K_END, None
    if kind == K_ACK:
        return K_ACK, None
    if plen > MAX_FRAME:
        raise ValueError(f"frame of {plen} bytes exceeds bound")
    if kind == K_CTRL:
        import json as _json
        return K_CTRL, _json.loads(_recv_into(sock, plen).decode())
    seq = None
    if kind == K_TENSOR_SEQ:
        seq = struct.unpack(">Q", _recv_into(sock, 8))[0]
        _RX_BYTES.n += 8
    cname = _recv_into(sock, clen).decode()
    if kind == K_BYTES:
        return K_BYTES, _recv_exact(sock, plen)
    dt = dtype_from_wire(_recv_into(sock, dlen).decode())
    shape = tuple(struct.unpack(">Q", _recv_into(sock, 8))[0]
                  for _ in range(ndim))
    buf = _recv_into(sock, plen)
    t0 = time.perf_counter()
    if cname == "raw":
        # zero-copy: the returned ndarray is a view over the rx buffer
        # (freshly allocated per frame, so it is exclusively owned)
        value = np.frombuffer(buf, dtype=dt).reshape(shape)
    else:
        value = _codec(cname).decode(memoryview(buf), shape, dt)
    dt_dec = time.perf_counter() - t0
    _DEC_HIST.record(dt_dec)
    if on_decode is not None:
        on_decode(dt_dec)
    if seq is not None:
        return K_TENSOR_SEQ, (seq, value)
    return K_TENSOR, value


class TensorServer:
    """Accepts one client streaming tensor frames; hands them to a callback
    and streams result frames back.  This is the host/DCN front door of a
    pipeline deployment — the role of the dispatcher's paired data socket +
    result server (reference src/dispatcher.py:85-105), on one connection.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = socket.create_server((host, port))
        self.address = self._srv.getsockname()

    def serve_once(self, handler, *, codec: str = "raw"):
        """Accept one client; for each tensor frame, reply with
        handler(array) as a tensor frame.  Returns after the client's END
        frame (echoed back)."""
        conn, _ = self._srv.accept()
        configure_socket(conn)
        try:
            while True:
                kind, value = recv_frame(conn)
                if kind == K_END:
                    send_end(conn)
                    return
                send_frame(conn, handler(value), codec=codec)
        finally:
            conn.close()

    def close(self):
        self._srv.close()


class TensorClient:
    """Client side: request/reply ``infer`` or full-duplex ``infer_stream``.

    ``timeout_s`` bounds how long ``infer_stream`` waits for the endpoint
    to drain after the last input (per-call override available); the old
    hardcoded 600 s default is kept for compatibility."""

    def __init__(self, host: str, port: int, *, timeout_s: float = 600.0):
        self._sock = configure_socket(socket.create_connection((host, port)))
        self.timeout_s = timeout_s

    def infer(self, arr: np.ndarray, *, codec: str = "raw") -> np.ndarray:
        send_frame(self._sock, arr, codec=codec)
        kind, value = recv_frame(self._sock)
        if kind != K_TENSOR:
            raise ConnectionError("expected tensor reply")
        return value

    def infer_stream(self, arrays, *, codec: str = "raw",
                     timeout_s: float | None = None) -> list:
        """Pipelined streaming against a ``Defer.serve_endpoint``: sends
        every input without waiting (keeping the remote pipeline full),
        collects in-order replies concurrently, ends the stream, and
        returns all results.  One call = the reference harness's whole
        send-loop + result-server pair (reference test/test.py:39-51).

        ``timeout_s`` bounds the post-END drain wait (default: the
        client's ``timeout_s``)."""
        if timeout_s is None:
            timeout_s = self.timeout_s

        results: list[np.ndarray] = []
        err: list[BaseException] = []

        def rx():
            try:
                while True:
                    kind, value = recv_frame(self._sock)
                    if kind == K_END:
                        return
                    results.append(value)
            except BaseException as e:  # noqa: BLE001 — surfaced below
                err.append(e)

        t = threading.Thread(target=rx, daemon=True)
        t.start()
        try:
            for a in arrays:
                if err:
                    break  # endpoint died: fail fast instead of pumping
                    # sends into a full socket buffer (sendall can block
                    # forever against a peer that stopped draining)
                send_frame(self._sock, a, codec=codec)
            if not err:
                send_end(self._sock)
        except OSError:
            # the send side broke: prefer the rx thread's root cause
            t.join(timeout=5.0)
            if not err:
                raise
        t.join(timeout=timeout_s)
        if err:
            raise err[0]
        if t.is_alive():
            raise TimeoutError(
                f"endpoint did not drain within {timeout_s:.0f}s")
        return results

    def close(self):
        try:
            send_end(self._sock)
            recv_frame(self._sock)
        except (OSError, ConnectionError):
            pass  # stream already ended / peer gone
        finally:
            self._sock.close()
