"""Device-resident transport tier: live ``jax.Array`` handoff across stages.

Every colocated tier so far still ROUND-TRIPS the host per hop: the
producing stage materializes its output with ``np.asarray`` (a D2H
sync), hands host bytes to the channel (``local`` by reference, ``shm``
through a ring), and the consuming stage's program re-uploads them with
a fresh device transfer.  On a real TPU mesh that is a D2H + H2D pair
per activation per hop — the exact cost GSPMD's co-scheduled programs
and MPK's mega-kernels exist to avoid (PAPERS.md).  This module is the
missing top rung of the tier ladder:

* :class:`IciPipe` — a bounded in-process frame stream (the
  :class:`~defer_tpu.transport.local.LocalPipe` machinery verbatim:
  seq stamping, in-order K_CTRL, cascading K_END, bounded backpressure,
  peer death poisoning both ends) whose tensor frames carry **live
  ``jax.Array``s**.  No ``np.asarray``, no codec, no socket payload:
  the consuming stage program ingests the device buffer directly, and
  the only host sync left in the whole chain is the dispatcher's result
  edge — exactly once per frame.
* **Cross-device hops** — when the two stages are pinned to *distinct*
  jax devices, :meth:`IciSender.send` performs one
  ``jax.device_put(x, device)`` (a device-to-device transfer, never via
  host) so the receiver's pinned program consumes the array without a
  placement conflict.  Same-device (or unpinned) hops pass the array by
  reference — zero copies.  The sender counts its cross-device puts and
  the (src, dst) device-id pairs, so stats can PROVE a hop moved data
  between distinct devices.
* **Negotiation** — the probe carries ``{"cmd": "tier_probe", "want":
  "ici", backend, platform, device_ids, pid, proto, token}``.  The
  grantor accepts only when the protocol version and pid match, the
  token resolves in this process's offer registry (live object handoff
  needs one address space — the same structural proof the ``local``
  tier uses), AND it can resolve every offered device id on its own
  backend: the resolve IS the same-mesh proof, the same
  proof-by-capability shape as the shm grant's segment open (a peer on
  another mesh/backend can name devices this process cannot resolve).
  The ``tier_reply`` carries the receiver's pinned device id (or None)
  so the sender knows where to ``device_put``.  Any failed check
  silently degrades the hop down the ladder
  (``transport.shm.offer_tier_ladder``) with one labeled
  ``transport.tier_fallback.<hop>`` count for the whole ladder.

The multi-device CPU host (``XLA_FLAGS
--xla_force_host_platform_device_count=N``, see
``utils.compat.force_host_device_count``) is the test vehicle: it gives
a real N-device mesh in one process, so grant validation, cross-device
``device_put``, and byte identity are exercised for real without a TPU.
"""

from __future__ import annotations

import os
import threading
import uuid

from ..obs import REGISTRY
from .framed import (K_CTRL, K_TENSOR, K_TENSOR_SEQ, PROTOCOL_VERSION,
                     recv_expect, send_ctrl)
from .local import LocalPipe, LocalReceiver, LocalSender, record_fallback

__all__ = ["IciPipe", "IciReceiver", "IciSender", "grant_ici",
           "offer_ici"]

#: tensor frames handed device-resident through ici pipes (the
#: device-resident analogue of ``transport.local_frames`` — ici hops
#: bump neither the wire counters nor the local/shm ones, so each
#: counter keeps meaning exactly one transport)
_ICI_FRAMES = REGISTRY.counter("transport.ici_frames")

#: cross-device ``device_put`` transfers performed by ici senders
_ICI_D2D = REGISTRY.counter("transport.ici_d2d")

#: token -> IciPipe: offers awaiting a grant.  Process-local on purpose
#: — a live jax.Array can only be handed within one address space, so
#: an unresolvable token refuses the offer structurally (same shape as
#: the local tier's registry proof).
_OFFERS: dict[str, "IciPipe"] = {}
_OFFERS_LOCK = threading.Lock()


def _device_of(arr):
    """The single jax device holding ``arr``, or None for host arrays
    (numpy inputs at the dispatcher's feed edge) and sharded arrays."""
    devices = getattr(arr, "devices", None)
    if devices is None:
        return None
    try:
        ds = devices()
        if len(ds) == 1:
            return next(iter(ds))
    except Exception:  # noqa: BLE001 — deleted/donated arrays
        return None
    return None


class IciSender(LocalSender):
    """Producer end of an ici hop (AsyncSender surface).

    ``send`` keeps the array device-resident: same-device (or unpinned)
    hops hand the live ``jax.Array`` by reference; a hop whose receiver
    is pinned to a *different* device pays exactly one
    ``jax.device_put`` — the device-to-device DMA the tier exists to
    expose — recorded in ``d2d``/``device_pairs`` so stats can assert a
    real cross-device transfer happened.  Everything else (bounded
    backpressure, ordered ctrl, cascading END, peer-death poisoning) is
    the LocalSender contract verbatim.
    """

    codec = "ici"   #: nominal; no codec (or host byte) ever touches ici

    def __init__(self, pipe: "IciPipe"):
        super().__init__(pipe)
        #: receiver's pinned jax device (from the tier_reply), or None
        self.dest_device = None
        #: cross-device device_put transfers this sender performed
        self.d2d = 0
        #: distinct (src_id, dst_id) pairs of those transfers
        self.device_pairs: set[tuple[int, int]] = set()

    def send(self, arr, *, seq: int | None = None) -> None:
        dest = self.dest_device
        if dest is not None:
            src = _device_of(arr)
            if src is None or src.id != dest.id:
                import jax
                arr = jax.device_put(arr, dest)
                if src is not None and src.id != dest.id:
                    # a real device-to-device transfer (never via host)
                    self.d2d += 1
                    _ICI_D2D.n += 1
                    self.device_pairs.add((src.id, dest.id))
        if seq is None:
            self._put((K_TENSOR, arr))
        else:
            self._put((K_TENSOR_SEQ, (seq, arr)))
        _ICI_FRAMES.n += 1


class IciReceiver(LocalReceiver):
    """Consumer end of an ici hop (AsyncReceiver surface): the
    LocalReceiver contract verbatim — tensor frames are live
    ``jax.Array``s the consuming stage program ingests directly."""


class IciPipe(LocalPipe):
    """One bounded in-process stream of device-resident frames."""

    sender_cls = IciSender
    receiver_cls = IciReceiver


# ---------------------------------------------------------------------------
# negotiation
# ---------------------------------------------------------------------------

def _register(pipe: IciPipe) -> str:
    token = uuid.uuid4().hex
    with _OFFERS_LOCK:
        _OFFERS[token] = pipe
    return token


def _claim(token) -> IciPipe | None:
    with _OFFERS_LOCK:
        return _OFFERS.pop(token, None)


def _mesh_ident(device=None) -> dict:
    """This process's half of the same-mesh proof: backend, platform,
    and the device ids the sender's outputs will live on (its pinned
    device, else the backend's default device)."""
    import jax
    devs = [device] if device is not None else [jax.devices()[0]]
    return {"backend": jax.default_backend(),
            "platform": devs[0].platform,
            "device_ids": [d.id for d in devs]}


def offer_ici(sock, *, depth: int = 8, hop: str | None = None,
              device=None, fallback: bool = True
              ) -> tuple[str, IciSender | None]:
    """Offer the device-resident tier on a freshly dialed data socket.

    Sends the ``tier_probe`` (first frame on the connection, so the
    reply cannot interleave with data) carrying this side's mesh
    identity — ``device`` is the jax device the sender's outputs are
    pinned to (None = backend default) — and awaits the ``tier_reply``.
    Granted: returns ``("ici", sender)`` with the sender's
    ``dest_device`` resolved from the reply's receiver device id, and
    the socket stays open as the hop's lifetime anchor.  Refused
    (cross-process peer, foreign mesh, version mismatch, tcp-pinned
    peer): ``("tcp", None)``, bumping ``transport.tier_fallback`` (per
    ``hop``) when ``fallback`` — ``fallback=False`` for ladder callers
    that will offer the next rung on the same socket, so one degraded
    hop never counts twice.  A host without a usable jax backend
    refuses locally (no probe) and returns ``("tcp", None)``.
    """
    try:
        ident = _mesh_ident(device)
    except Exception:  # noqa: BLE001 — no backend: the rung cannot hold
        if fallback:
            record_fallback(hop)
        return "tcp", None
    pipe = IciPipe(depth=depth)
    token = _register(pipe)
    try:
        send_ctrl(sock, {"cmd": "tier_probe", "want": "ici",
                         "pid": os.getpid(), "proto": PROTOCOL_VERSION,
                         "token": token, **ident})
        reply = recv_expect(sock, K_CTRL)
    finally:
        _claim(token)  # granted probes were already claimed by the peer
    if isinstance(reply, dict) and reply.get("cmd") == "tier_reply" \
            and reply.get("tier") == "ici":
        sender: IciSender = pipe.sender
        dev_id = reply.get("device")
        if dev_id is not None:
            import jax
            by_id = {d.id: d for d in jax.devices()}
            sender.dest_device = by_id.get(int(dev_id))
        return "ici", sender
    if fallback:
        record_fallback(hop)
    return "tcp", None


def grant_ici(msg) -> IciPipe | None:
    """Validate one ici ``tier_probe``; return the offered pipe when
    the same-process AND same-mesh claims both hold, else None (caller
    replies ``tier_reply: tcp``/the next rung and the hop degrades).

    Checks, in order: the probe wants ``ici``; the wire protocol
    version matches; the peer's pid is THIS process's (a live
    ``jax.Array`` can only be handed within one address space); the
    offered backend/platform match this process's jax backend; every
    offered device id RESOLVES on it — the resolve is the same-mesh
    proof (a peer on another mesh names devices this backend cannot
    resolve, so a forged pid alone is never enough); and the token
    resolves in this process's offer registry."""
    if not isinstance(msg, dict) or msg.get("want") != "ici":
        return None
    try:
        if int(msg.get("proto", -1)) != PROTOCOL_VERSION:
            return None
        if int(msg.get("pid", -1)) != os.getpid():
            return None
    except (TypeError, ValueError):
        return None
    try:
        import jax
        if msg.get("backend") != jax.default_backend():
            return None
        devs = {d.id: d for d in jax.devices()}
    except Exception:  # noqa: BLE001 — no backend here: cannot grant
        return None
    ids = msg.get("device_ids")
    if not isinstance(ids, (list, tuple)) or not ids:
        return None
    for i in ids:
        d = devs.get(i if isinstance(i, int) else None)
        if d is None or d.platform != msg.get("platform"):
            return None
    return _claim(msg.get("token"))
