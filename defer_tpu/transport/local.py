"""Colocated transport tier: zero-serialization in-process channels.

Every hop of the multi-process chain pays a TCP round trip plus a host
encode/decode — even when both endpoints live in the SAME process (the
in-process thread chains tests and smokes run, or stages deliberately
colocated on one host to share a device).  GSPMD's rule for co-scheduled
programs (PAPERS.md) is that a boundary transfer between colocated
stages should never touch the host wire path; this module is that rule
for the chain transport:

* :class:`LocalPipe` — a bounded in-memory frame stream.  Tensor frames
  carry the live ``ndarray``/``jax.Array`` BY REFERENCE: no codec, no
  framing, no socket, zero copies.  The queue is bounded, so the
  backpressure contract of the TCP path survives verbatim: a slow
  consumer parks the producer after ``depth`` frames, exactly like a
  full ``AsyncSender`` queue.  Sequence stamping (``send(arr, seq=)``)
  is preserved so replication fan-in bookkeeping and waterfall seqs keep
  working if a colocated hop ever sits on a stamped path.
* **Negotiation** — the sender dials TCP as always, then offers the fast
  path with a ``tier_probe`` control frame carrying its pid, protocol
  version, and a token it registered in this process's pipe registry.
  The receiver grants only when the pid matches, the protocol version
  matches, AND the token resolves in ITS registry — the registry lookup
  is the proof of same-process-ness (a remote peer's token can never
  resolve here).  Any failed check silently degrades the hop to plain
  TCP and bumps the ``transport.tier_fallback`` counter; the stream is
  byte-identical either way.

The third tier, ``device``, has no transport object at all: adjacent
stages that land on one device are FUSED into a single jit-compiled
stage program at deploy time (``partition.fuse_stages``), so the hop —
frame, queue, and everything — ceases to exist (the MPK
mega-kernelization direction, PAPERS.md).

Channel-surface compatibility: :class:`LocalSender` mimics
:class:`~defer_tpu.transport.channel.AsyncSender` (``send`` /
``send_ctrl`` / ``send_end`` / ``close`` / ``flush`` / ``enc`` /
watermarks) and :class:`LocalReceiver` mimics
:class:`~defer_tpu.transport.channel.AsyncReceiver` (``get`` /
``get_nowait`` / ``bind_gauge`` / ``bind_hist`` / ``release_gauge`` /
``dec``), so ``StageNode`` / ``ChainDispatcher`` swap them in without
caring which tier won.  The per-channel ``enc``/``dec`` histograms stay
EMPTY by design — a colocated hop does no codec work, and the obs plane
reading zero codec cost for it is the correct reading.
"""

from __future__ import annotations

import os
import queue
import threading
import time
import uuid

from ..obs import REGISTRY, LatencyHistogram
from .channel import ChannelError
from .framed import (K_CTRL, K_END, K_TENSOR, K_TENSOR_SEQ,
                     PROTOCOL_VERSION, recv_expect, send_ctrl)

__all__ = ["LocalPipe", "LocalReceiver", "LocalSender", "grant_local",
           "offer_local", "record_fallback"]

#: hops that wanted a colocated tier but degraded to tcp (failed
#: handshake: wrong pid, version mismatch, unknown token, refused peer)
_FALLBACK = REGISTRY.counter("transport.tier_fallback")


def record_fallback(hop: str | None = None) -> None:
    """Count one degraded hop: the process-global
    ``transport.tier_fallback`` counter plus — when the offering side
    named its hop (stage/cut ident, e.g. ``stage1.r0`` or ``chain``) —
    a per-hop labeled ``transport.tier_fallback.<hop>`` twin, so a
    silent tcp fallback is attributable to the hop that degraded
    instead of one anonymous process-wide count."""
    _FALLBACK.n += 1
    if hop:
        REGISTRY.counter(f"transport.tier_fallback.{hop}").n += 1
    from ..obs.events import emit as emit_event
    emit_event("tier_fallback", hop=hop)
#: tensor frames handed through local pipes (the colocated analogue of
#: ``transport.tx_frames`` — which local hops must NOT touch, so frame
#: counters keep meaning "bytes that crossed a wire")
_LOCAL_FRAMES = REGISTRY.counter("transport.local_frames")

#: token -> LocalPipe: offers awaiting a grant.  Process-local on
#: purpose — a probe from another process can never resolve its token
#: here, which is exactly the colocation proof the handshake needs.
_OFFERS: dict[str, "LocalPipe"] = {}
_OFFERS_LOCK = threading.Lock()


class LocalPipe:
    """One bounded in-memory frame stream (sender end + receiver end).

    Items are ``(kind, value)`` tuples shaped exactly like
    ``recv_frame``'s returns — ``(K_TENSOR, arr)``,
    ``(K_TENSOR_SEQ, (seq, arr))``, ``(K_CTRL, dict)``, ``(K_END,
    None)`` — so consumers cannot tell (and must not care) whether a
    frame came off a socket or a pipe.
    """

    #: end-class hooks so subclasses (the device-resident ici tier)
    #: inherit the pipe machinery — bounded backpressure, ordered ctrl,
    #: cascading END, both-direction peer-death poisoning — verbatim
    sender_cls: type["LocalSender"]
    receiver_cls: type["LocalReceiver"]

    def __init__(self, depth: int = 8):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self.depth = depth
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        #: sender enqueued its END (clean shutdown)
        self._ended = False
        #: sender abandoned the stream without an END (peer death — the
        #: pipe analogue of a cut TCP connection)
        self._sender_gone = False
        #: receiver will never consume again (its stream loop exited)
        self._receiver_gone = False
        #: shared gauge published by the receiver's bind_gauge, plus
        #: enqueue/dequeue counts maintained under ``_glock`` — gauge
        #: accounting goes through the COUNTS, never ``qsize()``, so a
        #: bind racing an in-flight put can't double-count an item (the
        #: producer reports it after the bind sees it absent, or the
        #: bind's backlog sweep covers it; never both)
        self._gauge = None
        self._glock = threading.Lock()
        self._enq = 0
        self._deq = 0
        self.sender = self.sender_cls(self)
        self.receiver = self.receiver_cls(self)


class LocalSender:
    """Producer end of a :class:`LocalPipe` (AsyncSender surface)."""

    #: waterfall sampling period — accepted for surface parity; local
    #: hops record no per-frame tx/rx spans (there is no tx/rx phase)
    sample_every: int = 0
    codec = "local"   #: nominal; no codec ever runs on a local hop

    def __init__(self, pipe: LocalPipe):
        self._pipe = pipe
        self._q = pipe._q
        self.depth = pipe.depth
        #: per-channel encode histogram — stays empty (zero codec work)
        self.enc = LatencyHistogram()
        self.hi = 0
        self.err: BaseException | None = None

    # -- producer side ------------------------------------------------------

    def send(self, arr, *, seq: int | None = None) -> None:
        if seq is None:
            self._put((K_TENSOR, arr))
        else:
            self._put((K_TENSOR_SEQ, (seq, arr)))
        _LOCAL_FRAMES.n += 1

    def send_ctrl(self, msg: dict) -> None:
        self._put((K_CTRL, dict(msg)))

    def send_end(self) -> None:
        self._put((K_END, None))
        self._pipe._ended = True

    def close(self, timeout: float | None = None) -> None:
        """END the stream.  Once enqueued the frame IS delivered — the
        consumer holds the same queue — so unlike ``AsyncSender.close``
        there is no tx thread to join; ``timeout`` bounds only the wait
        for a queue slot against a stalled (alive but not consuming)
        peer, keeping the dead-chain-fails-not-hangs contract."""
        self._put((K_END, None), timeout=timeout)
        self._pipe._ended = True

    def flush(self, timeout: float | None = None) -> None:
        """No-op: ``send`` hands the frame to the consumer synchronously
        (there is no encode/wire stage to drain)."""
        if self.err is not None:
            raise ChannelError("local channel peer gone") from self.err

    def detach(self) -> None:
        """Abandon the stream: called by the owner's teardown path.  A
        detach WITHOUT a prior END marks the sender dead so a consumer
        parked in ``get`` fails like it would on a cut TCP connection
        (after the clean END this is a no-op)."""
        if not self._pipe._ended:
            self._pipe._sender_gone = True

    def _put(self, item, timeout: float | None = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._pipe._receiver_gone:
                self.err = ConnectionError(
                    "local channel receiver abandoned the stream")
                raise ChannelError("local channel receiver gone") \
                    from self.err
            try:
                self._q.put(item, timeout=0.05)
            except queue.Full:
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"local channel full for {timeout:.1f}s "
                        f"(peer stopped consuming)")
                continue  # bounded queue: backpressure, like a full
                #   AsyncSender queue / a stalled TCP window
            with self._pipe._glock:
                self._pipe._enq += 1
                if self._pipe._gauge is not None:
                    self._pipe._gauge.inc()
            q = self._q.qsize()
            if q > self.hi:
                self.hi = q
            return

    def take_watermark(self) -> int:
        h = max(self.hi, self._q.qsize())
        self.hi = self._q.qsize()
        return h

    def qsize(self) -> int:
        return self._q.qsize()


class LocalReceiver:
    """Consumer end of a :class:`LocalPipe` (AsyncReceiver surface)."""

    sample_every: int = 0

    def __init__(self, pipe: LocalPipe):
        self._pipe = pipe
        self._q = pipe._q
        self.depth = pipe.depth
        #: per-channel decode histogram — stays empty (zero codec work)
        self.dec = LatencyHistogram()
        self.hi = 0
        self.err: BaseException | None = None

    def bind_gauge(self, name: str) -> None:
        g = REGISTRY.gauge(name)
        with self._pipe._glock:
            # backlog = enqueues whose producers already reported under
            # the lock; an in-flight put not yet counted here will see
            # the gauge and report itself — each item counted once
            g.inc(self._pipe._enq - self._pipe._deq)
            self._pipe._gauge = g

    def bind_hist(self, name: str) -> None:
        """Accepted for surface parity; a local hop has no recv+decode
        phase to time, so nothing is ever recorded under ``name``."""

    def release_gauge(self) -> None:
        """Reconcile the shared additive gauge AND mark this end gone so
        a producer parked in ``send`` wakes with :class:`ChannelError`
        instead of blocking forever against a dead stream."""
        self._pipe._receiver_gone = True
        with self._pipe._glock:
            g, self._pipe._gauge = self._pipe._gauge, None
            if g is not None:
                g.dec(self._pipe._enq - self._pipe._deq)

    def get(self, timeout: float | None = None) -> tuple:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                item = self._q.get(timeout=0.05)
            except queue.Empty:
                if self._pipe._sender_gone and self._q.empty():
                    self.err = ConnectionError(
                        "local channel peer closed mid-stream")
                    raise self.err
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(f"no frame within {timeout:.1f}s")
                continue
            return self._got(item)

    def get_nowait(self) -> tuple:
        try:
            item = self._q.get_nowait()
        except queue.Empty:
            if self._pipe._sender_gone:
                self.err = ConnectionError(
                    "local channel peer closed mid-stream")
                raise self.err from None
            raise
        return self._got(item)

    def _got(self, item) -> tuple:
        with self._pipe._glock:
            self._pipe._deq += 1
            if self._pipe._gauge is not None:
                self._pipe._gauge.dec()
        q = self._q.qsize()
        if q > self.hi:
            self.hi = q
        return item

    def take_watermark(self) -> int:
        h = max(self.hi, self._q.qsize())
        self.hi = self._q.qsize()
        return h

    def qsize(self) -> int:
        return self._q.qsize()


#: bound after the classes exist (LocalPipe is defined first)
LocalPipe.sender_cls = LocalSender
LocalPipe.receiver_cls = LocalReceiver


# ---------------------------------------------------------------------------
# negotiation
# ---------------------------------------------------------------------------

def _register(pipe: LocalPipe) -> str:
    token = uuid.uuid4().hex
    with _OFFERS_LOCK:
        _OFFERS[token] = pipe
    return token


def _claim(token) -> LocalPipe | None:
    with _OFFERS_LOCK:
        return _OFFERS.pop(token, None)


def offer_local(sock, *, depth: int = 8, hop: str | None = None,
                fallback: bool = True) -> tuple[str, LocalPipe | None]:
    """Offer the colocated fast path on a freshly dialed data socket.

    Sends the ``tier_probe`` control frame and synchronously awaits the
    peer's ``tier_reply`` (the probe is the FIRST frame on the
    connection, so the reply cannot interleave with data).  Returns
    ``("local", pipe)`` when granted — the caller sends all further
    frames through ``pipe.sender`` and keeps the socket only as the
    connection's lifetime anchor — or ``("tcp", None)`` after a refusal,
    bumping ``transport.tier_fallback`` (labeled per ``hop`` — see
    :func:`record_fallback`): the hop silently degrades to the
    status-quo wire path on the same socket.  ``fallback=False``
    suppresses the count — for callers that will offer the NEXT rung of
    the tier ladder (shm) on the same socket, so one degraded hop never
    counts twice.
    """
    pipe = LocalPipe(depth=depth)
    token = _register(pipe)
    try:
        send_ctrl(sock, {"cmd": "tier_probe", "want": "local",
                         "pid": os.getpid(), "proto": PROTOCOL_VERSION,
                         "token": token})
        reply = recv_expect(sock, K_CTRL)
    finally:
        _claim(token)  # granted probes were already claimed by the peer
    if isinstance(reply, dict) and reply.get("cmd") == "tier_reply" \
            and reply.get("tier") == "local":
        return "local", pipe
    if fallback:
        record_fallback(hop)
    return "tcp", None


def grant_local(msg) -> LocalPipe | None:
    """Validate one ``tier_probe`` control frame; return the offered
    pipe when the colocation claim holds, else None (caller replies
    ``tier_reply: tcp`` and the hop degrades).

    Checks, in order: the probe wants ``local``; the wire protocol
    version matches (a future v3 sender must not splice a v2 pipe); the
    peer's pid is THIS process's pid; and the token resolves in this
    process's offer registry — the structural proof both ends share one
    address space (a remote process's token can never resolve here, so
    a forged pid alone is never enough)."""
    if not isinstance(msg, dict) or msg.get("want") != "local":
        return None
    try:
        if int(msg.get("proto", -1)) != PROTOCOL_VERSION:
            return None
        if int(msg.get("pid", -1)) != os.getpid():
            return None
    except (TypeError, ValueError):
        return None
    return _claim(msg.get("token"))


def answer_probe(conn, msg, *, accept: bool = True):
    """Receiver-side handshake: validate ``msg`` (when ``accept``),
    send the ``tier_reply`` on ``conn``, and return the granted
    :class:`LocalPipe` or None.  The one helper every serve loop uses so
    a probe is ALWAYS answered — an unanswered probe would park the
    offering peer in its reply wait."""
    pipe = grant_local(msg) if accept else None
    send_ctrl(conn, {"cmd": "tier_reply",
                     "tier": "local" if pipe is not None else "tcp"})
    return pipe
