"""Seq-replay substrate: bounded retain-until-ack + self-healing fan-out.

The ordered fan machinery (``transport/replicate.py``) gives every frame
an exact stream position — the v2 ``K_TENSOR_SEQ`` stamp the fan-out
assigns and the fan-in merge releases in order.  This module adds the
one mechanism both halves of the robustness story stand on
(docs/ROBUSTNESS.md):

* :class:`ReplayBuffer` — a bounded window of sent-but-unacked frames,
  keyed by wire seq.  ``retain`` blocks when the window is full (the
  retained-frame memory is the backpressure bound), a cumulative
  ``ack(upto)`` releases everything below it, and ``unacked`` snapshots
  what a healed channel must replay.
* :class:`ReplayFanOut` — the :class:`~.replicate.FanOutSender` surface
  with per-channel ack-reader threads and a heal path: when a replica
  channel dies (send failure or ack-socket EOF), the channel re-dials
  the SAME address with :func:`~.framed.connect_retry` (the respawned
  replica binds its old port), re-sends the stream preamble
  (``stream_begin`` / ``trace``), replays the channel's unacked window
  in order, and resumes — emitting one ``failover`` flight-recorder
  event with the measured recovery time.  Replayed frames that the
  downstream fan-in already merged are deduped silently inside its
  replay window (``FanInMerge(replay_window=...)``), so a replay
  overlap can never corrupt or reorder the stream.

The ack protocol rides the reverse direction of the fan-path data
sockets — free by design, because fan paths always refuse tier offers
(no shm doorbell shares the socket) and replica dial-backs never probe:

* the fan-in's merge loop sends cumulative ``{"cmd": "replay_ack",
  "seq": N}`` control frames upstream on every fan-in connection
  (all frames below N are merged in order);
* each replica relays the ack one hop further upstream on its own
  inbound connection;
* the fan-out's ack readers release the replay window, and a
  ``{"cmd": "replay_done"}`` from a replica that completed its stream
  cleanly marks that channel's later EOF as shutdown, not death.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Sequence

from ..obs import REGISTRY, LatencyHistogram
from .channel import AsyncSender, ChannelError
from .framed import K_CTRL, K_END, connect_retry, recv_frame

__all__ = ["ReplayBuffer", "ReplayFanOut", "ACK_EVERY"]

#: fan-in ack cadence: one cumulative replay_ack per ACK_EVERY merged
#: frames (plus one on stream end).  Small enough that the retained
#: window stays shallow, large enough that acks never dominate the
#: reverse path.
ACK_EVERY = 8


class ReplayBuffer:
    """Bounded window of sent-but-unacked frames, keyed by wire seq.

    One producer calls :meth:`retain` before each send; ack-reader
    threads call :meth:`ack` with the downstream's cumulative merge
    position; a healing channel snapshots :meth:`unacked`.  ``retain``
    blocks while the window is full — retained-frame memory is the
    failover mechanism's backpressure bound, published as a gauge so
    the monitor can watch it (``gauge=`` name, absolute value).
    """

    def __init__(self, capacity: int = 256, *, gauge: str | None = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._frames: dict[int, object] = {}
        self._acked = 0            # every seq < _acked is released
        self._err: BaseException | None = None
        self._cv = threading.Condition()
        self._gauge = REGISTRY.gauge(gauge) if gauge else None
        #: lifetime high watermark of retained frames
        self.hi = 0

    def retain(self, seq: int, value, timeout: float | None = None) -> None:
        """Hold one frame until a cumulative ack releases it; blocks
        while the window is full (an already-acked seq is a no-op)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                if self._err is not None:
                    raise self._err
                if seq < self._acked:
                    return
                if len(self._frames) < self.capacity \
                        or seq in self._frames:
                    self._frames[seq] = value
                    if len(self._frames) > self.hi:
                        self.hi = len(self._frames)
                    if self._gauge is not None:
                        self._gauge.v = len(self._frames)
                    return
                if deadline is not None \
                        and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"replay window full ({self.capacity}) for "
                        f"{timeout:.1f}s — no ack from downstream")
                self._cv.wait(0.05)

    def ack(self, upto: int) -> None:
        """Cumulative release: drop every retained seq below ``upto``
        (all of them merged in order downstream).  Stale acks are
        no-ops — acks may arrive out of order across R relay paths."""
        with self._cv:
            if upto <= self._acked:
                return
            self._acked = upto
            for s in [s for s in self._frames if s < upto]:
                del self._frames[s]
            if self._gauge is not None:
                self._gauge.v = len(self._frames)
            self._cv.notify_all()

    def fail(self, exc: BaseException) -> None:
        """Wake a producer parked in :meth:`retain` with ``exc`` — a
        channel heal that hard-failed must not leave the stream hung."""
        with self._cv:
            if self._err is None:
                self._err = exc
            self._cv.notify_all()

    def unacked(self) -> list[tuple[int, object]]:
        """Snapshot of retained (seq, frame) pairs in seq order — what
        a healed channel replays (filtered to its own seq residues)."""
        with self._cv:
            return sorted(self._frames.items())

    def depth(self) -> int:
        with self._cv:
            return len(self._frames)

    @property
    def acked(self) -> int:
        with self._cv:
            return self._acked


class ReplayFanOut:
    """Round-robin replica fan-out that survives replica death.

    Presents the :class:`~.replicate.FanOutSender` surface (``send`` /
    ``send_ctrl`` / ``send_end`` / ``close`` / ``flush`` / ``qsize``
    and the telemetry properties) over R :class:`AsyncSender` channels,
    with three additions:

    * every tensor frame is retained in a shared :class:`ReplayBuffer`
      until the downstream fan-in's cumulative ``replay_ack`` releases
      it (ack-reader thread per channel on the data socket's reverse
      direction);
    * a dead channel — send failure, or ack-reader EOF without a
      ``replay_done`` — HEALS: re-dial the same address (the supervisor
      respawns replicas on their old ports), re-send the recorded
      stream preamble, replay the channel's unacked frames in order,
      and resume.  Channel assignment stays ``seq % R`` throughout, so
      a replayed frame always lands on the path whose fan-in slots it;
    * recovery is measured and emitted as one ``failover`` event.

    A replay can overlap frames the fan-in already merged (acks lag by
    up to ``ACK_EVERY``); the fan-in's merge dedups those silently
    inside its replay window.  Duplicate-tolerant downstream + replay-
    until-acked upstream is the whole failover contract.
    """

    def __init__(self, socks: Sequence, addrs: Sequence[tuple[str, int]],
                 *, depth: int = 8, codec: str = "raw",
                 gauge: str | None = None, span=None,
                 hist: str | None = None, window: int = 256,
                 redial_timeout_s: float = 30.0,
                 replay_gauge: str | None = "node.replay_depth"):
        if not socks:
            raise ValueError("ReplayFanOut needs at least one socket")
        if len(socks) != len(addrs):
            raise ValueError(f"{len(socks)} sockets but {len(addrs)} "
                             f"addresses")
        self._socks = list(socks)
        self._addrs = [tuple(a) for a in addrs]
        self.depth = depth
        self._codec = codec
        self._gauge_name = gauge
        self._span = span
        self._hist_name = hist
        self.redial_timeout_s = redial_timeout_s
        self._buf = ReplayBuffer(window, gauge=replay_gauge)
        self._chans = [self._new_chan(s) for s in self._socks]
        self._n = 0
        self._cv = threading.Condition()
        self._healing = [False] * len(self._chans)
        self._chan_err: list[BaseException | None] = \
            [None] * len(self._chans)
        #: END queued on the CURRENT channel object of slot i
        self._end_sent = [False] * len(self._chans)
        self._end_queued = False
        #: channel completed its stream cleanly (replay_done received):
        #: a later EOF there is shutdown, not death
        self._done = [False] * len(self._chans)
        self._closing = False
        #: heals performed (stats/obs: the failure-visibility counter)
        self.failovers = 0
        #: preamble ctrl frames a healed channel must re-send before
        #: replaying data (stream_begin / trace), latest per cmd
        self._preamble: list[dict] = []
        for i, s in enumerate(self._socks):
            self._start_ack_reader(i, s, self._chans[i])

    def _new_chan(self, sock) -> AsyncSender:
        return AsyncSender(sock, depth=self.depth, codec=self._codec,
                           gauge=self._gauge_name, span=self._span,
                           hist=self._hist_name)

    def _start_ack_reader(self, i: int, sock, chan) -> None:
        # each reader is bound to the channel GENERATION it was started
        # for: after a heal swaps the slot, the stale reader must never
        # act on the replacement (see _ack_loop's heal call)
        threading.Thread(target=self._ack_loop, args=(i, sock, chan),
                         daemon=True, name=f"replay-ack-{i}").start()

    # -- FanOutSender telemetry surface --------------------------------------

    @property
    def width(self) -> int:
        return len(self._chans)

    @property
    def sample_every(self) -> int:
        return self._chans[0].sample_every

    @sample_every.setter
    def sample_every(self, n: int) -> None:
        for ch in self._chans:
            ch.sample_every = n

    def take_watermark(self) -> int:
        return max(ch.take_watermark() for ch in self._chans)

    @property
    def hi(self) -> int:
        return max(ch.hi for ch in self._chans)

    @property
    def enc(self) -> LatencyHistogram:
        h = LatencyHistogram()
        for ch in self._chans:
            h.merge(ch.enc)
        return h

    def qsize(self) -> int:
        return sum(ch.qsize() for ch in self._chans)

    def replay_depth(self) -> int:
        """Frames currently retained for replay (the monitor gauge's
        pull twin)."""
        return self._buf.depth()

    # -- ack plane -----------------------------------------------------------

    def _ack_loop(self, i: int, sock, chan) -> None:
        """Read the channel's reverse direction: cumulative replay_acks
        release the window, replay_done marks a clean stream end, EOF
        without one triggers the heal."""
        try:
            while True:
                try:
                    kind, value = recv_frame(sock)
                except TimeoutError:
                    # an IDLE reverse path is not a death: the first
                    # ack only flows once the downstream fan-in merges
                    # frames (a cold-boot compile can hold it for tens
                    # of seconds), and the fan sockets carry a recv
                    # timeout.  Death announces itself as EOF, reset,
                    # or garbage — keep waiting through silence.
                    if self._closing or self._done[i]:
                        return
                    continue
                if kind == K_CTRL and isinstance(value, dict):
                    cmd = value.get("cmd")
                    if cmd == "replay_ack":
                        self._buf.ack(int(value.get("seq", 0)))
                    elif cmd == "replay_done":
                        self._done[i] = True
                elif kind == K_END:
                    break
        except (OSError, ConnectionError, ValueError):
            pass
        try:
            if self._closing or self._done[i]:
                return
            try:
                # heal THIS reader's channel generation, never the
                # current slot occupant: a send-path heal may already
                # have swapped in a healthy replacement, and _heal's
                # identity check then turns this call into a no-op
                # (healing the replacement would close a live replica's
                # socket — the exact cascade this guards against)
                self._heal(i, chan)
            except BaseException:  # noqa: BLE001 — recorded in
                pass               # _chan_err; surfaced on next send
        finally:
            # the reader owns its socket's close: _heal only shut the
            # socket down (waking this recv with EOF), because closing
            # an fd another thread is blocked in recv(2) on invites
            # fd-reuse corruption — the freed number is recycled by the
            # very next connect and the stale reader steals its bytes
            try:
                sock.close()
            except OSError:
                pass

    # -- heal ----------------------------------------------------------------

    def _heal(self, i: int, dead) -> None:
        """Replace channel ``i``: close the dead socket, re-dial the
        same address, re-send the preamble, replay the channel's
        unacked frames in order, swap in, measure and emit.  Exactly
        one healer per (slot, dead channel); concurrent detectors wait
        for its outcome."""
        with self._cv:
            while self._healing[i]:
                self._cv.wait(0.05)
            if self._chans[i] is not dead:
                # someone else already healed this very death
                if self._chan_err[i] is not None:
                    raise ChannelError(
                        f"replica channel {i} unrecoverable") \
                        from self._chan_err[i]
                return
            if self._closing:
                raise ChannelError(
                    f"replica channel {i} died during teardown")
            self._healing[i] = True
            ended = self._end_queued
        t0 = time.perf_counter()
        host, port = self._addrs[i]
        deadline = time.monotonic() + self.redial_timeout_s
        try:
            try:
                # shutdown, NOT close: the slot's ack reader may be
                # blocked in recv(2) on this fd — shutdown wakes it
                # with EOF while the fd number stays reserved until
                # the reader closes it itself (fd-reuse safety)
                self._socks[i].shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            attempt = 0
            while True:
                # the whole connect + preamble + replay is ONE retryable
                # attempt: a re-dial can land in the DYING process's
                # listen backlog (its established-conn RSTs race its
                # listener teardown), which accepts the connect and then
                # resets mid-replay — only the next dial reaches the
                # respawned replica
                budget = deadline - time.monotonic()
                if budget <= 0:
                    raise ChannelError(
                        f"replica channel {i} ({host}:{port}): no "
                        f"successful replay within "
                        f"{self.redial_timeout_s:.1f}s")
                sock = connect_retry(host, port, timeout_s=budget)
                ch = self._new_chan(sock)
                ch.sample_every = dead.sample_every
                with self._cv:
                    preamble = list(self._preamble)
                try:
                    for msg in preamble:
                        ch.send_ctrl(msg)
                    replayed = 0
                    for s, arr in self._buf.unacked():
                        if s % len(self._chans) == i:
                            ch.send(arr, seq=s)
                            replayed += 1
                    if ended:
                        # the old channel's END died with it: the healed
                        # stream still has to terminate
                        ch.send_end()
                    break
                except (ChannelError, OSError, ConnectionError,
                        TimeoutError) as e:
                    attempt += 1
                    from ..obs.events import emit as _emit
                    _emit("redial", addr=f"{host}:{port}",
                          attempt=attempt, delay_ms=0.0,
                          error=type(e).__name__)
                    try:
                        sock.close()
                    except OSError:
                        pass
            with self._cv:
                self._socks[i] = sock
                self._chans[i] = ch
                self._chan_err[i] = None
                self._end_sent[i] = ended
                self._healing[i] = False
                self.failovers += 1
                self._cv.notify_all()
            recovery_ms = (time.perf_counter() - t0) * 1e3
            from ..obs.events import emit as _emit
            _emit("failover",
                  hop=(self._span() if callable(self._span)
                       else self._span),
                  chan=i, addr=f"{host}:{port}", replayed=replayed,
                  recovery_ms=round(recovery_ms, 3))
            REGISTRY.counter("transport.failovers").n += 1
            self._start_ack_reader(i, sock, ch)
        except BaseException as e:  # noqa: BLE001 — surfaced to sender
            with self._cv:
                self._chan_err[i] = e
                self._healing[i] = False
                self._cv.notify_all()
            self._buf.fail(e if isinstance(e, ChannelError) else
                           ChannelError(f"replica channel {i} "
                                        f"({host}:{port}) could not be "
                                        f"healed: {e!r}"))
            raise

    def _current_chan(self, i: int) -> AsyncSender:
        """The live channel for slot ``i``, waiting out an in-flight
        heal; raises the slot's terminal error if healing failed."""
        with self._cv:
            while self._healing[i]:
                self._cv.wait(0.05)
            if self._chan_err[i] is not None:
                raise ChannelError(
                    f"replica channel {i} unrecoverable") \
                    from self._chan_err[i]
            return self._chans[i]

    # -- sender surface ------------------------------------------------------

    def send(self, arr, *, seq: int | None = None) -> None:
        """Retain, then round-robin like FanOutSender (tensor ``i`` to
        channel ``i % R`` stamped ``seq=i``; a caller-supplied seq is
        ignored — the fan-out owns its sequence segment).  A send that
        hits a dead channel heals it and retries; the retry can
        duplicate a frame the heal already replayed, which the
        downstream merge dedups inside its replay window."""
        s = self._n
        self._n += 1
        self._buf.retain(s, arr)
        i = s % len(self._chans)
        while True:
            ch = self._current_chan(i)
            try:
                ch.send(arr, seq=s)
                return
            except ChannelError:
                self._heal(i, ch)

    def send_ctrl(self, msg: dict) -> None:
        """Broadcast a control frame; stream-preamble commands
        (``stream_begin`` / ``trace``) are recorded so a healed channel
        can replay them ahead of its data."""
        if isinstance(msg, dict) and msg.get("cmd") in ("stream_begin",
                                                        "trace"):
            with self._cv:
                self._preamble = [m for m in self._preamble
                                  if m.get("cmd") != msg.get("cmd")]
                self._preamble.append(dict(msg))
        for i in range(len(self._chans)):
            while True:
                ch = self._current_chan(i)
                try:
                    ch.send_ctrl(msg)
                    break
                except ChannelError:
                    self._heal(i, ch)

    def send_end(self) -> None:
        self._end_queued = True
        for i in range(len(self._chans)):
            while True:
                ch = self._current_chan(i)
                with self._cv:
                    if self._end_sent[i]:
                        break  # a heal already terminated this channel
                try:
                    ch.send_end()
                    with self._cv:
                        self._end_sent[i] = True
                    break
                except ChannelError:
                    self._heal(i, ch)

    def flush(self, timeout: float | None = None) -> None:
        for i in range(len(self._chans)):
            self._current_chan(i).flush(timeout=timeout)

    @staticmethod
    def _join_chan(ch, timeout: float | None) -> None:
        """Wait for a channel whose END is already queued to drain and
        exit (AsyncSender.close without the second END)."""
        ch._thread.join(timeout)
        if ch.err is not None:
            raise ChannelError("transport tx thread died") from ch.err
        if ch._thread.is_alive():
            raise TimeoutError(
                f"tx queue did not drain in {timeout:.1f}s")

    def close(self, timeout: float | None = None) -> None:
        """END every channel and join them, healing channels that die
        with unacked frames still owed (their replay + END completes
        the stream on the respawned replica); the first terminal
        failure is raised after every channel got its close attempt."""
        self._end_queued = True
        first: BaseException | None = None
        for i in range(len(self._chans)):
            attempts = 0
            while True:
                try:
                    ch = self._current_chan(i)
                except ChannelError as e:
                    first = first or e
                    break
                with self._cv:
                    ended = self._end_sent[i]
                try:
                    if not ended:
                        ch.send_end()
                        with self._cv:
                            self._end_sent[i] = True
                    self._join_chan(ch, timeout)
                    break
                except ChannelError:
                    attempts += 1
                    if attempts > 3:
                        first = first or ChannelError(
                            f"replica channel {i} kept dying during "
                            f"close")
                        break
                    try:
                        self._heal(i, ch)
                    except BaseException as e:  # noqa: BLE001
                        first = first or e
                        break
                except TimeoutError as e:
                    first = first or e
                    break
        self._closing = True
        if first is not None:
            raise first
