"""Ordered fan-out / fan-in for data-parallel stage replicas.

A pipeline's steady-state period is its slowest stage; when one
indivisible stage dominates, no cut placement can fix it.  The hybrid
answer (MPMD pipeline + GSPMD literature, PAPERS.md) is to run R
data-parallel replicas of that stage *inside* the pipeline: the stage's
effective service time drops to ``compute / R`` — provided the stream's
order survives the parallel paths.  This module supplies the two order-
preserving halves over the framed transport (protocol v2 sequence
numbers, ``transport/framed.py``):

* :class:`FanOutSender` — round-robins tensor frames across R
  :class:`~defer_tpu.transport.channel.AsyncSender` channels, stamping
  each frame with a monotonically increasing sequence number
  (``K_TENSOR_SEQ``).  Strict round-robin means a stalled replica
  eventually blocks the producer on that channel's turn — backpressure
  is preserved per path, never routed around (which would starve the
  fan-in of the stalled replica's sequence slots anyway).
* :class:`FanInMerge` — a bounded reorder buffer fed by R upstream
  reader threads, releasing frames to the consumer STRICTLY in sequence
  order.  A gap (a replica running behind) parks the consumer even if
  later frames are buffered; a full buffer parks the reader threads
  (except for the frame the consumer is waiting on, which is always
  admitted — liveness), which stops their socket reads, so TCP pushes
  back on the fast replicas.  Frames are never silently reordered,
  duplicated, or dropped: duplicate/stale sequence numbers raise.

The merge ends when ALL R upstreams have delivered their END frame and
the buffer has drained in order; an END with sequence gaps outstanding
raises (a replica died mid-stream and its slots can never be filled).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Sequence

from ..obs import LatencyHistogram
from .channel import AsyncSender
from .framed import K_CTRL, K_END, K_TENSOR

__all__ = ["FanInMerge", "FanOutSender"]


class FanInMerge:
    """Bounded reorder buffer merging R sequence-stamped upstreams.

    Reader threads (one per upstream connection) call :meth:`put` /
    :meth:`put_ctrl` / :meth:`end` / :meth:`fail`; one consumer calls
    :meth:`get` and receives ``(kind, value)`` tuples shaped like
    ``recv_frame``'s: tensors strictly in sequence order (seq stripped),
    control frames ahead of buffered tensors, then ``(K_END, None)``
    once every upstream ended and the buffer drained.
    """

    def __init__(self, expected: int, *, capacity: int = 32,
                 replay_window: int = 0):
        if expected < 1:
            raise ValueError(f"expected must be >= 1, got {expected}")
        if capacity < max(expected, 1):
            # fewer slots than upstreams could park every reader with the
            # needed frame still in a socket nobody is reading
            raise ValueError(f"capacity {capacity} < expected {expected}")
        if replay_window < 0:
            raise ValueError(f"replay_window must be >= 0, "
                             f"got {replay_window}")
        self.expected = expected
        self.capacity = capacity
        #: failover tolerance: a duplicate/stale seq within this many
        #: positions behind the stream head is DROPPED silently (a
        #: healed fan-out replayed frames its acks had not yet covered,
        #: docs/ROBUSTNESS.md) instead of raising.  0 keeps the strict
        #: contract: any duplicate raises.
        self.replay_window = replay_window
        #: duplicates silently absorbed inside the replay window
        self.duplicates = 0
        self._buf: dict[int, object] = {}
        self._ctrl: list[dict] = []
        self._next = 0
        self._ends = 0
        self._err: BaseException | None = None
        self._cv = threading.Condition()

    # -- producer side (reader threads) -------------------------------------

    def put(self, seq: int, value, timeout: float | None = None) -> None:
        """Insert one tensor by sequence number; blocks while the buffer
        is full UNLESS ``seq`` is the one the consumer is parked on (the
        needed frame is always admitted, so a full buffer of future
        frames can never deadlock the stream).  Duplicate or stale
        sequence numbers raise ``ValueError``."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                if self._err is not None:
                    raise self._err
                if seq < self._next or seq in self._buf:
                    if self.replay_window > 0 \
                            and seq >= self._next - self.replay_window:
                        # failover replay overlap: already merged (or
                        # already buffered) — absorb, don't corrupt
                        self.duplicates += 1
                        return
                    raise ValueError(
                        f"duplicate/stale sequence {seq} "
                        f"(next expected {self._next})")
                if seq == self._next or len(self._buf) < self.capacity:
                    self._buf[seq] = value
                    self._cv.notify_all()
                    return
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"reorder buffer full ({self.capacity}) for "
                        f"{timeout:.1f}s waiting on seq {self._next}")
                self._cv.wait(0.05)

    def put_ctrl(self, msg: dict) -> None:
        """Queue a control frame — delivered to the consumer ahead of
        buffered tensors (control plane rides ahead of data, matching
        the single-path trace-context convention)."""
        with self._cv:
            self._ctrl.append(msg)
            self._cv.notify_all()

    def end(self) -> None:
        """One upstream delivered its END frame."""
        with self._cv:
            self._ends += 1
            if self._ends > self.expected:
                self._err = ConnectionError(
                    f"{self._ends} END frames from {self.expected} "
                    f"upstreams")
            self._cv.notify_all()

    def fail(self, exc: BaseException) -> None:
        """An upstream reader died: surface ``exc`` to everyone parked
        here (consumer and other readers alike)."""
        with self._cv:
            if self._err is None:
                self._err = exc
            self._cv.notify_all()

    # -- consumer side -------------------------------------------------------

    def _pop_locked(self):
        """One ready item under the lock, or None."""
        if self._ctrl:
            return K_CTRL, self._ctrl.pop(0)
        if self._next in self._buf:
            value = self._buf.pop(self._next)
            self._next += 1
            self._cv.notify_all()  # wake readers parked on a full buffer
            return K_TENSOR, value
        if self._err is not None:
            raise self._err
        if self._ends >= self.expected:
            if self._buf:
                raise ConnectionError(
                    f"all {self.expected} upstreams ended with sequence "
                    f"gap: waiting on {self._next}, "
                    f"{sorted(self._buf)[:4]}... still buffered")
            return K_END, None
        return None

    def get(self, timeout: float | None = None) -> tuple:
        """Next in-order ``(kind, value)``; TimeoutError past ``timeout``
        (None = wait forever), re-raises any reader's failure."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                got = self._pop_locked()
                if got is not None:
                    return got
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"no in-order frame within {timeout:.1f}s "
                        f"(waiting on seq {self._next}, "
                        f"{len(self._buf)} out-of-order buffered)")
                self._cv.wait(0.05)

    def get_nowait(self) -> tuple:
        """Non-blocking :meth:`get`; raises ``queue.Empty`` when the
        next in-sequence frame has not arrived (even if later frames are
        buffered — the consumer's cue to drain its compute window)."""
        with self._cv:
            got = self._pop_locked()
        if got is None:
            raise queue.Empty
        return got

    def qsize(self) -> int:
        with self._cv:
            return len(self._buf)

    @property
    def next_seq(self) -> int:
        """The cumulative merge position: every seq below this has been
        released in order — exactly what a ``replay_ack`` carries."""
        with self._cv:
            return self._next


class FanOutSender:
    """Round-robin tensor distribution across R replica channels.

    Presents the :class:`AsyncSender` surface (``send`` / ``send_ctrl``
    / ``send_end`` / ``close``) over R of them: tensor ``i`` goes to
    channel ``i % R`` stamped with sequence number ``i``; control and
    END frames broadcast to every channel (each replica needs the trace
    context, and the fan-in counts R ENDs).  ``send`` ignores a caller-
    supplied seq and stamps its own — a fan-out begins a fresh sequence
    segment (any upstream merge already restored order).
    """

    def __init__(self, socks: Sequence, *, depth: int = 8,
                 codec: str = "raw", gauge: str | None = None, span=None,
                 hist: str | None = None):
        if not socks:
            raise ValueError("FanOutSender needs at least one socket")
        self._chans = [AsyncSender(s, depth=depth, codec=codec,
                                   gauge=gauge, span=span, hist=hist)
                       for s in socks]
        self._n = 0
        self.depth = depth

    @property
    def width(self) -> int:
        return len(self._chans)

    @property
    def sample_every(self) -> int:
        return self._chans[0].sample_every

    @sample_every.setter
    def sample_every(self, n: int) -> None:
        for ch in self._chans:
            ch.sample_every = n

    def take_watermark(self) -> int:
        """Peak occupancy across the replica channels since last call."""
        return max(ch.take_watermark() for ch in self._chans)

    @property
    def hi(self) -> int:
        """Non-resetting watermark PEEK across the replica channels —
        what a ``stats`` reply reads (``StageNode._chan_hi``) without
        disturbing the obs_push reset cycle."""
        return max(ch.hi for ch in self._chans)

    @property
    def enc(self) -> LatencyHistogram:
        """Merged per-channel encode histogram (``AsyncSender.enc``)."""
        h = LatencyHistogram()
        for ch in self._chans:
            h.merge(ch.enc)
        return h

    def send(self, arr, *, seq: int | None = None) -> None:
        self._chans[self._n % len(self._chans)].send(arr, seq=self._n)
        self._n += 1

    def send_ctrl(self, msg: dict) -> None:
        for ch in self._chans:
            ch.send_ctrl(msg)

    def send_end(self) -> None:
        for ch in self._chans:
            ch.send_end()

    def close(self, timeout: float | None = None) -> None:
        """END every channel, then join them all; the first failure is
        raised after every channel got its close attempt."""
        first: BaseException | None = None
        for ch in self._chans:
            try:
                ch.close(timeout=timeout)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                if first is None:
                    first = e
        if first is not None:
            raise first

    def flush(self, timeout: float | None = None) -> None:
        for ch in self._chans:
            ch.flush(timeout=timeout)

    def qsize(self) -> int:
        return sum(ch.qsize() for ch in self._chans)
