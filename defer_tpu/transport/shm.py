"""Shared-memory transport tier: zero-copy same-host cross-process hops.

The ``local`` tier (``transport/local.py``) only engages when both hop
endpoints share one PROCESS; the repo's standard proof mode — and any
real deployment packing several stages per host — runs stages as
separate OS processes on one machine, where every activation still
crossed TCP loopback with a full codec pass.  This module is the
missing rung between ``local`` and ``tcp``: activations ride a
``multiprocessing.shared_memory`` ring of tensor slots, and the TCP
socket the hop dialed anyway is demoted to a tiny DOORBELL carrying
per-frame slot descriptors, control frames, and END — so seq stamping,
in-order K_CTRL, and the cascading END keep wire-protocol-v2 semantics
verbatim while the payload bytes never touch a socket.

* :class:`ShmRing` — one shared segment of ``slots`` fixed-capacity
  slots, created by the sender.  A tensor is written ONCE into the next
  free slot (``memoryview`` assignment — one memcpy, no codec, no
  framing) and announced with a ``shm_frame`` doorbell K_CTRL naming
  the slot, dtype, shape, and optional seq; the receiver maps the slot
  as an ``np.frombuffer`` view and materializes the (exclusively
  owned) array with one memcpy out — zero serialization, and no copy
  beyond the unavoidable write-in/read-out pair.  A frame fatter than
  the slot capacity GROWS the ring: the sender drains outstanding
  slots, swaps in a bigger segment, and announces it with a
  ``shm_grow`` doorbell that — riding the ordered socket — always
  arrives before any frame referencing it.
* **Backpressure** — the ring is bounded: the receiver returns one ack
  byte on the doorbell socket per consumed frame (slots are used and
  freed in FIFO order, so a count is enough), and a full ring parks
  the producer exactly like a full ``AsyncSender`` queue.  Peer death
  poisons both ends: socket EOF fails the receiver's frame source with
  ``ConnectionError``, and the sender's ack reader marks the channel
  dead so a parked producer wakes with :class:`ChannelError`.
* **Negotiation** — the sender creates the segment, then offers
  ``{"cmd": "tier_probe", "want": "shm", seg, boot_id, proto}`` on the
  freshly dialed socket.  The grantor accepts only when the protocol
  version matches, the boot id matches, and it can ACTUALLY OPEN the
  offered segment name — the open is the same-host proof, in the
  spirit of the local tier's "the registry lookup IS the proof" (a
  cross-host peer's ``/dev/shm`` name never resolves; the boot id
  guards pathological name collisions).  Any failed check silently
  degrades the hop to tcp on the same socket and bumps the
  ``transport.tier_fallback`` counter (plus its per-hop labeled twin).

Segment lifecycle: segments are named ``defer_shm_<pid>_<rand>`` so an
orphan is attributable.  The creating process unlinks on close/detach
and again from an ``atexit`` hook; the receiver also unlinks on its
teardown (mapped frames stay readable after unlink, so this is safe
mid-stream) — whichever end survives a crash reaps the segment.  When
BOTH ends die ungracefully (kill -9), :func:`sweep_orphan_segments` —
run by the dispatcher at deploy — unlinks any ``defer_shm_`` segment
whose creator pid is no longer alive, so a murdered chain never leaks
``/dev/shm``.
"""

from __future__ import annotations

import atexit
import os
import threading
import time
import uuid
from multiprocessing import shared_memory

import numpy as np

from ..obs import REGISTRY, LatencyHistogram
from .channel import ChannelError
from .framed import (K_CTRL, K_TENSOR, K_TENSOR_SEQ, PROTOCOL_VERSION,
                     dtype_from_wire, recv_expect, send_ctrl, send_end,
                     wire_dtype)
from .local import record_fallback

__all__ = ["ShmReceiver", "ShmRing", "ShmSender", "answer_tier_probe",
           "grant_shm", "offer_shm", "sweep_orphan_segments"]

#: tensor frames handed through shm rings (the same-host analogue of
#: ``transport.local_frames`` — wire frame counters keep meaning "bytes
#: that crossed a socket", which shm payloads never do)
_SHM_FRAMES = REGISTRY.counter("transport.shm_frames")

#: segment name prefix: ``defer_shm_<creator pid>_<rand>`` — the pid is
#: what lets the orphan sweep attribute (and reap) a dead chain's leaks
SEG_PREFIX = "defer_shm_"

#: default slot capacity; a fatter first frame grows the ring in place
DEFAULT_SLOT_BYTES = 1 << 20

#: rings created by THIS process and not yet unlinked (atexit backstop)
_LIVE_RINGS: "set[ShmRing]" = set()
_LIVE_LOCK = threading.Lock()


def _boot_id() -> str:
    """This host's boot id — the cheap same-host witness carried by the
    probe (the segment OPEN is the real proof; this guards name
    collisions across hosts that share a /dev/shm-like namespace)."""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            return f.read().strip()
    except OSError:
        import socket as _socket
        return f"host:{_socket.gethostname()}"


def _untrack(seg: shared_memory.SharedMemory) -> None:
    """Detach ``seg`` from multiprocessing's resource tracker: this
    module owns the unlink discipline (explicit + atexit + the deploy
    sweep), and the tracker double-managing the name leads to
    unregister races and bogus leak warnings on Python < 3.13."""
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister(seg._name, "shared_memory")
    except Exception:  # noqa: BLE001 — tracking is best-effort anyway
        pass


def _unlink_name(name: str) -> None:
    """Remove a segment NAME without touching the resource tracker
    (``SharedMemory.unlink`` unregisters internally, which double-faults
    after :func:`_untrack` already detached the name).  Idempotent."""
    try:
        import _posixshmem
        _posixshmem.shm_unlink("/" + name)
    except ImportError:
        try:
            os.unlink(os.path.join("/dev/shm", name))
        except OSError:
            pass
    except (OSError, FileNotFoundError):
        pass


def _open_segment(name: str) -> shared_memory.SharedMemory | None:
    """Map an existing segment by name, untracked; None if it does not
    resolve on this host (the grantor's refusal path)."""
    try:
        seg = shared_memory.SharedMemory(name=name)
    except (OSError, ValueError):
        return None
    _untrack(seg)
    return seg


@atexit.register
def _unlink_live_rings() -> None:
    with _LIVE_LOCK:
        rings = list(_LIVE_RINGS)
    for r in rings:
        r.unlink()


class ShmRing:
    """Sender-owned shared segment of ``slots`` fixed-capacity slots.

    Slots are claimed in FIFO ring order by the sender and freed in the
    same order by the receiver's acks, so the free-slot accounting is a
    plain counting semaphore — no per-slot state crosses the processes
    beyond the doorbell descriptor.
    """

    def __init__(self, *, slots: int = 8,
                 slot_bytes: int = DEFAULT_SLOT_BYTES):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        # 64-byte slot alignment keeps every np.frombuffer offset legal
        # for any real dtype
        self.slot_bytes = max(64, (int(slot_bytes) + 63) & ~63)
        self.slots = slots
        self.name = f"{SEG_PREFIX}{os.getpid()}_{uuid.uuid4().hex[:12]}"
        self._seg = shared_memory.SharedMemory(
            name=self.name, create=True, size=self.slots * self.slot_bytes)
        _untrack(self._seg)
        self._unlinked = False
        with _LIVE_LOCK:
            _LIVE_RINGS.add(self)

    @property
    def buf(self):
        return self._seg.buf

    def write(self, slot: int, data: memoryview) -> None:
        off = slot * self.slot_bytes
        self._seg.buf[off:off + data.nbytes] = data

    def unlink(self) -> None:
        """Remove the segment name (idempotent; existing mappings stay
        valid).  Both ends call this on teardown — whoever survives a
        crash reaps the name, and the double call is harmless."""
        if self._unlinked:
            return
        self._unlinked = True
        with _LIVE_LOCK:
            _LIVE_RINGS.discard(self)
        _unlink_name(self.name)  # the other end may have got there first

    def close(self) -> None:
        try:
            self._seg.close()
        except (OSError, BufferError):
            pass


class ShmSender:
    """Producer end of a shm hop (AsyncSender surface).

    ``send`` claims the next free slot (parking when the ring is full —
    the bounded-backpressure contract), memcpys the tensor in, and
    sends the doorbell descriptor; control frames and END ride the
    doorbell socket directly, so their ordering relative to tensors is
    the socket's FIFO — exactly the wire path's guarantee.
    """

    #: accepted for surface parity; shm hops record no per-frame rx/tx
    #: spans (there is no encode/decode phase to time)
    sample_every: int = 0
    codec = "shm"   #: nominal; no codec ever runs on a shm hop

    def __init__(self, sock, ring: ShmRing):
        self._sock = sock
        try:
            # the dialed socket inherits connect_retry's 30 s timeout; a
            # bare recv in the ack loop would hit it on any healthy-but-
            # idle hop (no frames -> no acks) and falsely poison the
            # channel — acks are events, not heartbeats
            sock.settimeout(None)
        except OSError:
            pass
        self._ring = ring
        self.depth = ring.slots
        #: per-channel encode histogram — stays empty (zero codec work)
        self.enc = LatencyHistogram()
        self.hi = 0
        self.err: BaseException | None = None
        self._ended = False
        self._free = threading.Semaphore(ring.slots)
        self._head = 0          # next slot index (FIFO ring order)
        self._inflight = 0      # frames written, not yet acked
        self._ilock = threading.Lock()
        #: serializes doorbell socket writes (a trace ctrl from the
        #: control path may race the stream thread's descriptors)
        self._wlock = threading.Lock()
        self._acks = threading.Thread(target=self._ack_loop, daemon=True,
                                      name="shm-ack-rx")
        self._acks.start()

    # -- ack backchannel -----------------------------------------------------

    def _ack_loop(self):
        """Count ack bytes off the doorbell socket; EOF/error marks the
        channel dead so a producer parked on a full ring wakes with
        :class:`ChannelError` — the receiver-gone contract."""
        try:
            while True:
                data = self._sock.recv(4096)
                if not data:
                    raise ConnectionError(
                        "shm doorbell peer closed (receiver gone)")
                with self._ilock:
                    self._inflight -= len(data)
                for _ in range(len(data)):
                    self._free.release()
        except BaseException as e:  # noqa: BLE001 — surfaced in send()
            self.err = e

    def _claim_slot(self, timeout: float | None = None) -> int:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self.err is not None:
                raise ChannelError("shm channel receiver gone") \
                    from self.err
            if self._free.acquire(timeout=0.05):
                slot = self._head % self._ring.slots
                self._head += 1
                return slot
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"shm ring full for {timeout:.1f}s "
                    f"(peer stopped consuming)")

    def _drain(self, timeout: float | None = None) -> None:
        """Park until every written frame has been acked (ring empty)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self.err is not None:
                raise ChannelError("shm channel receiver gone") \
                    from self.err
            with self._ilock:
                if self._inflight == 0:
                    return
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"shm ring did not drain in {timeout:.1f}s")
            time.sleep(0.002)

    # -- producer side -------------------------------------------------------

    def send(self, arr, *, seq: int | None = None) -> None:
        arr = np.ascontiguousarray(np.asarray(arr))
        if arr.nbytes > self._ring.slot_bytes:
            self._grow(arr.nbytes)
        slot = self._claim_slot()
        # byte-reinterpret BEFORE taking the memoryview: extension
        # dtypes (bfloat16's buffer format 'E') reject a plain
        # .cast("B"), while a uint8 view of the same contiguous data
        # is always castable
        self._ring.write(slot, memoryview(arr.view(np.uint8)).cast("B"))
        msg = {"cmd": "shm_frame", "slot": slot, "nbytes": arr.nbytes,
               "dtype": wire_dtype(arr.dtype), "shape": list(arr.shape)}
        if seq is not None:
            msg["seq"] = int(seq)
        with self._ilock:
            self._inflight += 1
            if self._inflight > self.hi:
                self.hi = self._inflight
        with self._wlock:
            send_ctrl(self._sock, msg)
        _SHM_FRAMES.n += 1

    def _grow(self, nbytes: int) -> None:
        """Swap in a segment with bigger slots: drain the ring (the
        receiver holds no copies — ``get`` materializes and acks), then
        announce the new name on the ordered doorbell so it precedes
        every frame that needs it."""
        self._drain()
        size = 1 << max(6, (int(nbytes) - 1).bit_length())
        new = ShmRing(slots=self._ring.slots, slot_bytes=size)
        with self._wlock:
            send_ctrl(self._sock, {"cmd": "shm_grow", "seg": new.name,
                                   "slots": new.slots,
                                   "slot_bytes": new.slot_bytes})
        old, self._ring = self._ring, new
        self.depth = new.slots
        old.unlink()
        old.close()

    def send_ctrl(self, msg: dict) -> None:
        if self.err is not None:
            raise ChannelError("shm channel receiver gone") from self.err
        with self._wlock:
            send_ctrl(self._sock, dict(msg))

    def send_end(self) -> None:
        with self._wlock:
            send_end(self._sock)
        self._ended = True

    def close(self, timeout: float | None = None) -> None:
        """Drain the ring, send END, release the segment name.  The
        drain-first order means the receiver acks its last frame BEFORE
        the END, so no ack can be in flight when the owner later closes
        the socket (an unread ack at close would RST the doorbell under
        the receiver's still-queued descriptors).  ``timeout`` bounds
        the wait against a stalled-but-alive peer — dead chains fail,
        not hang, matching ``AsyncSender.close``.  The segment name is
        released whether or not the drain succeeds — a failed close
        must not leak /dev/shm."""
        try:
            self._drain(timeout)
            self.send_end()
        finally:
            self._ring.unlink()
            self._ring.close()  # drop this end's mapping too, or a
            #   long-lived node leaks one mapped ring per served stream

    def flush(self, timeout: float | None = None) -> None:
        """Everything ``send`` accepted is already written and announced
        (the doorbell sendall is synchronous); only surface a dead
        peer, like ``LocalSender.flush``."""
        if self.err is not None:
            raise ChannelError("shm channel receiver gone") from self.err

    def detach(self) -> None:
        """Abandon the stream (owner teardown without an END): release
        the segment name — the doorbell socket's close is what fails
        the receiver, exactly like a cut TCP connection."""
        if not self._ended:
            self.err = self.err or ConnectionError(
                "shm channel abandoned by sender")
        self._ring.unlink()
        self._ring.close()

    def take_watermark(self) -> int:
        with self._ilock:
            h = max(self.hi, self._inflight)
            self.hi = self._inflight
        return h

    def qsize(self) -> int:
        with self._ilock:
            return self._inflight


class ShmReceiver:
    """Consumer end of a shm hop (AsyncReceiver surface).

    Wraps the hop's existing socket frame source (the
    :class:`~defer_tpu.transport.channel.AsyncReceiver` whose rx thread
    already owns the socket reads): ``shm_frame`` descriptors become
    tensors read out of the mapped slot (one memcpy into an exclusively
    owned array, then an immediate ack byte so the slot recycles);
    every other frame kind passes through untouched, so ctrl ordering
    and the cascading END are literally the wire path's.
    """

    sample_every: int = 0

    def __init__(self, sock, inner, seg: shared_memory.SharedMemory, *,
                 slot_bytes: int, slots: int):
        self._sock = sock
        self._inner = inner
        self._seg = seg
        self.slot_bytes = int(slot_bytes)
        self.depth = int(slots)
        #: per-channel decode histogram — stays empty (zero codec work)
        self.dec = LatencyHistogram()
        self.hi = 0
        self.err: BaseException | None = None
        self._closed = False

    # -- frame source --------------------------------------------------------

    def get(self, timeout: float | None = None) -> tuple:
        while True:
            try:
                kind, value = self._inner.get(timeout)
            except (ConnectionError, OSError):
                self._teardown()
                raise
            item = self._translate(kind, value)
            if item is not None:
                return item

    def get_nowait(self) -> tuple:
        while True:
            try:
                kind, value = self._inner.get_nowait()
            except (ConnectionError, OSError):
                self._teardown()
                raise
            item = self._translate(kind, value)
            if item is not None:
                return item

    def _translate(self, kind, value):
        """shm doorbells -> tensors; ``shm_grow`` swaps the mapping and
        yields nothing; everything else passes through."""
        if kind != K_CTRL or not isinstance(value, dict):
            return kind, value
        cmd = value.get("cmd")
        if cmd == "shm_frame":
            arr = np.frombuffer(
                self._seg.buf, dtype=dtype_from_wire(value["dtype"]),
                count=int(np.prod(value["shape"], dtype=np.int64))
                if value["shape"] else 1,
                offset=int(value["slot"]) * self.slot_bytes,
            ).reshape(value["shape"]).copy()  # exclusively owned
            try:
                self._sock.sendall(b"\x01")  # slot recycles, FIFO order
            except OSError as e:
                self.err = e  # sender gone: surface on ITS next send
            seq = value.get("seq")
            if seq is not None:
                return K_TENSOR_SEQ, (int(seq), arr)
            return K_TENSOR, arr
        if cmd == "shm_grow":
            old, old_name = self._seg, self._seg.name
            seg = _open_segment(value["seg"])
            if seg is None:
                raise ConnectionError(
                    f"shm_grow named a segment this host cannot open: "
                    f"{value['seg']!r}")
            self._seg = seg
            self.slot_bytes = int(value["slot_bytes"])
            self.depth = int(value["slots"])
            _unlink_name(old_name)  # sender also unlinks; harmless
            old.close()
            return None
        return kind, value

    def _teardown(self) -> None:
        """Stream over (clean or poisoned): reap the segment name now
        (the sender may be kill -9 dead — mapped data stays readable,
        the NAME must not leak), drop the mapping, and SHUT DOWN the
        doorbell socket — a plain close would not interrupt a peer
        blocked in recv, so a producer parked on a full ring would
        never learn this end is gone (the receiver-gone ->
        ``ChannelError`` contract rides the shutdown's EOF)."""
        if self._closed:
            return
        self._closed = True
        _unlink_name(self._seg.name)
        self._seg.close()
        try:
            import socket as _socket
            self._sock.shutdown(_socket.SHUT_RDWR)
        except OSError:
            pass  # peer already gone / socket already closed

    # -- AsyncReceiver surface parity ---------------------------------------

    def bind_gauge(self, name: str) -> None:
        self._inner.bind_gauge(name)

    def bind_hist(self, name: str) -> None:
        """Accepted for parity; a shm hop has no recv+decode phase to
        time, so nothing is ever recorded under ``name``."""

    def release_gauge(self) -> None:
        """Stream over (clean or not): reconcile the inner channel's
        gauge and release this end's segment mapping + name."""
        self._inner.release_gauge()
        self._teardown()

    def take_watermark(self) -> int:
        return self._inner.take_watermark()

    def qsize(self) -> int:
        return self._inner.qsize()


# ---------------------------------------------------------------------------
# negotiation
# ---------------------------------------------------------------------------

def offer_shm(sock, *, depth: int = 8,
              slot_bytes: int = DEFAULT_SLOT_BYTES,
              hop: str | None = None,
              fallback: bool = True) -> tuple[str, ShmSender | None]:
    """Offer the shared-memory tier on a freshly dialed data socket.

    Creates the ring, sends the ``tier_probe`` (first frame on the
    connection, so the reply cannot interleave with data), and awaits
    the ``tier_reply``.  Granted: returns ``("shm", sender)`` — the
    socket stays open as the hop's doorbell.  Refused (cross-host peer,
    version mismatch, tcp-pinned peer): the ring is unlinked, the
    ``transport.tier_fallback`` counter (and its per-``hop`` labeled
    twin) is bumped when ``fallback``, and the hop silently degrades to
    the status-quo wire path on the same socket."""
    ring = ShmRing(slots=depth, slot_bytes=slot_bytes)
    try:
        send_ctrl(sock, {"cmd": "tier_probe", "want": "shm",
                         "proto": PROTOCOL_VERSION, "boot_id": _boot_id(),
                         "pid": os.getpid(), "seg": ring.name,
                         "slots": ring.slots,
                         "slot_bytes": ring.slot_bytes})
        reply = recv_expect(sock, K_CTRL)
    except BaseException:
        ring.unlink()
        ring.close()
        raise
    if isinstance(reply, dict) and reply.get("cmd") == "tier_reply" \
            and reply.get("tier") == "shm":
        return "shm", ShmSender(sock, ring)
    ring.unlink()
    ring.close()
    if fallback:
        record_fallback(hop)
    return "tcp", None


def offer_tier_ladder(sock, *, tier: str, depth: int = 8,
                      hop: str | None = None, device=None):
    """Walk the sender-side tier ladder on a freshly dialed data
    socket: ici (same process + same mesh, device-resident) over local
    (same process, host ndarray by reference) over shm (same host,
    shared-memory ring) over tcp, one probe per rung on the SAME
    socket.  ``tier="auto"`` offers every rung; ``tier="ici"`` /
    ``"local"`` / ``"shm"`` pin that single rung's offer.  ``device``
    is the jax device the offering side's outputs are pinned to (the
    ici probe's mesh identity; None = backend default).  Returns
    ``(tier_out, tx_or_None, fell_back)`` — a granted rung's sender
    (the socket stays open as the hop's lifetime anchor / doorbell), or
    ``("tcp", None, True)`` when every offer was refused, with ONE
    fallback recorded for the whole ladder (an upper rung's refusal is
    not yet a fallback while a lower rung is still to be tried).  The
    single place the ladder's rung order and fallback accounting live,
    shared by stage hops and the dispatcher's first/result edges."""
    from .ici import offer_ici
    from .local import offer_local
    tx = None
    tier_out = "tcp"
    if tier in ("auto", "ici"):
        tier_out, tx = offer_ici(sock, depth=depth, hop=hop,
                                 device=device,
                                 fallback=(tier == "ici"))
        if tx is not None or tier == "ici":
            return tier_out, tx, tx is None
    if tier in ("auto", "local"):
        tier_out, pipe = offer_local(sock, depth=depth, hop=hop,
                                     fallback=(tier == "local"))
        if pipe is not None:
            tx = pipe.sender
        if tx is not None or tier == "local":
            return tier_out, tx, tx is None
    if tx is None:
        tier_out, tx = offer_shm(sock, depth=depth, hop=hop)
    return tier_out, tx, tx is None


def grant_shm(msg) -> shared_memory.SharedMemory | None:
    """Validate one shm ``tier_probe``; return the OPENED segment when
    the same-host claim holds, else None (caller replies ``tier_reply:
    tcp`` and the hop degrades).

    Checks, in order: the probe wants ``shm``; the wire protocol
    version matches; the boot id is this host's; and the offered
    segment name actually opens here — the open is the structural proof
    both ends share one shared-memory namespace (a remote host's name
    can never resolve, so a forged boot id alone is never enough)."""
    if not isinstance(msg, dict) or msg.get("want") != "shm":
        return None
    try:
        if int(msg.get("proto", -1)) != PROTOCOL_VERSION:
            return None
    except (TypeError, ValueError):
        return None
    if msg.get("boot_id") != _boot_id():
        return None
    if not isinstance(msg.get("seg"), str) \
            or not msg["seg"].startswith(SEG_PREFIX):
        return None
    return _open_segment(msg["seg"])


def answer_tier_probe(conn, msg, *, accept: bool = True, inner=None,
                      depth: int = 8, device=None):
    """Receiver-side handshake for EVERY colocated tier: validate
    ``msg`` (when ``accept``), send the ``tier_reply`` on ``conn``, and
    return ``(tier, receiver_or_None)`` — ``("ici", IciReceiver)``,
    ``("local", LocalReceiver)``, ``("shm", ShmReceiver)``, or
    ``("tcp", None)``.  ``inner`` is the hop's live socket frame source
    (required to grant shm — the doorbell rides it); ``device`` is the
    granting side's pinned jax device, echoed in the ici ``tier_reply``
    so the sender knows where to ``device_put`` cross-device frames.
    The one helper every granting serve loop uses so a probe is ALWAYS
    answered; refusal-only loops keep
    ``transport.local.answer_probe(..., accept=False)``, which refuses
    any want."""
    from .local import grant_local
    want = msg.get("want") if isinstance(msg, dict) else None
    if accept and want == "ici":
        from .ici import grant_ici
        pipe = grant_ici(msg)
        if pipe is not None:
            send_ctrl(conn, {"cmd": "tier_reply", "tier": "ici",
                             "device": None if device is None
                             else device.id})
            return "ici", pipe.receiver
    elif accept and want == "local":
        pipe = grant_local(msg)
        if pipe is not None:
            send_ctrl(conn, {"cmd": "tier_reply", "tier": "local"})
            return "local", pipe.receiver
    elif accept and want == "shm" and inner is not None:
        seg = grant_shm(msg)
        if seg is not None:
            # hand the proof's own mapping straight to the receiver
            # (re-opening by name would race the sender's unlink paths)
            rx = ShmReceiver(conn, inner, seg,
                             slot_bytes=int(msg.get("slot_bytes",
                                                    DEFAULT_SLOT_BYTES)),
                             slots=int(msg.get("slots", depth)))
            send_ctrl(conn, {"cmd": "tier_reply", "tier": "shm"})
            return "shm", rx
    send_ctrl(conn, {"cmd": "tier_reply", "tier": "tcp"})
    return "tcp", None


# ---------------------------------------------------------------------------
# orphan sweep
# ---------------------------------------------------------------------------

def sweep_orphan_segments(shm_dir: str = "/dev/shm") -> list[str]:
    """Unlink every ``defer_shm_<pid>_*`` segment whose creator pid is
    dead — the deploy-time backstop for chains whose BOTH hop ends were
    kill -9'd (either end surviving reaps its own segments inline).
    Returns the reaped names.  No-op on hosts without a /dev/shm-style
    directory."""
    reaped: list[str] = []
    try:
        names = os.listdir(shm_dir)
    except OSError:
        return reaped
    for name in names:
        if not name.startswith(SEG_PREFIX):
            continue
        rest = name[len(SEG_PREFIX):]
        pid_s, _, _ = rest.partition("_")
        try:
            pid = int(pid_s)
        except ValueError:
            continue
        if pid == os.getpid():
            continue  # this process's rings reap themselves
        try:
            os.kill(pid, 0)
            continue  # creator alive: the segment is (or may be) live
        except ProcessLookupError:
            pass
        except OSError:
            continue  # e.g. EPERM: alive under another uid — leave it
        try:
            os.unlink(os.path.join(shm_dir, name))
            reaped.append(name)
        except OSError:
            pass
    return reaped
