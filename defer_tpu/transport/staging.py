"""Host input-staging ring — ctypes binding over the native implementation.

The ingest half of the data plane: producers (socket readers, user threads)
push samples into a bounded native ring (``_native/staging.cpp``); the
dispatcher drains whole pipeline chunks as one contiguous
``[chunk, slot_bytes]`` block whose layout matches the SPMD engine's
transfer buffer, so feeding the device is a single ``device_put`` with no
per-sample Python work.  This is the reference's bounded ingest queue
(reference src/node.py:88-91,114) rebuilt native, with bounded waits
instead of forever-blocking loops.

Falls back to a pure-Python ring (same semantics, ``threading.Condition``)
when no C++ toolchain exists.
"""

from __future__ import annotations

import ctypes
import os
import threading

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "_native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libdeferstaging.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        from ..utils._nativebuild import ensure_built
        if not ensure_built(os.path.join(_NATIVE_DIR, "staging.cpp"),
                            _SO_PATH):
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        i64 = ctypes.c_int64
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.staging_create.restype = ctypes.c_void_p
        lib.staging_create.argtypes = [i64, i64]
        lib.staging_destroy.argtypes = [ctypes.c_void_p]
        lib.staging_push.restype = ctypes.c_int
        lib.staging_push.argtypes = [ctypes.c_void_p, u8p, i64, i64]
        lib.staging_pop_block.restype = i64
        lib.staging_pop_block.argtypes = [ctypes.c_void_p, u8p, i64, i64]
        lib.staging_close.argtypes = [ctypes.c_void_p]
        lib.staging_depth.restype = i64
        lib.staging_depth.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def _u8(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


class HostStagingRing:
    """Bounded MPSC staging ring of fixed-size f32 sample slots.

    ``slot_elems`` is the flattened per-sample element count (the SPMD
    engine's ``microbatch * buf_elems`` layout unit).  ``push`` accepts any
    float32 array of <= slot_elems elements (short samples are zero-padded
    — the homogeneous-buffer padding).  ``pop_block(chunk)`` returns a
    ``[chunk, slot_elems]`` f32 block plus the number of real samples.
    """

    def __init__(self, slot_elems: int, n_slots: int = 64):
        self.slot_elems = int(slot_elems)
        self.n_slots = int(n_slots)
        self._native = _load()
        if self._native is not None:
            self._h = self._native.staging_create(
                self.slot_elems * 4, self.n_slots)
            if not self._h:
                raise ValueError("staging_create rejected sizes")
        else:  # pure-Python fallback, same semantics
            self._h = None
            self._buf: list[np.ndarray] = []
            self._closed = False
            self._cv = threading.Condition()

    # -- producer side ---------------------------------------------------

    def push(self, sample: np.ndarray, timeout_s: float = 30.0) -> bool:
        """Stage one sample; False on timeout; ValueError after close."""
        flat = np.ascontiguousarray(sample, np.float32).reshape(-1)
        if flat.size > self.slot_elems:
            raise ValueError(f"sample of {flat.size} elems exceeds slot "
                             f"({self.slot_elems})")
        if self._h is not None:
            rc = self._native.staging_push(
                self._h, _u8(flat.view(np.uint8)), flat.size * 4,
                int(timeout_s * 1000))
            if rc < 0:
                raise ValueError("ring is closed")
            return rc == 1
        with self._cv:
            ok = self._cv.wait_for(
                lambda: len(self._buf) < self.n_slots or self._closed,
                timeout=timeout_s)
            if not ok:
                return False
            if self._closed:
                raise ValueError("ring is closed")
            pad = np.zeros(self.slot_elems, np.float32)
            pad[: flat.size] = flat
            self._buf.append(pad)
            self._cv.notify_all()
            return True

    def close(self):
        """End of stream: consumers drain the backlog, then see (0, None)."""
        if self._h is not None:
            self._native.staging_close(self._h)
        else:
            with self._cv:
                self._closed = True
                self._cv.notify_all()

    # -- consumer side ---------------------------------------------------

    def pop_block(self, chunk: int, timeout_s: float = 30.0):
        """-> (n_real, [chunk, slot_elems] f32 block) — the tail is already
        zero-filled bubble padding.  (0, None) on end-of-stream; raises
        TimeoutError if nothing arrives in time (bounded wait: a stalled
        producer can't wedge the serve loop)."""
        out = np.empty((chunk, self.slot_elems), np.float32)
        if self._h is not None:
            got = self._native.staging_pop_block(
                self._h, _u8(out.view(np.uint8).reshape(-1)), chunk,
                int(timeout_s * 1000))
            if got == 0:
                raise TimeoutError("staging ring: no input within timeout")
            if got < 0:
                return 0, None
            return int(got), out
        with self._cv:
            ok = self._cv.wait_for(
                lambda: self._buf or self._closed, timeout=timeout_s)
            if not ok:
                raise TimeoutError("staging ring: no input within timeout")
            if not self._buf:
                return 0, None
            got = min(len(self._buf), chunk)
            for i in range(got):
                out[i] = self._buf[i]
            del self._buf[:got]
            out[got:] = 0.0
            self._cv.notify_all()
            return got, out

    @property
    def depth(self) -> int:
        if self._h is not None:
            return int(self._native.staging_depth(self._h))
        with self._cv:
            return len(self._buf)

    def __del__(self):
        if getattr(self, "_h", None) and self._native is not None:
            self._native.staging_destroy(self._h)
            self._h = None

    @property
    def is_native(self) -> bool:
        return self._h is not None
