from .checkpoint import load_params, save_params
from .config import DeferConfig
from .metrics import PipelineMetrics, StopwatchWindow
from .profiling import profile_pipeline, trace
