from .config import DeferConfig
from .metrics import PipelineMetrics, StopwatchWindow
