"""Shared on-demand builder for the first-party C++ libraries.

One place owns the three rules both loaders (codec, staging ring) need:

- **staleness**: a ``.so`` older than its ``.cpp`` is rebuilt — a stale
  binary silently running old code is how the r5 lzb heap-overflow fix
  could have failed to take effect on machines with a pre-fix build;
- **no stale fallback**: if a needed rebuild fails, the caller gets
  ``False`` and must fall back to its NumPy/Python path, NEVER the
  known-stale binary;
- **atomic install**: g++ writes a temp path that is ``os.replace``d
  into place, so concurrent builders (pytest workers, parallel
  processes) can never leave a half-written library for ``CDLL``.
"""

from __future__ import annotations

import os
import subprocess


def ensure_built(src: str, so_path: str, timeout: float = 120.0) -> bool:
    """True iff ``so_path`` exists and is at least as new as ``src``."""
    if not os.path.exists(src):
        return os.path.exists(so_path)
    stale = (os.path.exists(so_path)
             and os.path.getmtime(src) > os.path.getmtime(so_path))
    if os.path.exists(so_path) and not stale:
        return True
    tmp = f"{so_path}.build.{os.getpid()}"
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-o", tmp,
             src],
            check=True, capture_output=True, timeout=timeout)
        os.replace(tmp, so_path)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
