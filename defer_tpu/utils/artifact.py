"""Timeout-safe benchmark-artifact writing.

The measurement scripts (bench_decode / bench_spec / xla_flag_sweep) run
long sweeps under wall-clock timeouts on a flaky tunnel; the contract is
that every completed row survives.  ``flush_artifact`` provides the two
properties they all need:

- **atomic**: write to ``path + ".part"`` then ``os.replace``, so a kill
  mid-write can never truncate the artifact;
- **merging**: rows already present on disk (e.g. from a timed-out first
  run, re-run with a row filter) are preserved unless the new payload
  re-measured them, and the headline ``value`` is recomputed over the
  MERGED rows — a partial re-run can only add information, never lose
  the rows the incremental-flush machinery exists to keep.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any


def flush_artifact(path: str | None, payload: dict[str, Any],
                   merge_key: str | None = None,
                   value_key: str = "tokens_per_s",
                   row_filter=None,
                   merge_prior: bool = False) -> dict[str, Any]:
    """Atomically write ``payload`` as one JSON line to ``path``.

    When ``merge_key`` names a dict of rows inside the payload, the
    headline ``"value"`` (when present in the payload) is recomputed as
    the max ``value_key`` over those rows — restricted to row names
    accepted by ``row_filter`` when given — so stdout and artifact can
    never disagree.

    ``merge_prior=True`` additionally keeps rows already on disk at
    ``path`` that this run did not re-measure.  Callers should pass it
    ONLY for a filtered partial re-run (e.g. bench_decode's
    ``DEFER_DECODE_ROWS``): merging unconditionally would let rows from
    an obsolete sweep configuration survive a full re-run and own the
    headline.  A missing, empty, or malformed prior artifact is
    ignored.

    When ``path`` is falsy nothing is written (value recomputation
    still happens); a failed write is reported on stderr but never
    raises — an unwritable artifact path must not kill the sweep the
    incremental flush exists to protect.  Returns the payload as
    written/printed.
    """
    if merge_key is not None:
        if path and merge_prior:
            try:
                with open(path) as f:
                    text = f.read().strip()
                prev = json.loads(text.splitlines()[-1]) if text else {}
                if not isinstance(prev, dict):
                    prev = {}
            except (OSError, ValueError):
                prev = {}
            merged = dict(prev.get(merge_key) or {}) \
                if isinstance(prev.get(merge_key), dict) else {}
            merged.update(payload.get(merge_key) or {})
            payload = {**payload, merge_key: merged}
        rows = payload.get(merge_key) or {}
        if "value" in payload:
            ok = [v[value_key] for k, v in rows.items()
                  if isinstance(v, dict) and value_key in v
                  and (row_filter is None or row_filter(k))]
            if ok:
                payload["value"] = max(ok)
    if not path:
        return payload
    try:
        tmp = path + ".part"
        with open(tmp, "w") as f:
            f.write(json.dumps(payload) + "\n")
        os.replace(tmp, path)
    except OSError as e:
        print(f"flush_artifact: could not write {path}: {e!r}",
              file=sys.stderr, flush=True)
    return payload
