"""Checkpoint save/restore for model parameters.

The reference ships weights once over TCP at startup and holds them in
memory (reference src/dispatcher.py:57, src/node.py:34) — nothing is ever
persisted.  Here weights are a first-class checkpointable pytree: orbax when
available (the TPU-ecosystem standard), with a dependency-free ``.npz``
format as both fallback and interchange.  Stage placement consumes the same
pytree (``StageSpec.select_params``), so "restore then deploy" is one line.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np

_SEP = "/"


def _leaf_key(node: str, path) -> str:
    """Stable flat key for one pytree leaf (shared by save and load)."""
    return node + _SEP + _SEP.join(
        str(getattr(p, "key", getattr(p, "idx", p))) for p in path)


def _npz_path(path: str) -> str:
    # np.savez appends ".npz" to suffix-less paths; normalize so save and
    # load always agree on the on-disk name
    return path if path.endswith(".npz") else path + ".npz"


def _flatten(params: dict[str, Any], materialize: bool = True
             ) -> dict[str, Any]:
    """Flat key->leaf map; ``materialize=False`` keeps leaves as-is so
    shape-only trees (``jax.eval_shape`` output) can be used as templates."""
    flat = {}
    for node, sub in params.items():
        leaves_with_paths = jax.tree_util.tree_flatten_with_path(sub)[0]
        for path, leaf in leaves_with_paths:
            flat[_leaf_key(node, path)] = np.asarray(leaf) if materialize \
                else leaf
    return flat


def save_params(path: str, params: dict[str, Any]):
    """Save a graph parameter pytree to ``<path>`` (npz)."""
    path = _npz_path(path)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    np.savez(path, **_flatten(params))


def load_params(path: str, like: dict[str, Any]) -> dict[str, Any]:
    """Restore parameters saved by :func:`save_params`.

    ``like`` provides the target structure (e.g. ``graph.init(key)`` output
    or its eval_shape); returned arrays match its treedef exactly.  Missing
    or extra keys fail loudly — a checkpoint/model mismatch should never be
    silent.
    """
    with np.load(_npz_path(path)) as data:
        stored = dict(data)
    out: dict[str, Any] = {}
    expected = _flatten(like, materialize=False)
    missing = set(expected) - set(stored)
    extra = set(stored) - set(expected)
    if missing or extra:
        raise ValueError(
            f"checkpoint mismatch: missing={sorted(missing)[:5]} "
            f"extra={sorted(extra)[:5]}")
    for node, sub in like.items():
        leaves_paths, treedef = jax.tree_util.tree_flatten_with_path(sub)
        leaves = []
        for path, leaf in leaves_paths:
            key = _leaf_key(node, path)
            arr = stored[key]
            if arr.shape != tuple(np.shape(leaf)):
                raise ValueError(
                    f"checkpoint leaf {key!r} has shape {arr.shape}, "
                    f"model expects {np.shape(leaf)}")
            leaves.append(arr)
        out[node] = jax.tree_util.tree_unflatten(treedef, leaves)
    return out


def save_params_orbax(path: str, params: dict[str, Any]):
    """Orbax-backed save (directory tree checkpoint); requires orbax."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), params, force=True)
    ckptr.wait_until_finished()


def load_params_orbax(path: str, like: dict[str, Any]) -> dict[str, Any]:
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    shapes = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype),
        like)
    return ckptr.restore(os.path.abspath(path), shapes)
