"""JAX version compatibility shims.

The engines target the current ``jax.shard_map(..., check_vma=...)`` API;
older installs (<= 0.4.x) only have ``jax.experimental.shard_map`` whose
replication-check kwarg is spelled ``check_rep``.  Every shard_map call in
the codebase goes through this one wrapper so the version probe happens
once, at import.
"""

from __future__ import annotations

import jax

_impl = getattr(jax, "shard_map", None)
_LEGACY = _impl is None
if _LEGACY:
    from jax.experimental.shard_map import shard_map as _impl  # type: ignore


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions (``check_vma``/``check_rep``).

    On the legacy API the replication checker is always disabled: the old
    ``check_rep`` implementation false-positives on valid programs (e.g.
    ``lax.cond`` branches — jax's own error suggests ``check_rep=False``
    as the workaround), and it is purely a debugging aid.  The modern
    ``check_vma`` checker honours the caller's flag."""
    if _LEGACY:
        return _impl(f, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)
    return _impl(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                 check_vma=check_vma)


def pcast_varying(x, axes):
    """``lax.pcast(..., to="varying")`` where the VMA type system exists;
    identity on legacy jax (no varying-manual-axes typing to satisfy)."""
    pc = getattr(jax.lax, "pcast", None)
    if pc is None:
        return x
    return pc(x, axes, to="varying")


def axis_size(axis_name) -> int:
    """Static size of a mapped mesh axis (``lax.axis_size`` on current
    jax; the ``core.axis_frame`` lookup on legacy versions, where the
    frame resolves directly to the int size)."""
    ax = getattr(jax.lax, "axis_size", None)
    if ax is not None:
        return ax(axis_name)
    from jax import core
    return core.axis_frame(axis_name)
