"""JAX version compatibility shims.

The engines target the current ``jax.shard_map(..., check_vma=...)`` API;
older installs (<= 0.4.x) only have ``jax.experimental.shard_map`` whose
replication-check kwarg is spelled ``check_rep``.  Every shard_map call in
the codebase goes through this one wrapper so the version probe happens
once, at import.
"""

from __future__ import annotations

import jax

_impl = getattr(jax, "shard_map", None)
_LEGACY = _impl is None
if _LEGACY:
    from jax.experimental.shard_map import shard_map as _impl  # type: ignore


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions (``check_vma``/``check_rep``).

    On the legacy API the replication checker is always disabled: the old
    ``check_rep`` implementation false-positives on valid programs (e.g.
    ``lax.cond`` branches — jax's own error suggests ``check_rep=False``
    as the workaround), and it is purely a debugging aid.  The modern
    ``check_vma`` checker honours the caller's flag."""
    if _LEGACY:
        return _impl(f, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)
    return _impl(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                 check_vma=check_vma)


def pcast_varying(x, axes):
    """``lax.pcast(..., to="varying")`` where the VMA type system exists;
    identity on legacy jax (no varying-manual-axes typing to satisfy)."""
    pc = getattr(jax.lax, "pcast", None)
    if pc is None:
        return x
    return pc(x, axes, to="varying")


def host_device_count_flags(flags: str | None, n: int) -> str:
    """``XLA_FLAGS`` string with the host-platform device-count flag
    forced to ``n`` (any existing count flag replaced) — shared by
    :func:`force_host_device_count` and ``run_chain``'s child-env
    rewrite so the flag format lives in one place."""
    import re

    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   flags or "").strip()
    return f"{flags} --xla_force_host_platform_device_count={n}".strip()


def force_host_device_count(n: int) -> tuple[bool, str]:
    """Arrange for the host platform to expose ``n`` XLA devices
    (``--xla_force_host_platform_device_count``) — the test vehicle for
    same-mesh multi-device work (the ici transport tier, sharding
    tests) on hosts without a real accelerator mesh.

    Must run BEFORE jax initializes its backends: the flag is read once
    at backend construction.  Returns ``(ok, reason)`` — ``ok`` is True
    when the flag took (or the backend already exposes >= n devices),
    False with a skip-worthy ``reason`` when jax already initialized
    with fewer devices (callers like the conftest fixture turn that
    into a skip instead of a wrong-mesh test run).
    """
    import os

    n = int(n)
    backends = getattr(getattr(jax._src, "xla_bridge", None),
                       "_backends", None)
    if backends:
        have = len(jax.devices())
        if have >= n:
            return True, f"backend already initialized with {have} devices"
        return False, (f"jax already initialized with {have} host "
                       f"device(s) < {n}; set XLA_FLAGS="
                       f"--xla_force_host_platform_device_count={n} "
                       f"before the first jax call")
    os.environ["XLA_FLAGS"] = host_device_count_flags(
        os.environ.get("XLA_FLAGS"), n)
    return True, f"XLA_FLAGS set for {n} host devices"


def axis_size(axis_name) -> int:
    """Static size of a mapped mesh axis (``lax.axis_size`` on current
    jax; the ``core.axis_frame`` lookup on legacy versions, where the
    frame resolves directly to the int size)."""
    ax = getattr(jax.lax, "axis_size", None)
    if ax is not None:
        return ax(axis_name)
    from jax import core
    return core.axis_frame(axis_name)
