"""Typed configuration for the pipeline runtime.

Replaces the reference's scattered hardcoded constants — ports 5000/5001/5002
(src/dispatcher.py:18, src/node.py:17), 512 KB chunk size
(src/dispatcher.py:24, src/node.py:111), Queue(1000) in-flight bound
(src/node.py:114), 5 s poll loops (src/node.py:33,96) — with one dataclass.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class DeferConfig:
    # samples per microbatch (the reference streams 1 image per message,
    # test/test.py:22 — microbatch=1 is the parity setting)
    microbatch: int = 1
    # pipeline steps fused into one jit-compiled scan call; the analogue of
    # the reference's in-flight window (Queue(1000), src/node.py:114)
    chunk: int = 16
    # dtype of the homogeneous inter-stage transfer buffer.  bfloat16 halves
    # ICI bytes — the TPU-idiomatic analogue of the reference's lossy ZFP
    # activation compression (src/node.py:107)
    buffer_dtype: str = "float32"
    # dtype activations are cast to inside each stage (None = model dtype)
    compute_dtype: str | None = None
    # keep the flat weight buffer in f32 and cast to compute_dtype inside
    # each stage branch — the mixed-precision TRAINING recipe (optimizer
    # updates in full precision); inference-only deployments leave this
    # off for half the HBM footprint
    master_weights: bool = False
    # stage->stage hop encoding: "buffer" sends the raw transfer buffer;
    # "int8" block-quantizes the hop in HBM (ICI moves ~1 byte/value — the
    # device-side analogue of the reference's ZFP wire compression)
    wire: str = "buffer"
    # extra batch-parallel pipeline replicas (mesh "data" axis)
    data_parallel: int = 1
    # intra-stage Megatron-style weight sharding (mesh "model" axis);
    # requires every parametric op in the model to implement TP hooks
    tensor_parallel: int = 1
    # "spmd" (shard_map + ppermute, primary) or "mpmd" (per-stage programs +
    # device_put relay, correctness oracle / debug)
    mode: str = "spmd"
    # seconds the dispatcher waits for more queue items before padding a
    # partial chunk with bubbles
    gather_timeout_s: float = 0.002
    # failure detection: once past the first (compile) dispatch, if a
    # pipeline dispatch makes no progress for this many seconds the serve
    # thread is declared dead and readers unblocked (the reference has no
    # failure handling at all — a dead node hangs the chain forever,
    # SURVEY.md §5; None disables).  The effective bound self-scales to the
    # deployment: max(watchdog_s, watchdog_scale * slowest completed
    # dispatch so far) — so a slow host whose legitimate dispatches take
    # tens of seconds (big chunk on the CPU fallback, device-shape
    # recompiles) raises its own threshold instead of being falsely
    # declared dead, while a genuinely wedged dispatch still fires in
    # bounded time.
    watchdog_s: float | None = 60.0
    # multiplier on the slowest completed dispatch (warmup/preflight
    # included — it covers the XLA compile, the natural upper bound for
    # any later legitimate dispatch)
    watchdog_scale: float = 8.0
    # run a full-chunk bubble probe through the freshly built pipeline
    # before serving traffic, so compile failures surface as handle.error
    # immediately instead of mid-stream
    preflight: bool = True
    # recovery, not just detection: when the watchdog declares a dispatch
    # hung, up to this many times the dispatcher REBUILDS the pipeline
    # (fresh jit, same weights), replays the fed-but-unemitted microbatches
    # from the resubmit log, and resumes the stream — the wedged thread is
    # abandoned (its generation can no longer emit).  0 restores
    # detection-only (error + sentinel on first fire).  SPMD mode only.
    max_recoveries: int = 1
