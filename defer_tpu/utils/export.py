"""Stage program serialization: StableHLO + weights instead of Keras JSON.

The reference's control plane ships each partition to its node as Keras
architecture JSON plus compressed weights over TCP (reference
src/dispatcher.py:44-65, rebuilt via ``model_from_json`` at src/node.py:31).
The TPU-native equivalent serializes the *compiled artifact*: the stage's
jaxpr lowered through ``jax.export`` to portable StableHLO bytes, plus the
stage's weight pytree — loadable in a process that has no model code at
all, with XLA recompiling for the local device.  Useful for MPMD
deployments where stage hosts are separate processes, and as the durable
"partition artifact" format.
"""

from __future__ import annotations

import io
import json
import os
import zipfile
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp
from jax import export as jax_export

from ..partition.stage import StageSpec

_MANIFEST = "manifest.json"
_PROGRAM = "stage.stablehlo"
_WEIGHTS = "weights.npz"


def stage_weight_leaves(stage: StageSpec,
                        params: dict[str, Any]) -> list[np.ndarray]:
    """The stage's weight pytree, flattened in the artifact's leaf order —
    the unit both full export and weights-only re-push ship."""
    leaves, _ = jax.tree.flatten(stage.select_params(params))
    return [np.asarray(l) for l in leaves]


def weights_blob(leaves: list[np.ndarray]) -> bytes:
    """npz-serialize a leaf list (the reweight payload)."""
    buf = io.BytesIO()
    np.savez(buf, **{f"w{i}": l for i, l in enumerate(leaves)})
    return buf.getvalue()


def _load_weights_blob(data: bytes, num: int) -> list:
    with np.load(io.BytesIO(data)) as npz:
        return [jnp.asarray(npz[f"w{i}"]) for i in range(num)]


def export_stage_bytes(stage: StageSpec, params: dict[str, Any],
                       *, batch: int = 1) -> bytes:
    """Serialize one pipeline stage to zip-archive bytes.

    Contents: portable StableHLO of the stage function specialized to
    ``batch``, the stage's weight pytree, and a JSON manifest with shapes
    and stage metadata (the analogue of the arch-JSON + weights pair the
    reference ships per node, src/dispatcher.py:44-65) — a single blob so
    the dispatcher can ship it over the control connection.
    """
    sp = stage.select_params(params)
    leaves, treedef = jax.tree.flatten(sp)
    leaves = [np.asarray(l) for l in leaves]

    def fn(flat_leaves, *xs):
        p = jax.tree.unflatten(treedef, flat_leaves)
        return stage.fn(p, *xs)

    # a JoinStageSpec (branched pipelines, docs/TRANSPORT.md) takes P
    # boundary tensors — one per merged branch path, in path order
    in_specs = tuple(getattr(stage, "in_specs", None)
                     or (stage.in_spec,))
    x_specs = [jax.ShapeDtypeStruct((batch,) + s.shape, s.dtype)
               for s in in_specs]
    leaf_specs = [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in leaves]
    exported = jax_export.export(jax.jit(fn))(leaf_specs, *x_specs)
    blob = exported.serialize()

    manifest = {
        "format": "defer_tpu.stage.v1",
        "index": stage.index,
        "name": stage.name,
        "graph": stage.graph.name,
        "input": getattr(stage, "input_name", None)
        or ",".join(stage.input_names),
        "output": stage.output_name,
        "batch": batch,
        "in_shape": list(in_specs[0].shape),
        "in_dtype": in_specs[0].dtype.name,
        "out_shape": list(stage.out_spec.shape),
        "out_dtype": stage.out_spec.dtype.name,
        "num_weights": len(leaves),
    }
    if len(in_specs) > 1:
        manifest["num_inputs"] = len(in_specs)
        manifest["in_shapes"] = [list(s.shape) for s in in_specs]
        manifest["in_dtypes"] = [s.dtype.name for s in in_specs]
    out = io.BytesIO()
    with zipfile.ZipFile(out, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr(_MANIFEST, json.dumps(manifest, indent=1))
        z.writestr(_PROGRAM, blob)
        z.writestr(_WEIGHTS, weights_blob(leaves))
    return out.getvalue()


def export_stage(stage: StageSpec, params: dict[str, Any], path: str,
                 *, batch: int = 1) -> None:
    """Serialize one pipeline stage to ``path`` (see export_stage_bytes)."""
    data = export_stage_bytes(stage, params, batch=batch)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        f.write(data)


class StageProgram:
    """A loaded stage artifact: callable, with swappable weights.

    ``fn(x)`` runs the stage's StableHLO program with its shipped weights
    on the local backend — no model code required (the analogue of the
    node's ``model_from_json`` + ``set_weights``, reference
    src/node.py:31-34).  ``reweight(blob)`` installs a fresh weight set
    (same shapes) without reloading the program — redeploy without
    restart.
    """

    def __init__(self, exported, leaves: list, manifest: dict):
        self._exported = exported
        self.manifest = manifest
        self.device = None
        self._install(leaves)

    def _install(self, leaves: list):
        if len(leaves) != self.manifest["num_weights"]:
            raise ValueError(
                f"expected {self.manifest['num_weights']} weight arrays, "
                f"got {len(leaves)}")
        call = self._exported.call
        self._leaves = leaves
        # *xs: a join-stage artifact (manifest["num_inputs"] > 1) takes
        # one array per merged branch path, single-input stages just one
        base = jax.jit(lambda *xs: call(leaves, *xs))
        if self.device is None:
            self.fn = base
        else:
            # committing the inputs pins the computation: jit places the
            # executable on its committed arguments' device.  device_put
            # of an array already resident there is a no-op, so the
            # device-resident (ici) hand-off path pays nothing here.
            dev = self.device
            self.fn = lambda *xs: base(
                *(jax.device_put(x, dev) for x in xs))

    def place(self, device) -> None:
        """Pin the program to one jax device: every call runs (and its
        output lives) there — the deployment half of the device-resident
        ``ici`` transport tier, where the UPSTREAM hop device_puts each
        activation onto this device and the program consumes it without
        any host round-trip."""
        self.device = device
        self._install(self._leaves)

    def reweight(self, blob: bytes):
        """Install a weights npz blob (shapes must match the artifact's)."""
        new = _load_weights_blob(blob, self.manifest["num_weights"])
        for i, (old, nw) in enumerate(zip(self._leaves, new)):
            if old.shape != nw.shape or old.dtype != nw.dtype:
                raise ValueError(
                    f"weight {i}: artifact has {old.shape}/{old.dtype}, "
                    f"re-push has {nw.shape}/{nw.dtype}")
        self._install(new)

    def __call__(self, *xs):
        return self.fn(*xs)


def load_stage_program(src) -> StageProgram:
    """Load an exported stage from a path or bytes into a StageProgram."""
    f = io.BytesIO(src) if isinstance(src, (bytes, bytearray)) else src
    with zipfile.ZipFile(f) as z:
        manifest = json.loads(z.read(_MANIFEST).decode())
        if manifest.get("format") != "defer_tpu.stage.v1":
            raise ValueError(f"{src!r:.80}: not a defer_tpu stage artifact")
        exported = jax_export.deserialize(z.read(_PROGRAM))
        leaves = _load_weights_blob(z.read(_WEIGHTS),
                                    manifest["num_weights"])
    return StageProgram(exported, leaves, manifest)


def load_stage(path: str):
    """Back-compat loader: returns ``(fn, manifest)``."""
    prog = load_stage_program(path)
    return prog.fn, prog.manifest


def export_pipeline(stages, params, directory: str, *, batch: int = 1):
    """Export every stage of a partition to ``directory/stage_<i>.zip``."""
    paths = []
    for s in stages:
        p = os.path.join(directory, f"stage_{s.index}.zip")
        export_stage(s, params, p, batch=batch)
        paths.append(p)
    return paths
