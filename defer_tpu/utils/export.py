"""Stage program serialization: StableHLO + weights instead of Keras JSON.

The reference's control plane ships each partition to its node as Keras
architecture JSON plus compressed weights over TCP (reference
src/dispatcher.py:44-65, rebuilt via ``model_from_json`` at src/node.py:31).
The TPU-native equivalent serializes the *compiled artifact*: the stage's
jaxpr lowered through ``jax.export`` to portable StableHLO bytes, plus the
stage's weight pytree — loadable in a process that has no model code at
all, with XLA recompiling for the local device.  Useful for MPMD
deployments where stage hosts are separate processes, and as the durable
"partition artifact" format.
"""

from __future__ import annotations

import io
import json
import os
import zipfile
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp
from jax import export as jax_export

from ..partition.stage import StageSpec

_MANIFEST = "manifest.json"
_PROGRAM = "stage.stablehlo"
_WEIGHTS = "weights.npz"


def export_stage(stage: StageSpec, params: dict[str, Any], path: str,
                 *, batch: int = 1) -> None:
    """Serialize one pipeline stage to ``path`` (a zip archive).

    Contents: portable StableHLO of the stage function specialized to
    ``batch``, the stage's weight pytree, and a JSON manifest with shapes
    and stage metadata (the analogue of the arch-JSON + weights pair the
    reference ships per node).
    """
    sp = stage.select_params(params)
    leaves, treedef = jax.tree.flatten(sp)
    leaves = [np.asarray(l) for l in leaves]

    def fn(flat_leaves, x):
        p = jax.tree.unflatten(treedef, flat_leaves)
        return stage.fn(p, x)

    x_spec = jax.ShapeDtypeStruct((batch,) + stage.in_spec.shape,
                                  stage.in_spec.dtype)
    leaf_specs = [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in leaves]
    exported = jax_export.export(jax.jit(fn))(leaf_specs, x_spec)
    blob = exported.serialize()

    manifest = {
        "format": "defer_tpu.stage.v1",
        "index": stage.index,
        "name": stage.name,
        "graph": stage.graph.name,
        "input": stage.input_name,
        "output": stage.output_name,
        "batch": batch,
        "in_shape": list(stage.in_spec.shape),
        "in_dtype": stage.in_spec.dtype.name,
        "out_shape": list(stage.out_spec.shape),
        "out_dtype": stage.out_spec.dtype.name,
        "num_weights": len(leaves),
    }
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr(_MANIFEST, json.dumps(manifest, indent=1))
        z.writestr(_PROGRAM, blob)
        buf = io.BytesIO()
        np.savez(buf, **{f"w{i}": l for i, l in enumerate(leaves)})
        z.writestr(_WEIGHTS, buf.getvalue())


def load_stage(path: str):
    """Load an exported stage: returns ``(fn, manifest)``.

    ``fn(x)`` runs the stage's StableHLO program with its shipped weights
    on the local backend — no model code required (the analogue of the
    node's ``model_from_json`` + ``set_weights``, reference
    src/node.py:31-34).
    """
    with zipfile.ZipFile(path) as z:
        manifest = json.loads(z.read(_MANIFEST).decode())
        if manifest.get("format") != "defer_tpu.stage.v1":
            raise ValueError(f"{path}: not a defer_tpu stage artifact")
        exported = jax_export.deserialize(z.read(_PROGRAM))
        with np.load(io.BytesIO(z.read(_WEIGHTS))) as npz:
            leaves = [jnp.asarray(npz[f"w{i}"])
                      for i in range(manifest["num_weights"])]

    call = exported.call

    def fn(x):
        return call(leaves, x)

    return jax.jit(fn), manifest


def export_pipeline(stages, params, directory: str, *, batch: int = 1):
    """Export every stage of a partition to ``directory/stage_<i>.zip``."""
    paths = []
    for s in stages:
        p = os.path.join(directory, f"stage_{s.index}.zip")
        export_stage(s, params, p, batch=batch)
        paths.append(p)
    return paths
