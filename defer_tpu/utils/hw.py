"""TPU hardware constants + chip identification (shared by the benches).

Public per-generation numbers used for MFU and for the analytic pipeline
model.  Peaks are bf16 dense FLOP/s per chip; ICI figures are one-way
bytes/s per link (the stage->stage hop rides one link of the torus).
Sources: public TPU spec sheets / the scaling-book tables.
"""

from __future__ import annotations

import os

PEAK_BF16_FLOPS: dict[str, float] = {
    "v2": 46e12,
    "v3": 123e12,
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}

#: one-way ICI bandwidth per link, bytes/s
ICI_BW_BYTES_S: dict[str, float] = {
    "v2": 5.0e10,
    "v3": 7.0e10,
    "v4": 4.5e10,
    "v5e": 4.5e10,
    "v5p": 9.0e10,
    "v6e": 9.0e10,
}

#: HBM bandwidth, bytes/s (public spec-sheet numbers)
HBM_BW_BYTES_S: dict[str, float] = {
    "v2": 7.0e11,
    "v3": 9.0e11,
    "v4": 1.228e12,
    "v5e": 8.19e11,
    "v5p": 2.765e12,
    "v6e": 1.64e12,
}


def hbm_bandwidth(gen: str) -> float:
    """HBM bytes/s for a generation; 0.0 when unknown."""
    return HBM_BW_BYTES_S.get(gen, 0.0)


def identify_chip(device) -> str:
    """Generation string for a jax device, or "unknown".

    Checks the PJRT ``device_kind`` first, then the environment hint this
    container sets for its tunneled chip (``PALLAS_AXON_TPU_GEN``).
    """
    kind = str(getattr(device, "device_kind", "")).lower().replace(" ", "")
    env_gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    for gen in ("v6e", "v5p", "v5e", "v4", "v3", "v2"):
        if gen in kind or gen == env_gen:
            return gen
    if "v5lite" in kind:
        return "v5e"
    return "unknown"


def peak_flops(gen: str) -> float:
    """bf16 peak FLOP/s for a generation; 0.0 when unknown (callers must
    not fabricate MFU against a guessed peak)."""
    return PEAK_BF16_FLOPS.get(gen, 0.0)


def ici_bandwidth(gen: str) -> float:
    """One-way ICI bytes/s per link; 0.0 when unknown."""
    return ICI_BW_BYTES_S.get(gen, 0.0)


def analytic_pipeline_model(stage_latencies_s: list[float],
                            bytes_per_hop: int,
                            ici_bw_bytes_s: float) -> dict:
    """Predicted N-chip pipeline speedup from measured single-chip inputs.

    The written, checkable basis for the >=1.5x multi-chip claim when only
    one chip exists to measure (BASELINE.md target):

    * single device runs the stages back to back: ``T1 = sum(lat)``;
    * the full pipeline's steady-state step time is its slowest stage,
      plus the ICI hop where it cannot overlap:
      ``Tstep = max(lat) + hop`` (hop fully serialized — conservative;
      XLA overlaps collective-permute with compute when it can);
    * predicted speedup = ``T1 / Tstep``; the balance ratio
      ``max/mean`` says how much of the ideal N is lost to partition skew.
    """
    lats = list(stage_latencies_s)
    n = len(lats)
    t1 = sum(lats)
    tmax = max(lats)
    hop_s = (bytes_per_hop / ici_bw_bytes_s) if ici_bw_bytes_s > 0 else 0.0
    tstep = tmax + hop_s
    return {
        "num_stages": n,
        "sum_stage_ms": round(t1 * 1e3, 4),
        "max_stage_ms": round(tmax * 1e3, 4),
        "hop_ms": round(hop_s * 1e3, 5),
        "balance_max_over_mean": round(tmax / (t1 / n), 4) if t1 else None,
        "predicted_speedup_vs_single_chip": round(t1 / tstep, 4)
        if tstep else None,
        "predicted_efficiency_vs_ideal": round(t1 / tstep / n, 4)
        if tstep else None,
        "comm_model": "hop serialized after slowest stage (conservative)",
    }
