"""Pipeline metrics — first-class per BASELINE.md (inferences/sec and
per-stage latency).  The reference only counts results in a timed window in
its harness (test/test.py:29-37); here the runtime itself records stats."""

from __future__ import annotations

import dataclasses
import time


@dataclasses.dataclass
class PipelineMetrics:
    num_stages: int = 0
    inferences: int = 0
    microbatch: int = 1
    steps: int = 0  # pipeline steps executed (each = one step on every stage)
    wall_s: float = 0.0
    chunk_calls: int = 0
    stage_latency_s: list[float] = dataclasses.field(default_factory=list)
    buffer_elems: int = 0
    buffer_bytes_per_hop: int = 0

    def clear_counters(self):
        """Zero the streaming counters (keep stage latencies / geometry) —
        e.g. after a harness's warmup pushes, before a measured window."""
        self.inferences = 0
        self.steps = 0
        self.wall_s = 0.0
        self.chunk_calls = 0

    @property
    def throughput(self) -> float:
        return self.inferences / self.wall_s if self.wall_s > 0 else 0.0

    @property
    def bubble_fraction(self) -> float:
        """Fraction of pipeline steps spent on fill/drain bubbles.

        Each emitted microbatch represents one fully-useful pipeline step
        (every stage worked on a real microbatch exactly once for it).
        """
        if self.steps == 0:
            return 0.0
        useful_steps = self.inferences / max(self.microbatch, 1)
        return max(0.0, 1.0 - useful_steps / self.steps)

    @property
    def duty_cycle(self) -> list[float]:
        """Per-stage busy fraction at steady state (stage latency / slowest
        stage).  The energy-analogue metric: the reference's headline -63%
        per-node energy (README.md:12) comes from each node idling between
        relays; duty cycle is the device-side measure of that idling."""
        if not self.stage_latency_s:
            return []
        slowest = max(self.stage_latency_s)
        if slowest <= 0:
            return [0.0] * len(self.stage_latency_s)
        return [l / slowest for l in self.stage_latency_s]

    @property
    def pipeline_efficiency(self) -> float:
        """Mean duty cycle — 1.0 means perfectly balanced stages."""
        d = self.duty_cycle
        return sum(d) / len(d) if d else 0.0

    def as_dict(self) -> dict:
        return {
            "num_stages": self.num_stages,
            "inferences": self.inferences,
            "wall_s": round(self.wall_s, 6),
            "throughput_per_s": round(self.throughput, 3),
            "chunk_calls": self.chunk_calls,
            "stage_latency_ms": [round(s * 1e3, 4) for s in self.stage_latency_s],
            "buffer_bytes_per_hop": self.buffer_bytes_per_hop,
            "bubble_fraction": round(self.bubble_fraction, 4),
            "duty_cycle": [round(d, 4) for d in self.duty_cycle],
            "pipeline_efficiency": round(self.pipeline_efficiency, 4),
        }


class StopwatchWindow:
    """Timed-window throughput counter reproducing the reference harness
    semantics (results drained in a window ÷ window seconds,
    test/test.py:25-37)."""

    def __init__(self, window_s: float):
        self.window_s = window_s
        self.count = 0
        self._t0 = time.perf_counter()

    def tick(self, n: int = 1) -> bool:
        """Record n results; returns False once the window has elapsed."""
        self.count += n
        return (time.perf_counter() - self._t0) < self.window_s

    @property
    def elapsed(self) -> float:
        return time.perf_counter() - self._t0

    @property
    def rate(self) -> float:
        e = self.elapsed
        return self.count / e if e > 0 else 0.0
