"""Pipeline metrics — first-class per BASELINE.md (inferences/sec and
per-stage latency).  The reference only counts results in a timed window in
its harness (test/test.py:29-37); here the runtime itself records stats.

Since the telemetry PR, the averages are backed by ``defer_tpu.obs``:
per-chunk push latency and per-stage latency are log-bucketed histograms
(p50/p95/p99/max), and :meth:`PipelineMetrics.bind` publishes every field
into the process-wide :data:`~defer_tpu.obs.REGISTRY` so one snapshot
carries the whole deployment.  The streaming counters stay plain ints —
the hot path pays attribute increments, never a registry lookup.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
import weakref

from ..obs import REGISTRY, LatencyHistogram

#: unique registry prefixes for successive deployments in one process
_PIPE_SEQ = itertools.count()


@dataclasses.dataclass
class PipelineMetrics:
    num_stages: int = 0
    inferences: int = 0
    microbatch: int = 1
    steps: int = 0  # pipeline steps executed (each = one step on every stage)
    wall_s: float = 0.0
    chunk_calls: int = 0
    stage_latency_s: list[float] = dataclasses.field(default_factory=list)
    buffer_elems: int = 0
    buffer_bytes_per_hop: int = 0
    #: per-chunk ``push`` wall time (host dispatch + collect), log-bucketed
    push_latency: LatencyHistogram = dataclasses.field(
        default_factory=LatencyHistogram)
    #: per-stage compiled-branch latency distributions (filled by
    #: ``record_stage_latency`` / ``SpmdPipeline.stage_latencies``)
    stage_hists: list[LatencyHistogram] = dataclasses.field(
        default_factory=list)
    #: registry prefix once bound (``bind``), e.g. "pipeline3"
    prefix: str | None = None

    def clear_counters(self):
        """Zero the streaming counters (keep stage latencies / geometry) —
        e.g. after a harness's warmup pushes, before a measured window."""
        self.inferences = 0
        self.steps = 0
        self.wall_s = 0.0
        self.chunk_calls = 0
        self.push_latency.clear()

    # -- registry view -----------------------------------------------------

    def bind(self, registry=None, prefix: str | None = None) -> str:
        """Publish this deployment's metrics into ``registry`` (default:
        the process-wide one) under ``prefix`` (default: a fresh
        ``pipeline<N>``).  Counters are exported via snapshot-time
        callbacks, so updating them stays a plain int increment; the
        histograms are registered as live instruments.  Returns the
        prefix.  Idempotent per instance."""
        if self.prefix is not None:
            return self.prefix
        registry = registry or REGISTRY
        self._registry = registry
        self.prefix = prefix or f"pipeline{next(_PIPE_SEQ)}"
        p = self.prefix
        # weakref callbacks: the registry must not keep dead deployments'
        # metrics alive (Defer.build makes a fresh pipeline per call);
        # once the deployment is collected its callbacks return None and
        # the snapshot drops them.  The histograms are registered as live
        # instruments — small, and useful post-mortem.
        ref = weakref.ref(self)
        for field in ("num_stages", "microbatch", "inferences", "steps",
                      "wall_s", "chunk_calls", "buffer_bytes_per_hop"):
            registry.register_callback(
                f"{p}.{field}",
                lambda r=ref, f=field:
                    getattr(r(), f) if r() is not None else None)
        registry.register_callback(
            f"{p}.throughput_per_s",
            lambda r=ref:
                round(r().throughput, 3) if r() is not None else None)
        # per-hop bytes-on-wire: every ppermute hop of the homogeneous
        # buffer carries bytes_per_hop per step, so the counters are
        # derived at snapshot time — zero cost on the push hot path
        if self.buffer_bytes_per_hop and self.num_stages:
            for k in range(self.num_stages):
                registry.register_callback(
                    f"{p}.hop{k}.bytes",
                    lambda r=ref: r().steps * r().buffer_bytes_per_hop
                    if r() is not None else None)
        # weak: the histogram lives (and dies) with this deployment; the
        # registry prunes the entry once the deployment is collected
        registry.register(f"{p}.push_latency_s", self.push_latency,
                          weak=True)
        return p

    def record_stage_latency(self, stage: int, seconds: float) -> None:
        """Feed one per-stage latency sample (grows the histogram list on
        demand and keeps the legacy ``stage_latency_s`` means in sync)."""
        while len(self.stage_hists) <= stage:
            self.stage_hists.append(LatencyHistogram())
            if self.prefix is not None:
                getattr(self, "_registry", REGISTRY).register(
                    f"{self.prefix}.stage{len(self.stage_hists) - 1}"
                    f".latency_s", self.stage_hists[-1], weak=True)
        h = self.stage_hists[stage]
        h.record(seconds)
        while len(self.stage_latency_s) <= stage:
            self.stage_latency_s.append(0.0)
        self.stage_latency_s[stage] = h.mean

    # -- derived views -----------------------------------------------------

    @property
    def throughput(self) -> float:
        return self.inferences / self.wall_s if self.wall_s > 0 else 0.0

    @property
    def bubble_fraction(self) -> float:
        """Fraction of pipeline steps spent on fill/drain bubbles.

        Each emitted microbatch represents one fully-useful pipeline step
        (every stage worked on a real microbatch exactly once for it).
        """
        if self.steps == 0:
            return 0.0
        useful_steps = self.inferences / max(self.microbatch, 1)
        return max(0.0, 1.0 - useful_steps / self.steps)

    @property
    def duty_cycle(self) -> list[float]:
        """Per-stage busy fraction at steady state (stage latency / slowest
        stage).  The energy-analogue metric: the reference's headline -63%
        per-node energy (README.md:12) comes from each node idling between
        relays; duty cycle is the device-side measure of that idling."""
        if not self.stage_latency_s:
            return []
        slowest = max(self.stage_latency_s)
        if slowest <= 0:
            return [0.0] * len(self.stage_latency_s)
        return [l / slowest for l in self.stage_latency_s]

    @property
    def pipeline_efficiency(self) -> float:
        """Mean duty cycle — 1.0 means perfectly balanced stages."""
        d = self.duty_cycle
        return sum(d) / len(d) if d else 0.0

    def as_dict(self) -> dict:
        d = {
            "num_stages": self.num_stages,
            "microbatch": self.microbatch,
            "inferences": self.inferences,
            "steps": self.steps,
            "wall_s": round(self.wall_s, 6),
            "throughput_per_s": round(self.throughput, 3),
            "chunk_calls": self.chunk_calls,
            "stage_latency_ms": [round(s * 1e3, 4) for s in self.stage_latency_s],
            "buffer_bytes_per_hop": self.buffer_bytes_per_hop,
            "bubble_fraction": round(self.bubble_fraction, 4),
            "duty_cycle": [round(d, 4) for d in self.duty_cycle],
            "pipeline_efficiency": round(self.pipeline_efficiency, 4),
        }
        if self.push_latency.count:
            d["push_latency_ms"] = self.push_latency.summary(scale=1e3,
                                                             ndigits=4)
        if any(h.count for h in self.stage_hists):
            d["stage_latency_percentiles_ms"] = [
                h.summary(scale=1e3, ndigits=4) for h in self.stage_hists]
        return d


class StopwatchWindow:
    """Timed-window throughput counter reproducing the reference harness
    semantics (results drained in a window ÷ window seconds,
    test/test.py:25-37)."""

    def __init__(self, window_s: float):
        self.window_s = window_s
        self.count = 0
        self._t0 = time.perf_counter()

    def tick(self, n: int = 1) -> bool:
        """Record n results; returns False once the window has elapsed."""
        self.count += n
        return (time.perf_counter() - self._t0) < self.window_s

    @property
    def elapsed(self) -> float:
        return time.perf_counter() - self._t0

    @property
    def rate(self) -> float:
        e = self.elapsed
        return self.count / e if e > 0 else 0.0
