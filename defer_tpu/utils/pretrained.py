"""Pretrained-weight import: standard checkpoint layouts -> graph params.

The reference benchmarks a *trained* model — ``ResNet50(weights="imagenet")``
(reference test/test.py:13-14) — where Keras downloads and maps the
checkpoint for it.  Here the converter is explicit: it maps the de-facto
standard ResNet50 checkpoint layout (torchvision ``state_dict`` names, NCHW/
OIHW tensors) onto this framework's layer-graph param pytree (NHWC/HWIO),
with shape-exact validation and loud errors for anything missing.

Accepted containers for :func:`load_pretrained_resnet50`:

* ``.npz`` — numpy archive keyed either by torchvision names
  (``conv1.weight``, ``layer1.0.conv1.weight``, ...) or by this
  framework's flat ``node/leaf`` names (``conv2d/w``, ``batchnorm/scale``);
* ``.pt`` / ``.pth`` / ``.bin`` — a ``torch.save``d ``state_dict`` (CPU
  torch is in the image; loaded with ``weights_only=True``);
* ``.safetensors`` — if the optional ``safetensors`` package is present.

Tensor-layout transforms applied for torchvision sources:

* conv kernels  OIHW -> HWIO  (``transpose(2, 3, 1, 0)``)
* fc weight     [out, in] -> [in, out]
* batchnorm     weight/bias/running_mean/running_var ->
  scale/bias/mean/var (same eps, 1e-5, on both sides)
"""

from __future__ import annotations

import os
from typing import Any, Callable

import numpy as np

from ..graph.ir import LayerGraph

#: torchvision bn leaf -> our BatchNorm leaf
_BN_LEAVES = {
    "weight": "scale",
    "bias": "bias",
    "running_mean": "mean",
    "running_var": "var",
}


def _conv_t(a: np.ndarray) -> np.ndarray:
    return np.transpose(a, (2, 3, 1, 0))  # OIHW -> HWIO


def _fc_t(a: np.ndarray) -> np.ndarray:
    return np.transpose(a, (1, 0))  # [out, in] -> [in, out]


def _ident(a: np.ndarray) -> np.ndarray:
    return a


def resnet50_torch_mapping(depths=(3, 4, 6, 3)
                           ) -> dict[tuple[str, str],
                                     tuple[str, Callable[[np.ndarray],
                                                         np.ndarray]]]:
    """(our_node, our_leaf) -> (torchvision_key, layout transform).

    The graph builder numbers ``conv2d_k``/``batchnorm_k`` pairs globally in
    build order (models/resnet.py): stem first, then per bottleneck the
    projection shortcut (first block of a stage) *before* conv1..conv3 —
    whereas torchvision lists ``downsample`` last.  This mapping encodes
    that order difference once, structurally, instead of relying on
    enumeration order of either side.
    """
    m: dict[tuple[str, str], tuple[str, Callable]] = {}

    def pair(our_idx: int, conv_key: str, bn_key: str):
        conv = "conv2d" if our_idx == 0 else f"conv2d_{our_idx}"
        bn = "batchnorm" if our_idx == 0 else f"batchnorm_{our_idx}"
        m[(conv, "w")] = (f"{conv_key}.weight", _conv_t)
        for theirs, ours in _BN_LEAVES.items():
            m[(bn, ours)] = (f"{bn_key}.{theirs}", _ident)

    pair(0, "conv1", "bn1")
    idx = 1
    for s, blocks in enumerate(depths):
        for i in range(blocks):
            t = f"layer{s + 1}.{i}"
            branches = [(f"{t}.conv1", f"{t}.bn1"),
                        (f"{t}.conv2", f"{t}.bn2"),
                        (f"{t}.conv3", f"{t}.bn3")]
            if i == 0:  # builder emits the projection shortcut first
                branches.insert(0, (f"{t}.downsample.0", f"{t}.downsample.1"))
            for conv_key, bn_key in branches:
                pair(idx, conv_key, bn_key)
                idx += 1
    m[("predictions", "w")] = ("fc.weight", _fc_t)
    m[("predictions", "b")] = ("fc.bias", _ident)
    return m


def _read_state_dict(path: str) -> dict[str, np.ndarray]:
    ext = os.path.splitext(path)[1].lower()
    if ext == ".npz":
        with np.load(path) as z:
            return {k: np.asarray(z[k]) for k in z.files}
    if ext in (".pt", ".pth", ".bin"):
        import torch  # CPU torch is baked into the image
        sd = torch.load(path, map_location="cpu", weights_only=True)
        if hasattr(sd, "state_dict"):
            sd = sd.state_dict()
        return {k: np.asarray(v.detach().cpu().numpy())
                for k, v in sd.items()}
    if ext == ".safetensors":
        try:
            from safetensors.numpy import load_file
        except ImportError as e:
            raise ImportError(
                "safetensors is not available in this environment; "
                "convert the checkpoint to .npz or .pt") from e
        return load_file(path)
    raise ValueError(f"unsupported checkpoint extension {ext!r} "
                     f"(want .npz, .pt/.pth/.bin, or .safetensors)")


def convert_resnet50_state_dict(sd: dict[str, np.ndarray],
                                expected: dict[str, Any],
                                depths=(3, 4, 6, 3)) -> dict[str, Any]:
    """torchvision ``state_dict`` -> graph params, shape-checked leaf by leaf.

    ``expected`` is the pytree from ``graph.init`` — its shapes are the
    contract; any missing source key or post-transform shape mismatch
    raises with the full offending list (no silent partial loads).
    """
    mapping = resnet50_torch_mapping(depths)
    out: dict[str, Any] = {}
    missing, mismatched = [], []
    for (node, leaf), (src, tf) in mapping.items():
        want = np.shape(expected[node][leaf])
        if src not in sd:
            missing.append(src)
            continue
        arr = tf(np.asarray(sd[src]))
        if arr.shape != want:
            mismatched.append(f"{src} -> {node}/{leaf}: got {arr.shape}, "
                              f"want {want}")
            continue
        out.setdefault(node, {})[leaf] = arr.astype(np.float32)
    if missing or mismatched:
        raise ValueError(
            f"checkpoint does not match ResNet50: "
            f"{len(missing)} missing keys {missing[:5]}..., "
            f"{len(mismatched)} shape mismatches {mismatched[:5]}")
    # parameter-free nodes (activations, pools, adds) keep their (empty)
    # init entries so the pytree structure is exactly graph.init's
    for node, leaves in expected.items():
        if node not in out:
            out[node] = leaves
    return out


def load_pretrained_resnet50(path: str, graph: LayerGraph | None = None,
                             depths=(3, 4, 6, 3)) -> dict[str, Any]:
    """Load a ResNet50 checkpoint (any accepted container) as graph params.

    Returns a pytree structurally identical to ``graph.init(key)`` with
    every parametric leaf replaced by the checkpoint's (layout-transformed)
    tensor.  ``graph`` defaults to ``models.resnet50()``.
    """
    import jax

    if graph is None:
        from ..models import resnet50
        graph = resnet50()
    # shapes only — no need to materialize a random init just to validate
    expected = jax.eval_shape(lambda: graph.init(jax.random.key(0)))
    sd = _read_state_dict(path)
    if any(k.startswith("conv1.") for k in sd):  # torchvision layout
        return convert_resnet50_state_dict(sd, expected, depths)
    # our own flat node/leaf layout: checkpoint.load_params already
    # restores it with loud missing/extra/shape validation
    from .checkpoint import load_params
    return load_params(path, expected)
