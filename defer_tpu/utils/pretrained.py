"""Pretrained-weight import: standard checkpoint layouts -> graph params.

The reference benchmarks a *trained* model — ``ResNet50(weights="imagenet")``
(reference test/test.py:13-14) — where Keras downloads and maps the
checkpoint for it.  Here the converter is explicit: it maps the de-facto
standard ResNet50 checkpoint layout (torchvision ``state_dict`` names, NCHW/
OIHW tensors) onto this framework's layer-graph param pytree (NHWC/HWIO),
with shape-exact validation and loud errors for anything missing.

Accepted containers for :func:`load_pretrained_resnet50`:

* ``.npz`` — numpy archive keyed either by torchvision names
  (``conv1.weight``, ``layer1.0.conv1.weight``, ...) or by this
  framework's flat ``node/leaf`` names (``conv2d/w``, ``batchnorm/scale``);
* ``.pt`` / ``.pth`` / ``.bin`` — a ``torch.save``d ``state_dict`` (CPU
  torch is in the image; loaded with ``weights_only=True``);
* ``.safetensors`` — if the optional ``safetensors`` package is present.

Tensor-layout transforms applied for torchvision sources:

* conv kernels  OIHW -> HWIO  (``transpose(2, 3, 1, 0)``)
* fc weight     [out, in] -> [in, out]
* batchnorm     weight/bias/running_mean/running_var ->
  scale/bias/mean/var (same eps, 1e-5, on both sides)
"""

from __future__ import annotations

import os
from typing import Any, Callable

import numpy as np

from ..graph.ir import LayerGraph

#: torchvision bn leaf -> our BatchNorm leaf
_BN_LEAVES = {
    "weight": "scale",
    "bias": "bias",
    "running_mean": "mean",
    "running_var": "var",
}


def _conv_t(a: np.ndarray) -> np.ndarray:
    return np.transpose(a, (2, 3, 1, 0))  # OIHW -> HWIO


def _fc_t(a: np.ndarray) -> np.ndarray:
    return np.transpose(a, (1, 0))  # [out, in] -> [in, out]


def _ident(a: np.ndarray) -> np.ndarray:
    return a


def resnet50_torch_mapping(depths=(3, 4, 6, 3)
                           ) -> dict[tuple[str, str],
                                     tuple[str, Callable[[np.ndarray],
                                                         np.ndarray]]]:
    """(our_node, our_leaf) -> (torchvision_key, layout transform).

    The graph builder numbers ``conv2d_k``/``batchnorm_k`` pairs globally in
    build order (models/resnet.py): stem first, then per bottleneck the
    projection shortcut (first block of a stage) *before* conv1..conv3 —
    whereas torchvision lists ``downsample`` last.  This mapping encodes
    that order difference once, structurally, instead of relying on
    enumeration order of either side.
    """
    m: dict[tuple[str, str], tuple[str, Callable]] = {}

    def pair(our_idx: int, conv_key: str, bn_key: str):
        conv = "conv2d" if our_idx == 0 else f"conv2d_{our_idx}"
        bn = "batchnorm" if our_idx == 0 else f"batchnorm_{our_idx}"
        m[(conv, "w")] = (f"{conv_key}.weight", _conv_t)
        for theirs, ours in _BN_LEAVES.items():
            m[(bn, ours)] = (f"{bn_key}.{theirs}", _ident)

    pair(0, "conv1", "bn1")
    idx = 1
    for s, blocks in enumerate(depths):
        for i in range(blocks):
            t = f"layer{s + 1}.{i}"
            branches = [(f"{t}.conv1", f"{t}.bn1"),
                        (f"{t}.conv2", f"{t}.bn2"),
                        (f"{t}.conv3", f"{t}.bn3")]
            if i == 0:  # builder emits the projection shortcut first
                branches.insert(0, (f"{t}.downsample.0", f"{t}.downsample.1"))
            for conv_key, bn_key in branches:
                pair(idx, conv_key, bn_key)
                idx += 1
    m[("predictions", "w")] = ("fc.weight", _fc_t)
    m[("predictions", "b")] = ("fc.bias", _ident)
    return m


def _fc1_t(h: int, w: int, c: int) -> Callable[[np.ndarray], np.ndarray]:
    """First-FC transform for VGG: torch flattens NCHW ([C,H,W] order per
    sample), this framework flattens NHWC — the weight's input axis must be
    re-permuted, not just transposed."""
    def t(a: np.ndarray) -> np.ndarray:
        out = a.shape[0]
        return (a.reshape(out, c, h, w).transpose(0, 2, 3, 1)
                .reshape(out, -1).T)
    t.__name__ = "_fc1_t"
    return t


def vgg_torch_mapping(cfg, spatial_hwc: tuple[int, int, int]
                      ) -> dict[tuple[str, str], tuple[str, Callable]]:
    """(our_node, our_leaf) -> (torchvision key, transform) for a VGG built
    by ``models.vgg.vgg(cfg, ...)``.

    torchvision's ``features`` Sequential numbers conv/relu/maxpool slots
    consecutively; the builder names ``conv{block}_{i}``.  ``spatial_hwc``
    is the activation shape entering ``flatten`` (needed because torch
    flattens CHW, we flatten HWC — see ``_fc1_t``).
    """
    m: dict[tuple[str, str], tuple[str, Callable]] = {}
    feat_idx = 0
    block, conv_in_block = 1, 1
    for v in cfg:
        if v == "M":
            feat_idx += 1
            block += 1
            conv_in_block = 1
        else:
            node = f"conv{block}_{conv_in_block}"
            m[(node, "w")] = (f"features.{feat_idx}.weight", _conv_t)
            m[(node, "b")] = (f"features.{feat_idx}.bias", _ident)
            feat_idx += 2  # conv + its relu
            conv_in_block += 1
    h, w, c = spatial_hwc
    m[("fc1", "w")] = ("classifier.0.weight", _fc1_t(h, w, c))
    m[("fc1", "b")] = ("classifier.0.bias", _ident)
    m[("fc2", "w")] = ("classifier.3.weight", _fc_t)
    m[("fc2", "b")] = ("classifier.3.bias", _ident)
    m[("predictions", "w")] = ("classifier.6.weight", _fc_t)
    m[("predictions", "b")] = ("classifier.6.bias", _ident)
    return m


def mobilenet_v2_torch_mapping() -> dict[tuple[str, str],
                                         tuple[str, Callable]]:
    """(our_node, our_leaf) -> (torchvision key, transform) for
    ``models.mobilenet.mobilenet_v2``.

    Mirrors the builder's auto-naming counters (conv2d_k / batchnorm_k /
    depthwiseconv2d_k in build order) against torchvision's module tree:
    ``features.0`` ConvBNReLU stem, ``features.1..17`` InvertedResiduals
    (``.conv`` holds [expand ConvBNReLU,] depthwise ConvBNReLU, linear
    conv, bn), ``features.18`` ConvBNReLU head, ``classifier.1`` Linear.
    Depthwise kernels are OIHW ``[C,1,k,k]`` -> HWIO ``[k,k,1,C]`` via the
    same transpose as dense convs.
    """
    from ..models.mobilenet import _V2_CFG
    m: dict[tuple[str, str], tuple[str, Callable]] = {}
    counters = {"conv2d": 0, "batchnorm": 0, "depthwiseconv2d": 0}

    def nm(base: str) -> str:
        n = counters[base]
        counters[base] += 1
        return base if n == 0 else f"{base}_{n}"

    def conv(src: str):
        m[(nm("conv2d"), "w")] = (f"{src}.weight", _conv_t)

    def dwconv(src: str):
        m[(nm("depthwiseconv2d"), "w")] = (f"{src}.weight", _conv_t)

    def bn(src: str):
        node = nm("batchnorm")
        for theirs, ours in _BN_LEAVES.items():
            m[(node, ours)] = (f"{src}.{theirs}", _ident)

    conv("features.0.0")
    bn("features.0.1")
    f = 1
    for expand, _out, reps, _stride in _V2_CFG:
        for _ in range(reps):
            base = f"features.{f}.conv"
            f += 1
            if expand != 1:
                conv(f"{base}.0.0")
                bn(f"{base}.0.1")
                dwconv(f"{base}.1.0")
                bn(f"{base}.1.1")
                conv(f"{base}.2")
                bn(f"{base}.3")
            else:
                dwconv(f"{base}.0.0")
                bn(f"{base}.0.1")
                conv(f"{base}.1")
                bn(f"{base}.2")
    conv(f"features.{f}.0")
    bn(f"features.{f}.1")
    m[("predictions", "w")] = ("classifier.1.weight", _fc_t)
    m[("predictions", "b")] = ("classifier.1.bias", _ident)
    return m


#: torchvision InceptionV3 ``BasicConv2d`` module prefixes, in the exact
#: order ``models.inception.inception_v3`` adds its conv/bn pairs.  The
#: builder constructs branches in torch constructor order (branch1x1,
#: branch5x5/3x3/7x7 chains, branch_pool), so this is a straight walk of
#: the torchvision module tree.
_INCEPTION_A = ("branch1x1", "branch5x5_1", "branch5x5_2", "branch3x3dbl_1",
                "branch3x3dbl_2", "branch3x3dbl_3", "branch_pool")
_INCEPTION_B = ("branch3x3", "branch3x3dbl_1", "branch3x3dbl_2",
                "branch3x3dbl_3")
_INCEPTION_C = ("branch1x1", "branch7x7_1", "branch7x7_2", "branch7x7_3",
                "branch7x7dbl_1", "branch7x7dbl_2", "branch7x7dbl_3",
                "branch7x7dbl_4", "branch7x7dbl_5", "branch_pool")
_INCEPTION_D = ("branch3x3_1", "branch3x3_2", "branch7x7x3_1",
                "branch7x7x3_2", "branch7x7x3_3", "branch7x7x3_4")
_INCEPTION_E = ("branch1x1", "branch3x3_1", "branch3x3_2a", "branch3x3_2b",
                "branch3x3dbl_1", "branch3x3dbl_2", "branch3x3dbl_3a",
                "branch3x3dbl_3b", "branch_pool")


def inception_v3_conv_order() -> list[str]:
    """torchvision module prefixes of every BasicConv2d, forward order."""
    order = ["Conv2d_1a_3x3", "Conv2d_2a_3x3", "Conv2d_2b_3x3",
             "Conv2d_3b_1x1", "Conv2d_4a_3x3"]
    blocks = (
        [("Mixed_5b", _INCEPTION_A), ("Mixed_5c", _INCEPTION_A),
         ("Mixed_5d", _INCEPTION_A), ("Mixed_6a", _INCEPTION_B)]
        + [(f"Mixed_6{s}", _INCEPTION_C) for s in "bcde"]
        + [("Mixed_7a", _INCEPTION_D), ("Mixed_7b", _INCEPTION_E),
           ("Mixed_7c", _INCEPTION_E)])
    for block, branches in blocks:
        order.extend(f"{block}.{br}" for br in branches)
    return order


def inception_v3_torch_mapping() -> dict[tuple[str, str],
                                         tuple[str, Callable]]:
    """(our_node, our_leaf) -> (torchvision key, transform) for
    ``models.inception.inception_v3``.

    Same builder-order-counter scheme as the MobileNetV2 mapping: the
    k-th conv2d/batchnorm pair the builder creates corresponds to the
    k-th ``BasicConv2d`` in torchvision forward order
    (``inception_v3_conv_order``).  ``AuxLogits.*`` keys are ignored —
    the aux head does not exist in eval-mode inference.
    """
    m: dict[tuple[str, str], tuple[str, Callable]] = {}
    for i, prefix in enumerate(inception_v3_conv_order()):
        conv = "conv2d" if i == 0 else f"conv2d_{i}"
        bn = "batchnorm" if i == 0 else f"batchnorm_{i}"
        m[(conv, "w")] = (f"{prefix}.conv.weight", _conv_t)
        for theirs, ours in _BN_LEAVES.items():
            m[(bn, ours)] = (f"{prefix}.bn.{theirs}", _ident)
    m[("predictions", "w")] = ("fc.weight", _fc_t)
    m[("predictions", "b")] = ("fc.bias", _ident)
    return m


def _fuse_qkv(q: np.ndarray, k: np.ndarray, v: np.ndarray) -> np.ndarray:
    """HF's separate q/k/v ``[out, in]`` matrices -> one fused ``[in, 3d]``."""
    return np.concatenate([q.T, k.T, v.T], axis=1)


def _fuse_qkv_bias(q: np.ndarray, k: np.ndarray, v: np.ndarray) -> np.ndarray:
    return np.concatenate([q, k, v])


def _fold_pos_tt(max_len: int) -> Callable:
    """position_embeddings[:max_len] + token_type_embeddings[0]:
    single-segment inputs add the segment-0 vector at every position
    pre-LN, so it folds into the positional table exactly; the real
    checkpoint's 512-row table is cropped to the deployed sequence
    length (HF slices position_ids the same way)."""
    def t(pos: np.ndarray, tt: np.ndarray) -> np.ndarray:
        return pos[:max_len] + tt[0]
    t.__name__ = "_fold_pos_tt"
    return t


def bert_torch_mapping(num_layers: int, max_len: int = 512
                       ) -> dict[tuple[str, str], tuple[Any, Callable]]:
    """(our_node, our_leaf_path) -> (HF state_dict key(s), transform) for
    ``models.bert.bert`` (post-LN blocks, fused qkv).

    HF prefix conventions: plain ``bert-base-uncased`` state_dicts carry
    ``bert.``-prefixed keys when saved from a task model; strip that
    before calling (see ``load_pretrained_bert_base``).
    """
    m: dict[tuple[str, str], tuple[Any, Callable]] = {}
    e = "embeddings"
    m[(e, "tok")] = (f"{e}.word_embeddings.weight", _ident)
    m[(e, "pos")] = ((f"{e}.position_embeddings.weight",
                      f"{e}.token_type_embeddings.weight"),
                     _fold_pos_tt(max_len))
    m[(e, "ln/scale")] = (f"{e}.LayerNorm.weight", _ident)
    m[(e, "ln/bias")] = (f"{e}.LayerNorm.bias", _ident)
    for i in range(num_layers):
        b = f"encoder.layer.{i}"
        node = f"block_{i}"
        a = f"{b}.attention"
        m[(node, "qkv/w")] = ((f"{a}.self.query.weight",
                               f"{a}.self.key.weight",
                               f"{a}.self.value.weight"), _fuse_qkv)
        m[(node, "qkv/b")] = ((f"{a}.self.query.bias",
                               f"{a}.self.key.bias",
                               f"{a}.self.value.bias"), _fuse_qkv_bias)
        m[(node, "proj/w")] = (f"{a}.output.dense.weight", _fc_t)
        m[(node, "proj/b")] = (f"{a}.output.dense.bias", _ident)
        m[(node, "ln1/scale")] = (f"{a}.output.LayerNorm.weight", _ident)
        m[(node, "ln1/bias")] = (f"{a}.output.LayerNorm.bias", _ident)
        m[(node, "fc1/w")] = (f"{b}.intermediate.dense.weight", _fc_t)
        m[(node, "fc1/b")] = (f"{b}.intermediate.dense.bias", _ident)
        m[(node, "fc2/w")] = (f"{b}.output.dense.weight", _fc_t)
        m[(node, "fc2/b")] = (f"{b}.output.dense.bias", _ident)
        m[(node, "ln2/scale")] = (f"{b}.output.LayerNorm.weight", _ident)
        m[(node, "ln2/bias")] = (f"{b}.output.LayerNorm.bias", _ident)
    m[("pooler", "w")] = ("pooler.dense.weight", _fc_t)
    m[("pooler", "b")] = ("pooler.dense.bias", _ident)
    return m


def load_pretrained_bert_base(path: str, graph: LayerGraph | None = None
                              ) -> dict[str, Any]:
    """Load an HF-layout BERT checkpoint (or our flat layout) as params."""
    if graph is None:
        from ..models import bert_base
        graph = bert_base()
    expected = _expected_shapes(graph)
    sd = _read_state_dict(path)
    # task-model saves prefix everything with "bert." — strip it
    if any(k.startswith("bert.") for k in sd):
        sd = {k[len("bert."):]: v for k, v in sd.items()
              if k.startswith("bert.")}
    if any(k.startswith("encoder.layer.") for k in sd):  # HF layout
        n_layers = sum(1 for n in graph.nodes if n.startswith("block_"))
        max_len = graph.input_spec.shape[0]
        return convert_state_dict(bert_torch_mapping(n_layers, max_len),
                                  sd, expected, "BERT")
    from .checkpoint import load_params
    return load_params(path, expected)


def _read_state_dict(path: str) -> dict[str, np.ndarray]:
    ext = os.path.splitext(path)[1].lower()
    if ext == ".npz":
        with np.load(path) as z:
            return {k: np.asarray(z[k]) for k in z.files}
    if ext in (".pt", ".pth", ".bin"):
        import torch  # CPU torch is baked into the image
        sd = torch.load(path, map_location="cpu", weights_only=True)
        if hasattr(sd, "state_dict"):
            sd = sd.state_dict()
        return {k: np.asarray(v.detach().cpu().numpy())
                for k, v in sd.items()}
    if ext == ".safetensors":
        try:
            from safetensors.numpy import load_file
        except ImportError as e:
            raise ImportError(
                "safetensors is not available in this environment; "
                "convert the checkpoint to .npz or .pt") from e
        return load_file(path)
    raise ValueError(f"unsupported checkpoint extension {ext!r} "
                     f"(want .npz, .pt/.pth/.bin, or .safetensors)")


def convert_state_dict(
    mapping: dict[tuple[str, str], tuple["str | tuple[str, ...]", Callable]],
    sd: dict[str, np.ndarray],
    expected: dict[str, Any],
    what: str,
) -> dict[str, Any]:
    """Apply a (our_node, our_leaf_path) -> (source_key(s), transform)
    mapping, shape-checked leaf by leaf.  ``source_key(s)`` may be a
    tuple — the transform then fuses several source arrays into one leaf
    (HF BERT's q/k/v -> fused qkv, segment fold).

    ``expected`` is the pytree from ``graph.init`` — its shapes are the
    contract; any missing source key or post-transform shape mismatch
    raises with the full offending list (no silent partial loads).
    """
    out: dict[str, Any] = {}
    missing, mismatched = [], []
    for (node, leaf), (src, tf) in mapping.items():
        # leaf may be a "/"-joined path into a nested node pytree, and
        # src may be a tuple of source keys fused by the transform
        # (e.g. HF BERT's separate q/k/v -> one fused qkv matrix)
        srcs = src if isinstance(src, tuple) else (src,)
        absent = [k for k in srcs if k not in sd]
        if absent:
            missing.extend(absent)
            continue
        path = leaf.split("/")
        want_leaf = expected[node]
        for part in path:
            want_leaf = want_leaf[part]
        want = np.shape(want_leaf)
        arr = tf(*(np.asarray(sd[k]) for k in srcs))
        if arr.shape != want:
            mismatched.append(f"{src} -> {node}/{leaf}: got {arr.shape}, "
                              f"want {want}")
            continue
        dst = out.setdefault(node, {})
        for part in path[:-1]:
            dst = dst.setdefault(part, {})
        dst[path[-1]] = arr.astype(np.float32)
    if missing or mismatched:
        raise ValueError(
            f"checkpoint does not match {what}: "
            f"{len(missing)} missing keys {missing[:5]}..., "
            f"{len(mismatched)} shape mismatches {mismatched[:5]}")
    # parameter-free nodes (activations, pools, adds) keep their (empty)
    # init entries so the pytree structure is exactly graph.init's
    for node, leaves in expected.items():
        if node not in out:
            out[node] = leaves
    return out


def convert_resnet50_state_dict(sd: dict[str, np.ndarray],
                                expected: dict[str, Any],
                                depths=(3, 4, 6, 3)) -> dict[str, Any]:
    """torchvision ResNet ``state_dict`` -> graph params (shape-checked)."""
    return convert_state_dict(resnet50_torch_mapping(depths), sd, expected,
                              "ResNet50")


def load_pretrained_resnet50(path: str, graph: LayerGraph | None = None,
                             depths=(3, 4, 6, 3)) -> dict[str, Any]:
    """Load a ResNet50 checkpoint (any accepted container) as graph params.

    Returns a pytree structurally identical to ``graph.init(key)`` with
    every parametric leaf replaced by the checkpoint's (layout-transformed)
    tensor.  ``graph`` defaults to ``models.resnet50()``.
    """
    import jax

    if graph is None:
        from ..models import resnet50
        graph = resnet50()
    # shapes only — no need to materialize a random init just to validate
    expected = jax.eval_shape(lambda: graph.init(jax.random.key(0)))
    sd = _read_state_dict(path)
    if any(k.startswith("conv1.") for k in sd):  # torchvision layout
        return convert_resnet50_state_dict(sd, expected, depths)
    # our own flat node/leaf layout: checkpoint.load_params already
    # restores it with loud missing/extra/shape validation
    from .checkpoint import load_params
    return load_params(path, expected)


def _expected_shapes(graph: LayerGraph):
    import jax
    return jax.eval_shape(lambda: graph.init(jax.random.key(0)))


def load_pretrained_vgg19(path: str,
                          graph: LayerGraph | None = None) -> dict[str, Any]:
    """Load a VGG19 checkpoint (torchvision layout or our flat layout)."""
    if graph is None:
        from ..models import vgg19
        graph = vgg19()
    expected = _expected_shapes(graph)
    sd = _read_state_dict(path)
    if any(k.startswith("features.") for k in sd):  # torchvision layout
        from ..models.vgg import VGG19_CFG
        pre_flatten = graph.nodes["flatten"].inputs[0]
        spatial = graph.out_spec(pre_flatten).shape
        return convert_state_dict(vgg_torch_mapping(VGG19_CFG, spatial),
                                  sd, expected, "VGG19")
    from .checkpoint import load_params
    return load_params(path, expected)


def load_pretrained_mobilenet_v2(path: str, graph: LayerGraph | None = None
                                 ) -> dict[str, Any]:
    """Load a MobileNetV2 checkpoint (torchvision or our flat layout)."""
    if graph is None:
        from ..models import mobilenet_v2
        graph = mobilenet_v2()
    expected = _expected_shapes(graph)
    sd = _read_state_dict(path)
    if any(k.startswith("features.") for k in sd):  # torchvision layout
        return convert_state_dict(mobilenet_v2_torch_mapping(), sd,
                                  expected, "MobileNetV2")
    from .checkpoint import load_params
    return load_params(path, expected)


def _crop_rows(n: int) -> Callable[[np.ndarray], np.ndarray]:
    def t(a: np.ndarray) -> np.ndarray:
        return a[:n]
    t.__name__ = "_crop_rows"
    return t


def gpt2_torch_mapping(num_layers: int, max_len: int
                       ) -> dict[tuple[str, str], tuple[str, Callable]]:
    """(our_node, our_leaf) -> (HF GPT-2 key, transform) for
    ``models.gpt.gpt``-family graphs (``gpt2_small`` for checkpoints).

    HF GPT-2 uses Conv1D modules whose weights are stored ``[in, out]``
    — exactly this framework's layout — so every projection maps with
    ``_ident`` (no transposes, unlike the torchvision CNN imports).  The
    fused ``attn.c_attn`` packs q|k|v along columns in the same order as
    our fused qkv split.  The LM head is weight-tied to ``wte`` in HF
    (logits = x @ wte.T): our untied ``lm_head`` imports ``wte.T`` with
    a zero bias.  The positional table is cropped to the graph's
    ``seq_len`` (HF ships 1024 rows).
    """
    m: dict[tuple[str, str], tuple[str, Callable]] = {
        ("embeddings", "wte"): ("wte.weight", _ident),
        ("embeddings", "wpe"): ("wpe.weight", _crop_rows(max_len)),
        ("final_ln", "scale"): ("ln_f.weight", _ident),
        ("final_ln", "bias"): ("ln_f.bias", _ident),
        ("lm_head", "w"): ("wte.weight", _fc_t),  # tied head: wte.T
        ("lm_head", "b"): ("wte.weight", _zero_rows),
    }
    for i in range(num_layers):
        h = f"h.{i}"
        blk = f"block_{i}"
        for ours, theirs in (("ln1", "ln_1"), ("ln2", "ln_2")):
            m[(blk, f"{ours}/scale")] = (f"{h}.{theirs}.weight", _ident)
            m[(blk, f"{ours}/bias")] = (f"{h}.{theirs}.bias", _ident)
        for ours, theirs in (("qkv", "attn.c_attn"), ("proj", "attn.c_proj"),
                             ("fc1", "mlp.c_fc"), ("fc2", "mlp.c_proj")):
            m[(blk, f"{ours}/w")] = (f"{h}.{theirs}.weight", _ident)
            m[(blk, f"{ours}/b")] = (f"{h}.{theirs}.bias", _ident)
    return m


def _zero_rows(a: np.ndarray) -> np.ndarray:
    """Zero bias sized by the source's leading dim (tied-head import)."""
    return np.zeros((a.shape[0],), np.float32)


def load_pretrained_gpt2(path: str, graph: LayerGraph | None = None
                         ) -> dict[str, Any]:
    """Load an HF GPT-2 checkpoint (``GPT2Model``/``GPT2LMHeadModel``
    state_dict, optionally ``transformer.``-prefixed) or our flat layout.

    No reference analogue (the reference is CNN-only); this extends the
    trained-deployment story (reference test/test.py:13-14) to the
    generation family: imported weights drive ``PipelinedDecoder`` /
    ``Defer.generate`` directly.
    """
    if graph is None:
        from ..models import gpt2_small
        graph = gpt2_small()
    expected = _expected_shapes(graph)
    sd = _read_state_dict(path)
    sd = {(k[len("transformer."):] if k.startswith("transformer.") else k): v
          for k, v in sd.items()}
    if any(k.startswith("h.0.") or k == "wte.weight" for k in sd):
        layers = sum(1 for node in expected if node.startswith("block_"))
        max_len = graph.input_spec.shape[0]
        return convert_state_dict(gpt2_torch_mapping(layers, max_len), sd,
                                  expected, "GPT-2")
    from .checkpoint import load_params
    return load_params(path, expected)


def load_pretrained_inception_v3(path: str, graph: LayerGraph | None = None
                                 ) -> dict[str, Any]:
    """Load an InceptionV3 checkpoint (torchvision or our flat layout).

    Reference parity: the reference benchmarks trained Keras models
    (reference test/test.py:13-14); InceptionV3 is BASELINE config 3.
    Inputs must be TF-style normalized (``(x-0.5)/0.5``) — torchvision's
    ``transform_input=True`` re-normalization is preprocessing, not part
    of the graph.
    """
    if graph is None:
        from ..models import inception_v3
        graph = inception_v3()
    expected = _expected_shapes(graph)
    sd = _read_state_dict(path)
    if any(k.startswith(("Conv2d_1a", "Mixed_")) for k in sd):
        return convert_state_dict(inception_v3_torch_mapping(), sd,
                                  expected, "InceptionV3")
    from .checkpoint import load_params
    return load_params(path, expected)


#: model-family name -> loader, for generic call sites (bench/CLI)
PRETRAINED_LOADERS: dict[str, Callable] = {
    "resnet50": load_pretrained_resnet50,
    "vgg19": load_pretrained_vgg19,
    "mobilenet_v2": load_pretrained_mobilenet_v2,
    "bert_base": load_pretrained_bert_base,
    "inception_v3": load_pretrained_inception_v3,
    "gpt2": load_pretrained_gpt2,
}


def load_pretrained(model: str, path: str,
                    graph: LayerGraph | None = None) -> dict[str, Any]:
    """Generic front door: ``load_pretrained("vgg19", path, graph)``."""
    if model not in PRETRAINED_LOADERS:
        raise ValueError(f"no pretrained loader for {model!r} "
                         f"(have {sorted(PRETRAINED_LOADERS)})")
    return PRETRAINED_LOADERS[model](path, graph)
