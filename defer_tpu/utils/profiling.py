"""Tracing / profiling hooks.

The reference's observability is throughput arithmetic and ad-hoc prints
(reference test/test.py:35-36, src/node.py:23); here profiling is a
first-class wrapper over ``jax.profiler`` plus a structured pipeline
breakdown that pairs with ``PipelineMetrics``.
"""

from __future__ import annotations

import contextlib
import time
from functools import partial
from typing import Any

import jax
import numpy as np


def timed_window(fn, *, min_iters=8, min_s=3.0, max_iters=512):
    """Warm call, then measure average seconds/iter over a timed window
    (the reference harness's measurement discipline, test/test.py:25-37)."""
    fn()  # warmup / compile
    t0 = time.perf_counter()
    n = 0
    while True:
        fn()
        n += 1
        dt = time.perf_counter() - t0
        if (n >= min_iters and dt >= min_s) or n >= max_iters:
            return dt / n


def amortized_forward_seconds(apply_fn, params, x0, k: int, *,
                              min_iters: int = 3, min_s: float = 2.0,
                              max_iters: int = 64) -> float:
    """Per-forward seconds with ``k`` forwards fused in ONE dispatch.

    On a chip behind a high-RTT link (the axon tunnel: ~76 ms/sync,
    PROFILE_r04.md) per-step dispatch+sync measures the link, not the
    chip; fusing K forwards into one on-device ``lax.scan`` amortizes the
    round trip away.  The per-step input perturbation ``x0 + t`` keeps
    every iteration's forward live — an invariant body would let XLA
    hoist the network out of the loop entirely and fake the number.
    """
    from jax import lax
    import jax.numpy as jnp

    from .xla_opts import jit_kwargs

    @partial(jax.jit, **jit_kwargs())
    def scan_fwd(p, x0, ts):
        def body(c, t):
            y = apply_fn(p, x0 + t)
            return c + y.astype(jnp.float32).sum(), None

        s, _ = lax.scan(body, jnp.float32(0), ts)
        return s

    if jnp.issubdtype(jnp.asarray(x0).dtype, jnp.integer):
        # integer inputs (token ids): alternate +0/+1 so ids stay valid
        # while the forward still depends on the step
        ts = (jnp.arange(k) % 2).astype(x0.dtype)
    else:
        ts = jnp.linspace(0, 1e-6, k).astype(x0.dtype)
    sec = timed_window(
        lambda: jax.block_until_ready(scan_fwd(params, x0, ts)),
        min_iters=min_iters, min_s=min_s, max_iters=max_iters)
    return sec / k


def pipeline_window_seconds(pipe, inputs, *, inflight: int = 2,
                            min_s: float = 2.5, max_chunks: int = 64):
    """Steady-state seconds per chunk with ``inflight`` chunk dispatches
    kept in flight (no per-chunk sync) and each completed chunk's result
    slab drained to the host.

    ``inputs`` must be a device block from ``pipe.stage_inputs`` — it is
    re-fed every chunk (the reference harness also re-feeds one image,
    test/test.py:20-23).  Warm-compiles with a bubble pass of the same
    resident block, so no extra chunk-sized buffer is staged."""
    import collections
    import math

    def run_window(m):
        pending = collections.deque()
        t0 = time.perf_counter()
        for _ in range(m):
            slab, _mask = pipe.push(inputs, raw=True)
            if slab is not None:
                pending.append(slab)
            while len(pending) > inflight:
                np.asarray(pending.popleft())
        while pending:
            np.asarray(pending.popleft())
        return time.perf_counter() - t0

    pipe.reset()
    slab, _ = pipe.push(inputs, n_real=0, raw=True)  # compile pass
    if slab is not None:
        np.asarray(slab)
    pipe.reset()
    run_window(2)  # post-compile warm pass
    t1 = max(run_window(1), 1e-4)
    m = max(2, min(max_chunks, math.ceil(min_s / t1)))
    # bill only the measured window to the deployment's metrics — the
    # compile/warm/calibration pushes above are harness artifacts that
    # would otherwise dominate bubble_fraction / throughput_per_s
    pipe.metrics.clear_counters()
    return run_window(m) / m


def measured_node_costs(graph, params, *, batch: int = 1,
                        compute_dtype=None, k: int = 32,
                        reps: int = 3) -> dict[str, float]:
    """Per-node measured seconds for every node of ``graph`` — the
    empirical cost map for latency-balanced partitioning
    (``graph.analysis.auto_cut_points(g, n, costs=...)``).

    Each op runs ``k`` iterations fused in ONE ``lax.scan`` dispatch
    (min over ``reps`` rounds, divided by ``k``) — per-call dispatch+sync
    timing would put the SAME floor under every node (tens of µs on a
    local backend, ~64 ms/sync through the axon tunnel once a large
    program has run), flattening the relative weights toward uniform and
    silently defeating the balancing.  Standalone per-op timing still
    ignores cross-op XLA fusion, so ABSOLUTE numbers overstate a fused
    stage; partitioning only needs the RELATIVE weights, where
    measurement beats the FLOP model for bandwidth-bound ops (pools,
    norms, elementwise) that the analytic model scores near zero.
    """
    import time as _time

    import jax
    import jax.numpy as jnp
    from jax import lax

    costs: dict[str, float] = {}
    for name in graph.topo_order:
        node = graph.nodes[name]
        in_specs = [graph.out_spec(i) for i in node.inputs]
        xs = []
        for s in in_specs:
            dt = s.dtype
            if compute_dtype is not None and jnp.issubdtype(
                    dt, jnp.floating):
                dt = compute_dtype
            xs.append(jnp.zeros((batch,) + s.shape, dt))
        p = params.get(name)
        if compute_dtype is not None and p is not None:
            p = jax.tree.map(
                lambda a: a.astype(compute_dtype)
                if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
                else a, p)

        def scan_op(pp, xx, ts, _op=node.op):
            # perturb the first input per step so the op stays live in
            # the loop (an invariant body would be hoisted out entirely)
            def body(c, t):
                if jnp.issubdtype(xx[0].dtype, jnp.floating):
                    x0 = xx[0] + (t * 1e-7).astype(xx[0].dtype)
                else:  # int ids: alternate +0/+1, stays a valid index set
                    x0 = xx[0] + (t.astype(jnp.int32) % 2).astype(
                        xx[0].dtype)
                y = _op.apply(pp, x0, *xx[1:])
                return c + y.astype(jnp.float32).sum(), None

            s, _ = lax.scan(body, jnp.float32(0), ts)
            return s

        fn = jax.jit(scan_op)
        ts = jnp.arange(k, dtype=jnp.float32)
        jax.block_until_ready(fn(p, xs, ts))  # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = _time.perf_counter()
            jax.block_until_ready(fn(p, xs, ts))
            best = min(best, _time.perf_counter() - t0)
        costs[name] = best / k
    return costs


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture an XLA/TPU profiler trace (view with tensorboard/xprof)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def profile_pipeline(pipe, params: dict[str, Any], *, iters: int = 20,
                     warmup: int = 2) -> dict:
    """Structured breakdown of a pipeline deployment.

    Returns per-stage compute latency, the steady-state step time of the
    fused pipeline program, the implied stage-imbalance factor (max stage /
    mean stage — the pipeline's efficiency ceiling), and transfer-buffer
    footprint.
    """
    lat = pipe.stage_latencies(params, iters=iters)
    inputs = np.zeros((pipe.chunk, pipe.microbatch) + pipe.in_spec.shape,
                      np.float32)
    pipe.reset()
    for _ in range(warmup):
        pipe.push(inputs, n_real=0)
    jax.block_until_ready(pipe._a)  # don't bill queued warmup work to t0
    t0 = time.perf_counter()
    pipe.push(inputs, n_real=0)
    jax.block_until_ready(pipe._a)
    step_s = (time.perf_counter() - t0) / pipe.chunk
    mean_lat = sum(lat) / len(lat)
    return {
        "num_stages": pipe.num_stages,
        "stage_latency_ms": [round(s * 1e3, 4) for s in lat],
        "stage_imbalance": round(max(lat) / mean_lat, 3) if mean_lat else 0.0,
        "pipeline_step_ms": round(step_s * 1e3, 4),
        "step_overhead_vs_max_stage": round(step_s / max(lat), 3)
        if max(lat) > 0 else 0.0,
        "buffer_bytes_per_hop": pipe.metrics.buffer_bytes_per_hop,
        "steady_state_throughput_per_s": round(
            pipe.microbatch / step_s, 2) if step_s else 0.0,
    }
