"""Env-driven XLA compiler options for the jit sites that matter.

This environment's TPU is compiled through a remote relay: TPU-only
``XLA_FLAGS`` die in the LOCAL client's flag parser before ever reaching
the remote compiler (measured — XLA_SWEEP_r05.json round 1), but
per-executable ``compiler_options`` ARE forwarded (probed: vmem limit,
latency-hiding scheduler, async collective-permute all compile).  So
flag experiments ride ``DEFER_XLA_COMPILER_OPTS`` instead:

    DEFER_XLA_COMPILER_OPTS="xla_tpu_scoped_vmem_limit_kib=65536 \
        xla_tpu_enable_latency_hiding_scheduler=true" python bench.py

Space- or comma-separated ``key=value`` pairs; applied by the hot jit
sites (SpmdPipeline's stage program, bench's baseline forwards).  Unset
means exactly the default compile — the helper returns ``{}`` so call
sites can splat it unconditionally.
"""

from __future__ import annotations

import os


def compiler_options() -> dict[str, str]:
    """Parsed ``DEFER_XLA_COMPILER_OPTS`` (empty dict when unset)."""
    raw = os.environ.get("DEFER_XLA_COMPILER_OPTS", "").replace(",", " ")
    out: dict[str, str] = {}
    for tok in raw.split():
        if "=" not in tok:
            raise ValueError(
                f"DEFER_XLA_COMPILER_OPTS entry {tok!r} is not key=value")
        k, v = tok.split("=", 1)
        out[k] = v
    return out


def jit_kwargs() -> dict:
    """``{"compiler_options": {...}}`` or ``{}`` — splat into jax.jit."""
    opts = compiler_options()
    return {"compiler_options": opts} if opts else {}


#: measured on the r5 flag sweep (XLA_SWEEP_r05.json): making the
#: stage->stage collective_permute asynchronous lifted the pipeline +53%
#: in-window (6,917 -> 10,551 img/s, pipeline MFU 0.288 -> 0.439) by
#: overlapping the ring hop with stage compute
RING_DEFAULTS = {"xla_enable_async_collective_permute": "true"}


def ring_jit_kwargs(devices) -> dict:
    """jit kwargs for ring (ppermute) programs: the measured-good TPU
    defaults, overridable key-by-key via ``DEFER_XLA_COMPILER_OPTS``
    (e.g. ``xla_enable_async_collective_permute=false`` restores the
    pre-default behavior — the flag sweep's control row does exactly
    that).  CPU/virtual meshes get only the explicit env options, never
    the TPU ring defaults (the CPU client rejects TPU-only flags).
    """
    first = devices.flat[0] if hasattr(devices, "flat") else devices[0]
    if getattr(first, "platform", "cpu") == "cpu":
        return jit_kwargs()
    return {"compiler_options": {**RING_DEFAULTS, **compiler_options()}}
