"""Env-driven XLA compiler options for the jit sites that matter.

This environment's TPU is compiled through a remote relay: TPU-only
``XLA_FLAGS`` die in the LOCAL client's flag parser before ever reaching
the remote compiler (measured — XLA_SWEEP_r05.json round 1), but
per-executable ``compiler_options`` ARE forwarded (probed: vmem limit,
latency-hiding scheduler, async collective-permute all compile).  So
flag experiments ride ``DEFER_XLA_COMPILER_OPTS`` instead:

    DEFER_XLA_COMPILER_OPTS="xla_tpu_scoped_vmem_limit_kib=65536 \
        xla_tpu_enable_latency_hiding_scheduler=true" python bench.py

Space- or comma-separated ``key=value`` pairs; applied by the hot jit
sites (SpmdPipeline's stage program, bench's baseline forwards).  Unset
means exactly the default compile — the helper returns ``{}`` so call
sites can splat it unconditionally.
"""

from __future__ import annotations

import os


def compiler_options() -> dict[str, str]:
    """Parsed ``DEFER_XLA_COMPILER_OPTS`` (empty dict when unset)."""
    raw = os.environ.get("DEFER_XLA_COMPILER_OPTS", "").replace(",", " ")
    out: dict[str, str] = {}
    for tok in raw.split():
        if "=" not in tok:
            raise ValueError(
                f"DEFER_XLA_COMPILER_OPTS entry {tok!r} is not key=value")
        k, v = tok.split("=", 1)
        out[k] = v
    return out


def jit_kwargs() -> dict:
    """``{"compiler_options": {...}}`` or ``{}`` — splat into jax.jit."""
    opts = compiler_options()
    return {"compiler_options": opts} if opts else {}
