"""Throughput/ratio benchmark for the first-party host-edge codecs.

The reference compresses every wire payload with ZFP-then-LZ4
(reference src/dispatcher.py:81-84); this framework's analogues are the
native blockfloat ``BFC1`` (lossy float codec) and ``LZB1`` (LZ77 byte
codec) from ``_native/codec.cpp``, layered as ``PipelineCodec`` the
same way.  This measures what the reference never did: encode/decode
MB/s and compression ratio per codec, on realistic payloads: the REAL
wire payload at a ResNet50 cut point (the pre-activation residual add
— dense, which is why the float-domain blockfloat, not byte-domain
LZ77, is the lever there — exactly the regime the reference shipped
through ZFP) and the post-ReLU activation (sparse, the LZ-favorable
case).

One JSON line on stdout; CPU-only (the host edge is where these run).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    from defer_tpu.codec import (BlockFloatCodec, LosslessCodec,
                                 PipelineCodec, RawCodec, native_available)
    from defer_tpu.models import resnet50

    # realistic payload: a mid-network ReLU activation (sparse, smooth
    # block statistics — what blockfloat/LZ77 actually see in service)
    g = resnet50()
    params = g.init(jax.random.key(0))
    x = np.asarray(jax.random.normal(jax.random.key(1), (2, 224, 224, 3)),
                   np.float32)

    def run_to(node):
        vals = {g.input_name: x}
        for nm in g.topo_order:
            nd = g.nodes[nm]
            vals[nm] = nd.op.apply(params.get(nm, {}),
                                   *(vals[i] for i in nd.inputs))
            if nm == node:
                return np.asarray(vals[nm], np.float32)
    add_out = run_to("add_2")
    relu_name = next(nm for nm, nd in g.nodes.items()
                     if "add_2" in nd.inputs
                     and type(nd.op).__name__ == "Activation")
    relu_out = run_to(relu_name)

    out = {"metric": "host_codec_throughput",
           "native_available": native_available(), "payloads": {}}
    codecs = [RawCodec(), BlockFloatCodec(bits=8), BlockFloatCodec(bits=12),
              LosslessCodec(),
              PipelineCodec(bits=12)]  # BFC1-in-LZB1, the ZFP+LZ4 shape

    for pname, payload in (("cut_point_add", add_out),
                           ("post_relu", relu_out)):
        nbytes = payload.nbytes
        rows = {}
        out["payloads"][pname] = {
            "shape": list(payload.shape), "mb": round(nbytes / 1e6, 3),
            "zero_fraction": round(float((payload == 0).mean()), 4),
            "rows": rows}
        print(f"--- {pname} ({nbytes / 1e6:.1f} MB, "
              f"{float((payload == 0).mean()):.0%} zeros)",
              file=sys.stderr, flush=True)
        _bench_codecs(codecs, payload, rows)
    print(json.dumps(out))


def _bench_codecs(codecs, payload, rows):
    # timing core shared with the planner's codec calibration
    # (defer_tpu.plan.cost.calibrate_codecs uses the same loop)
    from defer_tpu.plan.cost import bench_codec_instance

    nbytes = payload.nbytes
    for c in codecs:
        name = c.name + (f"{c.bits}" if hasattr(c, "bits") else "")
        reps = max(3, int(50e6 // max(nbytes, 1)))
        ratio, enc_bps, dec_bps = bench_codec_instance(c, payload,
                                                       reps=reps)
        enc = c.encode(payload)
        dec = c.decode(enc, payload.shape, payload.dtype)
        err = float(np.max(np.abs(dec.astype(np.float64)
                                  - payload.astype(np.float64))))
        scale = float(np.max(np.abs(payload))) or 1.0
        rows[name] = {
            "ratio": round(ratio, 3),
            "encode_mb_s": round(enc_bps / 1e6, 1),
            "decode_mb_s": round(dec_bps / 1e6, 1),
            "max_rel_err": round(err / scale, 6),
        }
        print(f"{name:16s} ratio {ratio:6.2f}x  "
              f"enc {enc_bps / 1e6:8.1f} MB/s  "
              f"dec {dec_bps / 1e6:8.1f} MB/s  "
              f"rel err {err / scale:.2e}", file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
