"""TPU benchmark for the pipelined KV-cache decoder (DECODE_r04.json).

Measures greedy autoregressive generation throughput of the GPT-2-small
geometry (12 layers, d=768, 50257 vocab) on the available chip(s):
tokens/sec across a microbatch sweep, plus an approximate model-FLOPs
utilisation from the per-token cost model

    flops/token ~= L * (24 d^2 + 4 pos_avg d) + 2 d V

(qkv+proj+mlp matmuls per layer, attention against the growing cache,
lm_head).  The whole generation runs as ONE scan dispatch per
``token_chunk`` tokens, so the tunnel's ~64 ms/sync (PROFILE_r04.md) is
paid once per chunk, not per token.

Prints one JSON dict on stdout.  If ``DEFER_DECODE_OUT`` is set, the
(partial) artifact is also rewritten after EVERY row — a wall-clock
timeout then costs the remaining rows, not the whole run (the r4/r5
lesson: the 30-row sweep once timed out at row 26 and left nothing).
``DEFER_DECODE_ROWS`` (comma-separated substrings) restricts the sweep
to matching row tags, e.g. ``DEFER_DECODE_ROWS=w8,mb64`` for a re-run
of just the missing rows.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from defer_tpu.models import gpt
    from defer_tpu.runtime.decode import PipelinedDecoder
    from defer_tpu.utils.hw import identify_chip, peak_flops

    devices = jax.devices()
    on_tpu = devices[0].platform != "cpu"
    out = {
        "metric": "gpt_small_pipelined_decode",
        "platform": devices[0].platform,
        "device_kind": str(getattr(devices[0], "device_kind", "")),
    }
    if on_tpu:
        layers, d, heads, vocab = 12, 768, 12, 50257
        max_len, plen, new = 512, 32, 128
        mbs = (8, 32, 64)
        cd = jnp.bfloat16
        gen = identify_chip(devices[0])
        peak = peak_flops(gen)
        out["tpu_generation"] = gen
    else:  # CPU smoke
        layers, d, heads, vocab = 4, 64, 2, 128
        max_len, plen, new = 48, 8, 16
        mbs = (4,)
        cd = None
        peak = 0.0

    graph = gpt(layers, d, heads, max_len, vocab=vocab)
    params = graph.init(jax.random.key(0))
    gqa_kv = max(1, heads // 6)  # GQA variant: 6-way query groups
    graph_gqa = gpt(layers, d, heads, max_len, vocab=vocab, kv_heads=gqa_kv)
    params_gqa = graph_gqa.init(jax.random.key(0))
    rng = np.random.default_rng(0)

    pos_avg = plen + new / 2

    def per_token_flops(kv):
        # per layer: qkv (d + 2*kv*hd cols) + proj (d) + mlp (8d) matmuls
        # at 2*d each, plus attention against the pos_avg-deep cache
        qkv_cols = d + 2 * kv * (d // heads)
        return (layers * (2 * d * (qkv_cols + d + 8 * d)
                          + 4 * pos_avg * d) + 2 * d * vocab)

    flops_tok = per_token_flops(heads)
    out["flops_per_token_model"] = flops_tok
    out["flops_per_token_gqa"] = per_token_flops(gqa_kv)
    out["config"] = {"layers": layers, "d_model": d, "vocab": vocab,
                     "prompt_len": plen, "new_tokens": new,
                     "max_len": max_len, "num_stages": 1}

    # token_chunk keeps ONE compiled program across warmup and the timed
    # run (the decode program cache is keyed by chunk length); the first
    # call compiles, the timed second call is dispatch-only
    token_chunk = 32
    sweep = {}
    variants = [("", graph, params, "buffer", None)]
    if on_tpu:
        variants.append((f"_gqa{gqa_kv}kv", graph_gqa, params_gqa,
                         "buffer", None))
        variants.append(("_int8kv", graph, params, "int8", None))
        # W8A16: int8 weights halve the dominant HBM stream vs bf16 —
        # the decode-side memory-bandwidth lever
        variants.append(("_w8", graph, params, "buffer", "int8"))
        variants.append(("_w8_int8kv", graph, params, "int8", "int8"))
    from defer_tpu.utils.artifact import flush_artifact

    row_filter = [s for s in os.environ.get("DEFER_DECODE_ROWS", ""
                                            ).split(",") if s]
    out_path = os.environ.get("DEFER_DECODE_OUT")

    def flush_partial():
        out["decode_sweep"] = sweep
        out["token_chunk"] = token_chunk
        out.setdefault("value", 0.0)
        out["unit"] = "tokens/sec"
        # merge keeps rows from a timed-out earlier run when re-running
        # with DEFER_DECODE_ROWS over the same DEFER_DECODE_OUT; the
        # headline value is recomputed over the merged rows
        return flush_artifact(out_path, dict(out),
                              merge_key="decode_sweep",
                              merge_prior=bool(row_filter))

    for mb in mbs:
        for vtag, vgraph, vparams, vcache, vwq in variants:
            for use_prefill in ((False, True) if on_tpu else (False,)):
                tag = f"mb{mb}{vtag}" + ("_prefill" if use_prefill else "")
                if row_filter and not any(s in tag for s in row_filter):
                    continue
                try:
                    dec = PipelinedDecoder(vgraph, vparams, num_stages=1,
                                           microbatch=mb, max_len=max_len,
                                           compute_dtype=cd,
                                           kv_cache=vcache,
                                           weight_dtype=vwq)
                    prompt = rng.integers(0, vocab,
                                          size=(mb, plen)).astype(np.int32)
                    kw = dict(max_new_tokens=new, token_chunk=token_chunk,
                              prefill=use_prefill)
                    t0 = time.perf_counter()
                    dec.generate(prompt, **kw)          # compile + run
                    compile_s = time.perf_counter() - t0
                    t0 = time.perf_counter()
                    toks = dec.generate(prompt, **kw)   # warm
                    dt = time.perf_counter() - t0
                    assert toks.shape == (mb, plen + new)
                    tps = mb * new / dt
                    row = {"tokens_per_s": round(tps, 2),
                           "ms_per_token_step": round(1e3 * dt / new, 3),
                           "wall_s": round(dt, 3),
                           "first_call_s": round(compile_s, 3)}
                    if peak:
                        ft = per_token_flops(
                            gqa_kv if "gqa" in vtag else heads)
                        row["mfu_decode"] = round(ft * tps / peak, 5)
                    sweep[tag] = row
                    print(f"{tag}: {tps:.1f} tok/s "
                          f"({1e3 * dt / new:.1f} ms/token-step, "
                          f"first call {compile_s:.1f}s)",
                          file=sys.stderr, flush=True)
                    del dec
                except Exception as e:  # noqa: BLE001 — OOM data point
                    sweep[tag] = {"error": repr(e)[:200]}
                    print(f"{tag}: {e!r}", file=sys.stderr, flush=True)
                flush_partial()
    final = flush_partial()
    print(json.dumps(final))


if __name__ == "__main__":
    main()
