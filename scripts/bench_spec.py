"""TPU benchmark for speculative decoding (runtime/speculative.py).

Random-init models cannot show a realistic draft acceptance rate, so the
measurement brackets the deployment envelope instead:

- ``decode_baseline``: plain PipelinedDecoder tokens/s, same geometry —
  the number speculative decoding must beat;
- ``spec_floor_*``: a cheap 2-layer draft with random weights (near-zero
  acceptance) — worst case, every round wastes its proposals;
- ``spec_perfect_*``: draft == target (acceptance 1.0) — the
  verification machinery at its ceiling, target forwards ~ new/(gamma+1)
  (the draft recompute here costs a full target forward per proposed
  token, so tokens/s is NOT the headline — ``target_forwards`` is);
- ``primitives``: measured seconds per verification forward (the
  length-bucketed ``Defer.logits``) and per draft forward, from which
  projected tokens/s at any acceptance rate follows analytically:
  E[tokens/round] = (1 - a^(g+1)) / (1 - a), round cost =
  g * t_draft + t_target.

If ``DEFER_SPEC_OUT`` is set, the artifact is rewritten after every
row (atomic, merging — ``defer_tpu.utils.artifact``), so a timeout
keeps completed rows; the final JSON line always prints on stdout.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    from defer_tpu import Defer, DeferConfig, speculative_generate
    from defer_tpu.models import gpt
    from defer_tpu.runtime.decode import PipelinedDecoder

    devices = jax.devices()
    on_tpu = devices[0].platform != "cpu"
    if on_tpu:
        tl, td, th = 12, 768, 12          # GPT-2-small target
        dl, dd, dh = 2, 256, 4            # cheap draft (~12% of target)
        vocab, max_len, plen, new, mb = 50257, 256, 32, 128, 8
        # every proposed-but-rejected token costs a full-sequence forward
        # through the tunnel; DEFER_SPEC_NEW trims the per-row round
        # count for a bounded re-run window
        new = int(os.environ.get("DEFER_SPEC_NEW", new))
        if plen + new > max_len:
            raise SystemExit(
                f"DEFER_SPEC_NEW={new}: prompt {plen} + new {new} exceeds "
                f"the decode buffer max_len {max_len}")
        gammas = tuple(int(g) for g in os.environ.get(
            "DEFER_SPEC_GAMMAS", "1,3,5").split(","))
        cd = "bfloat16"
    else:  # CPU smoke
        tl, td, th = 4, 64, 2
        dl, dd, dh = 2, 32, 2
        vocab, max_len, plen, new, mb = 128, 64, 8, 16, 2
        cd = None

    out = {
        "metric": "gpt_small_speculative_decode",
        "platform": devices[0].platform,
        "config": {"target_layers": tl, "d_target": td, "draft_layers": dl,
                   "d_draft": dd, "vocab": vocab, "prompt_len": plen,
                   "new_tokens": new, "batch": mb, "max_len": max_len},
    }
    out["value"] = 0.0
    out["unit"] = "tokens/sec"
    rows = {}
    out_path = os.environ.get("DEFER_SPEC_OUT")

    from defer_tpu.utils.artifact import flush_artifact

    def flush():
        # headline = best REALISTIC speculative row (spec_floor_*);
        # decode_baseline is the comparator and spec_perfect_* is a
        # machinery diagnostic (oracle draft), neither is the result
        out["rows"] = rows
        return flush_artifact(
            out_path, dict(out), merge_key="rows",
            row_filter=lambda k: k.startswith("spec_floor"))

    target = gpt(tl, td, th, max_len, vocab=vocab, name="spec_target")
    tparams = target.init(jax.random.key(0))
    draft = gpt(dl, dd, dh, max_len, vocab=vocab, name="spec_draft")
    dparams = draft.init(jax.random.key(1))
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, vocab, (mb, plen)).astype(np.int64)

    import jax.numpy as jnp
    cfg = DeferConfig(microbatch=mb, chunk=8,
                      compute_dtype=getattr(jnp, cd) if cd else None)
    defer = Defer(config=cfg)

    # -- plain decode baseline --------------------------------------------
    dec = PipelinedDecoder(target, tparams, num_stages=1, microbatch=mb,
                           max_len=max_len,
                           compute_dtype=getattr(jnp, cd) if cd else None)
    kw = dict(max_new_tokens=new, token_chunk=32)
    dec.generate(prompt.astype(np.int32), **kw)          # compile
    t0 = time.perf_counter()
    dec.generate(prompt.astype(np.int32), **kw)
    dt = time.perf_counter() - t0
    rows["decode_baseline"] = {"tokens_per_s": round(mb * new / dt, 2),
                               "wall_s": round(dt, 3)}
    print(f"decode_baseline: {mb * new / dt:.1f} tok/s", file=sys.stderr,
          flush=True)
    del dec
    flush()

    # -- speculative rows --------------------------------------------------
    def spec_row(tag, dg, dp, gamma, warm=True):
        a = dict(gamma=gamma, num_stages=1, draft_num_stages=1,
                 return_stats=True)
        if warm:  # buckets compile on first call
            speculative_generate(defer, target, tparams, dg, dp,
                                 prompt, new, **a)
        t0 = time.perf_counter()
        _, stats = speculative_generate(defer, target, tparams, dg, dp,
                                        prompt, new, **a)
        dt = time.perf_counter() - t0
        rows[tag] = {"tokens_per_s": round(mb * new / dt, 2),
                     "wall_s": round(dt, 3),
                     "accept_rate": round(stats["accept_rate"], 4),
                     "rounds": stats["rounds"],
                     "target_forwards": stats["target_forwards"],
                     "draft_forwards": stats["draft_forwards"]}
        print(f"{tag}: {mb * new / dt:.1f} tok/s "
              f"accept={stats['accept_rate']:.3f} "
              f"tf={stats['target_forwards']}", file=sys.stderr, flush=True)
        flush()

    for gamma in gammas if on_tpu else (3,):
        spec_row(f"spec_floor_g{gamma}", draft, dparams, gamma)
    spec_row("spec_perfect_g3", target, tparams, 3)

    # -- primitives: per-forward costs at the top bucket -------------------
    full = rng.integers(0, vocab, (mb, plen + new)).astype(np.int64)
    for name, g, p in (("t_target_fwd_s", target, tparams),
                       ("t_draft_fwd_s", draft, dparams)):
        defer.logits(g, p, full, num_stages=1)           # compile
        t0 = time.perf_counter()
        defer.logits(g, p, full, num_stages=1)
        rows.setdefault("primitives", {})[name] = round(
            time.perf_counter() - t0, 4)
    # projected tokens/s vs draft acceptance from the measured primitives
    tt = rows["primitives"]["t_target_fwd_s"]
    tdr = rows["primitives"]["t_draft_fwd_s"]
    proj = {}
    for a in (0.5, 0.7, 0.8, 0.9):
        g = 3
        exp_tokens = (1 - a ** (g + 1)) / (1 - a)
        proj[f"a{a}"] = round(mb * exp_tokens / (g * tdr + tt), 1)
    rows["projected_tokens_per_s_g3"] = proj
    print(json.dumps(flush()))


if __name__ == "__main__":
    main()
