"""Cost-model truth smoke: calibration closes the predict/measure loop.

A 3-stage resnet_tiny chain gets a delay-bound middle stage (decode-side
sleep on its inbound hop, encode-side sleep on its outbound hop — the
same vehicle as ``monitor_smoke.py``).  The deployed ``dsleep``/
``esleep`` codec names have NO row in the analytic codec table, so the
default cost model prices them via the ``raw`` fallback and predicts
the delay stage as compute-bound — the documented failure mode the
calibration loop exists to fix:

1. COMPUTE BASIS: a no-delay calibration run measures this host's real
   per-stage compute (the analytic roofline is meaningless on CPU).
2. CALIBRATE: ``fit_from_stats`` over the DELAY chain's own live
   telemetry (window-bounded against the post-warmup baseline snapshot)
   fits per-deployed-codec throughputs + host-sync / wire bandwidths
   into a versioned ``CalibratedConstants`` artifact.  The calibrated
   model must predict the bottleneck stage's measured service within
   ``--tolerance`` (15%); the default model must be measurably worse.
3. ROUNDTRIP: the calibrated constants survive plan JSON
   (``evaluate_cuts(..., hop_codecs=deployed)`` -> ``to_json`` ->
   ``plan_from_json`` -> ``cost_model_from_plan``) — the monitor's
   drift auditor rebuilds its predictions from exactly that artifact.
4. MONITOR: ``defer_tpu monitor --json`` against the running chain
   carries per-row ``pred_ms``/``meas_ms``/``err`` and the ``mfu``
   field; the human table renders the MFU / PRED / MEAS / ERR%
   columns.
5. DRIFT: a second chain with every sleep DOUBLED (the injected
   slowdown) audited against the SAME plan must fire a ``model_drift``
   flight-recorder event on the delay stage within ``--sustain`` (2)
   monitor intervals, exactly once per episode.
6. OVERHEAD: streaming wall with the live monitor + drift audit
   subscribed vs telemetry-off differs by < ``--max-overhead`` (5%) on
   the interleaved min-of-3 protocol; outputs stay byte-identical.

``--quick`` runs the chain in-process (thread nodes, real TCP sockets —
the CI mode); the default spawns real OS processes per stage.  Exit 0
on success; one JSON row on stdout (the ``cost_model_truth`` row of
``benchmarks/run.py``, CalibratedConstants embedded so the bench ledger
carries the calibration trajectory).
"""

import argparse
import contextlib
import io
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CPU_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def hop_codecs(delay_ms: float) -> list[str]:
    """Park the whole delay budget inside stage 1's process: decode-side
    sleep on its inbound hop, encode-side sleep on its outbound hop."""
    if delay_ms <= 0:
        return ["raw", "raw", "raw"]
    return [f"dsleep{delay_ms:g}+raw", f"esleep{delay_ms:g}+raw", "raw"]


class Chain:
    """One booted 3-stage chain (thread nodes or OS processes)."""

    def __init__(self, disp, addrs, *, procs=None, logs=None,
                 threads=None):
        self.disp = disp
        self.addrs = addrs
        self._procs = procs or []
        self._logs = logs or []
        self._threads = threads or []
        self.failed = False

    def close(self):
        from defer_tpu.runtime.node import _kill_procs
        try:
            if self.failed:
                _kill_procs(self._procs)
            self.disp.close()
            if not self.failed:
                for pr in self._procs:
                    try:
                        pr.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        pr.kill()
            for t in self._threads:
                t.join(timeout=30)
        finally:
            for lf in self._logs:
                lf.close()


def boot_inproc(stages, params, codecs, *, batch) -> Chain:
    from defer_tpu.runtime.node import ChainDispatcher, StageNode
    nodes = [StageNode(None, "127.0.0.1:0", None) for _ in range(3)]
    addrs = [f"127.0.0.1:{n.address[1]}" for n in nodes]
    threads = [threading.Thread(target=n.serve, daemon=True)
               for n in nodes]
    for t in threads:
        t.start()
    disp = ChainDispatcher(addrs[0], codec="raw")
    disp.deploy(stages, params, addrs, batch=batch, codecs=codecs)
    return Chain(disp, addrs, threads=threads)


def boot_procs(paths, codecs, *, log_dir, tag) -> Chain:
    from defer_tpu.runtime.node import ChainDispatcher, _await_binds
    from defer_tpu.runtime.node import _free_ports
    ports = _free_ports(4)
    addrs = [f"127.0.0.1:{p}" for p in ports[:3]]
    result = f"127.0.0.1:{ports[3]}"
    child_env = dict(os.environ)
    child_env.update(CPU_ENV)
    procs, logs = [], []
    for k in range(3):
        nxt = addrs[k + 1] if k < 2 else result
        # --tier tcp: the fit prices the dsleep/esleep wire codecs; an
        # auto-negotiated shm hop would bypass them
        argv = [sys.executable, "-m", "defer_tpu", "node",
                "--artifact", paths[k], "--listen", addrs[k],
                "--next", nxt, "--codec", codecs[k], "--tier", "tcp"]
        lf = open(os.path.join(log_dir, f"{tag}_node_{k}.log"), "w+")
        logs.append(lf)
        procs.append(subprocess.Popen(argv, env=child_env, stdout=lf,
                                      stderr=subprocess.STDOUT))
    _await_binds(procs, [f"stage{k}" for k in range(3)], logs, addrs)
    disp = ChainDispatcher(addrs[0], listen=result, codec="raw")
    return Chain(disp, addrs, procs=procs, logs=logs)


def run_monitor(addrs, *, interval_ms, iterations, plan_file,
                as_json=True, out: dict | None = None):
    """Invoke the REAL CLI (`defer_tpu monitor`) and return its parsed
    JSON lines (or, with as_json=False, the raw rendered text)."""
    from defer_tpu import cli
    argv = ["monitor", "--nodes", ",".join(addrs),
            "--interval-ms", str(interval_ms),
            "--iterations", str(iterations),
            "--plan", plan_file, "--model", "resnet_tiny"]
    if as_json:
        argv.append("--json")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        cli.main(argv)
    if not as_json:
        return buf.getvalue()
    docs = [json.loads(line) for line in buf.getvalue().strip()
            .splitlines() if line]
    if out is not None:
        out["docs"] = docs
    return docs


def _p50(s) -> float:
    return (s or {}).get("p50", 0.0) * 1e3 if (s or {}).get("count") \
        else 0.0


def service_from_stats(stats) -> dict[int, float]:
    """Per-stage live service ms from stats replies: the slowest of the
    decode / infer / encode phase p50s (each phase owns a thread)."""
    out = {}
    for row in stats:
        if row.get("stage") is None:
            continue
        out[row["stage"]] = max(_p50(row.get("infer_latency_s")),
                                _p50(row.get("decode_latency_s")),
                                _p50(row.get("encode_latency_s")))
    return out


def infer_from_stats(stats) -> dict[int, float]:
    """Per-stage COMPUTE ms (infer p50 only): the cost-model basis.
    Codec work is deliberately excluded — pricing the hops is the
    calibration artifact's job, not the compute term's."""
    out = {}
    for row in stats:
        if row.get("stage") is None:
            continue
        out[row["stage"]] = _p50(row.get("infer_latency_s"))
    return out


def compute_cost_model(graph, stages, measured_ms: dict[int, float], *,
                       batch: int):
    """A cost model whose COMPUTE is this host's measured no-delay
    per-stage service, spread uniformly over each stage's nodes (the
    analytic roofline cannot price a 1-core CPU host), with the
    DEFAULT analytic codec table — the uncalibrated strawman the
    artifact is fitted against.  Built at the chain's frame ``batch``
    so comm terms price the bytes that actually cross each hop."""
    from defer_tpu.plan import StageCostModel
    node_costs = {}
    order = graph.topo_order
    pos = {n: i for i, n in enumerate(order)}
    cuts = [s.output_name for s in stages[:-1]]
    bounds = [0] + [pos[c] + 1 for c in cuts] + [len(order)]
    for k in range(len(bounds) - 1):
        names = order[bounds[k]:bounds[k + 1]]
        per = max(measured_ms.get(k, 0.0), 1e-3) / 1e3 / len(names)
        for n in names:
            node_costs[n] = per
    return StageCostModel(graph, gen="v4", link_bw_s=1e9,
                          batch=batch, node_costs=node_costs)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="in-process thread chain (CI mode, no spawns)")
    ap.add_argument("--count", type=int, default=48,
                    help="timed microbatches per measured stream")
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--delay-ms", type=float, default=10.0,
                    help="per-side delay on the bottleneck stage's hops")
    ap.add_argument("--interval-ms", type=float, default=150.0,
                    help="obs_push reporting interval")
    ap.add_argument("--sustain", type=int, default=2,
                    help="intervals drift must hold to fire the event")
    ap.add_argument("--tolerance", type=float, default=0.15,
                    help="calibrated bottleneck prediction error bound")
    ap.add_argument("--max-overhead", type=float, default=0.05,
                    help="monitor+audit wall overhead bound vs all-off")
    args = ap.parse_args()

    import numpy as np

    import jax

    from defer_tpu import partition
    from defer_tpu.models import resnet_tiny
    from defer_tpu.obs import recorder
    from defer_tpu.plan import (CalibratedConstants, evaluate_cuts,
                                fit_from_stats, plan_from_json,
                                predict_stage_service_s)
    from defer_tpu.plan.replan import cost_model_from_plan
    from defer_tpu.utils.export import export_pipeline

    graph = resnet_tiny()
    params = graph.init(jax.random.key(0))
    stages = partition(graph, num_stages=3)
    cuts = [s.output_name for s in stages[:-1]]
    rng = np.random.default_rng(7)
    xs = [rng.standard_normal((args.batch, 32, 32, 3)).astype(np.float32)
          for _ in range(args.count)]
    deploys = hop_codecs(args.delay_ms)

    with tempfile.TemporaryDirectory(prefix="defer_cap_") as tmp:
        paths = None
        if not args.quick:
            paths = export_pipeline(stages, params, tmp, batch=args.batch)

        def boot(codecs, tag):
            if args.quick:
                return boot_inproc(stages, params, codecs,
                                   batch=args.batch)
            return boot_procs(paths, codecs, log_dir=tmp, tag=tag)

        # -- 1. compute basis: the no-delay run measures this host's
        # per-stage compute (always in-process: that IS the thing the
        # plan's node costs must predict)
        chain = boot_inproc(stages, params, hop_codecs(0),
                            batch=args.batch)
        try:
            chain.disp.stream(xs[:4])          # compile + connect
            chain.disp.stream(xs)
            base_ms = infer_from_stats(chain.disp.stats(chain.addrs))
        finally:
            chain.close()
        cost_default = compute_cost_model(graph, stages, base_ms,
                                          batch=args.batch)
        log(f"compute basis (no-delay run): "
            f"{ {k: round(v, 3) for k, v in base_ms.items()} } ms")

        # -- 2. calibrate from the delay chain's own telemetry ---------
        chain_off = boot(deploys, "off")
        chain_on = boot(deploys, "on")
        mon: dict = {}
        human = None
        try:
            chain_off.disp.stream(xs[:4])
            chain_on.disp.stream(xs[:4])       # compile + connect
            # window-bound the fit against the post-warmup snapshot so
            # compile-cold outliers never anchor a bandwidth
            stats_warm = chain_on.disp.stats(chain_on.addrs)
            chain_on.disp.stream(xs)
            stats_cal = chain_on.disp.stats(chain_on.addrs)
            meas_ms = service_from_stats(stats_cal)
            cal = fit_from_stats(graph, cuts, stats_cal,
                                 batch=args.batch, gen="unknown",
                                 prior=cost_default,
                                 baseline=stats_warm)
            cal_file = os.path.join(tmp, "calibration.json")
            cal.save(cal_file)
            cal = CalibratedConstants.load(cal_file)   # artifact roundtrip
            cost_cal = cal.apply(cost_default)

            # deploys[-1] is the dispatcher result hop; the cut hops
            # are the first len(cuts) entries
            stage_hops = deploys[:len(cuts)]
            pred_def = [s * 1e3 for s in predict_stage_service_s(
                graph, cuts, stage_hops, cost_default)]
            pred_cal = [s * 1e3 for s in predict_stage_service_s(
                graph, cuts, stage_hops, cost_cal)]
            bott = max(meas_ms, key=lambda k: meas_ms[k])
            assert bott == 1, f"delay stage not the bottleneck: {meas_ms}"
            err_cal = abs(pred_cal[bott] - meas_ms[bott]) / meas_ms[bott]
            err_def = abs(pred_def[bott] - meas_ms[bott]) / meas_ms[bott]
            log(f"bottleneck stage {bott}: measured "
                f"{meas_ms[bott]:.3f} ms, calibrated pred "
                f"{pred_cal[bott]:.3f} ms ({err_cal * 100:+.1f}%), "
                f"default pred {pred_def[bott]:.3f} ms "
                f"({err_def * 100:+.1f}%)")
            assert err_cal < args.tolerance, (
                f"calibrated prediction off by {err_cal * 100:.1f}% "
                f"(bound {args.tolerance * 100:.0f}%): "
                f"pred {pred_cal[bott]:.3f} vs meas {meas_ms[bott]:.3f}")
            # the default model prices the unknown dsleep/esleep names
            # as raw: it must be MEASURABLY worse, not coin-flip worse
            assert err_def > max(2 * err_cal, 0.5), (
                f"default model unexpectedly good: {err_def * 100:.1f}% "
                f"vs calibrated {err_cal * 100:.1f}%")

            # -- 3. plan JSON roundtrip: the deployed-codec plan carries
            # the calibrated constants to the monitor's auditor
            plan = evaluate_cuts(graph, cuts, cost_cal,
                                 hop_codecs=stage_hops)
            plan_file = os.path.join(tmp, "plan.json")
            with open(plan_file, "w") as f:
                json.dump(plan.to_json(), f)
            with open(plan_file) as f:
                plan_rt = plan_from_json(json.load(f))
            cost_rt = cost_model_from_plan(graph, plan_rt)
            pred_rt = [s * 1e3 for s in predict_stage_service_s(
                graph, plan_rt.cuts, plan_rt.codecs, cost_rt)]
            for a, b in zip(pred_rt, pred_cal):
                assert abs(a - b) <= 1e-6 + 1e-3 * b, (pred_rt, pred_cal)

            # -- 6. overhead: TWO identical delay chains, streamed
            # ALTERNATELY — "off" never sees telemetry, "on" streams
            # under a live monitor + drift-audit subscriber.
            # Interleaving cancels host drift; min-of-3 absorbs
            # scheduler spikes.
            mt = threading.Thread(
                target=run_monitor, args=(chain_on.addrs,),
                kwargs=dict(interval_ms=args.interval_ms,
                            iterations=40, plan_file=plan_file,
                            out=mon), daemon=True)
            mt.start()
            w_off, w_on = [], []
            for _ in range(3):
                t0 = time.perf_counter()
                outs_off = chain_off.disp.stream(xs)
                w_off.append(time.perf_counter() - t0)
                t0 = time.perf_counter()
                outs_on = chain_on.disp.stream(xs)
                w_on.append(time.perf_counter() - t0)
            wall_off, wall_on = min(w_off), min(w_on)
            mt.join(timeout=120)
            assert not mt.is_alive(), "monitor CLI did not finish"
            live_docs = mon["docs"]
            # -- 4. the human table renders the new columns
            human = run_monitor(chain_on.addrs,
                                interval_ms=args.interval_ms,
                                iterations=2, plan_file=plan_file,
                                as_json=False)
        except BaseException:
            chain_off.failed = chain_on.failed = True
            raise
        finally:
            chain_off.close()
            chain_on.close()

        # 6a. the audit must not corrupt the stream
        assert len(outs_on) == len(outs_off) == args.count
        for a, b in zip(outs_off, outs_on):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        # 4. monitor rows carry the audit + capacity fields
        assert live_docs, "no monitor output"
        audited = [d for d in live_docs
                   if any(r.get("pred_ms") and (r.get("err") is not None)
                          for r in d["rows"])]
        assert audited, f"no audited rows: {live_docs[-1]['rows']}"
        last = audited[-1]
        row1 = next(r for r in last["rows"] if r["stage"] == 1)
        assert "mfu" in row1 and row1["mfu"] is None, row1  # CPU: no peak
        assert abs(row1["err"]) < 0.25, (
            f"calibrated audit err {row1['err'] * 100:+.1f}% on the "
            f"nominal chain: {row1}")
        # the nominal chain matches its calibrated predictions on the
        # delay-bound stage: no sustained drift episode there (the
        # sub-ms fast stages ride 1-core contention and may wobble
        # past any honest threshold — they are not this row's claim)
        drifted = [d for d in live_docs
                   if any(f["stage"] == 1 for f in d["drift"])]
        assert not drifted, f"false drift on nominal chain: {drifted[0]}"
        for col in ("MFU%", "PRED", "MEAS", "ERR%"):
            assert col in human, f"monitor table lacks {col}:\n{human}"

        # -- 5. injected slowdown: every sleep doubled, audited against
        # the SAME plan -> model_drift on the delay stage within
        # --sustain intervals
        recorder().clear()
        slow = boot(hop_codecs(args.delay_ms * 2), "slow")
        mon2: dict = {}
        try:
            slow.disp.stream(xs[:4])
            mt2 = threading.Thread(
                target=run_monitor, args=(slow.addrs,),
                kwargs=dict(interval_ms=args.interval_ms,
                            iterations=30, plan_file=plan_file,
                            out=mon2), daemon=True)
            mt2.start()
            for _ in range(3):
                slow.disp.stream(xs)
            mt2.join(timeout=120)
            assert not mt2.is_alive(), "drift monitor did not finish"
        except BaseException:
            slow.failed = True
            raise
        finally:
            slow.close()
        drift_docs = [d for d in mon2["docs"] if d["drift"]]
        assert drift_docs, "model_drift never fired on the slowed chain"
        first = drift_docs[0]["drift"]
        by_stage = {f["stage"]: f for f in first}
        assert 1 in by_stage, first
        f1 = by_stage[1]
        assert f1["intervals"] == args.sustain, f1
        assert f1["rel_err"] > 0.5, f1     # 2x sleep: ~+100% drift
        # fires as soon as the audit has measurements: within --sustain
        # intervals of the first audited frame
        first_audit = next(i for i, d in enumerate(mon2["docs"])
                           if any(r.get("err") is not None
                                  for r in d["rows"]))
        first_drift = mon2["docs"].index(drift_docs[0])
        assert first_drift - first_audit < args.sustain + 2, (
            f"drift took {first_drift - first_audit} frames past the "
            f"first audited frame (sustain {args.sustain})")
        # ONE event per episode (StragglerDetector re-arm discipline)
        drift_events = [e for e in recorder().snapshot()
                        if e["kind"] == "model_drift"
                        and e["data"].get("stage") == 1]
        assert len(drift_events) == 1, drift_events

        # 6b. the telemetry tax
        overhead = wall_on / wall_off - 1.0
        log(f"overhead: {overhead * 100:+.2f}% "
            f"(bound {args.max_overhead * 100:.0f}%); drift fired "
            f"{f1['rel_err'] * 100:+.1f}% after {f1['intervals']} "
            f"intervals")
        assert overhead < args.max_overhead, (
            f"monitor+audit overhead {overhead * 100:.2f}% exceeds "
            f"{args.max_overhead * 100:.0f}% (on {wall_on:.3f}s vs off "
            f"{wall_off:.3f}s)")

        row = {"metric": "cost_model_truth",
               "value": round(err_cal, 4),
               "unit": "frac_abs_err_calibrated_bottleneck",
               "quick": args.quick, "count": args.count,
               "batch": args.batch, "delay_ms": args.delay_ms,
               "bottleneck": bott,
               "measured_ms": {str(k): round(v, 4)
                               for k, v in meas_ms.items()},
               "pred_calibrated_ms": [round(v, 4) for v in pred_cal],
               "pred_default_ms": [round(v, 4) for v in pred_def],
               "err_default": round(err_def, 4),
               "drift": f1,
               "monitor_frames": len(live_docs),
               "overhead": round(overhead, 4),
               "wall_off_s": round(wall_off, 4),
               "wall_on_s": round(wall_on, 4),
               "calibration": cal.to_json(),
               "cpu_count": os.cpu_count() or 1}

    print(json.dumps(row))
    log("capacity smoke: OK")


if __name__ == "__main__":
    main()
