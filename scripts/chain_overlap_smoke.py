"""Chain overlap smoke: prove the rx/compute/tx overlap is real and pays.

Two measurements over a 4-stage resnet_tiny chain:

1. OVERLAP RATIO (in-process thread chain, artificially slow codec):
   every hop uses a codec whose encode/decode sleep a fixed delay, so the
   per-phase histogram totals (``codec.encode_s`` + ``codec.decode_s`` +
   ``node.infer_s``) are a faithful "serial sum" of the work.  Asserts the
   overlapped wall time of the stream is < ``--max-ratio`` (default 0.8)
   of that sum, that rx/infer spans of adjacent microbatches actually
   overlap in time in the collected trace, and that the channel gauges
   (``node.rx_queue_depth`` / ``node.tx_queue_depth`` / ``node.inflight``)
   appear in the metrics snapshot.

2. SPEEDUP (multi-process chains): spawns the 4-stage chain as real OS
   processes, overlapped node loops vs the serial pre-overlap baseline
   (``--no-overlap``), identical inputs, warmup stream excluded from the
   window, byte-identical outputs required.  Two wire configurations:

   * plain ``bf8`` — the honest all-CPU measurement.  Its speedup is
     asserted >= ``--min-speedup`` (default 1.25) only on hosts with
     >= 8 CPUs: with fewer cores every phase competes for the same
     silicon and overlapping CPU-bound work cannot beat its sum (a
     1-core CI box measures ~1.0x by physics, not by regression).
   * ``sleep<ms>+bf8`` — the same bf8 bytes plus a fixed per-side delay
     that models the phases a CPU-bound localhost chain cannot express
     (accelerator compute, NIC serialization).  This speedup is asserted
     >= ``--min-speedup`` on every host: it is the portable proof that
     the overlap machinery actually hides non-CPU phase time.

Exit 0 on success; one JSON row on stdout (the ``chain_overlap`` row of
``benchmarks/run.py``).

Usage:  python scripts/chain_overlap_smoke.py [--trace-out FILE]
            [--metrics-out FILE] [--min-speedup 1.25] [--quick]
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: stage-node subprocesses must never touch a (single-client) TPU tunnel
CPU_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# part 1: in-process thread chain with a slow codec -> overlap ratio + trace
# ---------------------------------------------------------------------------

def overlap_ratio(stages, params, *, delay_s: float, count: int,
                  batch: int) -> dict:
    import numpy as np

    from defer_tpu.codec.codecs import RawCodec
    from defer_tpu.obs import REGISTRY, enable_tracing, tracer
    from defer_tpu.runtime.node import ChainDispatcher, StageNode
    from defer_tpu.transport import framed

    class SlowCodec(RawCodec):
        """Raw codec with a fixed sleep on both sides: makes the codec
        phases big and *exactly known*, so wall-vs-sum is a clean test."""
        name = "slow"

        def encode(self, arr):
            time.sleep(delay_s)
            return super().encode(arr)

        def decode(self, data, shape, dtype):
            time.sleep(delay_s)
            return super().decode(data, shape, dtype)

    framed._CODECS["slow"] = SlowCodec()
    for h in ("codec.encode_s", "codec.decode_s", "node.infer_s"):
        REGISTRY.histogram(h).clear()
    tr = enable_tracing(process="dispatcher")
    tr.start_trace()

    nodes = [StageNode(None, "127.0.0.1:0", None) for _ in range(len(stages))]
    addrs = [f"127.0.0.1:{n.address[1]}" for n in nodes]
    threads = [threading.Thread(target=n.serve, daemon=True) for n in nodes]
    for t in threads:
        t.start()
    rng = np.random.default_rng(0)
    xs = [rng.standard_normal((batch,) + tuple(stages[0].in_spec.shape))
          .astype(np.float32) for _ in range(count)]
    disp = ChainDispatcher(addrs[0], codec="slow")
    try:
        disp.deploy(stages, params, addrs, batch=batch)
        disp.stream(xs[:2])  # warm: jit compiles, connections, first frames
        for h in ("codec.encode_s", "codec.decode_s", "node.infer_s"):
            REGISTRY.histogram(h).clear()
        t0 = time.perf_counter()
        outs = disp.stream(xs)
        wall = time.perf_counter() - t0
    finally:
        disp.close()
    for t in threads:
        t.join(timeout=30)
    assert len(outs) == count, (len(outs), count)

    serial_sum = sum(REGISTRY.histogram(h).sum
                     for h in ("codec.encode_s", "codec.decode_s",
                               "node.infer_s"))
    snap = REGISTRY.snapshot()
    for g in ("node.rx_queue_depth", "node.tx_queue_depth", "node.inflight"):
        assert g in snap, f"gauge {g} missing from the metrics snapshot"

    # the trace must show phases of ADJACENT microbatches overlapping in
    # wall time within one stage: rx(j') concurrent with infer(j), j' > j
    spans = tracer().spans
    overlaps = 0
    for k in range(len(stages)):
        rxs = [s for s in spans if s["name"] == f"stage{k}.rx"]
        infers = [s for s in spans if s["name"] == f"stage{k}.infer"]
        for a in rxs:
            for b in infers:
                if a["args"].get("seq", 0) > b["args"].get("seq", 0) \
                        and a["ts_us"] < b["ts_us"] + b["dur_us"] \
                        and b["ts_us"] < a["ts_us"] + a["dur_us"]:
                    overlaps += 1
    assert overlaps > 0, "no rx/infer span overlap found in the trace"
    return {"wall_s": wall, "serial_sum_s": serial_sum,
            "ratio": wall / serial_sum, "span_overlaps": overlaps,
            "snapshot": snap}


# ---------------------------------------------------------------------------
# part 2: multi-process chain, bf8 -> speedup vs the serial node loop
# ---------------------------------------------------------------------------

def _free_ports(n):
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def timed_chain(paths, xs_warm, xs, *, overlap: bool, codec: str,
                log_dir: str):
    """Spawn one node process per stage artifact, warm the chain, stream
    ``xs`` timed, tear down.  Returns (outputs, seconds)."""
    from defer_tpu.runtime.node import ChainDispatcher

    n = len(paths)
    ports = _free_ports(n + 1)
    child_env = dict(os.environ)
    child_env.update(CPU_ENV)
    mode = "overlap" if overlap else "serial"
    procs, logs = [], []
    for i in range(n):
        # --tier tcp pins the hops to the pure wire path: this row
        # measures the rx/compute/tx OVERLAP, and an auto-negotiated
        # shm hop would bypass the slow codec being overlapped
        argv = [sys.executable, "-m", "defer_tpu", "node",
                "--artifact", paths[i],
                "--listen", f"127.0.0.1:{ports[i]}",
                "--next", f"127.0.0.1:{ports[i + 1]}",
                "--codec", codec, "--tier", "tcp"] \
            + ([] if overlap else ["--no-overlap"])
        lf = open(os.path.join(log_dir, f"{mode}_node_{i}.log"), "w+")
        logs.append(lf)
        procs.append(subprocess.Popen(argv, env=child_env, stdout=lf,
                                      stderr=subprocess.STDOUT))
    disp = ChainDispatcher(f"127.0.0.1:{ports[0]}",
                           listen=f"127.0.0.1:{ports[-1]}", codec=codec)
    try:
        disp.stream(xs_warm)   # boot + compile excluded from the window
        t0 = time.perf_counter()
        outs = disp.stream(xs)
        dt = time.perf_counter() - t0
    finally:
        disp.close()
        for pr in procs:
            try:
                pr.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pr.kill()
        for lf in logs:
            lf.close()
    return outs, dt


def speedup(stages, params, *, count: int, batch: int, codec: str) -> dict:
    import numpy as np

    from defer_tpu.utils.export import export_pipeline

    rng = np.random.default_rng(1)
    xs = [rng.standard_normal((batch,) + tuple(stages[0].in_spec.shape))
          .astype(np.float32) for _ in range(count)]
    xs_warm = xs[:4]
    with tempfile.TemporaryDirectory(prefix="defer_overlap_") as tmp:
        paths = export_pipeline(stages, params, tmp, batch=batch)
        slow_outs, slow_s = timed_chain(paths, xs_warm, xs, overlap=False,
                                        codec=codec, log_dir=tmp)
        log(f"serial:     {count * batch / slow_s:8.1f} inf/s "
            f"({slow_s:.2f}s)")
        fast_outs, fast_s = timed_chain(paths, xs_warm, xs, overlap=True,
                                        codec=codec, log_dir=tmp)
        log(f"overlapped: {count * batch / fast_s:8.1f} inf/s "
            f"({fast_s:.2f}s)")
    assert len(fast_outs) == len(slow_outs) == count
    for a, b in zip(fast_outs, slow_outs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    return {"serial_s": slow_s, "overlap_s": fast_s,
            "speedup": slow_s / fast_s,
            "serial_inf_s": count * batch / slow_s,
            "overlap_inf_s": count * batch / fast_s}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--min-speedup", type=float, default=1.25,
                    help="required overlapped/serial throughput ratio")
    ap.add_argument("--max-ratio", type=float, default=0.8,
                    help="required wall / serial-phase-sum bound (part 1)")
    ap.add_argument("--count", type=int, default=48,
                    help="timed microbatches through the chain")
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--codec", default="bf8")
    ap.add_argument("--delay-ms", type=float, default=5.0,
                    help="slow-codec per-side sleep (part 1)")
    ap.add_argument("--quick", action="store_true",
                    help="part 1 only (no multi-process spawns)")
    ap.add_argument("--trace-out", default=None, metavar="FILE")
    ap.add_argument("--metrics-out", default=None, metavar="FILE")
    args = ap.parse_args()

    import jax

    from defer_tpu import partition
    from defer_tpu.models import resnet_tiny
    from defer_tpu.obs import export_chrome_trace

    graph = resnet_tiny()
    params = graph.init(jax.random.key(0))
    stages = partition(graph, num_stages=4)

    r1 = overlap_ratio(stages, params, delay_s=args.delay_ms / 1e3,
                       count=min(args.count, 24), batch=4)
    log(f"overlap ratio: wall {r1['wall_s']:.2f}s vs serial phase sum "
        f"{r1['serial_sum_s']:.2f}s -> {r1['ratio']:.3f} "
        f"({r1['span_overlaps']} overlapping span pairs)")
    if args.trace_out:
        export_chrome_trace(args.trace_out)
        log(f"trace -> {args.trace_out}")
    if args.metrics_out:
        with open(args.metrics_out, "w") as f:
            json.dump(r1["snapshot"], f, indent=2, default=str)
            f.write("\n")
        log(f"metrics -> {args.metrics_out}")
    assert r1["ratio"] < args.max_ratio, (
        f"overlapped wall {r1['wall_s']:.2f}s is {r1['ratio']:.2f}x the "
        f"serial phase sum (bound {args.max_ratio})")

    cores = os.cpu_count() or 1
    row = {"metric": "chain_overlap", "unit": "x_vs_serial_node_loop",
           "stages": len(stages), "codec": args.codec,
           "batch": args.batch, "count": args.count, "cpu_count": cores,
           "overlap_wall_vs_phase_sum": round(r1["ratio"], 4)}
    if args.quick:
        row["value"] = None
    else:
        # plain bf8: byte-identity always; speedup asserted on hosts with
        # enough cores that compute/codec phases CAN physically overlap
        r_cpu = speedup(stages, params, count=args.count, batch=args.batch,
                        codec=args.codec)
        log(f"{args.codec} speedup: {r_cpu['speedup']:.3f}x "
            f"({'asserted' if cores >= 8 else f'informational on {cores} cpu(s)'})")
        if cores >= 8:
            assert r_cpu["speedup"] >= args.min_speedup, (
                f"{args.codec} overlap speedup {r_cpu['speedup']:.3f}x is "
                f"under the {args.min_speedup}x bar on {cores} cpus "
                f"(serial {r_cpu['serial_inf_s']:.1f} inf/s, overlapped "
                f"{r_cpu['overlap_inf_s']:.1f} inf/s)")
        # sleep-wrapped bf8 (same wire bytes + per-side non-CPU delay):
        # the portable overlap proof, asserted on every host
        wire = f"sleep{args.delay_ms:g}+{args.codec}"
        r_wire = speedup(stages, params, count=args.count,
                         batch=min(args.batch, 8), codec=wire)
        log(f"{wire} speedup: {r_wire['speedup']:.3f}x")
        assert r_wire["speedup"] >= args.min_speedup, (
            f"{wire} overlap speedup {r_wire['speedup']:.3f}x is under "
            f"the {args.min_speedup}x bar (serial "
            f"{r_wire['serial_inf_s']:.1f} inf/s, overlapped "
            f"{r_wire['overlap_inf_s']:.1f} inf/s)")
        row.update({
            "value": round(r_wire["speedup"], 4),
            "wire_codec": wire,
            "serial_inf_per_s": round(r_wire["serial_inf_s"], 2),
            "overlap_inf_per_s": round(r_wire["overlap_inf_s"], 2),
            f"{args.codec}_speedup": round(r_cpu["speedup"], 4),
            f"{args.codec}_speedup_asserted": cores >= 8,
        })
    print(json.dumps(row))
    log("chain overlap smoke: OK")


if __name__ == "__main__":
    main()
