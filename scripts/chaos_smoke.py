"""Chaos smoke: replica failover + zero-downtime live replan.

Both legs of the seq-replay substrate (docs/ROBUSTNESS.md) — the same
retain-until-ack / quiesce mechanism driven from its two entry points:

1. FAILOVER (multi-process): a 3-stage resnet_tiny chain with stage 1
   replicated R=2 and ``failover=True``, stage-1 frames slowed so the
   stream is mid-flight when a killer thread SIGKILLs replica 0.  The
   supervisor respawns it on its old port, the upstream fan-out heals
   (redial + preamble + replay of unacked frames), and the run must
   end byte-identical to an undisturbed reference over the same
   inputs.  The healed hop's ``failover`` flight-recorder event — read
   back through the nodes' teardown stats — carries the replayed-frame
   count and the recovery wall time, which becomes the bench row's
   value.

2. REPLAN (in-process persist chain): stream half the inputs, cut the
   chain over to a different set of cuts mid-stream via
   :class:`~defer_tpu.plan.replan.LiveReplan` (quiesce -> in-band
   redeploy onto the same processes -> resume), stream the rest.  The
   combined output must be byte-identical to the segment-wise
   composition of two plain runs; the receipt's ``cutover_ms`` lands
   in the row.

Exit 0 on success; one JSON row on stdout (the ``pipeline_failover``
row of ``benchmarks/run.py``).

Usage:  python scripts/chaos_smoke.py [--quick] [--count N]
            [--stage-delay-s 0.4]
"""

import argparse
import json
import os
import signal
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

from defer_tpu import partition  # noqa: E402
from defer_tpu.models import resnet_tiny  # noqa: E402
from defer_tpu.runtime.node import run_chain  # noqa: E402


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _failover_events(stats_rows: list) -> list[dict]:
    """Every ``failover`` flight-recorder event the teardown stats
    carried (the healed fan-out lives in the upstream stage's
    process; its events ride that node's stats payload)."""
    out = []
    for row in stats_rows:
        if not isinstance(row, dict):
            continue
        for e in (row.get("events") or {}).get("events", []):
            if e.get("kind") == "failover":
                out.append(e)
    return out


# ---------------------------------------------------------------------------
# leg 1: kill -9 a mid-chain replica, multi-process
# ---------------------------------------------------------------------------

def run_failover(count: int, stage_delay_s: float, kill_at: int) -> dict:
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    stages = partition(g, num_stages=3)
    rng = np.random.default_rng(0)
    xs = [rng.standard_normal((1,) + stages[0].in_spec.shape)
          .astype(np.float32) for _ in range(count)]
    started = threading.Event()

    def feeder():
        for i, x in enumerate(xs):
            if i == kill_at:
                started.set()
            yield x

    def on_spawn(procs):
        # procs are one per stage REPLICA in stage-major order:
        # [s0, s1.r0, s1.r1, s2] — kill stage 1, replica 0
        def killer():
            started.wait(180)
            time.sleep(0.3)
            log(f"chaos: SIGKILL pid {procs[1].pid} (stage 1, replica 0)")
            procs[1].send_signal(signal.SIGKILL)
        threading.Thread(target=killer, daemon=True).start()

    stats: list = []
    with tempfile.TemporaryDirectory() as tmp:
        t0 = time.perf_counter()
        outs = run_chain(stages, params, feeder(), batch=1,
                         replicas={1: 2}, failover=True,
                         on_spawn=on_spawn, artifact_dir=tmp,
                         stage_delays=[0.0, stage_delay_s, 0.0],
                         stats_out=stats)
        wall_s = time.perf_counter() - t0
    with tempfile.TemporaryDirectory() as tmp:
        ref = run_chain(stages, params, list(xs), batch=1,
                        artifact_dir=tmp)
    if len(outs) != count or len(ref) != count:
        raise SystemExit(f"FAIL: {len(outs)} outputs, {len(ref)} "
                         f"reference, wanted {count}")
    for i, (a, b) in enumerate(zip(outs, ref)):
        np.testing.assert_array_equal(a, b, err_msg=f"sample {i}")
    evs = _failover_events(stats)
    if not evs:
        raise SystemExit("FAIL: stream survived but no `failover` event "
                         "reached the teardown stats — the kill missed "
                         "the in-flight window (raise --stage-delay-s)")
    ev = evs[-1]["data"]
    log(f"chaos: byte-identical x{count}, {len(evs)} failover(s), "
        f"replayed={ev.get('replayed')}, "
        f"recovery={ev.get('recovery_ms')}ms, wall={wall_s:.1f}s")
    return {"byte_identical": True, "count": count,
            "failovers": len(evs),
            "replayed": int(ev.get("replayed", 0)),
            "recovery_ms": float(ev.get("recovery_ms", 0.0)),
            "wall_s": round(wall_s, 2)}


# ---------------------------------------------------------------------------
# leg 2: live replan cutover, in-process persist chain
# ---------------------------------------------------------------------------

def run_replan(count: int) -> dict:
    from defer_tpu.graph.analysis import valid_cut_points
    from defer_tpu.plan.cost import StageCostModel
    from defer_tpu.plan.replan import LiveReplan
    from defer_tpu.plan.solver import evaluate_cuts, solve
    from defer_tpu.runtime.node import ChainDispatcher, StageNode

    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    cost = StageCostModel(g)
    plan1 = solve(g, 3, cost)
    valid = [c for c in g.topo_order if c in set(valid_cut_points(g))]
    cuts2 = next(([a, b] for i, a in enumerate(valid)
                  for b in valid[i + 1:]
                  if [a, b] != list(plan1.cuts)), None)
    if cuts2 is None:
        raise SystemExit("FAIL: no alternative cut pair on resnet_tiny")
    plan2 = evaluate_cuts(g, cuts2, cost)
    rng = np.random.default_rng(7)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(count)]
    cut = count // 2

    def boot(persist: bool):
        nodes = [StageNode(None, "127.0.0.1:0", None, persist=persist)
                 for _ in range(3)]
        addrs = [f"127.0.0.1:{n.address[1]}" for n in nodes]
        ths = [threading.Thread(target=n.serve, daemon=True)
               for n in nodes]
        for t in ths:
            t.start()
        return addrs, ths

    addrs, ths = boot(True)
    disp = ChainDispatcher(addrs[0], codec="raw")
    disp.deploy(partition(g, list(plan1.cuts)), params, addrs, batch=1)
    live = LiveReplan(disp, g, params, addrs, batch=1)
    outs = disp.stream(xs[:cut])
    receipt = live.apply(plan2)
    outs += disp.stream(xs[cut:])
    disp.close()
    live.shutdown()
    for t in ths:
        t.join(timeout=30)

    def plain(cuts, inputs):
        p_addrs, p_ths = boot(False)
        d = ChainDispatcher(p_addrs[0], codec="raw")
        d.deploy(partition(g, list(cuts)), params, p_addrs, batch=1)
        got = d.stream(inputs)
        d.close()
        for t in p_ths:
            t.join(timeout=30)
        return got

    ref = plain(plan1.cuts, xs[:cut]) + plain(plan2.cuts, xs[cut:])
    for i, (a, b) in enumerate(zip(outs, ref)):
        np.testing.assert_array_equal(a, b, err_msg=f"sample {i}")
    log(f"chaos: replan byte-identical x{count}, "
        f"cutover={receipt['cutover_ms']}ms, "
        f"quiesced={receipt['quiesced']}")
    return {"replan_byte_identical": True,
            "cutover_ms": float(receipt["cutover_ms"]),
            "quiesced": receipt["quiesced"],
            "old_cuts": list(plan1.cuts),
            "new_cuts": list(plan2.cuts)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CI budget: fewer frames, single kill trial")
    ap.add_argument("--count", type=int, default=0,
                    help="frames per leg (0 = 16 quick / 24 full)")
    ap.add_argument("--stage-delay-s", type=float, default=0.4,
                    help="per-frame stage-1 delay keeping the kill "
                         "inside the in-flight window")
    args = ap.parse_args()
    count = args.count or (16 if args.quick else 24)

    t0 = time.time()
    fo = run_failover(count, args.stage_delay_s, kill_at=count // 3)
    rp = run_replan(max(8, count // 2))
    row = {"metric": "pipeline_failover",
           "value": round(fo["recovery_ms"], 3),
           "unit": "ms recovery",
           **fo, **rp,
           "elapsed_s": round(time.time() - t0, 1)}
    print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
