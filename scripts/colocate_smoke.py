"""Colocated fast-path smoke: prove the transport tiers pay.

A 3-stage resnet_tiny chain is made codec-delay-bound the same way
``replication_smoke.py`` does: stage 0's outbound hop uses a decode-side
delay codec (``dsleep<ms>+raw``) and stage 1's an encode-side one
(``esleep<ms>+raw``), so every frame charges the chain a fixed non-CPU
delay per inter-stage hop — the resource profile of real host
serialization cost, expressible on a 1-core box.  The colocated tiers
eliminate exactly that cost: a ``local`` hop hands the live array
through an in-memory channel (no codec runs at all) and a ``device`` hop
fuses the two stages into one jit program (no hop at all).

Checks:

1. QUICK / LOCAL (in-process thread chain): the same inputs through the
   all-TCP chain and the all-``auto`` chain (every hop negotiates
   ``local``) — byte-identical outputs, every stats row reports the
   negotiated ``local`` tier, zero ``codec.*`` histogram samples on the
   colocated run, and min-of-3 wall ≥ ``--quick-min-speedup`` faster.

2. FUSED (in-process): ``hop_tiers=["device","device"]`` collapses the
   chain to ONE stage program — byte-identical to the 3-stage TCP chain,
   and the inter-stage frame provably GONE: zero wire tensor frames
   during the stream, fewer local frames than the unfused local chain,
   and no ``stage1.*``/``stage2.*`` or ``.rx``/``.tx`` spans in the
   collected trace (span/counter absence, not just speed).

3. PLANNER: given the hop-tier map, the solver's colocated plan predicts
   a bottleneck ≤ (strictly < on this comm-bound model) the TCP-only
   plan's — cut placement exploits colocation.

4. FULL (multi-process, skipped with ``--quick``): the same chain as
   real OS processes — 3 separate processes over TCP vs ONE process
   hosting all 3 stages (``node --co-stage``, hops negotiated local via
   the tier_probe handshake) — byte-identical outputs, negotiated tiers
   visible in ``stats``, measured speedup ≥ ``--min-speedup`` (1.5).

Exit 0 on success; one JSON row on stdout (the ``colocated_fastpath``
row of ``benchmarks/run.py``).

Usage:  python scripts/colocate_smoke.py [--quick] [--delay-ms D]
            [--count N] [--min-speedup 1.5]
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: stage-node subprocesses must never touch a (single-client) TPU tunnel
CPU_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def hop_codecs(delay_ms: float) -> list[str]:
    """Per-stage outbound codecs charging ``delay_ms`` of non-CPU codec
    time to each inter-stage hop (decode-side on hop 0->1, encode-side
    on hop 1->2); the result hop stays raw."""
    return [f"dsleep{delay_ms:g}+raw", f"esleep{delay_ms:g}+raw", "raw"]


# ---------------------------------------------------------------------------
# in-process chains
# ---------------------------------------------------------------------------

def run_inproc(stages, params, xs, *, tier: str, codecs, streams: int = 3):
    """Thread-per-node chain under ``tier``; streams ``xs`` ``streams``
    times (after a warm stream) and keeps the MIN wall — single-stream
    walls jitter >15% on this 1-core box.  Returns (outs, wall, stats).
    """
    from defer_tpu.runtime.node import ChainDispatcher, StageNode

    nodes = [StageNode(None, "127.0.0.1:0", None, tier=tier)
             for _ in range(len(stages))]
    addrs = [f"127.0.0.1:{n.address[1]}" for n in nodes]
    threads = [threading.Thread(target=n.serve, daemon=True)
               for n in nodes]
    for t in threads:
        t.start()
    disp = ChainDispatcher(addrs[0], codec="raw", tier=tier)
    try:
        disp.deploy(stages, params, addrs, batch=xs[0].shape[0],
                    codecs=codecs, tiers=[tier] * len(stages))
        disp.stream(xs[:2])  # warm: compile + connect + negotiate
        wall = float("inf")
        for _ in range(streams):
            t0 = time.perf_counter()
            outs = disp.stream(xs)
            wall = min(wall, time.perf_counter() - t0)
        stats = disp.stats(addrs)
    finally:
        disp.close()
    for t in threads:
        t.join(timeout=60)
    return outs, wall, stats


def quick_check(stages, params, xs, *, delay_ms: float,
                min_speedup: float) -> dict:
    import numpy as np

    from defer_tpu.obs import REGISTRY

    codecs = hop_codecs(delay_ms)
    base, base_s, base_st = run_inproc(stages, params, xs, tier="tcp",
                                       codecs=codecs)
    enc0 = REGISTRY.histogram("codec.encode_s").summary().get("count", 0)
    loc, loc_s, loc_st = run_inproc(stages, params, xs, tier="local",
                                    codecs=codecs)
    enc1 = REGISTRY.histogram("codec.encode_s").summary().get("count", 0)

    assert len(base) == len(loc) == len(xs)
    for a, b in zip(base, loc):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    tiers = [s["tier"] for s in loc_st]
    assert tiers == ["local"] * 3, f"hops did not negotiate local: {tiers}"
    assert enc1 == enc0, (
        f"local hops recorded {enc1 - enc0} codec.encode_s samples; "
        f"the colocated path must do ZERO codec work")
    speedup = base_s / loc_s
    log(f"quick: tcp {len(xs) / base_s:6.1f} inf/s, local "
        f"{len(xs) / loc_s:6.1f} inf/s -> {speedup:.2f}x")
    assert speedup >= min_speedup, (
        f"colocated speedup {speedup:.3f}x under the {min_speedup}x bar "
        f"(tcp {base_s:.3f}s vs local {loc_s:.3f}s)")
    return {"tcp_s": round(base_s, 4), "local_s": round(loc_s, 4),
            "speedup": round(speedup, 4), "tiers": tiers}


def fused_check(stages, params, xs, *, delay_ms: float, base) -> dict:
    """Device-tier fusion: the inter-stage frames must be GONE —
    asserted by span and counter ABSENCE, not timing."""
    import numpy as np

    from defer_tpu.obs import REGISTRY, enable_tracing, tracer
    from defer_tpu.partition import fuse_stages

    fused, groups = fuse_stages(stages, ["device", "device"])
    assert len(fused) == 1, groups
    tr = enable_tracing(process="dispatcher")
    tr.start_trace()
    tx0 = REGISTRY.counter("transport.tx_frames").value
    lf0 = REGISTRY.counter("transport.local_frames").value
    outs, wall, stats = run_inproc(fused, params, xs, tier="local",
                                   codecs=["raw"], streams=1)
    tx_frames = REGISTRY.counter("transport.tx_frames").value - tx0
    local_frames = REGISTRY.counter("transport.local_frames").value - lf0
    spans = {s.get("name", "") for s in tracer().drain()}
    tr.enabled = False

    for a, b in zip(base, outs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # frame elimination: the fused+local chain moved ZERO tensor frames
    # over any wire (the one deploy blob is the only wire frame), and
    # only 2 local hops remain (disp -> fused stage -> result)
    assert tx_frames <= 2, f"{tx_frames} wire frames on a fused chain"
    assert local_frames == 2 * (len(xs) + 2), (
        f"expected 2 hops x {len(xs) + 2} frames through local pipes, "
        f"got {local_frames}")
    gone = [n for n in spans
            if n.startswith(("stage1.", "stage2."))
            or n.endswith((".rx", ".tx", ".rx_wait", ".tx_wait"))]
    assert not gone, f"fused chain still recorded hop spans: {gone}"
    assert any(n.startswith("stage0.infer") for n in spans), spans
    log(f"fused: 1 stage, wire tensor frames 0 (+{tx_frames} ctrl/blob), "
        f"{local_frames} local handoffs, no stage1/stage2 or rx/tx spans")
    return {"stages": len(fused), "wire_frames": tx_frames,
            "local_frames": local_frames}


# ---------------------------------------------------------------------------
# planner: the hop-tier map changes the plan
# ---------------------------------------------------------------------------

def planner_check() -> dict:
    from defer_tpu import GraphBuilder
    from defer_tpu.graph import ops
    from defer_tpu.plan import StageCostModel, solve

    b = GraphBuilder("fatcut")
    x = b.input((4096,))
    for i in range(3):
        x = b.add(ops.Dense(4096), x, name=f"d{i}")
    x = b.add(ops.Dense(8), x, name="head")
    g = b.build()
    costs = {"d0": 1e-3, "d1": 1e-3, "d2": 1e-3, "head": 1e-4}
    cm = StageCostModel(g, gen="v4", link_bw_s=1e6, node_costs=costs)
    p_tcp = solve(g, 3, cm)
    p_colo = solve(g, 3, cm,
                   hop_tiers={c: "local" for c in ("d0", "d1", "d2")})
    assert p_colo.bottleneck_s <= p_tcp.bottleneck_s, (
        p_colo.bottleneck_s, p_tcp.bottleneck_s)
    assert p_colo.bottleneck_s < p_tcp.bottleneck_s, (
        "comm-bound model: the colocated plan must be strictly better")
    log(f"planner: tcp bottleneck {p_tcp.bottleneck_s * 1e3:.3f} ms "
        f"({p_tcp.bound_by}-bound) vs colocated "
        f"{p_colo.bottleneck_s * 1e3:.3f} ms ({p_colo.bound_by}-bound), "
        f"hop tiers {p_colo.hop_tiers}")
    return {"tcp_bottleneck_ms": round(p_tcp.bottleneck_s * 1e3, 4),
            "colocated_bottleneck_ms": round(p_colo.bottleneck_s * 1e3, 4),
            "predicted_speedup": round(
                p_tcp.bottleneck_s / p_colo.bottleneck_s, 4),
            "hop_tiers": p_colo.hop_tiers}


# ---------------------------------------------------------------------------
# multi-process: one colocated process vs three TCP processes
# ---------------------------------------------------------------------------

def _free_ports(n):
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def timed_chain(paths, xs_warm, xs, *, colocate: bool, delay_ms: float,
                log_dir: str):
    """Spawn the 3-stage chain — 3 OS processes (TCP hops) or ONE
    process hosting all 3 stages (``--co-stage``, local hops) — warm it,
    stream ``xs`` timed, tear down.  Returns (outputs, seconds, stats)."""
    from defer_tpu.runtime.node import (ChainDispatcher, _await_binds,
                                        _kill_procs)

    codecs = hop_codecs(delay_ms)
    ports = _free_ports(4)
    addrs = [f"127.0.0.1:{p}" for p in ports[:3]]
    result = f"127.0.0.1:{ports[3]}"
    nxt = addrs[1:] + [result]
    # dispatcher edges are always cross-process: keep them on "auto"
    # (they negotiate shm as before) — only the IN-process co-stage
    # hops pin "local", since auto's top rung is now the ici tier
    tier = "auto" if colocate else "tcp"
    if colocate:
        argv = [sys.executable, "-m", "defer_tpu", "node",
                "--artifact", paths[0], "--listen", addrs[0],
                "--next", nxt[0], "--codec", codecs[0], "--tier", "local"]
        for k in (1, 2):
            # the LAST housemate's outbound is the result edge (cross-
            # process): a "local" pin there could only degrade to tcp
            co_tier = "local" if k < 2 else "auto"
            argv += ["--co-stage",
                     f"listen={addrs[k]};artifact={paths[k]}"
                     f";next={nxt[k]};codec={codecs[k]};tier={co_tier}"
                     f";accept=1"]
        argvs = [argv]
        proc_of = [0, 0, 0]
    else:
        argvs = [[sys.executable, "-m", "defer_tpu", "node",
                  "--artifact", paths[k], "--listen", addrs[k],
                  "--next", nxt[k], "--codec", codecs[k], "--tier", "tcp"]
                 for k in range(3)]
        proc_of = [0, 1, 2]

    child_env = dict(os.environ)
    child_env.update(CPU_ENV)
    mode = "coloc" if colocate else "tcp"
    procs, logs = [], []
    failed = True
    try:
        for i, a in enumerate(argvs):
            lf = open(os.path.join(log_dir, f"{mode}_proc_{i}.log"), "w+")
            logs.append(lf)
            procs.append(subprocess.Popen(a, env=child_env, stdout=lf,
                                          stderr=subprocess.STDOUT))
        _await_binds(procs, [f"stage{k}" for k in range(3)], logs, addrs,
                     proc_of=proc_of)
        disp = ChainDispatcher(addrs[0], listen=result, codec="raw",
                               tier=tier)
        try:
            disp.stream(xs_warm)  # boot+compile+negotiation excluded
            t0 = time.perf_counter()
            outs = disp.stream(xs)
            dt = time.perf_counter() - t0
            stats = disp.stats(addrs)
            failed = False
        finally:
            if failed:
                _kill_procs(procs)
            disp.close()
            if not failed:
                for pr in procs:
                    try:
                        pr.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        pr.kill()
    except BaseException:
        _kill_procs(procs)
        raise
    finally:
        for lf in logs:
            lf.close()
    return outs, dt, stats


def speedup_check(stages, params, *, count: int, batch: int,
                  delay_ms: float, min_speedup: float) -> dict:
    import numpy as np

    from defer_tpu.runtime.node import _BindRace
    from defer_tpu.utils.export import export_pipeline

    def with_retry(**kw):
        for attempt in range(3):
            try:
                return timed_chain(**kw)
            except _BindRace as e:
                log(f"bind race on attempt {attempt + 1} ({e}); retrying")
        return timed_chain(**kw)

    rng = np.random.default_rng(1)
    xs = [rng.standard_normal((batch, 32, 32, 3)).astype(np.float32)
          for _ in range(count)]
    xs_warm = xs[:4]
    with tempfile.TemporaryDirectory(prefix="defer_colo_") as tmp:
        paths = export_pipeline(stages, params, tmp, batch=batch)
        base, base_s, _ = with_retry(paths=paths, xs_warm=xs_warm, xs=xs,
                                     colocate=False, delay_ms=delay_ms,
                                     log_dir=tmp)
        log(f"3-process tcp:      {count * batch / base_s:8.1f} inf/s "
            f"({base_s:.2f}s)")
        colo, colo_s, stats = with_retry(paths=paths, xs_warm=xs_warm,
                                         xs=xs, colocate=True,
                                         delay_ms=delay_ms, log_dir=tmp)
        log(f"1-process colocated:{count * batch / colo_s:8.1f} inf/s "
            f"({colo_s:.2f}s)")
    assert len(base) == len(colo) == count
    for a, b in zip(base, colo):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    tiers = {s["stage"]: s["tier"] for s in stats}
    # both inter-stage hops negotiated local inside the one process (the
    # result hop crosses back to the dispatcher process -> tcp)
    assert tiers[0] == "local" and tiers[1] == "local", tiers
    speedup = base_s / colo_s
    log(f"negotiated tiers {tiers} -> {speedup:.3f}x")
    assert speedup >= min_speedup, (
        f"colocated speedup {speedup:.3f}x is under the {min_speedup}x "
        f"bar (tcp {count * batch / base_s:.1f} inf/s, colocated "
        f"{count * batch / colo_s:.1f} inf/s)")
    return {"tcp_s": base_s, "colocated_s": colo_s,
            "speedup": round(speedup, 4),
            "tcp_inf_s": round(count * batch / base_s, 2),
            "colocated_inf_s": round(count * batch / colo_s, 2),
            "tiers": {str(k): v for k, v in sorted(tiers.items())}}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--min-speedup", type=float, default=1.5,
                    help="required colocated/tcp throughput ratio "
                         "(multi-process chain)")
    ap.add_argument("--quick-min-speedup", type=float, default=1.5,
                    help="required ratio for the in-process quick check "
                         "(delay-dominated, so the bar holds even with "
                         "1-core scheduling noise)")
    ap.add_argument("--count", type=int, default=24,
                    help="timed microbatches through each chain")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--delay-ms", type=float, default=25.0,
                    help="per-hop codec delay the fast path eliminates")
    ap.add_argument("--quick", action="store_true",
                    help="in-process + planner checks only (no spawns)")
    args = ap.parse_args()

    import numpy as np

    import jax

    from defer_tpu import partition
    from defer_tpu.models import resnet_tiny

    graph = resnet_tiny()
    params = graph.init(jax.random.key(0))
    stages = partition(graph, num_stages=3)

    rng = np.random.default_rng(0)
    q_count, q_batch = min(args.count, 12), min(args.batch, 2)
    xs = [rng.standard_normal((q_batch, 32, 32, 3)).astype(np.float32)
          for _ in range(q_count)]
    r_quick = quick_check(stages, params, xs,
                          delay_ms=min(args.delay_ms, 15.0),
                          min_speedup=args.quick_min_speedup)
    base, _, _ = run_inproc(stages, params, xs, tier="tcp",
                            codecs=["raw"] * 3, streams=1)
    r_fused = fused_check(stages, params, xs, delay_ms=args.delay_ms,
                          base=base)
    r_plan = planner_check()

    row = {"metric": "colocated_fastpath", "unit": "x_vs_tcp_chain",
           "stages": len(stages), "hop_tiers": ["local", "local"],
           "count": args.count, "batch": args.batch,
           "delay_ms": args.delay_ms,
           "cpu_count": os.cpu_count() or 1,
           "quick": r_quick, "fused": r_fused, "planner": r_plan}
    if args.quick:
        row["value"] = None
    else:
        r = speedup_check(stages, params, count=args.count,
                          batch=args.batch, delay_ms=args.delay_ms,
                          min_speedup=args.min_speedup)
        row.update({"value": r["speedup"], **{
            k: v for k, v in r.items() if k != "speedup"}})
    print(json.dumps(row))
    log("colocated fast-path smoke: OK")


if __name__ == "__main__":
    main()
