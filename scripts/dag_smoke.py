"""DAG-pipeline smoke: prove branch-parallel stage graphs pay.

A linear cut cannot split the parallel branches of a fork/join region
(``graph.analysis.branch_regions``), so a branching model's region body
serializes inside one stage.  The DAG planner (``plan/dag.py``) instead
mirrors the graph: a broadcast fork, one concurrent sub-pipeline per
branch, an all-paths ``(path, seq)`` join (``transport/branch.py``).
This smoke makes that win measurable on a 1-core host with the
delay-bound pattern (see replication_smoke.py): the two conv branches of
inception_tiny's ``mixed_3`` reduction region each cost a fixed
simulated device delay (``node --infer-delay-ms``, sleeping — not
spinning — so concurrent branch processes overlap like real
accelerators), and the planner scores the same delays as ``node_costs``
— prediction and deployment share one cost regime.

Checks:

1. PLANNER (predictive): with uniform per-heavy-op device delays,
   ``solve_dag``'s critical-path plan STRICTLY beats the best linear
   plan's predicted bottleneck on inception_tiny and on the branched
   MoE family (``moe_branched_tiny`` — the DAG-visible formulation of
   moe_tiny's fused MoE layer, one expert per branch); on the fused
   ``moe_tiny`` itself (no separable regions) the DAG solver degrades
   to exactly the linear plan — never worse.

2. QUICK (in-process thread nodes): the two-branch delay-bound
   inception_tiny chain deployed branch-parallel
   (``ChainDispatcher.deploy_topology``) vs the best linear-cut chain
   at the SAME node count — byte-identical outputs vs the serial
   composition of the deployment's own stage programs (exact), tight
   allclose vs the fused single program, and min-of-3-streams wall
   >= ``--quick-min-speedup`` better.

3. FULL (multi-process, skipped with ``--quick``): the same comparison
   with every topology vertex as a real ``defer_tpu node`` OS process
   (the deployment shape ``chain --dag`` ships), min-of-3 streams,
   measured speedup >= ``--min-speedup`` (default 1.5).  The delays
   sleep rather than burn CPU, so the win is real on a 1-core host.

Exit 0 on success; one JSON row on stdout (the ``dag_pipeline`` row of
``benchmarks/run.py``), recording planned vs linear critical path.

Usage:  python scripts/dag_smoke.py [--quick] [--delay-ms D] [--count N]
            [--min-speedup 1.5] [--quick-min-speedup 1.45]
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: stage-node subprocesses must never touch a (single-client) TPU tunnel
CPU_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}

TINY = 1e-6   #: per-node seconds for every non-heavy op


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def delay_costs(graph, heavy: dict) -> dict:
    """Uniform delay-bound cost map: ``heavy`` (node -> seconds) on the
    simulated-device ops, ``TINY`` elsewhere — the regime where both the
    planner's prediction and the deployed chain are bound by the same
    per-frame device time."""
    return {n: heavy.get(n, TINY) for n in graph.topo_order}


def two_branch_delays(graph, delay_s: float, join: str = "mixed_3"):
    """Per-node delays putting ``delay_s`` of simulated device time on
    EACH of the two conv branches of inception_tiny's ``join`` reduction
    region (the pool branch stays free): a linear stage must serialize
    2*delay_s, concurrent branch processes pay delay_s."""
    from defer_tpu.graph.analysis import branch_regions
    region = next(r for r in branch_regions(graph) if r.join == join)
    heavy = {}
    for b in region.branches[:2]:
        for n in b.nodes:
            heavy[n] = delay_s / len(b.nodes)
    return region, heavy


# ---------------------------------------------------------------------------
# part 1: the planner strictly beats the linear plan on branching graphs
# ---------------------------------------------------------------------------

def planner_check(delay_s: float) -> dict:
    from defer_tpu.models import inception_tiny, moe_branched_tiny, moe_tiny
    from defer_tpu.plan import StageCostModel, best_linear_plan, solve_dag

    out = {}
    cases = []
    g = inception_tiny()
    _, heavy = two_branch_delays(g, delay_s)
    cases.append((g, heavy, 5))
    g = moe_branched_tiny()
    heavy = {n: delay_s for n in g.topo_order
             if n.startswith("block_") or "_e" in n}
    # 12 processes: both 4-expert regions fan out (3 trunk segments +
    # 8 expert branches); under that, the serialized experts floor both
    # planners equally
    cases.append((g, heavy, 12))
    for g, heavy, budget in cases:
        cm = StageCostModel(g, gen="v5e", link_bw_s=1e12,
                            node_costs=delay_costs(g, heavy))
        dag = solve_dag(g, cm, num_nodes=budget)
        lin = best_linear_plan(g, cm, budget)
        assert dag.bottleneck_s < lin.bottleneck_s, (
            f"{g.name}: DAG bottleneck {dag.bottleneck_s * 1e3:.3f} ms "
            f"does not strictly beat linear "
            f"{lin.bottleneck_s * 1e3:.3f} ms at {budget} nodes")
        assert dag.parallel_regions, g.name
        log(f"planner: {g.name} @ {budget} nodes: DAG "
            f"{dag.bottleneck_s * 1e3:.3f} ms (cp "
            f"{dag.critical_path_s * 1e3:.3f} ms) vs linear "
            f"{lin.bottleneck_s * 1e3:.3f} ms -> "
            f"{lin.bottleneck_s / dag.bottleneck_s:.3f}x")
        out[g.name] = {
            "budget": budget,
            "dag_bottleneck_ms": round(dag.bottleneck_s * 1e3, 4),
            "dag_critical_path_ms": round(dag.critical_path_s * 1e3, 4),
            "linear_bottleneck_ms": round(lin.bottleneck_s * 1e3, 4),
            "predicted_speedup": round(
                lin.bottleneck_s / dag.bottleneck_s, 4)}

    # the fused MoE has no separable regions: the DAG solver must
    # degrade to exactly the linear plan, never worse
    g = moe_tiny()
    cm = StageCostModel(g, gen="v5e")
    dag = solve_dag(g, cm, num_nodes=4)
    lin = best_linear_plan(g, cm, 4)
    assert not dag.parallel_regions
    assert abs(dag.bottleneck_s - lin.bottleneck_s) <= 1e-12, (
        dag.bottleneck_s, lin.bottleneck_s)
    log(f"planner: {g.name} has no separable regions -> DAG degenerates "
        f"to the linear plan ({dag.num_stages} stages), as it must")
    out[g.name] = {"degenerate_linear": True,
                   "bottleneck_ms": round(dag.bottleneck_s * 1e3, 4)}
    return out


# ---------------------------------------------------------------------------
# shared: build the two deployments (branch-parallel vs best linear)
# ---------------------------------------------------------------------------

def build_deployments(delay_s: float):
    """(graph, params, dag topology+delays, linear topology+delays).

    Both topologies come from the SAME delay-bound cost model and the
    same node budget; per-vertex delays are the summed per-node delays
    of the vertex's slice, so the deployed chains are bound by exactly
    the seconds the planner scored."""
    import jax

    from defer_tpu import partition
    from defer_tpu.models import inception_tiny
    from defer_tpu.plan import StageCostModel, best_linear_plan, solve_dag
    from defer_tpu.runtime.topology import ChainTopology

    graph = inception_tiny()
    _, heavy = two_branch_delays(graph, delay_s)
    costs = delay_costs(graph, heavy)
    cm = StageCostModel(graph, gen="v5e", link_bw_s=1e12,
                        node_costs=costs)
    budget = 5
    dag = solve_dag(graph, cm, num_nodes=budget)
    assert dag.parallel_regions, dag.to_json()
    dag_topo = ChainTopology.from_json(dag.topology_json())
    dag_delays = {v.vid: sum(heavy.get(n, 0.0) for n in v.nodes)
                  for v in dag_topo.vertices}

    lin = best_linear_plan(graph, cm, budget)
    lin_stages = partition(graph, lin.cuts if lin.num_stages > 1 else [])
    lin_topo = ChainTopology.linear(lin_stages)
    lin_delays = {v.vid: sum(heavy.get(n, 0.0) for n in v.nodes)
                  for v in lin_topo.vertices}

    params = graph.init(jax.random.key(0))
    pred = {"dag_bottleneck_ms": round(dag.bottleneck_s * 1e3, 4),
            "dag_critical_path_ms": round(dag.critical_path_s * 1e3, 4),
            "linear_bottleneck_ms": round(lin.bottleneck_s * 1e3, 4),
            "linear_stages": lin.num_stages, "budget": budget,
            "dag_labels": [v.label for v in dag_topo.vertices]}
    return graph, params, (dag_topo, dag_delays), \
        (lin_topo, lin_delays), pred


def min_of_3_streams(disp, xs) -> float:
    """Min wall over 3 identical streams on one live deployment (this
    1-core host jitters >15% on single streams — BASELINE lesson)."""
    walls = []
    for _ in range(3):
        t0 = time.perf_counter()
        disp.stream(xs)
        walls.append(time.perf_counter() - t0)
    return min(walls)


def serial_reference(topo, stages, params, xs, batch: int):
    """Outputs of the serial composition of the deployment's OWN stage
    programs — the byte-identity reference (per-stage StableHLO vs the
    fused single program differ ~1e-6 in fusion, so THIS is the exact
    contract a distributed deployment must honor)."""
    import numpy as np

    from defer_tpu.utils.export import export_stage_bytes, \
        load_stage_program

    progs = [load_stage_program(export_stage_bytes(s, params, batch=batch))
             for s in stages]
    graph_input = topo.entry.inputs[0]
    outs = []
    for x in xs:
        vals = {}
        for v, p in zip(topo.vertices, progs):
            ins = [x if name == graph_input else vals[name]
                   for name in v.inputs]
            vals[v.output] = np.asarray(p(*ins))
        outs.append(vals[topo.exit.output])
    return outs


# ---------------------------------------------------------------------------
# part 2: in-process thread chains (quick mode)
# ---------------------------------------------------------------------------

def run_inproc(graph, params, topo, delays, xs, batch: int):
    """Thread-per-vertex deployment of ``topo``; returns (outs,
    min-of-3 wall seconds, stats)."""
    from defer_tpu.runtime.node import ChainDispatcher, StageNode

    stages = topo.stage_specs(graph)
    nodes = [StageNode(None, "127.0.0.1:0", None)
             for _ in topo.vertices]
    addrs = [f"127.0.0.1:{n.address[1]}" for n in nodes]
    threads = [threading.Thread(target=n.serve, daemon=True)
               for n in nodes]
    for t in threads:
        t.start()
    disp = ChainDispatcher(addrs[0], codec="raw")
    try:
        disp.deploy_topology(topo, stages, params, addrs, batch=batch,
                             stage_delays=delays)
        outs = disp.stream(xs)      # warm: compile + connect (untimed)
        wall = min_of_3_streams(disp, xs)
        stats = disp.stats(addrs)
    finally:
        disp.close()
    for t in threads:
        t.join(timeout=60)
    return outs, wall, stats


def quick_check(graph, params, dag_dep, lin_dep, *, count: int,
                batch: int, min_speedup: float) -> dict:
    import numpy as np

    dag_topo, dag_delays = dag_dep
    lin_topo, lin_delays = lin_dep
    rng = np.random.default_rng(0)
    in_spec = graph.out_spec(dag_topo.entry.inputs[0])
    xs = [rng.standard_normal((batch,) + in_spec.shape).astype(np.float32)
          for _ in range(count)]

    lin_outs, lin_wall, _ = run_inproc(graph, params, lin_topo,
                                       lin_delays, xs, batch)
    dag_outs, dag_wall, stats = run_inproc(graph, params, dag_topo,
                                           dag_delays, xs, batch)
    assert len(dag_outs) == len(lin_outs) == count

    # byte-identity: the branched deployment == serial composition of
    # its own stage programs, exactly
    ref = serial_reference(dag_topo, dag_topo.stage_specs(graph),
                           params, xs, batch)
    for a, b in zip(ref, dag_outs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and tight allclose vs the fused single-program forward
    import jax
    fwd = jax.jit(graph.apply)
    worst = max(float(np.abs(np.asarray(fwd(params, x)) - y).max())
                for x, y in zip(xs, dag_outs))
    assert worst < 1e-4, worst

    # every branch vertex processed every frame (broadcast, not split)
    per_branch = {s.get("branch"): s.get("processed") for s in stats
                  if s.get("branch") is not None}
    warm_total = count * 4  # warm + 3 timed streams on one connection
    assert per_branch and all(v == warm_total for v in per_branch.values()
                              ), per_branch

    speedup = lin_wall / dag_wall
    log(f"quick: linear {count * batch / lin_wall:6.1f} inf/s, "
        f"branch-parallel {count * batch / dag_wall:6.1f} inf/s -> "
        f"{speedup:.3f}x (branch split {per_branch})")
    assert speedup >= min_speedup, (
        f"in-process branch-parallel speedup {speedup:.3f}x under the "
        f"{min_speedup}x bar (linear {lin_wall:.3f}s vs dag "
        f"{dag_wall:.3f}s)")
    return {"linear_s": round(lin_wall, 4), "dag_s": round(dag_wall, 4),
            "speedup": round(speedup, 4),
            "max_abs_err_vs_single_program": worst}


# ---------------------------------------------------------------------------
# part 3: multi-process deployment — the >= 1.5x measured claim
# ---------------------------------------------------------------------------

def _free_ports(n):
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def timed_procs(graph, params, topo, delays, xs, *, batch: int,
                log_dir: str):
    """Every topology vertex as a real ``defer_tpu node`` OS process
    (the ``chain --dag`` deployment shape): spawn, warm, min-of-3
    streams, teardown.  Returns (outs, wall_s)."""
    from defer_tpu.runtime.node import (ChainDispatcher, _await_binds,
                                        _kill_procs, dag_vertex_argv)
    from defer_tpu.utils.export import export_stage

    stages = topo.stage_specs(graph)
    vs = topo.vertices
    ports = _free_ports(len(vs) + 1)
    addrs = [f"127.0.0.1:{ports[i]}" for i in range(len(vs))]
    result = f"127.0.0.1:{ports[-1]}"

    argvs = []
    for v, stage in zip(vs, stages):
        path = os.path.join(log_dir, f"vertex_{v.vid}.zip")
        if not os.path.exists(path):
            export_stage(stage, params, path, batch=batch)
        argvs.append(dag_vertex_argv(v, path, addrs=addrs,
                                     result_addr=result, codec="raw",
                                     stage_delays=delays))

    child_env = dict(os.environ)
    child_env.update(CPU_ENV)
    procs, logs = [], []
    labels = [v.label for v in vs]
    failed = True
    try:
        for v, argv in zip(vs, argvs):
            lf = open(os.path.join(
                log_dir, f"node_{v.label.replace('.', '_')}.log"), "w+")
            logs.append(lf)
            procs.append(subprocess.Popen(argv, env=child_env, stdout=lf,
                                          stderr=subprocess.STDOUT))
        _await_binds(procs, labels, logs, addrs,
                     proc_of=list(range(len(vs))))
        disp = ChainDispatcher(addrs[0], listen=result, codec="raw")
        try:
            outs = disp.stream(xs)   # boot+compile excluded from window
            wall = min_of_3_streams(disp, xs)
            failed = False
        finally:
            if failed:
                _kill_procs(procs)   # dead sockets make close() fast
            disp.close()
            if not failed:
                for pr in procs:
                    try:
                        pr.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        pr.kill()
    except BaseException:
        _kill_procs(procs)
        raise
    finally:
        for lf in logs:
            lf.close()
    return outs, wall


def speedup_check(graph, params, dag_dep, lin_dep, *, count: int,
                  batch: int, min_speedup: float) -> dict:
    import numpy as np

    from defer_tpu.runtime.node import _BindRace

    def with_retry(**kw):
        for attempt in range(3):
            try:
                return timed_procs(**kw)
            except _BindRace as e:
                log(f"bind race on attempt {attempt + 1} ({e}); retrying")
        return timed_procs(**kw)

    dag_topo, dag_delays = dag_dep
    lin_topo, lin_delays = lin_dep
    rng = np.random.default_rng(1)
    in_spec = graph.out_spec(dag_topo.entry.inputs[0])
    xs = [rng.standard_normal((batch,) + in_spec.shape).astype(np.float32)
          for _ in range(count)]
    with tempfile.TemporaryDirectory(prefix="defer_dag_smoke_") as tmp:
        lin_dir = os.path.join(tmp, "lin")
        dag_dir = os.path.join(tmp, "dag")
        os.makedirs(lin_dir)
        os.makedirs(dag_dir)
        lin_outs, lin_wall = with_retry(
            graph=graph, params=params, topo=lin_topo, delays=lin_delays,
            xs=xs, batch=batch, log_dir=lin_dir)
        log(f"linear:          {count * batch / lin_wall:8.1f} inf/s "
            f"({lin_wall:.2f}s min-of-3)")
        dag_outs, dag_wall = with_retry(
            graph=graph, params=params, topo=dag_topo, delays=dag_delays,
            xs=xs, batch=batch, log_dir=dag_dir)
        log(f"branch-parallel: {count * batch / dag_wall:8.1f} inf/s "
            f"({dag_wall:.2f}s min-of-3)")
    assert len(dag_outs) == len(lin_outs) == count
    ref = serial_reference(dag_topo, dag_topo.stage_specs(graph),
                           params, xs, batch)
    for a, b in zip(ref, dag_outs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    speedup = lin_wall / dag_wall
    assert speedup >= min_speedup, (
        f"branch-parallel speedup {speedup:.3f}x under the "
        f"{min_speedup}x bar (linear {lin_wall:.2f}s vs dag "
        f"{dag_wall:.2f}s, min-of-3)")
    return {"linear_s": round(lin_wall, 4), "dag_s": round(dag_wall, 4),
            "speedup": round(speedup, 4),
            "linear_inf_s": round(count * batch / lin_wall, 2),
            "dag_inf_s": round(count * batch / dag_wall, 2)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--min-speedup", type=float, default=1.5,
                    help="required dag/linear wall ratio (multi-process)")
    ap.add_argument("--quick-min-speedup", type=float, default=1.45,
                    help="required ratio for the in-process quick check "
                         "(thread scheduling noise, slightly lower bar)")
    ap.add_argument("--count", type=int, default=12,
                    help="frames per timed stream")
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--delay-ms", type=float, default=40.0,
                    help="simulated device seconds per heavy branch")
    ap.add_argument("--quick", action="store_true",
                    help="planner + in-process checks only (no spawns)")
    args = ap.parse_args()

    delay_s = args.delay_ms / 1e3
    r_planner = planner_check(delay_s)
    graph, params, dag_dep, lin_dep, pred = build_deployments(delay_s)
    log(f"deploying {pred['dag_labels']} vs {pred['linear_stages']} "
        f"linear stages @ {pred['budget']} nodes")
    r_quick = quick_check(graph, params, dag_dep, lin_dep,
                          count=min(args.count, 10), batch=args.batch,
                          min_speedup=args.quick_min_speedup)

    row = {"metric": "dag_pipeline", "unit": "x_vs_linear_chain",
           "model": graph.name, "count": args.count, "batch": args.batch,
           "delay_ms": args.delay_ms, "cpu_count": os.cpu_count() or 1,
           "planned": pred, "planner": r_planner, "quick": r_quick}
    if args.quick:
        row["value"] = None
    else:
        r = speedup_check(graph, params, dag_dep, lin_dep,
                          count=args.count, batch=args.batch,
                          min_speedup=args.min_speedup)
        row.update({"value": r["speedup"],
                    **{k: v for k, v in r.items() if k != "speedup"}})
    print(json.dumps(row))
    log("dag pipeline smoke: OK")


if __name__ == "__main__":
    main()
