"""Decode steady-state X-ray: zero recompiles, accounted dispatches.

The mb64 bf16 decode cliff (docs/DECODE_CLIFF.md, DECODE_r05.json:
560 ms/token-step against 26 ms for the int8-KV variant of the SAME
shapes, with a 96.8 s first call) is a compile-side pathology, so the
guard this smoke pins down is the mechanism the cliff would have to
break through on the host side:

1. ZERO STEADY-STATE RECOMPILES: after one warmup ``generate``, a
   second ``generate`` with identical arguments must reach XLA ZERO
   times (the decode program cache is keyed by
   ``(chunk_steps, sample, top_k)`` — ``runtime/decode.py``), measured
   by the ``jax.monitoring`` compile listener, and must emit no
   ``recompile`` flight-recorder event while armed.
2. ACCOUNTED DISPATCHES: the steady-state run performs EXACTLY
   ``ceil(num_steps / chunk_steps)`` scan dispatches (the
   ``decode.dispatches`` counter) — no hidden per-token host round
   trips — and the summed ``decode.dispatch_s`` stays a sane share of
   the generation wall (<= ~1: dispatch cannot exceed the wall it is
   part of).

Shapes are the CPU-smoke geometry of ``scripts/bench_decode.py``
(gpt 4L / d=64 / 2 heads / vocab 128, mb=4, 16 new tokens,
token_chunk=32), so this is the same program family the TPU bench
drives — only the backend differs.  Exit 0 on success; one JSON row on
stdout (the ``decode_profile`` row of ``benchmarks/run.py``).
"""

import argparse
import json
import math
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="accepted for CI symmetry; the smoke is "
                         "already the small CPU geometry")
    ap.add_argument("--new-tokens", type=int, default=16)
    ap.add_argument("--microbatch", type=int, default=4)
    ap.add_argument("--token-chunk", type=int, default=32)
    ap.add_argument("--max-dispatch-share", type=float, default=1.02,
                    help="summed dispatch seconds / generation wall "
                         "upper bound (dispatch is part of the wall; "
                         "> 1 means double counting)")
    args = ap.parse_args()

    import numpy as np

    import jax

    from defer_tpu.models import gpt
    from defer_tpu.obs import recompile_watcher, recorder
    from defer_tpu.obs.registry import REGISTRY
    from defer_tpu.runtime.decode import PipelinedDecoder

    layers, d, heads, vocab = 4, 64, 2, 128
    max_len, plen = 48, 8
    mb, new = args.microbatch, args.new_tokens

    graph = gpt(layers, d, heads, max_len, vocab=vocab)
    params = graph.init(jax.random.key(0))
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, vocab, size=(mb, plen)).astype(np.int32)

    watcher = recompile_watcher()
    watcher.install()
    watcher.disarm()
    rec = recorder()

    dec = PipelinedDecoder(graph, params, num_stages=1, microbatch=mb,
                           max_len=max_len)
    kw = dict(max_new_tokens=new, token_chunk=args.token_chunk)

    t0 = time.perf_counter()
    toks = dec.generate(prompt, **kw)           # compile + run
    first_call_s = time.perf_counter() - t0
    assert toks.shape == (mb, plen + new), toks.shape
    c_warm = watcher.count
    assert c_warm > 0, (
        "warmup generate reached XLA zero times — the compile "
        "listener is not hooked, so the zero-recompile claim below "
        "would be vacuous")

    # steady state: identical args -> pure program-cache hits
    watcher.arm()
    ev0 = sum(1 for e in rec.snapshot() if e["kind"] == "recompile")
    d_count = REGISTRY.counter("decode.dispatches")
    d_hist = REGISTRY.histogram("decode.dispatch_s")
    n0, s0 = d_count.value, d_hist.summary().get("sum", 0.0)
    t0 = time.perf_counter()
    toks2 = dec.generate(prompt, **kw)
    wall_s = time.perf_counter() - t0
    recompiles = watcher.count - c_warm
    events = sum(1 for e in rec.snapshot()
                 if e["kind"] == "recompile") - ev0
    assert recompiles == 0, (
        f"steady-state generate hit XLA {recompiles} time(s) — the "
        f"decode program cache is not keying these calls identically")
    assert events == 0, f"{events} recompile event(s) in steady state"
    np.testing.assert_array_equal(toks, toks2)

    # dispatch accounting: the schedule's chunk count, nothing more
    dispatches = d_count.value - n0
    num_steps, chunk_steps = dec._schedule(plen + new, 0,
                                           args.token_chunk)
    want = math.ceil(num_steps / chunk_steps)
    assert dispatches == want, (
        f"steady-state generate made {dispatches} dispatches, "
        f"schedule says {want} ({num_steps} steps / {chunk_steps} "
        f"per chunk)")
    disp_s = d_hist.summary().get("sum", 0.0) - s0
    share = disp_s / wall_s
    assert share <= args.max_dispatch_share, (
        f"dispatch share {share:.3f} exceeds "
        f"{args.max_dispatch_share} — dispatch seconds larger than "
        f"the wall they live in")

    tps = mb * new / wall_s
    log(f"decode steady state: {tps:.1f} tok/s ({wall_s * 1e3:.1f} ms "
        f"for {new} tokens x mb{mb}), {dispatches} dispatches "
        f"(schedule {want}), dispatch share {share:.3f}, warmup "
        f"{c_warm} compiles in {first_call_s:.2f}s, steady recompiles "
        f"0, events 0")
    row = {"metric": "decode_profile", "value": round(tps, 2),
           "unit": "tokens/sec",
           "recompiles_steady": recompiles,
           "recompile_events_steady": events,
           "warmup_compiles": c_warm,
           "first_call_s": round(first_call_s, 3),
           "wall_s": round(wall_s, 4),
           "dispatches": dispatches,
           "chunk_steps": chunk_steps,
           "dispatch_share": round(share, 4),
           "config": {"layers": layers, "d_model": d, "heads": heads,
                      "vocab": vocab, "prompt_len": plen,
                      "new_tokens": new, "microbatch": mb,
                      "token_chunk": args.token_chunk},
           "cpu_count": os.cpu_count() or 1}
    print(json.dumps(row))
    log("decode profile smoke: OK")


if __name__ == "__main__":
    main()
