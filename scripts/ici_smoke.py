"""Device-resident transport-tier smoke: prove the ici fast path pays.

A 3-stage COPY-BOUND chain ("copychain": a thin 1 KB input Tiled into a
33 MB fat activation, reduced back to thin, then a small Dense head) on
a FORCED 4-device host mesh (``utils.compat.force_host_device_count`` —
a real multi-device jax platform in one process, the test vehicle for
same-mesh work without a TPU).  The fat boundary crosses the
``fan -> squash`` hop; stage placement follows the planner's wisdom:
the fat boundary stays ON-DEVICE (both sides pinned to device 0) and
the thin ``squash -> head`` boundary crosses the mesh (device 0 ->
device 1) with one real cross-device ``jax.device_put`` per frame —
asserted from stats with distinct (src, dst) device ids.

Unlike every earlier tier bench this chain is NOT delay-codec-bound:
the work eliminated is real memory traffic.  The reference point for
the speedup bar is the ``shm`` tier, whose TWO memcpys per hop per
frame (ring write-in + read-out) are real on every backend — exactly
the two memory passes the device-resident path eliminates.  The
``local`` tier is measured and reported too, but on THIS vehicle it is
already effectively device-resident: jax's CPU backend aliases host
views of its own buffers in both directions (``np.asarray`` of a CPU
array is a zero-copy view, and feeding such a view back into a jit is
a zero-copy import — measured, not assumed), so all-ici ~= all-local
here by physics.  On a real accelerator the local tier's host
crossings are D2H + H2D DMAs — the cost the planner's ``host_sync``
term models and the per-stage ``host_sync`` histogram measures; the
ici rows' ZERO samples in that histogram are the vehicle-independent
proof the round-trip is gone.

Checks:

1. All four chains (tcp / shm / local / ici) produce BYTE-IDENTICAL
   outputs; every hop's negotiated tier (dispatcher edges included) is
   asserted from stats.
2. All-ici >= ``--min-speedup`` (1.3) min-of-3 streams vs all-shm (the
   two eliminated memory passes), and not slower than all-local beyond
   noise (>= ``--local-floor``, default 0.7 — equality is the expected
   reading on a zero-copy-interop host; the ratio jitters +-0.2 on
   this 1-core box).
3. ZERO ``codec.*`` AND ZERO ``host_sync`` samples on every ici hop
   (the local chain records one host_sync sample per frame per stage —
   the instrument provably works); the dispatcher's result edge
   host-syncs exactly once per frame.
4. At least one hop performs a real cross-device ``device_put``:
   stage 1's stats carry ``ici_d2d == frames`` with device pair
   ``[0, 1]``.
5. PLANNER: ``TIER_CODECS["ici"]`` + the ``host_sync`` term give the
   strict ordering device < ici < local < shm < tcp on the bench
   graph's fat boundary, an ici hop-tier map beats the all-tcp plan's
   bottleneck strictly, and the tier survives the plan-JSON roundtrip.

Exit 0 on success; one JSON row on stdout (the ``ici_fastpath`` row of
``benchmarks/run.py``).

Usage:  python scripts/ici_smoke.py [--quick] [--reps R] [--count N]
            [--min-speedup 1.3] [--local-floor 0.7]
"""

import argparse
import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from defer_tpu.utils.compat import force_host_device_count  # noqa: E402

#: the forced same-mesh vehicle: must land before jax's backend init
#: (benchmarks/run.py pins children to a 1-device mesh — override it)
_OK, _WHY = force_host_device_count(4)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_graph(reps: int):
    """copychain: thin -> FAT (reps x 256 f32) -> thin -> head."""
    from defer_tpu import GraphBuilder
    from defer_tpu.graph import ops

    b = GraphBuilder("copychain")
    x = b.input((256,))
    x = b.add(ops.Tile(reps), x, name="fan")
    x = b.add(ops.ReduceMean(axis=1), x, name="squash")
    x = b.add(ops.Dense(256), x, name="head")
    return b.build()


def run_chain(stages, params, xs, *, tier, devices=None, streams=3):
    """Thread-per-node in-process chain (the only process shape a
    device-resident hop can exist in); returns (outs, min_wall, stats,
    dispatcher_tiers)."""
    from defer_tpu.runtime.node import ChainDispatcher, StageNode

    nodes = [StageNode(None, "127.0.0.1:0", None, tier=tier,
                       tier_accept=True)
             for _ in range(len(stages))]
    addrs = [f"127.0.0.1:{nd.address[1]}" for nd in nodes]
    threads = [threading.Thread(target=nd.serve, daemon=True)
               for nd in nodes]
    for t in threads:
        t.start()
    disp = ChainDispatcher(addrs[0], codec="raw", tier=tier)
    try:
        disp.deploy(stages, params, addrs, batch=xs[0].shape[0],
                    tiers=[tier] * len(stages), devices=devices)
        disp.stream(xs[:2])  # warm: compile + connect + negotiate
        wall = float("inf")
        for _ in range(streams):
            t0 = time.perf_counter()
            outs = disp.stream(xs)
            wall = min(wall, time.perf_counter() - t0)
        stats = disp.stats(addrs)
    finally:
        disp.close()
    for t in threads:
        t.join(timeout=60)
    return outs, wall, stats, (disp.tier_out, disp.tier_in)


def planner_check(graph, reps: int) -> dict:
    """The acceptance planner block: strict tier ordering on the fat
    boundary + ici map strict-win + plan-JSON roundtrip."""
    from defer_tpu.plan import StageCostModel, plan_from_json, solve

    costs = {"fan": 1e-4, "squash": 1e-4, "head": 1e-4}
    cm = StageCostModel(graph, gen="v5e", link_bw_s=1e9, node_costs=costs)
    fat = "fan"
    order = {t: cm.with_hop_tiers({fat: t}).comm_seconds(fat, t)
             for t in ("device", "ici", "local", "shm")}
    order["tcp"] = cm.best_codec(fat)[1]
    seq = [order[t] for t in ("device", "ici", "local", "shm", "tcp")]
    assert seq == sorted(seq) and len(set(seq)) == len(seq), (
        f"tier ordering not strict on the fat boundary: {order}")
    p_tcp = solve(graph, 3, cm)
    tiers = {"fan": "ici", "squash": "ici"}
    p_ici = solve(graph, 3, cm, hop_tiers=tiers)
    assert p_ici.bottleneck_s < p_tcp.bottleneck_s, (
        f"ici map did not beat tcp: {p_ici.bottleneck_s} vs "
        f"{p_tcp.bottleneck_s}")
    doc = p_ici.to_json()
    rt = plan_from_json(doc)
    assert rt.hop_tiers == p_ici.hop_tiers and "ici" in rt.hop_tiers
    log(f"planner: tcp bottleneck {p_tcp.bottleneck_s * 1e3:.3f} ms vs "
        f"ici {p_ici.bottleneck_s * 1e3:.3f} ms; fat-boundary tier "
        f"order (us): "
        + " < ".join(f"{t}={order[t] * 1e6:.2f}"
                     for t in ("device", "ici", "local", "shm", "tcp")))
    return {"tcp_bottleneck_ms": round(p_tcp.bottleneck_s * 1e3, 4),
            "ici_bottleneck_ms": round(p_ici.bottleneck_s * 1e3, 4),
            "hop_tiers": p_ici.hop_tiers}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="smaller fat activation + fewer frames (CI)")
    ap.add_argument("--reps", type=int, default=0,
                    help="tile factor: fat bytes = reps * 1024 (default "
                         "32768 full / 16384 quick)")
    ap.add_argument("--count", type=int, default=8)
    ap.add_argument("--min-speedup", type=float, default=1.3,
                    help="all-ici vs all-shm bar (the two real memcpys "
                         "per hop per frame the tier eliminates)")
    ap.add_argument("--local-floor", type=float, default=0.7,
                    help="all-ici vs all-local floor — a regression "
                         "guard, not a win bar: the ratio is expected "
                         "~1.0 on this zero-copy-interop vehicle and "
                         "jitters +-0.2 on the 1-core box")
    args = ap.parse_args()

    import jax
    import numpy as np

    from defer_tpu import partition
    from defer_tpu.obs import REGISTRY

    devs = jax.devices()
    assert len(devs) >= 3, (
        f"forced host mesh did not come up ({_WHY}); have {devs}")
    log(f"host mesh: {len(devs)} x {devs[0].platform} devices ({_WHY})")

    reps = args.reps or (16384 if args.quick else 32768)
    graph = build_graph(reps)
    params = graph.init(jax.random.key(0))
    stages = partition(graph, ["fan", "squash"])
    fat_mb = graph.out_spec("fan").size * 4 / 1e6
    log(f"copychain: fat boundary {fat_mb:.1f} MB f32, "
        f"{args.count} frames, min-of-3 streams")

    rng = np.random.default_rng(0)
    xs = [rng.standard_normal((1, 256)).astype(np.float32)
          for _ in range(args.count)]

    def hist_count(name):
        return int(REGISTRY.histogram(name).summary().get("count", 0))

    # -- the four chains ----------------------------------------------------
    tcp_o, tcp_w, tcp_st, _ = run_chain(stages, params, xs, tier="tcp",
                                        streams=1)
    assert [s["tier"] for s in tcp_st] == ["tcp"] * 3
    shm_o, shm_w, shm_st, _ = run_chain(stages, params, xs, tier="shm")
    assert [s["tier"] for s in shm_st] == ["shm"] * 3
    loc_o, loc_w, loc_st, _ = run_chain(stages, params, xs, tier="local")
    assert [s["tier"] for s in loc_st] == ["local"] * 3
    # the local chain host-syncs once per frame per stage — the
    # instrument the ici rows must show ZERO samples on
    n_loc_frames = args.count * 3 + 2  # 3 streams + 2 warm frames
    assert all(s["host_sync_s"]["count"] == n_loc_frames
               for s in loc_st), [s["host_sync_s"] for s in loc_st]

    enc0 = hist_count("codec.encode_s")
    dec0 = hist_count("codec.decode_s")
    hs0 = hist_count("node.host_sync_s")
    chs0 = hist_count("chain.host_sync_s")
    ici_o, ici_w, ici_st, disp_tiers = run_chain(
        stages, params, xs, tier="auto", devices=[0, 0, 1])

    # 1. negotiated tiers, every hop + both dispatcher edges
    assert [s["tier"] for s in ici_st] == ["ici"] * 3, ici_st
    assert [s["tier_in"] for s in ici_st] == ["ici"] * 3
    assert disp_tiers == ("ici", "ici"), disp_tiers
    assert [s["device"] for s in ici_st] == [0, 0, 1]

    # byte identity across ALL tiers
    for name, outs in (("tcp", tcp_o), ("shm", shm_o), ("local", loc_o)):
        for a, b in zip(outs, ici_o):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        log(f"all-ici byte-identical to all-{name}")

    # 3. zero codec work, zero host syncs on the device-resident chain
    assert hist_count("codec.encode_s") == enc0, "ici hop encoded"
    assert hist_count("codec.decode_s") == dec0, "ici hop decoded"
    assert hist_count("node.host_sync_s") == hs0, (
        "an ici hop materialized to host")
    assert all(s["host_sync_s"]["count"] == 0 for s in ici_st)
    n_frames = args.count * 3 + 2
    assert hist_count("chain.host_sync_s") - chs0 == n_frames, (
        "result edge must host-sync exactly once per frame")

    # 4. the real cross-device transfer: squash(dev0) -> head(dev1)
    assert ici_st[1]["ici_d2d"] == n_frames, ici_st[1]
    assert ici_st[1]["ici_device_pairs"] == [[0, 1]], ici_st[1]
    src, dst = ici_st[1]["ici_device_pairs"][0]
    assert src != dst

    # 2. the speedups
    v_shm = shm_w / ici_w
    v_loc = loc_w / ici_w
    v_tcp = tcp_w / ici_w
    log(f"walls (min-of-3, {args.count} frames): tcp {tcp_w:.3f}s, "
        f"shm {shm_w:.3f}s, local {loc_w:.3f}s, ici {ici_w:.3f}s")
    log(f"all-ici: {v_shm:.2f}x vs shm, {v_loc:.2f}x vs local, "
        f"{v_tcp:.2f}x vs tcp")
    assert v_shm >= args.min_speedup, (
        f"ici {v_shm:.3f}x vs shm under the {args.min_speedup}x bar — "
        f"the two per-hop memcpys were not eliminated")
    assert v_loc >= args.local_floor, (
        f"ici {v_loc:.3f}x vs local under the {args.local_floor} floor "
        f"(expected ~1.0 on a zero-copy-interop host)")

    planner = planner_check(graph, reps)

    row = {
        "metric": "ici_fastpath",
        "value": round(v_shm, 4),
        "unit": "x_vs_shm_chain",
        "stages": 3, "fat_mb": round(fat_mb, 1),
        "count": args.count, "quick": bool(args.quick),
        "devices": [s["device"] for s in ici_st],
        "d2d_pairs": ici_st[1]["ici_device_pairs"],
        "speedup_vs_shm": round(v_shm, 4),
        "speedup_vs_local": round(v_loc, 4),
        "speedup_vs_tcp": round(v_tcp, 4),
        "host_sync_counts_ici": [s["host_sync_s"]["count"]
                                 for s in ici_st],
        "host_sync_counts_local": [s["host_sync_s"]["count"]
                                   for s in loc_st],
        "planner": planner,
        "note": ("vs_local ~1.0 expected: jax CPU host interop is "
                 "zero-copy both ways, so the local tier is already "
                 "device-resident on this vehicle; shm's two memcpys "
                 "per hop are real on every backend"),
    }
    print(json.dumps(row))
    log("ici fast-path smoke: OK")


if __name__ == "__main__":
    main()
