"""Telemetry smoke test: tiny CPU pipeline -> non-empty trace + metrics.

Runs a 2-stage resnet_tiny SPMD pipeline on the CPU backend with tracing
enabled, then asserts that (a) the Chrome-trace export contains dispatcher
and per-stage spans sharing one trace id, and (b) the metrics registry
snapshot carries per-stage latency percentiles and per-hop byte counters.
Exit 0 on success; any assertion failure is loud.  Cheap enough for a
tier-1 time budget (~15 s, dominated by one XLA compile).

Usage:  python scripts/metrics_smoke.py [--out-dir DIR]
"""

import argparse
import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default=None,
                    help="keep the exports here (default: tempdir)")
    args = ap.parse_args()

    import numpy as np

    import jax

    from defer_tpu import SpmdPipeline, partition, pipeline_mesh
    from defer_tpu.models import resnet_tiny
    from defer_tpu.obs import REGISTRY, enable_tracing, tracer

    tr = enable_tracing(process="dispatcher")
    tr.start_trace()

    graph = resnet_tiny()
    params = graph.init(jax.random.key(0))
    stages = partition(graph, num_stages=2)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(2),
                        microbatch=1, chunk=4)
    xs = np.zeros((4, 1, 32, 32, 3), np.float32)
    for _ in range(3):
        pipe.push(xs)
    pipe.flush()
    pipe.stage_latencies(iters=2)

    out_dir = args.out_dir or tempfile.mkdtemp(prefix="defer_obs_smoke_")
    trace_path = os.path.join(out_dir, "trace.json")
    metrics_path = os.path.join(out_dir, "metrics.json")
    tr.export_chrome(trace_path)
    REGISTRY.dump_json(metrics_path)

    # ---- assertions: the exports are non-empty and self-consistent
    t = json.load(open(trace_path))
    events = [e for e in t["traceEvents"] if e.get("ph") == "X"]
    assert events, "trace export has no spans"
    trace_ids = {e["args"].get("trace_id") for e in events}
    assert len(trace_ids) == 1, f"spans span {len(trace_ids)} trace ids"
    names = {e["name"] for e in events}
    assert any(n.startswith("spmd.push") for n in names), names
    assert any(n.startswith("stage0") for n in names), names

    m = json.load(open(metrics_path))
    prefix = pipe.metrics.prefix
    stage0 = m[f"{prefix}.stage0.latency_s"]
    for q in ("p50", "p95", "p99", "max"):
        assert q in stage0, stage0
    hop0 = m[f"{prefix}.hop0.bytes"]
    assert hop0 > 0, "per-hop byte counter did not accumulate"
    push = m[f"{prefix}.push_latency_s"]
    assert push["count"] >= 3, push

    print(json.dumps({
        "metric": "metrics_smoke", "value": 1, "unit": "ok",
        "spans": len(events),
        "push_p99_ms": round(push["p99"] * 1e3, 3),
        "trace": trace_path, "metrics": metrics_path,
    }))
    print("metrics smoke: OK", file=sys.stderr)
    # clean up tempdir exports unless the caller asked to keep them
    if args.out_dir is None:
        for p in (trace_path, metrics_path):
            os.unlink(p)
        os.rmdir(out_dir)


if __name__ == "__main__":
    main()
