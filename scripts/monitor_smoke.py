"""Live observability smoke: the push plane closes the loop while the
stream is in flight.

A 3-stage resnet_tiny chain gets a delay-bound middle stage (decode-side
sleep on its inbound hop, encode-side sleep on its outbound hop — the
resource profile of an accelerator-bound stage this 1-core host cannot
express with real compute, as in ``replication_smoke.py``).  While the
stream runs, the ``defer_tpu monitor`` plane (obs_subscribe ->
per-node obs_push frames -> ClusterView) must see it:

1. LIVE ROWS: ``defer_tpu monitor --json`` against the running chain
   reports per-stage rows (>= 2 pushes each) whose counts and
   percentiles CONVERGE to the nodes' own ``stats`` replies.
2. BOTTLENECK: the monitor's bottleneck id names the delay-bound stage.
3. STRAGGLER -> REPLAN: against a baseline-corrected plan (analytic
   plan corrected by a no-delay calibration run's live telemetry), the
   detector flags the delay stage after exactly ``--sustain`` (2)
   reporting intervals, and the replan suggestion's largest correction
   names that stage.
4. WATERFALL + CLOCKS: with ``trace_sample_every`` the sampled frames'
   per-stage infer spans — recorded in different OS processes in full
   mode, clock-aligned via the min-RTT ``clock_adjust`` handshake —
   form a waterfall with NO negative inter-stage gaps on one Perfetto
   timeline (exported to prove it).
5. OVERHEAD: streaming wall with full telemetry (tracing + sampling +
   reporter pushes + a live monitor subscriber) vs the same chain with
   everything off differs by < ``--max-overhead`` (default 5%); outputs
   stay byte-identical.

``--quick`` runs the chain in-process (thread nodes, real TCP sockets —
the CI mode); the default spawns real OS processes per stage.  Exit 0 on
success; one JSON row on stdout (the ``obs_overhead`` row of
``benchmarks/run.py``).
"""

import argparse
import contextlib
import io
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CPU_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def hop_codecs(delay_ms: float) -> list[str]:
    """Park the whole delay budget inside stage 1's process: decode-side
    sleep on its inbound hop, encode-side sleep on its outbound hop."""
    if delay_ms <= 0:
        return ["raw", "raw", "raw"]
    return [f"dsleep{delay_ms:g}+raw", f"esleep{delay_ms:g}+raw", "raw"]


class Chain:
    """One booted 3-stage chain (thread nodes or OS processes)."""

    def __init__(self, disp, addrs, *, procs=None, logs=None,
                 threads=None):
        self.disp = disp
        self.addrs = addrs
        self._procs = procs or []
        self._logs = logs or []
        self._threads = threads or []
        self.failed = False

    def close(self):
        from defer_tpu.runtime.node import _kill_procs
        try:
            if self.failed:
                _kill_procs(self._procs)
            self.disp.close()
            if not self.failed:
                for pr in self._procs:
                    try:
                        pr.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        pr.kill()
            for t in self._threads:
                t.join(timeout=30)
        finally:
            for lf in self._logs:
                lf.close()


def boot_inproc(stages, params, codecs, *, batch, sample=0) -> Chain:
    from defer_tpu.runtime.node import ChainDispatcher, StageNode
    nodes = [StageNode(None, "127.0.0.1:0", None) for _ in range(3)]
    addrs = [f"127.0.0.1:{n.address[1]}" for n in nodes]
    threads = [threading.Thread(target=n.serve, daemon=True)
               for n in nodes]
    for t in threads:
        t.start()
    disp = ChainDispatcher(addrs[0], codec="raw",
                           trace_sample_every=sample)
    disp.deploy(stages, params, addrs, batch=batch, codecs=codecs)
    return Chain(disp, addrs, threads=threads)


def boot_procs(paths, codecs, *, log_dir, tag, sample=0) -> Chain:
    from defer_tpu.runtime.node import ChainDispatcher, _await_binds
    from defer_tpu.runtime.node import _free_ports
    ports = _free_ports(4)
    addrs = [f"127.0.0.1:{p}" for p in ports[:3]]
    result = f"127.0.0.1:{ports[3]}"
    child_env = dict(os.environ)
    child_env.update(CPU_ENV)
    procs, logs = [], []
    for k in range(3):
        nxt = addrs[k + 1] if k < 2 else result
        # --tier tcp: this row measures the OBSERVABILITY plane over a
        # delay-bound wire chain; an auto-negotiated shm hop would
        # bypass the dsleep/esleep codecs the straggler story rests on
        argv = [sys.executable, "-m", "defer_tpu", "node",
                "--artifact", paths[k], "--listen", addrs[k],
                "--next", nxt, "--codec", codecs[k], "--tier", "tcp"]
        lf = open(os.path.join(log_dir, f"{tag}_node_{k}.log"), "w+")
        logs.append(lf)
        procs.append(subprocess.Popen(argv, env=child_env, stdout=lf,
                                      stderr=subprocess.STDOUT))
    _await_binds(procs, [f"stage{k}" for k in range(3)], logs, addrs)
    disp = ChainDispatcher(addrs[0], listen=result, codec="raw",
                           trace_sample_every=sample)
    return Chain(disp, addrs, procs=procs, logs=logs)


def run_monitor_json(addrs, *, interval_ms, iterations, plan_file=None,
                     model=None, out: dict | None = None):
    """Invoke the REAL CLI (`defer_tpu monitor --json`) and return its
    parsed output lines."""
    from defer_tpu import cli
    argv = ["monitor", "--nodes", ",".join(addrs),
            "--interval-ms", str(interval_ms),
            "--iterations", str(iterations), "--json"]
    if plan_file:
        argv += ["--plan", plan_file, "--model", model or "resnet_tiny"]
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        cli.main(argv)
    docs = [json.loads(line) for line in buf.getvalue().strip()
            .splitlines() if line]
    if out is not None:
        out["docs"] = docs
    return docs


def service_from_stats(stats) -> dict[int, float]:
    """Per-stage live service ms from stats replies: the slowest of the
    decode / infer / encode phase p50s (each phase owns a thread)."""
    def p50(s):
        return (s or {}).get("p50", 0.0) * 1e3 if (s or {}).get("count") \
            else 0.0
    out = {}
    for row in stats:
        if row.get("stage") is None:
            continue
        out[row["stage"]] = max(p50(row.get("infer_latency_s")),
                                p50(row.get("decode_latency_s")),
                                p50(row.get("encode_latency_s")))
    return out


def baseline_plan(graph, stages, measured_ms: dict[int, float]):
    """The 'active plan' the straggler detector compares against: the
    deployment's cuts, corrected so each stage's predicted cost matches
    the no-delay calibration run — the honest expectation a live
    deviation is measured from."""
    from defer_tpu.plan import (StageCostModel, cost_model_from_plan,
                                evaluate_cuts, replan)
    cuts = [s.output_name for s in stages[:-1]]
    n = len(graph.topo_order)
    cm = StageCostModel(graph, gen="v4", link_bw_s=1e9,
                        node_costs={m: 1e-4 for m in graph.topo_order})
    rough = evaluate_cuts(graph, cuts, cm)
    rp = replan(graph, rough,
                {k: max(v, 1e-3) / 1e3 for k, v in measured_ms.items()},
                cost_model_from_plan(graph, rough))
    log(f"baseline plan: measured {measured_ms} -> corrected "
        f"stage_cost_ms {rp.old_plan_corrected.to_json()['stage_cost_ms']}"
        f" ({n} nodes)")
    return rp.old_plan_corrected


def waterfall_gaps(spans, sample_every: int) -> tuple[int, list[float]]:
    """Min inter-stage gap (us) across every sampled frame's infer
    waterfall: stage k+1's infer must start at or after stage k's infer
    END on the shared clock-aligned timeline."""
    by_seq: dict[int, dict[int, dict]] = {}
    for s in spans:
        name = s["name"]
        if not name.endswith(".infer") or not name.startswith("stage"):
            continue
        k = int(name.split(".")[0][len("stage"):])
        seq = s["args"].get("seq")
        if seq is None:
            continue
        by_seq.setdefault(seq, {})[k] = s
    gaps = []
    complete = 0
    for seq, stages_of in sorted(by_seq.items()):
        if len(stages_of) < 3:
            continue
        complete += 1
        for k in range(2):
            a, b = stages_of[k], stages_of[k + 1]
            gaps.append(b["ts_us"] - (a["ts_us"] + a["dur_us"]))
    return complete, gaps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="in-process thread chain (CI mode, no spawns)")
    ap.add_argument("--count", type=int, default=48,
                    help="timed microbatches per measured stream")
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--delay-ms", type=float, default=10.0,
                    help="per-side delay on the bottleneck stage's hops")
    ap.add_argument("--interval-ms", type=float, default=150.0,
                    help="obs_push reporting interval")
    ap.add_argument("--sustain", type=int, default=2,
                    help="intervals a deviation must hold to be flagged")
    ap.add_argument("--max-overhead", type=float, default=0.05,
                    help="telemetry wall overhead bound vs all-off")
    args = ap.parse_args()

    import numpy as np

    import jax

    from defer_tpu import partition
    from defer_tpu.models import resnet_tiny
    from defer_tpu.obs import tracer
    from defer_tpu.obs.cluster import expected_stage_ms
    from defer_tpu.utils.export import export_pipeline

    graph = resnet_tiny()
    params = graph.init(jax.random.key(0))
    stages = partition(graph, num_stages=3)
    rng = np.random.default_rng(3)
    xs = [rng.standard_normal((args.batch, 32, 32, 3)).astype(np.float32)
          for _ in range(args.count)]
    delays = hop_codecs(args.delay_ms)
    tr = tracer()

    with tempfile.TemporaryDirectory(prefix="defer_mon_") as tmp:
        paths = None
        if not args.quick:
            paths = export_pipeline(stages, params, tmp, batch=args.batch)

        def boot(codecs, tag, sample=0):
            if args.quick:
                return boot_inproc(stages, params, codecs,
                                   batch=args.batch, sample=sample)
            return boot_procs(paths, codecs, log_dir=tmp, tag=tag,
                              sample=sample)

        # -- calibration: a no-delay run's live telemetry IS the plan's
        # expectation (always in-process: it measures this host's
        # per-stage compute, which is what the plan should predict)
        tr.enabled = False
        chain = boot_inproc(stages, params, hop_codecs(0),
                            batch=args.batch)
        try:
            chain.disp.stream(xs[:4])          # compile + connect
            chain.disp.stream(xs)
            base_ms = service_from_stats(chain.disp.stats(chain.addrs))
        finally:
            chain.close()
        plan = baseline_plan(graph, stages, base_ms)
        plan_file = os.path.join(tmp, "plan.json")
        with open(plan_file, "w") as f:
            json.dump(plan.to_json(), f)

        # -- overhead experiment: TWO identical delay chains, streamed
        # ALTERNATELY — "off" never sees telemetry, "on" runs tracing +
        # 1-in-4 waterfall sampling + clock alignment + per-node
        # reporters + a live monitor subscriber.  Interleaving makes
        # each off/on pair see the same background load, so host drift
        # (which on this 1-core box dwarfs the telemetry tax between
        # two separated measurement phases) cancels; min-of-3 absorbs
        # per-stream scheduler spikes on top.
        sample_every = 4
        tr.enabled = False
        chain_off = boot(delays, "off")
        chain_on = boot(delays, "on", sample=sample_every)
        mon: dict = {}
        final_docs = live_docs = None
        try:
            chain_off.disp.stream(xs[:4])
            tr.clear()
            tr.enabled = True
            tr.process = "dispatcher"
            tr.start_trace()
            offsets = chain_on.disp.align_clocks(chain_on.addrs)
            chain_on.disp.stream(xs[:4])
            mt = threading.Thread(
                target=run_monitor_json, args=(chain_on.addrs,),
                kwargs=dict(interval_ms=args.interval_ms,
                            iterations=40, plan_file=plan_file,
                            model="resnet_tiny", out=mon), daemon=True)
            mt.start()
            w_off, w_on = [], []
            for _ in range(3):
                tr.enabled = False
                t0 = time.perf_counter()
                outs_off = chain_off.disp.stream(xs)
                w_off.append(time.perf_counter() - t0)
                tr.enabled = True
                t0 = time.perf_counter()
                outs_on = chain_on.disp.stream(xs)
                w_on.append(time.perf_counter() - t0)
            wall_off, wall_on = min(w_off), min(w_on)
            mt.join(timeout=120)
            assert not mt.is_alive(), "monitor CLI did not finish"
            live_docs = mon["docs"]
            stats_on = chain_on.disp.stats(chain_on.addrs)
            # a fresh one-shot monitor AFTER the stream: the converged
            # snapshot compared against the nodes' own stats replies
            final_docs = run_monitor_json(
                chain_on.addrs, interval_ms=args.interval_ms,
                iterations=2, plan_file=plan_file, model="resnet_tiny")
            chain_on.disp.collect_trace(chain_on.addrs)
        except BaseException:
            chain_off.failed = chain_on.failed = True
            raise
        finally:
            tr.enabled = True  # chain_on teardown spans are harmless
            chain_off.close()
            chain_on.close()
        log(f"telemetry off: {args.count * args.batch / wall_off:7.1f} "
            f"inf/s ({wall_off:.3f}s)")
        log(f"telemetry on:  {args.count * args.batch / wall_on:7.1f} "
            f"inf/s ({wall_on:.3f}s, {len(live_docs)} live monitor "
            f"frames)")

        # 5a. telemetry must not corrupt the stream
        assert len(outs_on) == len(outs_off) == args.count
        for a, b in zip(outs_off, outs_on):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        # 1. live rows appeared while streaming and converge to stats
        assert live_docs, "no monitor output"
        rows_live = [d for d in live_docs
                     if len(d["rows"]) == 3
                     and all(r["pushes"] >= 2 for r in d["rows"])]
        assert rows_live, (
            f"monitor never showed 3 live rows: {live_docs[-1]}")
        by_stage = {s["stage"]: s for s in stats_on
                    if s.get("stage") is not None}
        final = final_docs[-1]
        for r in final["rows"]:
            s = by_stage[r["stage"]]
            assert r["processed"] == s["processed"], (r, s)
            got, want = r["infer_ms"]["p50"], \
                s["infer_latency_s"]["p50"] * 1e3
            assert abs(got - want) <= 0.1 * max(want, 0.01), (got, want)

        # 2. the delay-bound stage is the live bottleneck
        assert final["bottleneck"] == 1, final
        last_live = rows_live[-1]
        assert last_live["bottleneck"] == 1, last_live

        # 3. straggler flagged within --sustain intervals; replan names it
        flagged = [d for d in live_docs if d["stragglers"]]
        assert flagged, "straggler detector never fired"
        first = flagged[0]
        f1 = {f["stage"]: f for f in first["stragglers"]}
        assert 1 in f1, first["stragglers"]
        assert f1[1]["intervals"] == args.sustain, f1[1]
        assert f1[1]["ratio"] > 1.5, f1[1]
        # only the delay-bound stage stays flagged once sustained
        assert {f["stage"] for f in flagged[-1]["stragglers"]} == {1}, \
            flagged[-1]["stragglers"]
        with_replan = [d for d in flagged if "replan" in d]
        assert with_replan, "no replan suggestion surfaced"
        corr = with_replan[-1]["replan"]["corrections"]
        assert max(corr, key=lambda k: corr[k]) == "1", corr
        first_flag_frame = live_docs.index(first) + 1

        # 4. clock-aligned waterfall: sampled frames' per-stage infer
        # spans sit in order on one timeline, no negative gaps
        spans = tr.spans
        names = {s["name"] for s in spans}
        assert any(n.endswith(".rx_wait") for n in names), sorted(names)
        assert any(n.endswith(".tx_wait") for n in names), sorted(names)
        complete, gaps = waterfall_gaps(spans, sample_every)
        assert complete >= args.count // sample_every, (
            f"only {complete} complete sampled waterfalls")
        min_gap = min(gaps)
        assert min_gap >= -200, (
            f"negative inter-stage gap {min_gap}us — clock alignment "
            f"failed (offsets {offsets})")
        trace_file = os.path.join(tmp, "waterfall.json")
        from defer_tpu.obs import export_chrome_trace
        export_chrome_trace(trace_file)
        doc = json.load(open(trace_file))
        procs_seen = {e["args"]["name"] for e in doc["traceEvents"]
                      if e["ph"] == "M"}
        want_procs = 1 if args.quick else 4  # shared tracer in-process
        assert len(procs_seen) >= want_procs, procs_seen
        tr.enabled = False
        tr.clear()

        # 5b. the telemetry tax
        overhead = wall_on / wall_off - 1.0
        log(f"overhead: {overhead * 100:+.2f}% "
            f"(bound {args.max_overhead * 100:.0f}%), min waterfall gap "
            f"{min_gap}us over {complete} sampled frames, straggler "
            f"flagged at monitor frame {first_flag_frame}")
        assert overhead < args.max_overhead, (
            f"telemetry overhead {overhead * 100:.2f}% exceeds "
            f"{args.max_overhead * 100:.0f}% (on {wall_on:.3f}s vs off "
            f"{wall_off:.3f}s)")

        row = {"metric": "obs_overhead", "value": round(overhead, 4),
               "unit": "frac_wall_overhead_vs_no_trace",
               "quick": args.quick, "count": args.count,
               "batch": args.batch, "delay_ms": args.delay_ms,
               "interval_ms": args.interval_ms,
               "wall_off_s": round(wall_off, 4),
               "wall_on_s": round(wall_on, 4),
               "bottleneck": final["bottleneck"],
               "straggler": f1[1],
               "replan_argmax_stage": 1,
               "monitor_frames": len(live_docs),
               "first_flag_frame": first_flag_frame,
               "sampled_waterfalls": complete,
               "min_waterfall_gap_us": round(min_gap, 1),
               "clock_offset_us": {a: round(v["offset_us"], 1)
                                   for a, v in offsets.items()},
               "cpu_count": os.cpu_count() or 1}

        # -- full mode only: the run_chain wiring (plan= + stats_out=
        # appends the live obs row with stragglers + replan suggestion)
        if not args.quick:
            from defer_tpu.runtime.node import run_chain
            stats2: list = []
            run_chain(stages, params, xs[:16], batch=args.batch,
                      hop_codecs=delays, artifact_dir=tmp,
                      stats_out=stats2, plan=plan, graph=graph,
                      report_interval_ms=args.interval_ms)
            obs_rows = [r["obs"] for r in stats2 if "obs" in r]
            assert obs_rows, f"run_chain appended no obs row: {stats2}"
            ob = obs_rows[0]
            assert ob["bottleneck"] == 1, ob
            assert any(f["stage"] == 1 for f in ob["stragglers"]), ob
            rcorr = ob["replan"]["corrections"]
            # keys are ints in-process (str once JSON-serialized)
            assert str(max(rcorr, key=lambda k: rcorr[k])) == "1", rcorr
            row["run_chain_obs"] = {
                "bottleneck": ob["bottleneck"],
                "stragglers": ob["stragglers"]}

    print(json.dumps(row))
    log("monitor smoke: OK")


if __name__ == "__main__":
    main()
