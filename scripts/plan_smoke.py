"""Planner smoke: prove bottleneck cuts beat (or match) quantile cuts.

Three checks, all against the SAME calibrated cost model:

1. PREDICTED (resnet/vgg/gpt tiny graphs): the DP solver's plan must
   score a bottleneck <= the greedy quantile cuts' bottleneck — the
   solver is provably optimal on its own model, so anything else is a
   solver bug.

2. MEASURED (same graphs): each cut set is deployed as an in-process
   stage-node chain (threads, real framed transport + codec) and the
   per-stage rx/infer/tx span durations are folded into the telemetry
   PR's ``LatencyHistogram``s; the measured bottleneck-stage time
   (max over stages of the slowest phase p50) for bottleneck cuts must
   be <= ``--tolerance`` x the quantile cuts' (identical cut sets short-
   circuit to equal).

3. SKEWED CHAIN (strict): a synthetic model whose FLOP midpoint sits
   exactly on a fat activation boundary — the quantile heuristic cuts
   there, shipping a ~256 KB bf8 frame per microbatch, while the comm-
   aware solver cuts one layer later at a 64-element boundary for the
   same compute balance.  The quantile chain must measure STRICTLY
   slower (wall and bottleneck-stage time, ``--min-improvement``
   margin).  This is the failure mode the planner exists to avoid.

Exit 0 on success; one JSON row on stdout (the ``plan_vs_quantile`` row
of ``benchmarks/run.py``).

Usage:  python scripts/plan_smoke.py [--quick] [--count N] [--json-out F]
"""

import argparse
import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def skewed_graph():
    """FLOP midpoint == fat activation boundary: quantile's worst case."""
    from defer_tpu import GraphBuilder
    from defer_tpu.graph import ops
    b = GraphBuilder("skewed")
    x = b.input((64,))
    x = b.add(ops.Dense(16384), x, name="fat")    # 64 -> 16 K elems
    x = b.add(ops.Dense(64), x, name="back")      # same FLOPs as "fat"
    b.add(ops.Dense(64), x, name="head")
    return b.build()


def run_inproc_chain(stages, params, xs, *, codec: str, warm: int = 2,
                     batch: int) -> dict:
    """Stream ``xs`` through an in-process thread chain; return wall
    seconds + per-stage phase summaries built from the trace spans."""
    import numpy as np

    from defer_tpu.obs import LatencyHistogram, enable_tracing, tracer
    from defer_tpu.runtime.node import ChainDispatcher, StageNode

    tr = enable_tracing(process="dispatcher")
    tr.start_trace()
    nodes = [StageNode(None, "127.0.0.1:0", None) for _ in stages]
    addrs = [f"127.0.0.1:{n.address[1]}" for n in nodes]
    threads = [threading.Thread(target=n.serve, daemon=True) for n in nodes]
    for t in threads:
        t.start()
    disp = ChainDispatcher(addrs[0], codec=codec)
    try:
        disp.deploy(stages, params, addrs, batch=batch)
        disp.stream(xs[:warm])     # compile + connect excluded
        tracer().drain()           # drop warmup spans
        t0 = time.perf_counter()
        outs = disp.stream(xs)
        wall = time.perf_counter() - t0
        spans = tracer().drain()
    finally:
        disp.close()
    for t in threads:
        t.join(timeout=60)
    assert len(outs) == len(xs), (len(outs), len(xs))

    # fold span durations into the telemetry PR's histograms: per stage,
    # per phase (rx decode / infer / tx encode+send)
    hists: dict[tuple[int, str], LatencyHistogram] = {}
    for s in spans:
        name = s.get("name", "")
        for phase in ("rx", "infer", "tx"):
            if name.endswith(f".{phase}") and name.startswith("stage"):
                try:
                    k = int(name[len("stage"):-len(phase) - 1])
                except ValueError:
                    break
                hists.setdefault((k, phase), LatencyHistogram()).record(
                    s["dur_us"] / 1e6)
                break
    per_stage = {}
    for (k, phase), h in sorted(hists.items()):
        per_stage.setdefault(k, {})[phase] = h.summary()
    # bottleneck-stage time: the slowest phase p50 across all stages —
    # the steady-state period of the overlapped chain
    bottleneck = 0.0
    for k, phases in per_stage.items():
        for phase, summ in phases.items():
            bottleneck = max(bottleneck, summ.get("p50", 0.0))
    return {"wall_s": wall, "per_input_s": wall / len(xs),
            "bottleneck_stage_s": bottleneck, "stages": per_stage,
            "outs": outs}


def compare_cuts(graph, params, plan_cuts, q_cuts, *, codec: str,
                 count: int, batch: int, int_input: bool = False) -> dict:
    """Measured steady-state comparison of two cut sets on one graph."""
    import numpy as np

    from defer_tpu import partition
    rng = np.random.default_rng(0)
    shape = (batch,) + tuple(graph.input_spec.shape)
    if int_input:
        xs = [rng.integers(0, 16, shape).astype(np.int32)
              for _ in range(count)]
    else:
        xs = [rng.standard_normal(shape).astype(np.float32)
              for _ in range(count)]
    r_plan = run_inproc_chain(partition(graph, list(plan_cuts)), params,
                              xs, codec=codec, batch=batch)
    if list(q_cuts) == list(plan_cuts):
        r_q = r_plan
    else:
        r_q = run_inproc_chain(partition(graph, list(q_cuts)), params,
                               xs, codec=codec, batch=batch)
    return {
        "plan_cuts": list(plan_cuts), "quantile_cuts": list(q_cuts),
        "identical_cuts": list(q_cuts) == list(plan_cuts),
        "plan_wall_s": round(r_plan["wall_s"], 4),
        "quantile_wall_s": round(r_q["wall_s"], 4),
        "plan_bottleneck_stage_ms":
            round(r_plan["bottleneck_stage_s"] * 1e3, 4),
        "quantile_bottleneck_stage_ms":
            round(r_q["bottleneck_stage_s"] * 1e3, 4),
        "_plan": r_plan, "_q": r_q,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--count", type=int, default=12,
                    help="timed microbatches per measured chain")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--skew-count", type=int, default=16)
    ap.add_argument("--skew-batch", type=int, default=8)
    ap.add_argument("--link-bw", type=float, default=1e8,
                    help="modeled hop bandwidth (1e8 = host-edge "
                         "ethernet-class, where codecs matter)")
    ap.add_argument("--tolerance", type=float, default=1.25,
                    help="measured bottleneck-stage slack for the "
                         "balanced model graphs (noise on tiny stages)")
    ap.add_argument("--min-improvement", type=float, default=1.05,
                    help="required strict measured win on the skewed "
                         "chain (quantile / bottleneck)")
    ap.add_argument("--quick", action="store_true",
                    help="predicted comparisons only (no chains)")
    ap.add_argument("--json-out", default=None, metavar="FILE")
    args = ap.parse_args()

    import jax

    from defer_tpu import models
    from defer_tpu.graph.analysis import auto_cut_points
    from defer_tpu.plan import (StageCostModel, calibrate_codecs,
                                evaluate_cuts, solve)

    log("calibrating host codecs (raw/lzb/bf8/bf16)...")
    codecs = calibrate_codecs(("raw", "lzb", "bf8", "bf16"))
    for n, c in codecs.items():
        log(f"  {n:5s} ratio {c.ratio:6.2f}x  "
            f"enc {c.encode_bytes_per_s / 1e6:8.1f} MB/s  "
            f"dec {c.decode_bytes_per_s / 1e6:8.1f} MB/s")

    graphs = [("resnet_tiny", models.resnet_tiny(), 4, False),
              ("vgg_tiny", models.vgg_tiny(), 4, False),
              ("gpt_tiny", models.gpt_tiny(), 4, True)]
    rows = {}
    from defer_tpu.utils.profiling import measured_node_costs
    for name, g, n_stages, int_in in graphs:
        # compute side calibrated on THIS backend (the TPU roofline's
        # relative weights are meaningless on a CPU host); comm side
        # calibrated above.  The quantile baseline stays the status-quo
        # default (analytic FLOPs) — that is what the planner replaces.
        params = g.init(jax.random.key(0))
        node_costs = measured_node_costs(g, params, batch=args.batch,
                                         k=8, reps=2)
        cm = StageCostModel(g, batch=args.batch, codecs=codecs,
                            link_bw_s=args.link_bw,
                            node_costs=node_costs)
        plan = solve(g, n_stages, cm)
        q_cuts = auto_cut_points(g, n_stages)
        q_plan = evaluate_cuts(g, q_cuts, cm, objective="quantile")
        assert plan.bottleneck_s <= q_plan.bottleneck_s * (1 + 1e-9), (
            f"{name}: solver bottleneck {plan.bottleneck_s} > quantile "
            f"{q_plan.bottleneck_s} — the DP is not optimal")
        row = {
            "predicted_plan_ms": round(plan.bottleneck_s * 1e3, 6),
            "predicted_quantile_ms": round(q_plan.bottleneck_s * 1e3, 6),
            "predicted_speedup": round(
                q_plan.bottleneck_s / plan.bottleneck_s, 4)
            if plan.bottleneck_s > 0 else None,
            "hop_codecs": plan.codecs,
        }
        log(f"{name}: predicted bottleneck {plan.bottleneck_s * 1e3:.4f} "
            f"ms (cuts {plan.cuts}) vs quantile "
            f"{q_plan.bottleneck_s * 1e3:.4f} ms (cuts {q_cuts})")
        if not args.quick:
            m = compare_cuts(g, params, plan.cuts, q_cuts, codec="raw",
                             count=args.count, batch=args.batch,
                             int_input=int_in)
            del m["_plan"], m["_q"]
            row.update(m)
            log(f"{name}: measured bottleneck-stage "
                f"{row['plan_bottleneck_stage_ms']:.3f} ms (plan) vs "
                f"{row['quantile_bottleneck_stage_ms']:.3f} ms (quantile)"
                f"{' [identical cuts]' if row['identical_cuts'] else ''}")
            assert (row["plan_bottleneck_stage_ms"]
                    <= row["quantile_bottleneck_stage_ms"]
                    * args.tolerance), (
                f"{name}: measured bottleneck-stage time for bottleneck "
                f"cuts exceeds quantile's by more than the "
                f"{args.tolerance}x noise tolerance")
        rows[name] = row

    # -- the skewed chain: quantile cuts the fat boundary, and pays ------
    g = skewed_graph()
    cm = StageCostModel(g, batch=args.skew_batch, codecs=codecs,
                        link_bw_s=args.link_bw)
    plan = solve(g, 2, cm)
    q_cuts = auto_cut_points(g, 2)
    assert q_cuts == ["fat"], f"skew setup drifted: quantile cut {q_cuts}"
    assert plan.cuts != q_cuts, (
        f"skew setup drifted: solver also cut at {plan.cuts}")
    q_plan = evaluate_cuts(g, q_cuts, cm, objective="quantile")
    skew_row = {
        "predicted_plan_ms": round(plan.bottleneck_s * 1e3, 6),
        "predicted_quantile_ms": round(q_plan.bottleneck_s * 1e3, 6),
        "plan_cuts": plan.cuts, "quantile_cuts": q_cuts,
    }
    assert plan.bottleneck_s < q_plan.bottleneck_s, \
        "skewed chain: solver did not beat quantile even on its own model"
    if not args.quick:
        params = g.init(jax.random.key(0))
        m = compare_cuts(g, params, plan.cuts, q_cuts, codec="bf8",
                         count=args.skew_count, batch=args.skew_batch)
        del m["_plan"], m["_q"]
        skew_row.update(m)
        wall_gain = m["quantile_wall_s"] / m["plan_wall_s"]
        stage_gain = (m["quantile_bottleneck_stage_ms"]
                      / max(m["plan_bottleneck_stage_ms"], 1e-9))
        skew_row["measured_wall_improvement"] = round(wall_gain, 4)
        skew_row["measured_bottleneck_improvement"] = round(stage_gain, 4)
        log(f"skewed: quantile wall {m['quantile_wall_s']:.3f}s vs plan "
            f"{m['plan_wall_s']:.3f}s ({wall_gain:.2f}x); bottleneck-"
            f"stage {m['quantile_bottleneck_stage_ms']:.2f} ms vs "
            f"{m['plan_bottleneck_stage_ms']:.2f} ms ({stage_gain:.2f}x)")
        assert wall_gain >= args.min_improvement, (
            f"skewed chain: bottleneck cuts only {wall_gain:.3f}x faster "
            f"by wall time (need >= {args.min_improvement}x strict win)")
        assert stage_gain >= args.min_improvement, (
            f"skewed chain: bottleneck-stage time only {stage_gain:.3f}x "
            f"better (need >= {args.min_improvement}x strict win)")
    rows["skewed"] = skew_row

    row = {"metric": "plan_vs_quantile",
           "unit": "x_quantile_over_bottleneck",
           "value": skew_row.get("measured_wall_improvement"),
           "link_bw": args.link_bw,
           "models": rows}
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(row, f, indent=2, default=str)
            f.write("\n")
    print(json.dumps(row, default=str))
    log("plan smoke: OK")


if __name__ == "__main__":
    main()
