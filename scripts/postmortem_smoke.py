"""Black-box smoke: kill -9 forensics + journaling overhead.

Two legs over the flight-recorder journal (docs/OBSERVABILITY.md,
"Black box & postmortem"):

1. FIRST FAULT (multi-process): a 3-stage resnet_tiny chain with
   stage 1 replicated R=2, ``failover=True`` and ``--journal-dir`` on
   every process, stage-1 frames slowed so the stream is mid-flight
   when a killer thread SIGKILLs replica 0.  The supervisor respawns
   it AND auto-emits a postmortem bundle; after the stream completes
   (byte-identical to an undisturbed reference) the smoke re-runs
   :func:`~defer_tpu.obs.collect_postmortem` OFFLINE — every process
   is gone, only the on-disk journals remain — and asserts the
   verdict: ``first_fault`` names the killed replica (``stage1.r0``),
   the journal-stop evidence backs it, the nearest DOWNSTREAM stage is
   the first-ranked casualty, and the aligned timeline has no negative
   inter-process gap (the dispatcher's ``replica_respawn`` event lands
   at/after the victim journal's last write — clocks from different
   dead processes, aligned purely by their anchor records).

2. OVERHEAD: one in-process 3-stage delay chain (dsleep/esleep hop
   codecs park the budget in stage 1), streamed with the journal
   STOPPED then STARTED, alternately, three rounds — interleaving
   cancels host drift, min-of-3 absorbs scheduler spikes — and the
   journaling wall tax must stay under ``--max-overhead`` (default
   5%).

Exit 0 on success; one JSON row on stdout (the ``blackbox_overhead``
row of ``benchmarks/run.py``).

Usage:  python scripts/postmortem_smoke.py [--quick] [--count N]
            [--stage-delay-s 0.4] [--max-overhead 0.05]
"""

import argparse
import glob
import json
import os
import signal
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

from defer_tpu import partition  # noqa: E402
from defer_tpu.models import resnet_tiny  # noqa: E402
from defer_tpu.runtime.node import run_chain  # noqa: E402


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# leg 1: kill -9 a replica, then explain it from the journals alone
# ---------------------------------------------------------------------------

def run_first_fault(count: int, stage_delay_s: float, kill_at: int,
                    jdir: str, out_dir: str) -> dict:
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    stages = partition(g, num_stages=3)
    rng = np.random.default_rng(0)
    xs = [rng.standard_normal((1,) + stages[0].in_spec.shape)
          .astype(np.float32) for _ in range(count)]
    started = threading.Event()

    def feeder():
        for i, x in enumerate(xs):
            if i == kill_at:
                started.set()
            yield x

    def on_spawn(procs):
        # procs are one per stage REPLICA in stage-major order:
        # [s0, s1.r0, s1.r1, s2] — kill stage 1, replica 0
        def killer():
            started.wait(180)
            time.sleep(0.3)
            log(f"postmortem: SIGKILL pid {procs[1].pid} "
                f"(stage 1, replica 0)")
            procs[1].send_signal(signal.SIGKILL)
        threading.Thread(target=killer, daemon=True).start()

    with tempfile.TemporaryDirectory() as tmp:
        outs = run_chain(stages, params, feeder(), batch=1,
                         replicas={1: 2}, failover=True,
                         on_spawn=on_spawn, artifact_dir=tmp,
                         stage_delays=[0.0, stage_delay_s, 0.0],
                         journal_dir=jdir)
    with tempfile.TemporaryDirectory() as tmp:
        ref = run_chain(stages, params, list(xs), batch=1,
                        artifact_dir=tmp)
    if len(outs) != count or len(ref) != count:
        raise SystemExit(f"FAIL: {len(outs)} outputs, {len(ref)} "
                         f"reference, wanted {count}")
    for i, (a, b) in enumerate(zip(outs, ref)):
        np.testing.assert_array_equal(a, b, err_msg=f"sample {i}")

    # the supervisor's autopsy fired fire-and-forget ~0.75s after the
    # respawn, mid-stream — its bundle must be on disk by now
    deadline = time.time() + 10
    auto = []
    while time.time() < deadline:
        auto = sorted(glob.glob(os.path.join(jdir, "bundle-*",
                                             "bundle.json")))
        if auto:
            break
        time.sleep(0.2)
    assert auto, (f"no auto-emitted bundle under {jdir} — the failover "
                  f"supervisor's autopsy never landed")
    with open(auto[0]) as fh:
        auto_bundle = json.load(fh)
    assert auto_bundle.get("reason", "").startswith("failover:"), \
        auto_bundle.get("reason")
    assert len(auto_bundle["procs"]) >= 4, auto_bundle["procs"]

    # OFFLINE collect: every chain process has exited; the bundle is
    # assembled from nothing but the on-disk journals
    from defer_tpu.obs import collect_postmortem
    bundle = collect_postmortem(jdir, out_dir=out_dir,
                                reason="postmortem_smoke offline")
    for w in bundle["warnings"]:
        log(f"postmortem: bundle warning: {w}")

    procs = bundle["procs"]
    names = {p["proc"] for p in procs}
    want = {"dispatcher", "stage0", "stage1.r0", "stage1.r1", "stage2"}
    assert want <= names, f"journals missing: {want - names}"
    # the killed pid AND its respawn both journaled under stage1.r0
    r0 = [p for p in procs if p["proc"] == "stage1.r0"]
    assert len(r0) >= 2, (f"expected dead + respawned stage1.r0 "
                          f"journals, got {r0}")

    v = bundle["verdict"]
    assert v["first_fault"] == "stage1.r0", v
    assert any("stops at" in e for e in v["evidence"]), v["evidence"]
    assert v["casualties"], "no casualties ranked"
    first_cas = v["casualties"][0]
    assert first_cas["proc"] == "stage2", v["casualties"]
    assert first_cas["role"] == "downstream", v["casualties"]
    assert isinstance(bundle["events_dropped"], int)

    # clock alignment across DEAD processes: the supervisor's
    # replica_respawn (dispatcher clock) must land at/after the
    # victim's last journal write (victim clock) — a negative gap
    # means the anchor alignment is wrong
    respawn = next(e for e in bundle["timeline"]
                   if e["kind"] == "replica_respawn")
    victim_last = min(p["last_us"] for p in r0)
    gap_s = (respawn["t_us"] - victim_last) / 1e6
    assert gap_s >= 0, (f"respawn at {respawn['t_us']}us precedes the "
                        f"victim's last write {victim_last}us "
                        f"({gap_s:.3f}s) — clock alignment failed")
    ts = [e["t_us"] for e in bundle["timeline"]]
    assert ts == sorted(ts), "merged timeline is not time-ordered"

    trace = os.path.join(out_dir, "trace.json")
    with open(trace) as fh:
        doc = json.load(fh)
    tprocs = {e["args"]["name"] for e in doc["traceEvents"]
              if e["ph"] == "M"}
    assert len(tprocs) >= 5, tprocs

    log(f"postmortem: byte-identical x{count}, {len(procs)} journals, "
        f"first_fault={v['first_fault']}, casualty[0]={first_cas['proc']}"
        f" ({first_cas['role']}), respawn gap +{gap_s:.2f}s, "
        f"auto bundle at {os.path.dirname(auto[0])}")
    return {"byte_identical": True, "count": count,
            "journals": len(procs),
            "first_fault": v["first_fault"],
            "casualties": [c["proc"] for c in v["casualties"]],
            "respawn_gap_s": round(gap_s, 3),
            "events_dropped": bundle["events_dropped"],
            "auto_bundle": True,
            "timeline_events": len(bundle["timeline"])}


# ---------------------------------------------------------------------------
# leg 2: the journaling tax
# ---------------------------------------------------------------------------

def run_overhead(count: int, delay_ms: float, rounds: int,
                 root: str) -> dict:
    from defer_tpu.obs import (read_process_journals, start_journal,
                               stop_journal)
    from defer_tpu.runtime.node import ChainDispatcher, StageNode

    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    stages = partition(g, num_stages=3)
    codecs = [f"dsleep{delay_ms:g}+raw", f"esleep{delay_ms:g}+raw",
              "raw"]
    rng = np.random.default_rng(3)
    xs = [rng.standard_normal((1,) + stages[0].in_spec.shape)
          .astype(np.float32) for _ in range(count)]

    nodes = [StageNode(None, "127.0.0.1:0", None) for _ in range(3)]
    addrs = [f"127.0.0.1:{n.address[1]}" for n in nodes]
    ths = [threading.Thread(target=n.serve, daemon=True) for n in nodes]
    for t in ths:
        t.start()
    disp = ChainDispatcher(addrs[0], codec="raw")
    disp.deploy(stages, params, addrs, batch=1, codecs=codecs)
    try:
        disp.stream(xs[:4])       # compile + connect outside the clock
        # ONE chain, alternating journal-off / journal-on streams:
        # each pair sees the same background load, so host drift
        # cancels; min-of-3 absorbs per-stream scheduler spikes
        w_off, w_on = [], []
        for r in range(rounds):
            stop_journal()
            t0 = time.perf_counter()
            disp.stream(xs)
            w_off.append(time.perf_counter() - t0)
            start_journal(os.path.join(root, f"round{r}"), "bench")
            t0 = time.perf_counter()
            disp.stream(xs)
            w_on.append(time.perf_counter() - t0)
        stop_journal()
    finally:
        disp.close()
        for t in ths:
            t.join(timeout=30)
    wall_off, wall_on = min(w_off), min(w_on)
    overhead = wall_on / wall_off - 1.0
    # the journal must have actually spilled during the on-streams
    spilled = sum(len(j["records"])
                  for r in range(rounds)
                  for j in read_process_journals(
                      os.path.join(root, f"round{r}")))
    assert spilled > 0, "journal-on rounds wrote no records"
    log(f"postmortem: journaling off {wall_off:.3f}s / on {wall_on:.3f}s"
        f" -> overhead {overhead * 100:+.2f}% ({spilled} records "
        f"spilled over {rounds} rounds)")
    return {"wall_off_s": round(wall_off, 4),
            "wall_on_s": round(wall_on, 4),
            "overhead": overhead, "spilled_records": spilled}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CI budget: fewer frames")
    ap.add_argument("--count", type=int, default=0,
                    help="frames for the kill leg (0 = 12 quick / 18 "
                         "full)")
    ap.add_argument("--stage-delay-s", type=float, default=0.4,
                    help="per-frame stage-1 delay keeping the kill "
                         "inside the in-flight window")
    ap.add_argument("--delay-ms", type=float, default=6.0,
                    help="per-hop codec delay for the overhead leg")
    ap.add_argument("--max-overhead", type=float, default=0.05,
                    help="journaling wall overhead bound vs journal-off")
    args = ap.parse_args()
    count = args.count or (12 if args.quick else 18)

    t0 = time.time()
    with tempfile.TemporaryDirectory() as jdir, \
            tempfile.TemporaryDirectory() as out:
        ff = run_first_fault(count, args.stage_delay_s,
                             kill_at=count // 3, jdir=jdir,
                             out_dir=os.path.join(out, "bundle"))
        ov = run_overhead(32 if args.quick else 48, args.delay_ms,
                          rounds=3, root=os.path.join(out, "bench"))
    assert ov["overhead"] < args.max_overhead, (
        f"journaling overhead {ov['overhead'] * 100:.2f}% exceeds "
        f"{args.max_overhead * 100:.0f}% (on {ov['wall_on_s']}s vs off "
        f"{ov['wall_off_s']}s)")
    row = {"metric": "blackbox_overhead",
           "value": round(ov["overhead"], 4),
           "unit": "frac_wall_overhead_vs_no_journal",
           "quick": args.quick,
           **ff, **{k: v for k, v in ov.items() if k != "overhead"},
           "elapsed_s": round(time.time() - t0, 1)}
    print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
