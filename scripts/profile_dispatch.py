"""Per-phase breakdown of the flat ~76 ms/step seen in BENCH_r03.json.

Measures, on the real chip, each candidate component of a pipeline step:

  sync_rtt        — trivial jit program, block_until_ready per call
                    (host<->device round trip incl. the axon tunnel)
  async_dispatch  — same program, 100 chained calls, one final block
                    (marginal cost of an *enqueued* execution)
  h2d / d2h       — host->device and device->host of one ResNet50 input /
                    output block
  compute_b{B}    — ResNet50 bf16 forward at batch B, amortized over a
                    K-step on-device lax.scan (per-step device compute,
                    no per-step host involvement)
  stepwise_b{B}   — the same forward dispatched per step with a sync
                    (the r3 bench protocol — what produced the 76 ms)

Prints one JSON dict; PROFILE_r04.md is written from this.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp


def timeit(fn, iters, warmup=2):
    for _ in range(warmup):
        fn()
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    return (time.perf_counter() - t0) / iters


def main():
    devices = jax.devices()
    dev = devices[0]
    out = {"device_kind": str(getattr(dev, "device_kind", "")),
           "platform": dev.platform}
    print(f"profiling on {dev.platform} | {out['device_kind']}",
          file=sys.stderr, flush=True)

    # --- 1. sync round-trip of a trivial program
    f = jax.jit(lambda x: x + 1.0)
    x0 = jnp.zeros(())
    jax.block_until_ready(f(x0))
    out["sync_rtt_ms"] = round(
        timeit(lambda: jax.block_until_ready(f(x0)), 20) * 1e3, 3)

    # --- 2. marginal cost of an async (queued) dispatch
    def chain(n=100):
        y = x0
        for _ in range(n):
            y = f(y)
        jax.block_until_ready(y)

    chain(5)
    t0 = time.perf_counter()
    chain(100)
    out["async_dispatch_ms"] = round((time.perf_counter() - t0) / 100 * 1e3,
                                     4)

    # --- 3. host<->device transfers (one input / 32-batch input)
    one = np.zeros((1, 224, 224, 3), np.float32)
    b32 = np.zeros((32, 224, 224, 3), np.float32)
    out["h2d_1img_ms"] = round(
        timeit(lambda: jax.block_until_ready(jax.device_put(one)), 10) * 1e3,
        3)
    out["h2d_32img_ms"] = round(
        timeit(lambda: jax.block_until_ready(jax.device_put(b32)), 10) * 1e3,
        3)
    # d2h must convert a FRESH device array each iteration — jax.Array
    # caches its host copy, so re-converting one array times a cache hit.
    # The jit bump adds one (measured-above) async dispatch to each iter.
    bump = jax.jit(lambda x: x + 1.0)
    dlogits = jnp.zeros((32, 1000), jnp.float32)
    jax.block_until_ready(dlogits)
    out["d2h_32logits_ms"] = round(
        timeit(lambda: np.asarray(bump(dlogits)), 10) * 1e3, 3)

    # --- 4. ResNet50 bf16 forward: true device compute via on-device scan
    from defer_tpu.graph.analysis import total_flops
    from defer_tpu.models import resnet50
    from defer_tpu.utils.hw import identify_chip, peak_flops

    g = resnet50()
    params = jax.tree.map(lambda a: jnp.asarray(a, jnp.bfloat16),
                          g.init(jax.random.key(0)))
    flops = float(total_flops(g))
    peak = peak_flops(identify_chip(dev))
    out["flops_per_img"] = flops
    out["peak_flops"] = peak

    from defer_tpu.utils.profiling import amortized_forward_seconds

    fwd = jax.jit(lambda p, x: g.apply(p, x))

    for batch, k in ((1, 64), (8, 64), (32, 32), (64, 32), (128, 16)):
        try:
            xk = jnp.zeros((batch, 224, 224, 3), jnp.bfloat16)
            sec = amortized_forward_seconds(g.apply, params, xk, k)
            out[f"compute_b{batch}_ms_per_step"] = round(sec * 1e3, 3)
            out[f"compute_b{batch}_mfu"] = round(
                flops * batch / sec / peak, 4) if peak else None
            print(f"compute b{batch}: {sec * 1e3:.3f} ms/step "
                  f"MFU {out[f'compute_b{batch}_mfu']}",
                  file=sys.stderr, flush=True)
        except Exception as e:  # noqa: BLE001 — OOM at big batches is data
            out[f"compute_b{batch}_error"] = repr(e)[:200]

    # --- 5. the r3 protocol for contrast: per-step dispatch + sync
    for batch in (1, 32):
        xb = jnp.zeros((batch, 224, 224, 3), jnp.bfloat16)
        jax.block_until_ready(fwd(params, xb))
        sec = timeit(lambda: jax.block_until_ready(fwd(params, xb)), 8)
        out[f"stepwise_b{batch}_ms"] = round(sec * 1e3, 3)

    # --- 5b. the tunnel's two latency modes: re-measure the same trivial
    # scalar sync from step 1 now that a large program has run.  Observed:
    # ~0.04 ms in a pristine session, ~62-65 ms after the first big
    # executable — EVERY subsequent sync (block_until_ready or d2h, any
    # payload size) pays it, and spinning on is_ready() doesn't dodge it.
    # This, not per-step compute or h2d, is the flat ~70-80 ms of the r3
    # bench.
    scalar0 = jnp.zeros(())
    out["sync_rtt_after_heavy_ms"] = round(
        timeit(lambda: jax.block_until_ready(f(scalar0)), 20) * 1e3, 3)

    # --- 6. per-step dispatch, async window (W in flight, block at end)
    for batch, w in ((32, 16),):
        xb = jnp.zeros((batch, 224, 224, 3), jnp.bfloat16)
        jax.block_until_ready(fwd(params, xb))

        def window():
            ys = [fwd(params, xb) for _ in range(w)]
            jax.block_until_ready(ys[-1])

        sec = timeit(window, 4) / w
        out[f"async_window_b{batch}_ms_per_step"] = round(sec * 1e3, 3)

    print(json.dumps(out))


if __name__ == "__main__":
    main()
