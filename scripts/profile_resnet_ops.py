"""Per-op breakdown of ResNet50 bf16 step time on the TPU chip.

VERDICT r4 weakness #4: best measured MFU was ~41% with no evidence of
where the ceiling is.  This script times every parametric op of the
deployed graph standalone (scan-amortized, batch-128 bf16, same layouts
as the pipeline), compares each against its FLOP lower bound at chip
peak, and reports which ops are MXU-bound vs bandwidth-bound — the
committed per-op evidence for (or against) a conv-bound ceiling.

Output: one JSON object on stdout ({"rows": [...], "totals": {...}}).
"""

import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    import jax
    import jax.numpy as jnp
    import numpy as np

    from defer_tpu.models import resnet50
    from defer_tpu.utils.hw import identify_chip, peak_flops
    from defer_tpu.utils.profiling import amortized_forward_seconds

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    gen = identify_chip(dev)
    peak = peak_flops(gen) if on_tpu else 0.0
    log(f"profile: {dev.platform} {gen} peak={peak / 1e12:.0f} TF/s "
        f"batch={batch}")

    graph = resnet50()
    params = graph.init(jax.random.key(0))
    params = jax.tree.map(lambda a: a.astype(jnp.bfloat16), params)

    rows = []
    for name in graph.topo_order:
        node = graph.nodes[name]
        in_specs = [graph.out_spec(i) for i in node.inputs]
        flops = node.op.flops(tuple(in_specs), node.out_spec) * batch
        xs = [jnp.zeros((batch,) + s.shape, jnp.bfloat16)
              for s in in_specs]
        p = params.get(name)
        if len(xs) == 1:
            sec = amortized_forward_seconds(
                lambda pp, xx, _op=node.op: _op.apply(pp, xx), p, xs[0],
                16, min_s=0.5, max_iters=8)
        else:
            # multi-input (Add): plain jit loop — cheap elementwise op,
            # dispatch amortization matters less here
            import time as _t
            fn = jax.jit(lambda pp, *xx, _op=node.op: _op.apply(pp, *xx))
            jax.block_until_ready(fn(p, *xs))
            t0 = _t.perf_counter()
            for _ in range(8):
                out = fn(p, *xs)
            jax.block_until_ready(out)
            sec = (_t.perf_counter() - t0) / 8
        row = {
            "node": name,
            "op": repr(node.op),
            "ms": round(sec * 1e3, 4),
            "gflops": round(flops / 1e9, 3),
        }
        if peak > 0:
            row["mfu"] = round(flops / sec / peak, 4)
            # bytes touched (bf16 in+out+params): the bandwidth-bound test
            nbytes = 2 * (sum(batch * s.size for s in in_specs)
                          + batch * node.out_spec.size
                          + sum(np.size(l) for l in
                                jax.tree.leaves(p or {})))
            row["gb_per_s"] = round(nbytes / sec / 1e9, 1)
        rows.append(row)
        log(f"  {name:28s} {row['ms']:9.3f} ms  {row['gflops']:8.1f} GF"
            + (f"  MFU {row['mfu']:.2f}" if "mfu" in row else ""))

    total_ms = sum(r["ms"] for r in rows)
    total_gf = sum(r["gflops"] for r in rows)
    from defer_tpu.utils.profiling import timed_window
    fwd = jax.jit(graph.apply)
    x = jnp.zeros((batch,) + graph.input_spec.shape, jnp.bfloat16)
    fused_s = timed_window(lambda: jax.block_until_ready(fwd(params, x)),
                           min_s=2.0, max_iters=64)
    out = {
        "metric": "resnet50_per_op_profile",
        "batch": batch,
        "platform": dev.platform,
        "tpu_generation": gen if on_tpu else None,
        "rows": sorted(rows, key=lambda r: -r["ms"]),
        "totals": {
            "sum_of_op_ms": round(total_ms, 3),
            "fused_graph_ms": round(fused_s * 1e3, 3),
            "fusion_gain": round(total_ms / (fused_s * 1e3), 3),
            "sum_gflops": round(total_gf, 1),
            "fused_mfu": round(total_gf * 1e9 / fused_s / peak, 4)
            if peak > 0 else None,
            # if every op ran at peak, the floor:
            "flop_floor_ms": round(total_gf / peak * 1e6, 3)
            if peak > 0 else None,
        },
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
