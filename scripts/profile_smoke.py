"""Stage-interior profiling smoke: the X-ray accounts for the frame.

A 3-stage resnet_tiny chain with a delay-bound middle stage (the
``monitor_smoke.py`` rig: decode/encode-side sleeps on stage 1's hops)
streams while the ``defer_tpu profile`` plane attaches to it:

1. PHASES SUM: a live ``defer_tpu profile`` window (the REAL CLI, in a
   thread, over the nodes' ctrl sockets) returns per-node DELTA phase
   breakdowns whose dispatch + queue + device + host_sync seconds tile
   the measured ``infer`` wall within ``--phase-tol`` (10%) on EVERY
   stage — the decomposition is exhaustive, not decorative.  With
   ``--spans`` the merged Perfetto export must carry all three phases'
   spans for all three stages.
2. RECOMPILE TELEMETRY: after warmup, an injected input-shape change
   must bump the ``jax.compiles`` counter for every stage program and
   fire EXACTLY ONE ``recompile`` flight-recorder event in this
   process (episode discipline: one event per burst, not one per XLA
   invocation); a subsequent stream at the original shape must compile
   NOTHING (the steady-state-zero claim the decode bench relies on).
3. SESSION OVERHEAD: two identical delay chains streamed alternately
   (min-of-3, the ``monitor_smoke`` interleave that cancels host
   drift); one carries an active profile session for the whole
   measurement, the other is left alone.  The session must cost
   < ``--max-overhead`` (5%) wall — attaching the profiler to a
   production stream is free, because a session only SNAPSHOTS the
   always-on phase histograms (two ``perf_counter`` calls + two O(1)
   histogram records per frame, priced inside ``monitor_smoke``'s
   telemetry bound).

The chain runs in-process (thread nodes over real TCP sockets — the
ctrl protocol, clock probes, and span dumps all ride the real wire);
``--quick`` only shrinks the frame counts for CI.  Exit 0 on success;
one JSON row on stdout (the ``profile_overhead`` row of
``benchmarks/run.py``).
"""

import argparse
import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def hop_codecs(delay_ms: float) -> list[str]:
    if delay_ms <= 0:
        return ["raw", "raw", "raw"]
    return [f"dsleep{delay_ms:g}+raw", f"esleep{delay_ms:g}+raw", "raw"]


def boot_inproc(stages, params, codecs, *, batch, sample=0):
    from defer_tpu.runtime.node import ChainDispatcher, StageNode
    nodes = [StageNode(None, "127.0.0.1:0", None) for _ in range(3)]
    addrs = [f"127.0.0.1:{n.address[1]}" for n in nodes]
    threads = [threading.Thread(target=n.serve, daemon=True)
               for n in nodes]
    for t in threads:
        t.start()
    disp = ChainDispatcher(addrs[0], codec="raw",
                           trace_sample_every=sample)
    disp.deploy(stages, params, addrs, batch=batch, codecs=codecs)
    return disp, addrs, threads


def run_profile_cli(addrs, *, seconds, out_path, trace_out=None,
                    done: dict | None = None):
    """Invoke the REAL ``defer_tpu profile`` CLI against the chain."""
    from defer_tpu import cli
    argv = ["profile", "--nodes", ",".join(addrs),
            "--seconds", str(seconds), "--out", out_path]
    if trace_out:
        # default --sample-every 0: record every frame's phase spans —
        # works on any stream, stamped or not (1-in-N sampling needs a
        # dispatcher started with trace_sample_every >= 1)
        argv += ["--spans", "--trace-out", trace_out]
    cli.main(argv)
    if done is not None:
        done["ok"] = True


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="smaller frame counts (CI mode)")
    ap.add_argument("--count", type=int, default=0,
                    help="microbatches per measured stream "
                         "(0 = 24 quick / 48 full)")
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--delay-ms", type=float, default=5.0,
                    help="per-side delay on the bottleneck stage's hops")
    ap.add_argument("--phase-tol", type=float, default=0.10,
                    help="|dispatch+device+host_sync - infer| bound, "
                         "relative to the infer wall")
    ap.add_argument("--max-overhead", type=float, default=0.05,
                    help="active-session wall overhead bound")
    args = ap.parse_args()
    count = args.count or (24 if args.quick else 48)

    import tempfile

    import numpy as np

    import jax

    from defer_tpu import partition
    from defer_tpu.models import resnet_tiny
    from defer_tpu.obs import recorder, recompile_watcher, tracer
    from defer_tpu.obs.registry import REGISTRY

    graph = resnet_tiny()
    params = graph.init(jax.random.key(0))
    stages = partition(graph, num_stages=3)
    rng = np.random.default_rng(7)
    xs = [rng.standard_normal((args.batch, 32, 32, 3)).astype(np.float32)
          for _ in range(count)]
    delays = hop_codecs(args.delay_ms)
    tr = tracer()
    tr.enabled = False

    with tempfile.TemporaryDirectory(prefix="defer_prof_") as tmp:
        # ---- 1. phase sums under a live CLI window ------------------
        disp, addrs, _ = boot_inproc(stages, params, delays,
                                     batch=args.batch)
        prof_json = os.path.join(tmp, "profile.json")
        trace_json = os.path.join(tmp, "trace.json")
        try:
            t0 = time.perf_counter()
            disp.stream(xs[:4])                 # compile + connect
            w1 = time.perf_counter() - t0
            # window long enough that streaming is what fills it
            window_s = max(2.0, 3.0 * w1)
            done: dict = {}
            th = threading.Thread(
                target=run_profile_cli, args=(addrs,),
                kwargs=dict(seconds=window_s, out_path=prof_json,
                            trace_out=trace_json, done=done),
                daemon=True)
            th.start()
            while th.is_alive():
                disp.stream(xs)
            th.join(timeout=120)
            assert done.get("ok"), "profile CLI did not finish"
        finally:
            disp.close()
        doc = json.load(open(prof_json))
        assert len(doc["nodes"]) == 3, doc
        sums = {}
        for addr, rep in doc["nodes"].items():
            ph = rep["phases"]
            inf = ph["infer"]
            assert inf["count"] > 0, (addr, rep)
            got = sum(ph[k]["sum_s"]
                      for k in ("dispatch", "queue", "device",
                                "host_sync"))
            rel = abs(got - inf["sum_s"]) / inf["sum_s"]
            sums[rep["node"]] = {
                "infer_s": round(inf["sum_s"], 4),
                "phase_sum_s": round(got, 4),
                "rel_err": round(rel, 4),
                "frames": inf["count"],
                "dispatch_share": rep.get("dispatch_share")}
            log(f"{rep['node']}: infer {inf['sum_s']:.3f}s over "
                f"{inf['count']} frames, phases sum {got:.3f}s "
                f"(rel err {rel * 100:.2f}%, dispatch share "
                f"{rep.get('dispatch_share')})")
            assert rel <= args.phase_tol, (
                f"{rep['node']}: dispatch+device+host_sync = {got:.4f}s "
                f"does not account for infer = {inf['sum_s']:.4f}s "
                f"(rel err {rel * 100:.1f}% > "
                f"{args.phase_tol * 100:.0f}%)")
            # the window may split a frame: counts agree to +-2
            for k in ("dispatch", "queue", "device", "host_sync"):
                assert abs(ph[k]["count"] - inf["count"]) <= 2, (k, ph)
        trace = json.load(open(trace_json))
        names = {e["name"] for e in trace["traceEvents"]
                 if e.get("ph") == "X"}
        for k in range(3):
            for phase in ("dispatch", "queue", "device", "host_sync",
                          "infer"):
                assert f"stage{k}.{phase}" in names, (
                    f"stage{k}.{phase} span missing from the merged "
                    f"trace: {sorted(names)}")
        tr.enabled = False
        tr.clear()

        # ---- 2. recompile telemetry on an injected shape change -----
        # (the wire protocol pins the batch per deployment, so the
        # injected "shape change" is a fresh deploy at batch+1 — the
        # same process compiles three NEW stage programs while armed)
        watcher = recompile_watcher()
        watcher.install()
        watcher.disarm()        # part 1's profile session armed it
        rec = recorder()
        ev0 = sum(1 for e in rec.snapshot()
                  if e["kind"] == "recompile")
        disp, addrs, _ = boot_inproc(stages, params, delays,
                                     batch=args.batch)
        disp2 = None
        try:
            disp.stream(xs[:4])                 # warm at the base shape
            c_warm = watcher.count
            assert c_warm > 0, "warmup compiles were not counted"
            assert sum(1 for e in rec.snapshot()
                       if e["kind"] == "recompile") == ev0, (
                "warmup compiles fired events before arm()")
            watcher.arm()
            disp.stream(xs[:8])                 # steady state
            assert watcher.count == c_warm, (
                f"steady-state stream compiled "
                f"{watcher.count - c_warm} programs")
            odd = [rng.standard_normal(
                (args.batch + 1, 32, 32, 3)).astype(np.float32)
                for _ in range(2)]
            disp2, _, _ = boot_inproc(stages, params, delays,
                                      batch=args.batch + 1)
            disp2.stream(odd)                   # every stage compiles
            c1 = watcher.count
            ev1 = sum(1 for e in rec.snapshot()
                      if e["kind"] == "recompile")
            assert c1 - c_warm >= 3, (
                f"shape change compiled only {c1 - c_warm} programs "
                f"(expected >= 3, one per stage)")
            assert ev1 - ev0 == 1, (
                f"expected exactly one recompile event per process per "
                f"episode, saw {ev1 - ev0}")
            # steady state again: both deployments now cached
            disp.stream(xs[:8])
            disp2.stream(odd)
            assert watcher.count == c1, (
                f"steady-state stream still compiled "
                f"{watcher.count - c1} programs")
            log(f"recompile telemetry: warmup {c_warm} compiles / 0 "
                f"events, injected {c1 - c_warm} -> 1 event, steady "
                f"state 0")
        finally:
            disp.close()
            if disp2 is not None:
                disp2.close()

        # ---- 3. an active session costs nothing ---------------------
        disp_off, addrs_off, _ = boot_inproc(stages, params, delays,
                                             batch=args.batch)
        disp_on, addrs_on, _ = boot_inproc(stages, params, delays,
                                           batch=args.batch)
        try:
            disp_off.stream(xs[:4])
            disp_on.stream(xs[:4])
            sess_out = os.path.join(tmp, "session.json")
            done2: dict = {}
            # generous window: the CLI sleeps it out while we measure
            th = threading.Thread(
                target=run_profile_cli, args=(addrs_on,),
                kwargs=dict(seconds=3600.0, out_path=sess_out,
                            done=done2), daemon=True)
            # the CLI sleeps --seconds; interrupt it by closing from
            # this side is not part of the protocol, so bound the
            # window instead: measure first, with the session open
            w_off, w_on = [], []
            th2 = None
            try:
                # profile_start lands before the first on-round: poll
                # the node's stats 'profiling' flag
                from defer_tpu.runtime.node import (_connect_retry,
                                                    _parse_hostport)
                from defer_tpu.transport.framed import (K_CTRL,
                                                        recv_expect,
                                                        send_ctrl,
                                                        send_end)
                th2 = th
                th.start()
                deadline = time.time() + 60
                while time.time() < deadline:
                    s = _connect_retry(*_parse_hostport(addrs_on[0]),
                                       timeout_s=10)
                    send_ctrl(s, {"cmd": "stats"})
                    st = recv_expect(s, K_CTRL)
                    send_end(s)
                    s.close()
                    if st.get("profiling"):
                        break
                    time.sleep(0.05)
                else:
                    raise AssertionError("profile session never opened")
                for _ in range(3):
                    t0 = time.perf_counter()
                    outs_off = disp_off.stream(xs)
                    w_off.append(time.perf_counter() - t0)
                    t0 = time.perf_counter()
                    outs_on = disp_on.stream(xs)
                    w_on.append(time.perf_counter() - t0)
            finally:
                # release the sleeping CLI thread: stop the sessions
                # out from under it is harmless — it exits on
                # profile_stop's profile_err reply
                if th2 is not None and th2.is_alive():
                    for a in addrs_on:
                        s = _connect_retry(*_parse_hostport(a),
                                           timeout_s=10)
                        send_ctrl(s, {"cmd": "profile_stop"})
                        recv_expect(s, K_CTRL)
                        send_end(s)
                        s.close()
            wall_off, wall_on = min(w_off), min(w_on)
            assert len(outs_on) == len(outs_off) == count
            for a, b in zip(outs_off, outs_on):
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(b))
        finally:
            disp_off.close()
            disp_on.close()
        overhead = wall_on / wall_off - 1.0
        log(f"session off: {count * args.batch / wall_off:7.1f} inf/s "
            f"({wall_off:.3f}s)")
        log(f"session on:  {count * args.batch / wall_on:7.1f} inf/s "
            f"({wall_on:.3f}s, {overhead * 100:+.2f}% — bound "
            f"{args.max_overhead * 100:.0f}%)")
        assert overhead < args.max_overhead, (
            f"active profile session costs {overhead * 100:.2f}% "
            f"(> {args.max_overhead * 100:.0f}%) wall")

    row = {"metric": "profile_overhead", "value": round(overhead, 4),
           "unit": "frac_wall_overhead_vs_no_session",
           "quick": args.quick, "count": count, "batch": args.batch,
           "delay_ms": args.delay_ms,
           "wall_off_s": round(wall_off, 4),
           "wall_on_s": round(wall_on, 4),
           "phase_sums": sums,
           "recompiles_injected": c1 - c_warm,
           "recompile_events": ev1 - ev0,
           "registry_compiles": REGISTRY.counter("jax.compiles").value,
           "cpu_count": os.cpu_count() or 1}
    print(json.dumps(row))
    log("profile smoke: OK")


if __name__ == "__main__":
    main()
