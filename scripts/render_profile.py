"""Render PROFILE_r04.md from scripts/profile_dispatch.py's JSON output.

Usage: python scripts/render_profile.py PROFILE_r04.json > PROFILE_r04.md
"""

import json
import sys


def main():
    with open(sys.argv[1]) as f:
        d = json.loads(f.read().strip().splitlines()[-1])

    def g(k, fmt="{:.3f}"):
        v = d.get(k)
        return fmt.format(v) if isinstance(v, (int, float)) else "n/a"

    rtt = d.get("sync_rtt_ms", float("nan"))
    lines = [
        "# PROFILE r4 — where the flat ~76 ms/step of BENCH_r03 goes",
        "",
        f"Measured on `{d.get('device_kind', '?')}` "
        f"(platform `{d.get('platform', '?')}`) by "
        "`scripts/profile_dispatch.py`; raw JSON in `PROFILE_r04.json`.",
        "",
        "## Per-phase cost of one pipeline step",
        "",
        "| Phase | ms | Notes |",
        "|---|---|---|",
        f"| sync round trip, pristine session (`sync_rtt_ms`) | "
        f"{g('sync_rtt_ms')} | trivial jit program, dispatch + "
        "block_until_ready, measured before any large program has run |",
        f"| sync round trip after first heavy program "
        f"(`sync_rtt_after_heavy_ms`) | {g('sync_rtt_after_heavy_ms')} | "
        "same trivial sync re-measured after one ResNet executable: the "
        "tunnel permanently drops into a slow mode where EVERY sync "
        "(block_until_ready or d2h, any payload size) pays this; spinning "
        "on `is_ready()` does not dodge it |",
        f"| marginal enqueued dispatch (`async_dispatch_ms`) | "
        f"{g('async_dispatch_ms', '{:.4f}')} | 100 chained executions, one "
        "final block — the cost a dispatch adds when nobody waits on it |",
        f"| h2d, one 224×224×3 f32 image | {g('h2d_1img_ms')} | 602 KB "
        "device_put |",
        f"| h2d, 32-image batch | {g('h2d_32img_ms')} | 19.3 MB |",
        f"| d2h, 32×1000 f32 logits (fresh array) | {g('d2h_32logits_ms')} "
        "| includes one enqueued dispatch |",
    ]
    for b in (1, 8, 32, 64, 128):
        k = f"compute_b{b}_ms_per_step"
        if k in d:
            lines.append(
                f"| ResNet50 bf16 forward, batch {b} (scan-amortized) | "
                f"{g(k)} | device compute only; MFU "
                f"{g(f'compute_b{b}_mfu', '{:.4f}')} |")
        ek = f"compute_b{b}_error"
        if ek in d:
            lines.append(f"| ResNet50 forward, batch {b} | error | "
                         f"`{d[ek][:80]}` |")
    for b in (1, 32):
        k = f"stepwise_b{b}_ms"
        if k in d:
            lines.append(
                f"| ResNet50 forward, batch {b}, per-step dispatch+sync | "
                f"{g(k)} | the r3 protocol — the per-sync round trip "
                "dominates |")
    k = "async_window_b32_ms_per_step"
    if k in d:
        lines.append(
            f"| ResNet50 forward, batch 32, 16 dispatches in flight | "
            f"{g(k)} | per-step cost when only the window edge syncs |")

    comp32 = d.get("compute_b32_ms_per_step")
    step32 = d.get("stepwise_b32_ms")
    slow = d.get("sync_rtt_after_heavy_ms")
    lines += ["", "## Reading", ""]
    if slow is not None:
        lines.append(
            f"* The tunnel has two latency modes: ~{rtt:.2f} ms per sync in "
            f"a pristine session, ~{slow:.0f} ms per sync once the first "
            "large executable has run — and a real deployment is always in "
            "the slow mode. The r3 bench synced after every step, so every "
            f"step paid that ~{slow:.0f} ms — that is why step time was "
            "flat (75.95→83.34 ms) across a 32× batch increase and "
            "best-case MFU was 1.5% (`BENCH_r03.json`).")
    else:
        lines.append(
            "* (sync_rtt_after_heavy_ms missing from this JSON — re-run "
            "scripts/profile_dispatch.py for the two-mode sync breakdown.)")
    if comp32 is not None and step32:
        lines.append(
            f"* Actual device compute at batch 32 is {comp32:.3f} ms/step — "
            f"{step32 / max(comp32, 1e-9):.0f}× smaller than the stepwise "
            "number. The overhead is sync latency, not compute, transfer, "
            "or dispatch.")
    lines += [
        "* Mitigation shipped in r4 (`bench.py`, "
        "`defer_tpu/utils/profiling.py`): fuse K steps per dispatch "
        "(`lax.scan`), keep ≥2 chunk dispatches in flight, sync only at "
        "window edges, drain results as one slab per chunk "
        "(`SpmdPipeline.push(raw=True)`).",
        "",
        f"Model: {g('flops_per_img', '{:.3e}')} FLOPs/img vs chip peak "
        f"{g('peak_flops', '{:.3e}')} FLOP/s.",
    ]
    print("\n".join(lines))


if __name__ == "__main__":
    main()
