"""Stage-replication smoke: prove hybrid pipeline/data-parallelism pays.

A 3-stage resnet_tiny chain is given an artificial bottleneck stage:
stage 1's inbound hop uses a decode-side delay codec (``dsleep<ms>+raw``)
and its outbound hop an encode-side one (``esleep<ms>+raw``), so every
frame costs the *stage-1 process* a fixed non-CPU delay on each side —
the resource profile of an accelerator-bound fat stage a 1-core host
cannot express with real compute.  No cut can fix a single slow stage;
running R=2 data-parallel replicas of it (``--replicas stage1=2``,
ordered fan-out/fan-in with protocol-v2 sequence numbers) should halve
its effective service time.

Checks:

1. QUICK (in-process thread chain): replicated vs serial over identical
   inputs — byte-identical outputs in identical ORDER (the reorder merge
   is exercised for real: per-replica ``stage1.rN.*`` spans must appear
   in the collected trace, and the round-robin split must show in per-
   replica ``stats``), measured speedup >= ``--quick-min-speedup``.

2. SOLVER (predictive): on a cost model with one dominating stage, the
   replica-aware solver must replicate that stage and predict a
   bottleneck <= the best cuts-only plan's (the full DP-vs-brute-force
   property lives in tests/test_plan.py).

3. SPEEDUP (multi-process, skipped with ``--quick``): the same chain as
   real OS processes — R=2 replicas of stage 1 vs the unreplicated
   baseline, warmup excluded, byte-identical outputs required, measured
   throughput >= ``--min-speedup`` (default 1.5) better.  The delays
   sleep rather than burn CPU, so the win is real even on a 1-core CI
   host.

Exit 0 on success; one JSON row on stdout (the ``stage_replication`` row
of ``benchmarks/run.py``).

Usage:  python scripts/replication_smoke.py [--quick] [--delay-ms D]
            [--count N] [--min-speedup 1.5]
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: stage-node subprocesses must never touch a (single-client) TPU tunnel
CPU_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def hop_codecs(delay_ms: float) -> list[str]:
    """Per-stage outbound codecs that park the whole delay budget inside
    stage 1's process(es): decode-side sleep on its inbound hop,
    encode-side sleep on its outbound hop."""
    return [f"dsleep{delay_ms:g}+raw", f"esleep{delay_ms:g}+raw", "raw"]


# ---------------------------------------------------------------------------
# part 1: in-process thread chain — byte-identity, ordering, trace, speedup
# ---------------------------------------------------------------------------

def run_inproc(stages, params, xs, *, replicate: int, delay_ms: float):
    """Thread-per-node chain with the delay codecs; stage 1 optionally
    replicated.  Returns (outs, seconds, stats, spans)."""
    from defer_tpu.obs import enable_tracing, tracer
    from defer_tpu.runtime.node import ChainDispatcher, StageNode

    tr = enable_tracing(process="dispatcher")
    tr.start_trace()
    r1 = max(1, replicate)
    groups = [
        [StageNode(None, "127.0.0.1:0", None)],
        [StageNode(None, "127.0.0.1:0", None,
                   replica=j if r1 > 1 else None) for j in range(r1)],
        [StageNode(None, "127.0.0.1:0", None, fan_in=r1)],
    ]
    addr_groups = [[f"127.0.0.1:{n.address[1]}" for n in grp]
                   for grp in groups]
    flat = [n for grp in groups for n in grp]
    threads = [threading.Thread(target=n.serve, daemon=True) for n in flat]
    for t in threads:
        t.start()
    disp = ChainDispatcher(addr_groups[0][0], codec="raw")
    try:
        disp.deploy(stages, params, addr_groups, batch=xs[0].shape[0],
                    codecs=hop_codecs(delay_ms))
        disp.stream(xs[:2])            # warm: compile + connect
        tracer().drain()               # drop warmup spans
        t0 = time.perf_counter()
        outs = disp.stream(xs)
        dt = time.perf_counter() - t0
        stats = disp.stats([a for grp in addr_groups for a in grp])
        spans = tracer().drain()
    finally:
        disp.close()
    for t in threads:
        t.join(timeout=60)
    return outs, dt, stats, spans


def quick_check(stages, params, *, count: int, batch: int,
                delay_ms: float, min_speedup: float) -> dict:
    import numpy as np

    rng = np.random.default_rng(0)
    xs = [rng.standard_normal((batch, 32, 32, 3)).astype(np.float32)
          for _ in range(count)]
    base, base_s, _, _ = run_inproc(stages, params, xs, replicate=1,
                                    delay_ms=delay_ms)
    rep, rep_s, stats, spans = run_inproc(stages, params, xs, replicate=2,
                                          delay_ms=delay_ms)
    assert len(base) == len(rep) == count
    for a, b in zip(base, rep):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # the round-robin actually split the stream across both replicas
    per_rep = {s["replica"]: s["processed"] for s in stats
               if s.get("stage") == 1}
    assert set(per_rep) == {0, 1}, per_rep
    assert min(per_rep.values()) >= count // 2 - 1, per_rep

    # per-replica spans prove the interleave is observable
    names = {s.get("name", "") for s in spans}
    for r in (0, 1):
        assert any(n.startswith(f"stage1.r{r}.") for n in names), (
            f"no stage1.r{r}.* spans in the trace: {sorted(names)[:10]}")

    speedup = base_s / rep_s
    log(f"quick: serial {count * batch / base_s:6.1f} inf/s, replicated "
        f"{count * batch / rep_s:6.1f} inf/s -> {speedup:.3f}x "
        f"(split {per_rep})")
    assert speedup >= min_speedup, (
        f"in-process replication speedup {speedup:.3f}x under the "
        f"{min_speedup}x bar")
    return {"serial_s": base_s, "replicated_s": rep_s,
            "speedup": round(speedup, 4),
            "replica_split": {str(k): v for k, v in per_rep.items()}}


# ---------------------------------------------------------------------------
# part 2: the solver predicts replication for a dominating stage
# ---------------------------------------------------------------------------

def solver_check() -> dict:
    """One fat indivisible stage: cuts alone cannot beat it, replicas
    can.  The full optimality property (DP == brute force) is in
    tests/test_plan.py; this is the smoke-level sanity tie-in."""
    from defer_tpu import GraphBuilder
    from defer_tpu.graph import ops
    from defer_tpu.plan import StageCostModel, solve, solve_replicated

    b = GraphBuilder("fatstage")
    x = b.input((16,))
    x = b.add(ops.Dense(16), x, name="pre")
    x = b.add(ops.Dense(16), x, name="fat")
    x = b.add(ops.Dense(16), x, name="post")
    g = b.build()
    costs = {"pre": 1e-4, "fat": 1e-3, "post": 1e-4}  # fat dominates 10x
    cm = StageCostModel(g, gen="v4", link_bw_s=1e9, node_costs=costs)
    budget = 4
    rp = solve_replicated(g, cm, num_nodes=budget)
    cuts_only = min((solve(g, s, cm) for s in range(1, 4)),
                    key=lambda p: p.bottleneck_s)
    assert rp.bottleneck_s <= cuts_only.bottleneck_s * (1 + 1e-9), (
        rp.bottleneck_s, cuts_only.bottleneck_s)
    assert max(rp.replicas) > 1, (
        f"solver kept every stage unreplicated for a 10x-dominant "
        f"stage: {rp.to_json()}")
    # the replicated stage must be the one containing the fat node
    k = rp.bottleneck_stage if max(rp.replicas) == 1 else \
        rp.replicas.index(max(rp.replicas))
    log(f"solver: cuts-only bottleneck {cuts_only.bottleneck_s * 1e3:.3f} "
        f"ms vs hybrid {rp.bottleneck_s * 1e3:.3f} ms "
        f"(cuts {rp.cuts}, replicas {rp.replicas}, budget {budget})")
    return {"cuts_only_bottleneck_ms": round(cuts_only.bottleneck_s * 1e3, 4),
            "hybrid_bottleneck_ms": round(rp.bottleneck_s * 1e3, 4),
            "predicted_speedup": round(
                cuts_only.bottleneck_s / rp.bottleneck_s, 4),
            "replicas": rp.replicas, "cuts": rp.cuts,
            "replicated_stage": k}


# ---------------------------------------------------------------------------
# part 3: multi-process chain — the >= 1.5x measured throughput claim
# ---------------------------------------------------------------------------

def _free_ports(n):
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def timed_chain(paths, xs_warm, xs, *, replicate: int, delay_ms: float,
                log_dir: str):
    """Spawn the 3-stage chain as OS processes (stage 1 as ``replicate``
    replicas), warm it, stream ``xs`` timed, tear down.  Returns
    (outputs, seconds, stats).  Uses run_chain's hardening helpers
    (bind await, kill-all teardown) so a lost port race or dead child
    fails fast and attributed instead of stalling out the dispatcher
    timeout; the caller retries on ``_BindRace``."""
    from defer_tpu.runtime.node import (ChainDispatcher, _await_binds,
                                        _kill_procs)

    codecs = hop_codecs(delay_ms)
    r1 = max(1, replicate)
    ports = _free_ports(2 + r1 + 1)
    s1_addrs = [f"127.0.0.1:{ports[1 + j]}" for j in range(r1)]
    s2_addr = f"127.0.0.1:{ports[1 + r1]}"
    result = f"127.0.0.1:{ports[-1]}"
    mode = f"rep{r1}"
    # --tier tcp everywhere: this row measures stage REPLICATION over
    # the wire protocol; an auto-negotiated shm hop on the non-fan
    # boundaries would bypass the dsleep/esleep codecs that make the
    # middle stage the bottleneck
    argvs = [[sys.executable, "-m", "defer_tpu", "node",
              "--artifact", paths[0], "--listen", f"127.0.0.1:{ports[0]}",
              "--next", ",".join(s1_addrs), "--codec", codecs[0],
              "--tier", "tcp"]]
    for j in range(r1):
        argv = [sys.executable, "-m", "defer_tpu", "node",
                "--artifact", paths[1], "--listen", s1_addrs[j],
                "--next", s2_addr, "--codec", codecs[1], "--tier", "tcp"]
        if r1 > 1:
            argv += ["--replica", str(j)]
        argvs.append(argv)
    argv = [sys.executable, "-m", "defer_tpu", "node",
            "--artifact", paths[2], "--listen", s2_addr,
            "--next", result, "--codec", codecs[2], "--tier", "tcp"]
    if r1 > 1:
        argv += ["--fan-in", str(r1)]
    argvs.append(argv)

    child_env = dict(os.environ)
    child_env.update(CPU_ENV)
    procs, logs = [], []
    all_addrs = [f"127.0.0.1:{ports[0]}"] + s1_addrs + [s2_addr]
    labels = [f"node{i}" for i in range(len(argvs))]
    failed = True
    try:
        for i, argv in enumerate(argvs):
            lf = open(os.path.join(log_dir, f"{mode}_node_{i}.log"), "w+")
            logs.append(lf)
            procs.append(subprocess.Popen(argv, env=child_env, stdout=lf,
                                          stderr=subprocess.STDOUT))
        _await_binds(procs, labels, logs, all_addrs)
        disp = ChainDispatcher(f"127.0.0.1:{ports[0]}", listen=result,
                               codec="raw")
        try:
            disp.stream(xs_warm)   # boot+compile excluded from the window
            t0 = time.perf_counter()
            outs = disp.stream(xs)
            dt = time.perf_counter() - t0
            stats = disp.stats(all_addrs)
            failed = False
        finally:
            if failed:
                _kill_procs(procs)  # dead sockets make close() fast
            disp.close()
            if not failed:
                for pr in procs:
                    try:
                        pr.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        pr.kill()
    except BaseException:
        _kill_procs(procs)
        raise
    finally:
        for lf in logs:
            lf.close()
    return outs, dt, stats


def speedup_check(stages, params, *, count: int, batch: int,
                  delay_ms: float, min_speedup: float) -> dict:
    import numpy as np

    from defer_tpu.utils.export import export_pipeline

    from defer_tpu.runtime.node import _BindRace

    def with_retry(**kw):
        for attempt in range(3):
            try:
                return timed_chain(**kw)
            except _BindRace as e:
                log(f"bind race on attempt {attempt + 1} ({e}); retrying")
        return timed_chain(**kw)

    rng = np.random.default_rng(1)
    xs = [rng.standard_normal((batch, 32, 32, 3)).astype(np.float32)
          for _ in range(count)]
    xs_warm = xs[:4]
    with tempfile.TemporaryDirectory(prefix="defer_repl_") as tmp:
        paths = export_pipeline(stages, params, tmp, batch=batch)
        base, base_s, _ = with_retry(paths=paths, xs_warm=xs_warm, xs=xs,
                                     replicate=1, delay_ms=delay_ms,
                                     log_dir=tmp)
        log(f"serial:     {count * batch / base_s:8.1f} inf/s "
            f"({base_s:.2f}s)")
        rep, rep_s, stats = with_retry(paths=paths, xs_warm=xs_warm,
                                       xs=xs, replicate=2,
                                       delay_ms=delay_ms, log_dir=tmp)
        log(f"replicated: {count * batch / rep_s:8.1f} inf/s "
            f"({rep_s:.2f}s)")
    assert len(base) == len(rep) == count
    for a, b in zip(base, rep):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    per_rep = {s["replica"]: s["processed"] for s in stats
               if s.get("stage") == 1}
    speedup = base_s / rep_s
    log(f"stage1 split across replicas: {per_rep} -> {speedup:.3f}x")
    assert speedup >= min_speedup, (
        f"stage replication speedup {speedup:.3f}x is under the "
        f"{min_speedup}x bar (serial {count * batch / base_s:.1f} inf/s, "
        f"replicated {count * batch / rep_s:.1f} inf/s)")
    return {"serial_s": base_s, "replicated_s": rep_s,
            "speedup": round(speedup, 4),
            "serial_inf_s": round(count * batch / base_s, 2),
            "replicated_inf_s": round(count * batch / rep_s, 2),
            "replica_split": {str(k): v for k, v in per_rep.items()}}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--min-speedup", type=float, default=1.5,
                    help="required replicated/serial throughput ratio "
                         "(multi-process chain)")
    ap.add_argument("--quick-min-speedup", type=float, default=1.2,
                    help="required ratio for the in-process quick check "
                         "(more scheduling noise, lower bar)")
    ap.add_argument("--count", type=int, default=24,
                    help="timed microbatches through each chain")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--delay-ms", type=float, default=25.0,
                    help="per-side bottleneck-stage delay")
    ap.add_argument("--quick", action="store_true",
                    help="in-process + solver checks only (no spawns)")
    args = ap.parse_args()

    import jax

    from defer_tpu import partition
    from defer_tpu.models import resnet_tiny

    graph = resnet_tiny()
    params = graph.init(jax.random.key(0))
    stages = partition(graph, num_stages=3)

    r_quick = quick_check(stages, params, count=min(args.count, 16),
                          batch=min(args.batch, 2),
                          delay_ms=min(args.delay_ms, 15.0),
                          min_speedup=args.quick_min_speedup)
    r_solver = solver_check()

    row = {"metric": "stage_replication", "unit": "x_vs_serial_chain",
           "stages": len(stages), "replicas": {"stage1": 2},
           "count": args.count, "batch": args.batch,
           "delay_ms": args.delay_ms,
           "cpu_count": os.cpu_count() or 1,
           "quick": r_quick, "solver": r_solver}
    if args.quick:
        row["value"] = None
    else:
        r = speedup_check(stages, params, count=args.count,
                          batch=args.batch, delay_ms=args.delay_ms,
                          min_speedup=args.min_speedup)
        row.update({"value": r["speedup"], **{
            k: v for k, v in r.items() if k != "speedup"}})
    print(json.dumps(row))
    log("stage replication smoke: OK")


if __name__ == "__main__":
    main()
