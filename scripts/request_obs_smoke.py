"""Request-observability smoke: tracing, the flight recorder, and
latency attribution composed with the serving front door.

The deployment is the serving smoke's delay-bound 3-stage chain
(``dsleep``: each frame charges the chain a fixed non-CPU cost inside
stage 1, so per-request time is governed by physics, not CPU luck)
behind a front door — and this script proves the observability plane
over it (the ISSUE 11 acceptance bars):

1. OVERHEAD < ``--max-overhead`` (5%): two identical deployments
   streamed ALTERNATELY (the ``obs_overhead`` interleaving — host
   drift cancels, min-of-3 absorbs scheduler spikes): "off" never sees
   telemetry; "on" runs request-scoped tracing (1-in-``--sample``
   frames), the flight recorder, and a live ClusterView subscriber.

2. BURST EVENTS: the PR 7 open-loop Poisson trace with a 2x burst is
   played against the traced door by a deadline tenant.  The burst
   must provoke sheds (admission) and straggler flags (a detector
   polling the live view against a deliberately tight expectation),
   and the MERGED flight-recorder log — door ring + node events off
   the obs_push stream — must contain both, in per-process seq order,
   with ZERO ring drops at default capacity.

3. ATTRIBUTION: for the sampled requests of the burst, the folded
   budget buckets (admission + gather + per-stage compute + per-hop
   transport + result edge — ``obs/attrib.py``) of the p50 AND p99
   requests sum to within ``--tolerance`` (10%) of each request's
   measured end-to-end latency, and the exported Perfetto trace
   carries front-door, dispatcher, and stage spans on one timeline
   (distinct OS processes in full mode, clock-aligned).

``--quick`` keeps the chain in-process (thread nodes — the CI mode);
the default spawns real OS ``defer_tpu node`` processes.  Exit 0 on
success; one JSON row on stdout (the ``request_attribution`` row of
``benchmarks/run.py``).
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import jax  # noqa: E402

from defer_tpu import partition  # noqa: E402
from defer_tpu.models import resnet_tiny  # noqa: E402
from defer_tpu.obs import tracer  # noqa: E402
from defer_tpu.obs.attrib import attribute_sampled  # noqa: E402
from defer_tpu.obs.cluster import (ClusterView,  # noqa: E402
                                   StragglerDetector)
from defer_tpu.obs.events import merge_events, recorder  # noqa: E402
from defer_tpu.runtime.node import ChainDispatcher, StageNode  # noqa: E402
from defer_tpu.serve import (LoadGenerator, ServeClient,  # noqa: E402
                             poisson_trace)
from defer_tpu.serve.frontdoor import (ChainBackend,  # noqa: E402
                                       ServeFrontDoor)

CPU_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
IN_SHAPE = (32, 32, 3)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


class Deployment:
    def __init__(self, door, disp, addrs, *, threads=None, procs=None,
                 logs=None, view=None):
        self.door = door
        self.disp = disp
        self.addrs = addrs
        self.view = view
        self._threads = threads or []
        self._procs = procs or []
        self._logs = logs or []

    @property
    def addr(self):
        return self.door.address

    def close(self):
        from defer_tpu.runtime.node import _kill_procs
        if self.view is not None:
            self.view.close()
        self.door.stop()
        if self._procs:
            _kill_procs(self._procs)
        for t in self._threads:
            t.join(timeout=30)
        for lf in self._logs:
            lf.close()


def boot_door(stages, params, width, codecs, *, quick, log_dir, tag,
              sample=0, align=False) -> Deployment:
    if quick:
        nodes = [StageNode(None, "127.0.0.1:0", None) for _ in stages]
        addrs = [f"127.0.0.1:{n.address[1]}" for n in nodes]
        threads = [threading.Thread(target=n.serve, daemon=True)
                   for n in nodes]
        for t in threads:
            t.start()
        disp = ChainDispatcher(addrs[0], codec="raw")
        disp.deploy(stages, params, addrs, batch=width, codecs=codecs)
        dep = dict(threads=threads)
    else:
        from defer_tpu.runtime.node import _await_binds, _free_ports
        from defer_tpu.utils.export import export_pipeline
        paths = export_pipeline(stages, params,
                                os.path.join(log_dir, f"art_{tag}"),
                                batch=width)
        ports = _free_ports(len(stages) + 1)
        addrs = [f"127.0.0.1:{p}" for p in ports[:-1]]
        result = f"127.0.0.1:{ports[-1]}"
        env = {**os.environ, **CPU_ENV}
        procs, logs = [], []
        for k in range(len(stages)):
            nxt = addrs[k + 1] if k + 1 < len(stages) else result
            # --tier tcp: the delay-bound story rides the dsleep codec,
            # which an auto-negotiated shm hop would bypass
            argv = [sys.executable, "-m", "defer_tpu", "node",
                    "--artifact", paths[k], "--listen", addrs[k],
                    "--next", nxt, "--codec", codecs[k],
                    "--tier", "tcp"]
            lf = open(os.path.join(log_dir, f"{tag}_node{k}.log"), "w+")
            logs.append(lf)
            procs.append(subprocess.Popen(argv, env=env, stdout=lf,
                                          stderr=subprocess.STDOUT))
        _await_binds(procs, [f"stage{k}" for k in range(len(stages))],
                     logs, addrs)
        disp = ChainDispatcher(addrs[0], listen=result, codec="raw")
        dep = dict(procs=procs, logs=logs)
    if align and not quick:
        # re-anchor the stage processes' tracers so the sampled
        # requests' cross-process waterfalls share one timeline
        disp.align_clocks(addrs)
    door = ServeFrontDoor(backend=ChainBackend(
        disp, width, IN_SHAPE, trace_sample_every=sample)).start()
    return Deployment(door, disp, addrs, **dep)


def run_streams(addr, data, *, suffix, deadline_ms=None):
    """All tenants' samples through concurrent clients; returns wall."""
    host, port = addr

    def one(t):
        ServeClient(host, port, t + suffix,
                    deadline_ms=deadline_ms).stream(data[t])

    t0 = time.perf_counter()
    ths = [threading.Thread(target=one, args=(t,)) for t in data]
    for th in ths:
        th.start()
    for th in ths:
        th.join(timeout=300)
    return time.perf_counter() - t0


def check_seq_order(merged):
    """Per-process seqs must be non-decreasing along the merged log."""
    last: dict = {}
    for ev in merged:
        prev = last.get(ev["proc"])
        assert prev is None or ev["seq"] >= prev, (
            f"merged log reordered {ev['proc']} events: "
            f"{ev['seq']} after {prev}")
        last[ev["proc"]] = ev["seq"]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="in-process thread chain (CI mode)")
    ap.add_argument("--delay-ms", type=float, default=25.0)
    ap.add_argument("--per-tenant", type=int, default=8)
    ap.add_argument("--width", type=int, default=4)
    ap.add_argument("--sample", type=int, default=4,
                    help="request-scoped waterfall sampling period")
    ap.add_argument("--tolerance", type=float, default=0.10,
                    help="attribution sum-vs-wall bound (fraction)")
    ap.add_argument("--max-overhead", type=float, default=0.05)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    stages = partition(g, num_stages=3)
    codecs = [f"dsleep{args.delay_ms:g}+raw", "raw", "raw"]
    rng = np.random.default_rng(args.seed)
    tenants = ("alpha", "beta", "gamma")
    data = {t: [rng.standard_normal(IN_SHAPE).astype(np.float32)
                for _ in range(args.per_tenant)] for t in tenants}
    tr = tracer()
    rec = recorder()
    rec.clear()
    row = {"metric": "request_attribution", "unit": "frac_residual_p99",
           "mode": "quick" if args.quick else "full",
           "width": args.width, "delay_ms": args.delay_ms,
           "sample_every": args.sample}

    with tempfile.TemporaryDirectory(prefix="req_obs_") as tmp:
        # telemetry-off twin FIRST (its backend must not begin a trace)
        tr.enabled = False
        off = boot_door(stages, params, args.width, codecs,
                        quick=args.quick, log_dir=tmp, tag="off")
        tr.enabled = True
        tr.process = "serve"
        tr.start_trace()
        on = boot_door(stages, params, args.width, codecs,
                       quick=args.quick, log_dir=tmp, tag="on",
                       sample=args.sample, align=True)
        # the live plane: a ClusterView subscribed to the traced
        # chain's nodes (events ride its obs_push stream)
        on.view = ClusterView().connect(on.addrs, interval_ms=150.0,
                                        probe_clocks=False)
        try:
            # warm both chains outside the timed windows
            tr.enabled = False
            run_streams(off.addr, data, suffix="_w")
            tr.enabled = True
            run_streams(on.addr, data, suffix="_w")

            # -- 1. overhead: interleaved min-of-3 ---------------------
            w_off, w_on = [], []
            for rep in range(3):
                tr.enabled = False
                w_off.append(run_streams(off.addr, data,
                                         suffix=f"_o{rep}"))
                tr.enabled = True
                w_on.append(run_streams(on.addr, data,
                                        suffix=f"_t{rep}"))
            off.door.healthcheck()
            on.door.healthcheck()
            wall_off, wall_on = min(w_off), min(w_on)
            overhead = wall_on / wall_off - 1.0
            log(f"request_obs: telemetry off {wall_off:.3f}s vs on "
                f"{wall_on:.3f}s -> {overhead * 100:+.2f}% "
                f"(bound {args.max_overhead * 100:.0f}%)")
            assert overhead < args.max_overhead, (
                f"recorder+tracing overhead {overhead * 100:.2f}% "
                f"exceeds {args.max_overhead * 100:.0f}%")

            # -- 2. the PR 7 burst: sheds + stragglers on one log ------
            cap_hz = args.width / (args.delay_ms / 1e3)
            offsets = poisson_trace(0.6 * cap_hz, 6.0,
                                    seed=args.seed + 1,
                                    bursts=[(1.5, 3.5, 2.0)])
            slo_ms = 10 * args.delay_ms
            host, port = on.addr
            client = ServeClient(host, port, "burst",
                                 deadline_ms=0.8 * slo_ms,
                                 timeout_s=300.0)
            # a deliberately tight expectation: the delay-bound stage 1
            # must flag as a sustained straggler while the burst runs
            detector = StragglerDetector([1.0, 1.0, 1.0], sustain=2)
            flags = []
            halt = threading.Event()

            def poll():
                while not halt.is_set():
                    flags.extend(detector.observe(on.view))
                    halt.wait(0.2)

            pt = threading.Thread(target=poll, daemon=True)
            pt.start()
            gen = LoadGenerator(client, data["alpha"], offsets).run()
            time.sleep(0.5)  # one more push interval for late events
            halt.set()
            pt.join(timeout=10)
            log(f"request_obs: burst offered {gen['offered']} "
                f"shed {gen['shed']} p99 {gen['latency_p99_ms']:.1f}ms; "
                f"straggler flags {sorted({f.stage for f in flags})}")
            assert gen["shed"] > 0, "the 2x burst should shed"
            assert any(f.stage == 1 for f in flags), \
                "the delay-bound stage was never flagged"
            merged = on.view.events()
            kinds = {e["kind"] for e in merged}
            assert "shed" in kinds and "straggler" in kinds, kinds
            sheds = [e for e in merged if e["kind"] == "shed"]
            assert len(sheds) == gen["shed"], (len(sheds), gen["shed"])
            check_seq_order(merged)
            assert rec.dropped == 0 and on.view.events_dropped == 0, \
                "the default-capacity ring must not drop under the burst"

            # -- 3. attribution of the sampled burst requests ----------
            if not args.quick:
                on.disp.collect_trace(on.addrs)
            spans = tr.spans
            reps = [r for r in attribute_sampled(
                spans, hop_tiers=["tcp"] * 4) if r.tenant == "burst"]
            assert len(reps) >= max(4, gen["completed"]
                                    // (2 * max(args.sample, 1))), \
                f"too few sampled requests attributed: {len(reps)}"
            picks = {"p50": reps[len(reps) // 2], "p99": reps[
                min(len(reps) - 1, int(0.99 * (len(reps) - 1)))]}
            for which, rep in picks.items():
                log(f"request_obs: {which} rid={rep.rid} wall "
                    f"{rep.wall_ms:.1f}ms sum {rep.sum_ms:.1f}ms "
                    f"residual {rep.residual_ms:+.1f}ms")
                assert rep.ok(args.tolerance), (which, rep.to_json())
                assert rep.buckets["transport.hop1"] >= \
                    0.5 * args.delay_ms, rep.to_json()
            # the trace spans front door + dispatcher + every stage on
            # one timeline (distinct OS processes in full mode)
            names = {s["name"] for s in spans}
            for want in ("serve.request", "serve.gather", "chain.tx",
                         "stage0.infer", "stage1.infer", "stage2.infer",
                         "serve.deliver"):
                assert want in names, (want, sorted(names)[:40])
            procs_seen = {s["proc"] for s in spans}
            if not args.quick:
                assert len(procs_seen) >= 4, procs_seen
            trace_file = os.path.join(tmp, "request_trace.json")
            from defer_tpu.obs import export_chrome_trace
            export_chrome_trace(trace_file)
            assert os.path.getsize(trace_file) > 0

            row.update(
                value=round(abs(picks["p99"].residual_ms)
                            / max(picks["p99"].wall_ms, 1e-9), 4),
                overhead_frac=round(overhead, 4),
                wall_off_s=round(wall_off, 4),
                wall_on_s=round(wall_on, 4),
                burst={"offered": gen["offered"], "shed": gen["shed"],
                       "p99_ms": gen["latency_p99_ms"],
                       "slo_ms": slo_ms},
                sampled_requests=len(reps),
                p50_attrib=picks["p50"].to_json(),
                p99_attrib=picks["p99"].to_json(),
                events={"merged": len(merged),
                        "sheds": len(sheds),
                        "stragglers": len([e for e in merged
                                           if e["kind"] == "straggler"]),
                        "dropped": 0},
                trace_procs=len(procs_seen),
                cpu_count=os.cpu_count() or 1)
        finally:
            tr.enabled = True  # teardown spans are harmless
            off.close()
            on.close()
            tr.enabled = False
            tr.clear()

    print(json.dumps(row), flush=True)
    log("request_obs smoke: OK")


if __name__ == "__main__":
    main()
