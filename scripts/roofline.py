"""Analytic roofline for the MFU-ceiling question (VERDICT r4 #4).

The tunnel's ~4.3 ms dispatch floor makes standalone per-op timing blind
below that floor (PROFILE_OPS_r05.json: every top conv costs exactly the
floor), so the per-op evidence for where the ceiling sits comes from
shape math instead: for every node of the deployed graph, per-sample
FLOPs (the ops' own ``flops`` methods, 2*MAC) and minimum HBM traffic at
bf16, then

    t_min(op) = max(flops / peak_bf16, bytes / hbm_bw)

summed in two scenarios:

- ``unfused``: every op reads its inputs and writes its output (what
  running each op standalone would cost at best);
- ``fused``: elementwise ops (BN / activation / add / pad) are free —
  their bytes ride the producing conv's write and consuming conv's read,
  the XLA behavior PROFILE_OPS_r05's 10.8x fusion gain confirms —
  weights are read once per batch, conv in/out tensors move once each.

``ceiling_mfu = total_flops / (peak * sum t_min)`` is the best MFU any
schedule could reach under the roofline; the measured number
(BENCH_r05_builder.json) is judged against it.

Pure shape math: runs anywhere, no device needed.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


ELEMENTWISE = {"BatchNorm", "Activation", "Add", "ZeroPad2D", "LayerNorm",
               "Dropout"}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50")
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--gen", default="v5e")
    args = ap.parse_args()

    import jax

    from defer_tpu import models
    from defer_tpu.graph.analysis import node_flops
    from defer_tpu.utils.hw import hbm_bandwidth, peak_flops

    graph = getattr(models, args.model)()
    peak = peak_flops(args.gen)
    bw = hbm_bandwidth(args.gen)
    if not peak or not bw:
        raise SystemExit(f"unknown TPU generation {args.gen!r} "
                         f"(no peak/bandwidth table entry)")
    b = args.batch
    bpe = 2  # bf16

    rows = []
    tot = {"flops": 0.0, "t_unfused": 0.0, "t_fused": 0.0,
           "bytes_fused": 0.0}
    for name, node in graph.nodes.items():
        in_specs = tuple(graph.out_spec(i) for i in node.inputs)
        out = node.out_spec
        fl = float(node_flops(graph, name)) * b
        act_bytes = (sum(s.size for s in in_specs) + out.size) * b * bpe
        w_bytes = 0.0
        if node.param_spec:
            w_bytes = sum(float(np.prod(l.shape)) * bpe for l in
                          jax.tree.leaves(node.param_spec))
        kind = type(node.op).__name__
        ew = kind in ELEMENTWISE
        t_unf = max(fl / peak, (act_bytes + w_bytes) / bw)
        t_fus = 0.0 if ew else max(fl / peak, (act_bytes + w_bytes) / bw)
        tot["flops"] += fl
        tot["t_unfused"] += t_unf
        tot["t_fused"] += t_fus
        if not ew:
            tot["bytes_fused"] += act_bytes + w_bytes
        rows.append({"node": name, "op": kind, "gflops": round(fl / 1e9, 2),
                     "mbytes": round((act_bytes + w_bytes) / 1e6, 2),
                     "intensity": round(fl / (act_bytes + w_bytes), 1),
                     "t_min_us": round(t_fus * 1e6, 1),
                     "bound": ("ew-fused" if ew else
                               "compute" if fl / peak >=
                               (act_bytes + w_bytes) / bw else "memory")})

    out = {
        "metric": f"{args.model}_roofline",
        "gen": args.gen, "batch": b,
        "peak_bf16_tflops": peak / 1e12, "hbm_gb_s": bw / 1e9,
        "total_gflops": round(tot["flops"] / 1e9, 1),
        "ceiling_mfu_fused": round(
            tot["flops"] / (peak * tot["t_fused"]), 4),
        "ceiling_mfu_unfused": round(
            tot["flops"] / (peak * tot["t_unfused"]), 4),
        "t_fused_ms": round(tot["t_fused"] * 1e3, 3),
        "memory_bound_ops": sorted(
            [r for r in rows if r["bound"] == "memory"],
            key=lambda r: -r["t_min_us"])[:10],
        "top_ops_by_t": sorted([r for r in rows if r["bound"] != "ew-fused"],
                               key=lambda r: -r["t_min_us"])[:10],
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
