"""Serving front door smoke: prove the admission layer's three claims.

The chain is made DELAY-bound the way every smoke on this 1-core box
does it (``dsleep<ms>+raw``: a decode-side sleep charges each frame a
fixed non-CPU cost — the resource profile of real serialization /
accelerator time), so per-frame amortization is measurable by physics
rather than by CPU luck.

Checks (the ISSUE 7 acceptance bars):

1. MULTI-TENANT BYTE-IDENTITY: >= 3 concurrent client streams over ONE
   deployed chain produce per-request outputs byte-identical to each
   request run alone through the same serving path.

2. CONTINUOUS BATCHING >= ``--min-speedup`` (1.5): the same offered
   load served (a) sequentially, one stream at a time, one sample per
   frame — today's single-client dispatcher model — vs (b) through the
   front door: concurrent tenants, samples coalesced across tenants
   into width-W frames.  min-of-3 walls each (1-core jitter rule).

3. SLO SHEDDING UNDER A 2x BURST: a deterministic open-loop Poisson
   trace with a 2x-rate burst phase is played against the door twice —
   a deadline-bound tenant (admission sheds when the predicted
   completion blows the SLO) and a no-deadline tenant (nothing sheds).
   The shedding run's admitted-request p99 stays within the SLO; the
   no-shedding run blows it.

Plus a decode row: the continuous-batching decode engine (gpt_tiny,
requests joining/leaving between steps) byte-identical to solo runs,
with sustained tokens/s reported for the batched vs sequential drive.

``--quick`` keeps everything in-process (thread-per-stage chain nodes);
the full mode runs the SAME chain as real OS ``defer_tpu node``
processes.  Exit 0 on success; one JSON row on stdout (the
``serving_frontdoor`` row of ``benchmarks/run.py``).

Usage:  python scripts/serve_smoke.py [--quick] [--delay-ms D]
            [--per-tenant N] [--min-speedup 1.5] [--seed S]
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import jax  # noqa: E402

from defer_tpu import partition  # noqa: E402
from defer_tpu.models import resnet_tiny  # noqa: E402
from defer_tpu.models.gpt import gpt_tiny  # noqa: E402
from defer_tpu.runtime.node import ChainDispatcher, StageNode  # noqa: E402
from defer_tpu.serve import (ContinuousBatchEngine,  # noqa: E402
                             DecodeRequest, LoadGenerator, ServeClient,
                             poisson_trace)
from defer_tpu.serve.frontdoor import (ChainBackend,  # noqa: E402
                                       ServeFrontDoor)

CPU_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
IN_SHAPE = (32, 32, 3)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def hop_codecs(delay_ms: float) -> list[str]:
    """Decode-side delay on the stage0->stage1 hop: every frame charges
    the chain ``delay_ms`` of non-CPU time inside stage 1."""
    return [f"dsleep{delay_ms:g}+raw", "raw", "raw"]


class Deployment:
    """One booted 3-stage chain + front door (threads or processes)."""

    def __init__(self, door, disp, *, threads=None, procs=None,
                 logs=None):
        self.door = door
        self.disp = disp
        self._threads = threads or []
        self._procs = procs or []
        self._logs = logs or []

    @property
    def addr(self):
        return self.door.address

    def close(self):
        from defer_tpu.runtime.node import _kill_procs
        self.door.stop()
        if self._procs:
            _kill_procs(self._procs)
        for t in self._threads:
            t.join(timeout=30)
        for lf in self._logs:
            lf.close()


def boot_door(stages, params, width, codecs, *, quick: bool,
              log_dir: str, tag: str, window: int = 8) -> Deployment:
    if quick:
        nodes = [StageNode(None, "127.0.0.1:0", None) for _ in stages]
        addrs = [f"127.0.0.1:{n.address[1]}" for n in nodes]
        threads = [threading.Thread(target=n.serve, daemon=True)
                   for n in nodes]
        for t in threads:
            t.start()
        disp = ChainDispatcher(addrs[0], codec="raw")
        disp.deploy(stages, params, addrs, batch=width, codecs=codecs)
        dep = dict(threads=threads)
    else:
        from defer_tpu.runtime.node import _await_binds, _free_ports
        from defer_tpu.utils.export import export_pipeline
        paths = export_pipeline(stages, params,
                                os.path.join(log_dir, f"art_{tag}"),
                                batch=width)
        ports = _free_ports(len(stages) + 1)
        addrs = [f"127.0.0.1:{p}" for p in ports[:-1]]
        result = f"127.0.0.1:{ports[-1]}"
        env = {**os.environ, **CPU_ENV}
        procs, logs = [], []
        for k in range(len(stages)):
            nxt = addrs[k + 1] if k + 1 < len(stages) else result
            # --tier tcp: this row measures the serving front door over
            # a delay-bound wire chain; an auto-negotiated shm hop
            # would bypass the dsleep codec that makes it delay-bound
            argv = [sys.executable, "-m", "defer_tpu", "node",
                    "--artifact", paths[k], "--listen", addrs[k],
                    "--next", nxt, "--codec", codecs[k],
                    "--tier", "tcp"]
            lf = open(os.path.join(log_dir, f"{tag}_node{k}.log"), "w+")
            logs.append(lf)
            procs.append(subprocess.Popen(argv, env=env, stdout=lf,
                                          stderr=subprocess.STDOUT))
        _await_binds(procs, [f"stage{k}" for k in range(len(stages))],
                     logs, addrs)
        disp = ChainDispatcher(addrs[0], listen=result, codec="raw")
        dep = dict(procs=procs, logs=logs)
    door = ServeFrontDoor(
        backend=ChainBackend(disp, width, IN_SHAPE, window=window)).start()
    return Deployment(door, disp, **dep)


def run_streams(addr, data, *, concurrent: bool, suffix: str,
                deadline_ms=None):
    """Each tenant's samples through one client; returns (outs, wall)."""
    host, port = addr
    outs = {}

    def one(t):
        c = ServeClient(host, port, t + suffix, deadline_ms=deadline_ms)
        outs[t] = c.stream(data[t])

    t0 = time.perf_counter()
    if concurrent:
        ths = [threading.Thread(target=one, args=(t,)) for t in data]
        for th in ths:
            th.start()
        for th in ths:
            th.join(timeout=300)
    else:
        for t in data:
            one(t)
    return outs, time.perf_counter() - t0


def assert_identical(a, b, what):
    for t in a:
        for i, (oa, ob) in enumerate(zip(a[t], b[t])):
            assert oa[0] == "ok" and ob[0] == "ok", (what, t, i, oa, ob)
            assert np.array_equal(oa[1], ob[1]), \
                f"{what}: tenant {t} sample {i} NOT byte-identical"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="in-process thread chain (CI mode)")
    ap.add_argument("--delay-ms", type=float, default=25.0)
    ap.add_argument("--per-tenant", type=int, default=8)
    ap.add_argument("--width", type=int, default=4)
    ap.add_argument("--min-speedup", type=float, default=1.5)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    stages = partition(g, num_stages=3)
    codecs = hop_codecs(args.delay_ms)
    rng = np.random.default_rng(args.seed)
    tenants = ("alpha", "beta", "gamma")
    data = {t: [rng.standard_normal(IN_SHAPE).astype(np.float32)
                for _ in range(args.per_tenant)] for t in tenants}
    row = {"metric": "serving_frontdoor", "unit": "x", "tenants": 3,
           "width": args.width, "delay_ms": args.delay_ms,
           "per_tenant": args.per_tenant,
           "mode": "quick" if args.quick else "full"}

    with tempfile.TemporaryDirectory(prefix="serve_smoke_") as tmp:
        # ---- the batched (front door) deployment: width W -------------
        wide = boot_door(stages, params, args.width, codecs,
                         quick=args.quick, log_dir=tmp, tag="wide")
        # ---- the sequential baseline: width 1, streams one at a time --
        narrow = boot_door(stages, params, 1, codecs,
                           quick=args.quick, log_dir=tmp, tag="narrow")
        try:
            # 1. BYTE-IDENTITY on the batched door: solo (one stream at
            # a time) vs 3 concurrent tenants, same chain
            solo, _ = run_streams(wide.addr, data, concurrent=False,
                                  suffix="_solo")
            log("serve_smoke: solo reference streams done")
            seq_walls, bat_walls = [], []
            for rep in range(3):  # min-of-3: 1-core wall jitter rule
                conc, bw = run_streams(wide.addr, data, concurrent=True,
                                       suffix=f"_c{rep}")
                assert_identical(solo, conc, f"concurrent rep {rep}")
                bat_walls.append(bw)
                _, sw = run_streams(narrow.addr, data, concurrent=False,
                                    suffix=f"_s{rep}")
                seq_walls.append(sw)
            wide.door.healthcheck()
            narrow.door.healthcheck()
            seq_wall, bat_wall = min(seq_walls), min(bat_walls)
            speedup = seq_wall / bat_wall
            log(f"serve_smoke: sequential {seq_wall:.3f}s vs batched "
                f"{bat_wall:.3f}s -> {speedup:.2f}x")
            assert speedup >= args.min_speedup, (
                f"continuous batching {speedup:.2f}x < "
                f"{args.min_speedup}x (seq {seq_wall:.3f}s, batched "
                f"{bat_wall:.3f}s)")
            row.update(value=round(speedup, 3),
                       byte_identical=True,
                       sequential_wall_s=round(seq_wall, 4),
                       batched_wall_s=round(bat_wall, 4),
                       samples_per_s=round(
                           3 * args.per_tenant / bat_wall, 2))

            # 2. SLO SHEDDING under a 2x-overload burst ----------------
            # capacity of the wide door ~ W / frame_delay; drive the
            # steady phases just under it and the burst at 2x
            cap_hz = args.width / (args.delay_ms / 1e3)
            base_hz = 0.6 * cap_hz
            dur = 6.0
            bursts = [(1.5, 3.5, 2.0)]
            offsets = poisson_trace(base_hz, dur, seed=args.seed + 1,
                                    bursts=bursts)
            slo_ms = 10 * args.delay_ms
            samples = data["alpha"]
            host, port = wide.addr

            def play(tenant, deadline_ms):
                c = ServeClient(host, port, tenant,
                                deadline_ms=deadline_ms,
                                timeout_s=300.0)
                return LoadGenerator(c, samples, offsets).run()

            noshed = play("burst_noshed", None)
            log(f"serve_smoke: no-shed p99 "
                f"{noshed['latency_p99_ms']:.1f}ms (SLO {slo_ms:g}ms)")
            shed = play("burst_shed", 0.8 * slo_ms)
            log(f"serve_smoke: shed p99 {shed['latency_p99_ms']:.1f}ms, "
                f"shed rate {shed['shed_rate']:.2%}")
            assert noshed["latency_p99_ms"] > slo_ms, (
                "the no-shedding baseline should have blown the "
                f"{slo_ms:g}ms SLO under the 2x burst "
                f"(p99 {noshed['latency_p99_ms']:.1f}ms) — raise the "
                "burst or lower the SLO")
            assert shed["latency_p99_ms"] <= slo_ms, (
                f"shedding failed its SLO: admitted p99 "
                f"{shed['latency_p99_ms']:.1f}ms > {slo_ms:g}ms")
            assert shed["shed"] > 0, "the burst should shed something"
            row.update(slo_ms=slo_ms,
                       trace={"base_rate_hz": round(base_hz, 1),
                              "burst": bursts, "duration_s": dur,
                              "offered": len(offsets)},
                       shed_p99_ms=shed["latency_p99_ms"],
                       shed_rate=shed["shed_rate"],
                       noshed_p99_ms=noshed["latency_p99_ms"])
        finally:
            wide.close()
            narrow.close()

    # 3. CONTINUOUS-BATCHING DECODE (in-process engine) ----------------
    gg = gpt_tiny()
    gp = gg.init(jax.random.key(1))
    prompts = [rng.integers(0, 97, (4,)).astype(np.int32)
               for _ in range(4)]
    new_tok = 8

    def reqs():
        return [DecodeRequest(prompt=p, max_new_tokens=new_tok,
                              request_id=i, seed=i)
                for i, p in enumerate(prompts)]

    solo_out, seq_wall = {}, 0.0
    eng = ContinuousBatchEngine(gg, gp, num_stages=2, width=4)
    eng.run_all(reqs()[:1])  # compile outside the timed windows
    for req in reqs():
        eng1 = ContinuousBatchEngine(gg, gp, num_stages=2, width=4)
        t0 = time.perf_counter()
        solo_out[req.request_id] = eng1.run_all([req])[req.request_id]
        seq_wall += time.perf_counter() - t0
    eng2 = ContinuousBatchEngine(gg, gp, num_stages=2, width=4)
    t0 = time.perf_counter()
    batched = eng2.run_all(reqs())
    bat_wall = time.perf_counter() - t0
    for rid, ids in solo_out.items():
        assert np.array_equal(batched[rid], ids), \
            f"decode request {rid} not byte-identical to its solo run"
    row.update(decode_tokens_per_s=round(
        len(prompts) * new_tok / bat_wall, 1),
        decode_speedup=round(seq_wall / bat_wall, 2))
    log(f"serve_smoke: decode batched {row['decode_tokens_per_s']} "
        f"tok/s ({row['decode_speedup']}x vs sequential), "
        f"byte-identical")

    print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
