"""Shared-memory transport-tier smoke: prove the same-host fast path pays.

A 3-stage resnet_tiny chain is made codec-delay-bound the same way
``colocate_smoke.py`` does: stage 0's outbound hop uses a decode-side
delay codec (``dsleep<ms>+raw``) and stage 1's an encode-side one
(``esleep<ms>+raw``), so every frame charges the chain a fixed non-CPU
delay per inter-stage hop.  The shm tier eliminates exactly that cost:
activations cross a ``multiprocessing.shared_memory`` ring (one memcpy
per side, no codec, no socket payload bytes) while the TCP socket is
demoted to a per-frame doorbell — and unlike the ``local`` tier this
works BETWEEN separate OS processes, the repo's standard proof mode.

Checks:

1. QUICK (in-process thread chain, ``tier="shm"`` pins the shm offer so
   the local rung doesn't win): the same inputs through the all-TCP
   chain and the all-shm chain — byte-identical outputs, every stats
   row reports the negotiated ``shm`` tier on BOTH ends, zero
   ``codec.*`` histogram samples on the shm run, zero per-hop fallback
   counts, and min-of-3 wall >= ``--quick-min-speedup``.

2. FALLBACK: a hop whose peer refuses the offer degrades to tcp with
   the stream byte-identical and the refused hop's ``tier_fallbacks``
   stat incremented — attributable, unlike a never-offered hop.

3. PLANNER: given a shm hop-tier map, the solver's plan crosses a fat
   boundary the all-TCP plan avoids (strictly better predicted
   bottleneck on the comm-bound model), and the tier survives the
   plan-JSON roundtrip.

4. FULL (multi-process, skipped with ``--quick``): the same chain as 3
   REAL OS processes — all hops (dispatcher edges included) negotiated
   ``shm`` via the tier_probe handshake vs the all-TCP chain —
   byte-identical outputs, min-of-3 streams, measured speedup >=
   ``--min-speedup`` (1.5), zero codec samples on every stage's stats
   row, and no ``defer_shm_*`` segment left in /dev/shm afterwards.

Exit 0 on success; one JSON row on stdout (the ``shm_fastpath`` row of
``benchmarks/run.py``).

Usage:  python scripts/shm_smoke.py [--quick] [--delay-ms D]
            [--count N] [--min-speedup 1.5]
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: stage-node subprocesses must never touch a (single-client) TPU tunnel
CPU_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def hop_codecs(delay_ms: float) -> list[str]:
    """Per-stage outbound codecs charging ``delay_ms`` of non-CPU codec
    time to each inter-stage hop (decode-side on hop 0->1, encode-side
    on hop 1->2); the result hop stays raw."""
    return [f"dsleep{delay_ms:g}+raw", f"esleep{delay_ms:g}+raw", "raw"]


def segments() -> set:
    try:
        return {n for n in os.listdir("/dev/shm")
                if n.startswith("defer_shm_")}
    except OSError:
        return set()


# ---------------------------------------------------------------------------
# in-process chains (quick mode)
# ---------------------------------------------------------------------------

def run_inproc(stages, params, xs, *, tier: str, codecs, accepts=None,
               streams: int = 3):
    """Thread-per-node chain under ``tier``; warm stream then ``streams``
    timed streams keeping the MIN wall (single-stream walls jitter >15%
    on this 1-core box).  Returns (outs, wall, stats)."""
    from defer_tpu.runtime.node import ChainDispatcher, StageNode

    nodes = [StageNode(None, "127.0.0.1:0", None, tier=tier,
                       tier_accept=True if accepts is None else accepts[i])
             for i in range(len(stages))]
    addrs = [f"127.0.0.1:{n.address[1]}" for n in nodes]
    threads = [threading.Thread(target=n.serve, daemon=True)
               for n in nodes]
    for t in threads:
        t.start()
    disp = ChainDispatcher(addrs[0], codec="raw", tier=tier)
    try:
        disp.deploy(stages, params, addrs, batch=xs[0].shape[0],
                    codecs=codecs, tiers=[tier] * len(stages))
        disp.stream(xs[:2])  # warm: compile + connect + negotiate
        wall = float("inf")
        for _ in range(streams):
            t0 = time.perf_counter()
            outs = disp.stream(xs)
            wall = min(wall, time.perf_counter() - t0)
        stats = disp.stats(addrs)
    finally:
        disp.close()
    for t in threads:
        t.join(timeout=60)
    return outs, wall, stats


def quick_check(stages, params, xs, *, delay_ms: float,
                min_speedup: float) -> dict:
    import numpy as np

    from defer_tpu.obs import REGISTRY

    codecs = hop_codecs(delay_ms)
    base, base_s, base_st = run_inproc(stages, params, xs, tier="tcp",
                                       codecs=codecs)
    enc0 = REGISTRY.histogram("codec.encode_s").summary().get("count", 0)
    before = segments()
    shm, shm_s, shm_st = run_inproc(stages, params, xs, tier="shm",
                                    codecs=codecs)
    enc1 = REGISTRY.histogram("codec.encode_s").summary().get("count", 0)

    assert len(base) == len(shm) == len(xs)
    for a, b in zip(base, shm):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    tiers = [s["tier"] for s in shm_st]
    tiers_in = [s["tier_in"] for s in shm_st]
    assert tiers == ["shm"] * 3, f"hops did not negotiate shm: {tiers}"
    assert tiers_in == ["shm"] * 3, tiers_in
    assert [s["tier_fallbacks"] for s in shm_st] == [0] * 3
    assert enc1 == enc0, (
        f"shm hops recorded {enc1 - enc0} codec.encode_s samples; "
        f"the shared-memory path must do ZERO codec work")
    assert segments() <= before, "quick chain leaked /dev/shm segments"
    speedup = base_s / shm_s
    log(f"quick: tcp {len(xs) / base_s:6.1f} inf/s, shm "
        f"{len(xs) / shm_s:6.1f} inf/s -> {speedup:.2f}x")
    assert speedup >= min_speedup, (
        f"shm speedup {speedup:.3f}x under the {min_speedup}x bar "
        f"(tcp {base_s:.3f}s vs shm {shm_s:.3f}s)")
    return {"tcp_s": round(base_s, 4), "shm_s": round(shm_s, 4),
            "speedup": round(speedup, 4), "tiers": tiers}


def fallback_check(stages, params, xs, *, base) -> dict:
    """A refused offer degrades the hop to tcp — byte-identical stream,
    and the DEGRADED hop (not its neighbors) carries the fallback."""
    import numpy as np

    outs, _, stats = run_inproc(stages, params, xs, tier="shm",
                                codecs=["raw"] * 3,
                                accepts=[True, False, True], streams=1)
    for a, b in zip(base, outs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    by_stage = {s["stage"]: s for s in stats}
    assert by_stage[0]["tier"] == "tcp" \
        and by_stage[0]["tier_fallbacks"] >= 1, by_stage[0]
    assert by_stage[1]["tier"] == "shm" \
        and by_stage[1]["tier_fallbacks"] == 0, by_stage[1]
    log(f"fallback: refused hop degraded to tcp with tier_fallbacks="
        f"{by_stage[0]['tier_fallbacks']}, granted hop untouched")
    return {"degraded_hop_fallbacks": by_stage[0]["tier_fallbacks"],
            "granted_hop_fallbacks": by_stage[1]["tier_fallbacks"]}


# ---------------------------------------------------------------------------
# planner: the shm hop-tier map changes the plan
# ---------------------------------------------------------------------------

def planner_check() -> dict:
    from defer_tpu import GraphBuilder
    from defer_tpu.graph import ops
    from defer_tpu.plan import StageCostModel, plan_from_json, solve

    b = GraphBuilder("fatcut")
    x = b.input((4096,))
    for i in range(3):
        x = b.add(ops.Dense(4096), x, name=f"d{i}")
    x = b.add(ops.Dense(8), x, name="head")
    g = b.build()
    costs = {"d0": 1e-3, "d1": 1e-3, "d2": 1e-3, "head": 1e-4}
    cm = StageCostModel(g, gen="v4", link_bw_s=1e6, node_costs=costs)
    p_tcp = solve(g, 3, cm)
    p_shm = solve(g, 3, cm,
                  hop_tiers={c: "shm" for c in ("d0", "d1", "d2")})
    assert p_shm.bottleneck_s < p_tcp.bottleneck_s, (
        "comm-bound model: the shm plan must be strictly better")
    assert plan_from_json(p_shm.to_json()).hop_tiers == p_shm.hop_tiers
    log(f"planner: tcp bottleneck {p_tcp.bottleneck_s * 1e3:.3f} ms "
        f"vs shm {p_shm.bottleneck_s * 1e3:.3f} ms, hop tiers "
        f"{p_shm.hop_tiers}")
    return {"tcp_bottleneck_ms": round(p_tcp.bottleneck_s * 1e3, 4),
            "shm_bottleneck_ms": round(p_shm.bottleneck_s * 1e3, 4),
            "predicted_speedup": round(
                p_tcp.bottleneck_s / p_shm.bottleneck_s, 4),
            "hop_tiers": p_shm.hop_tiers}


# ---------------------------------------------------------------------------
# multi-process: 3 real OS processes, shm hops vs tcp hops
# ---------------------------------------------------------------------------

def timed_chain(paths, xs_warm, xs, *, tier: str, delay_ms: float,
                log_dir: str, streams: int = 3):
    """Spawn the 3-stage chain as 3 SEPARATE OS processes under
    ``tier``, warm it, stream ``xs`` ``streams`` times keeping the min
    wall, tear down.  Returns (outputs, seconds, stats)."""
    import socket as _socket

    from defer_tpu.runtime.node import (ChainDispatcher, _await_binds,
                                        _kill_procs)

    codecs = hop_codecs(delay_ms)
    socks = [_socket.create_server(("127.0.0.1", 0)) for _ in range(4)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    addrs = [f"127.0.0.1:{p}" for p in ports[:3]]
    result = f"127.0.0.1:{ports[3]}"
    nxt = addrs[1:] + [result]
    argvs = [[sys.executable, "-m", "defer_tpu", "node",
              "--artifact", paths[k], "--listen", addrs[k],
              "--next", nxt[k], "--codec", codecs[k], "--tier", tier]
             for k in range(3)]

    child_env = dict(os.environ)
    child_env.update(CPU_ENV)
    procs, logs = [], []
    failed = True
    try:
        for i, a in enumerate(argvs):
            lf = open(os.path.join(log_dir, f"{tier}_proc_{i}.log"), "w+")
            logs.append(lf)
            procs.append(subprocess.Popen(a, env=child_env, stdout=lf,
                                          stderr=subprocess.STDOUT))
        _await_binds(procs, [f"stage{k}" for k in range(3)], logs, addrs,
                     proc_of=[0, 1, 2])
        disp = ChainDispatcher(addrs[0], listen=result, codec="raw",
                               tier=tier)
        try:
            disp.stream(xs_warm)  # boot+compile+negotiation excluded
            dt = float("inf")
            for _ in range(streams):
                t0 = time.perf_counter()
                outs = disp.stream(xs)
                dt = min(dt, time.perf_counter() - t0)
            stats = disp.stats(addrs)
            failed = False
        finally:
            if failed:
                _kill_procs(procs)
            disp.close()
            if not failed:
                for pr in procs:
                    try:
                        pr.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        pr.kill()
    except BaseException:
        _kill_procs(procs)
        raise
    finally:
        for lf in logs:
            lf.close()
    return outs, dt, stats


def speedup_check(stages, params, *, count: int, batch: int,
                  delay_ms: float, min_speedup: float) -> dict:
    import numpy as np

    from defer_tpu.runtime.node import _BindRace
    from defer_tpu.utils.export import export_pipeline

    def with_retry(**kw):
        for attempt in range(3):
            try:
                return timed_chain(**kw)
            except _BindRace as e:
                log(f"bind race on attempt {attempt + 1} ({e}); retrying")
        return timed_chain(**kw)

    rng = np.random.default_rng(1)
    xs = [rng.standard_normal((batch, 32, 32, 3)).astype(np.float32)
          for _ in range(count)]
    xs_warm = xs[:4]
    before = segments()
    with tempfile.TemporaryDirectory(prefix="defer_shm_smoke_") as tmp:
        paths = export_pipeline(stages, params, tmp, batch=batch)
        base, base_s, _ = with_retry(paths=paths, xs_warm=xs_warm, xs=xs,
                                     tier="tcp", delay_ms=delay_ms,
                                     log_dir=tmp)
        log(f"3-process tcp: {count * batch / base_s:8.1f} inf/s "
            f"({base_s:.2f}s)")
        shm, shm_s, stats = with_retry(paths=paths, xs_warm=xs_warm,
                                       xs=xs, tier="shm",
                                       delay_ms=delay_ms, log_dir=tmp)
        log(f"3-process shm: {count * batch / shm_s:8.1f} inf/s "
            f"({shm_s:.2f}s)")
    assert len(base) == len(shm) == count
    for a, b in zip(base, shm):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    tiers = {s["stage"]: s["tier"] for s in stats}
    # EVERY hop negotiated shm: both inter-stage hops, the inbound side
    # of each stage, and the last stage's result dial-back
    assert all(t == "shm" for t in tiers.values()), tiers
    assert all(s["tier_in"] == "shm" for s in stats), stats
    # zero codec work on shm hops, asserted per stage OFF the live
    # channels (each row's encode/decode summaries are per-channel)
    for s in stats:
        assert not s["encode_latency_s"].get("count"), s["stage"]
        assert not s["decode_latency_s"].get("count"), s["stage"]
    assert segments() <= before, "full chain leaked /dev/shm segments"
    speedup = base_s / shm_s
    log(f"negotiated tiers {tiers} -> {speedup:.3f}x")
    assert speedup >= min_speedup, (
        f"shm speedup {speedup:.3f}x is under the {min_speedup}x bar "
        f"(tcp {count * batch / base_s:.1f} inf/s, shm "
        f"{count * batch / shm_s:.1f} inf/s)")
    return {"tcp_s": base_s, "shm_s": shm_s,
            "speedup": round(speedup, 4),
            "tcp_inf_s": round(count * batch / base_s, 2),
            "shm_inf_s": round(count * batch / shm_s, 2),
            "tiers": {str(k): v for k, v in sorted(tiers.items())}}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--min-speedup", type=float, default=1.5,
                    help="required shm/tcp throughput ratio "
                         "(multi-process chain, min-of-3 streams)")
    ap.add_argument("--quick-min-speedup", type=float, default=1.5,
                    help="required ratio for the in-process quick check "
                         "(delay-dominated, so the bar holds even with "
                         "1-core scheduling noise)")
    ap.add_argument("--count", type=int, default=24,
                    help="timed microbatches through each chain")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--delay-ms", type=float, default=25.0,
                    help="per-hop codec delay the shm path eliminates")
    ap.add_argument("--quick", action="store_true",
                    help="in-process + planner checks only (no spawns)")
    args = ap.parse_args()

    import numpy as np

    import jax

    from defer_tpu import partition
    from defer_tpu.models import resnet_tiny

    graph = resnet_tiny()
    params = graph.init(jax.random.key(0))
    stages = partition(graph, num_stages=3)

    rng = np.random.default_rng(0)
    q_count, q_batch = min(args.count, 12), min(args.batch, 2)
    xs = [rng.standard_normal((q_batch, 32, 32, 3)).astype(np.float32)
          for _ in range(q_count)]
    r_quick = quick_check(stages, params, xs,
                          delay_ms=min(args.delay_ms, 15.0),
                          min_speedup=args.quick_min_speedup)
    base, _, _ = run_inproc(stages, params, xs, tier="tcp",
                            codecs=["raw"] * 3, streams=1)
    r_fall = fallback_check(stages, params, xs, base=base)
    r_plan = planner_check()

    row = {"metric": "shm_fastpath", "unit": "x_vs_tcp_chain",
           "stages": len(stages), "hop_tiers": ["shm", "shm"],
           "count": args.count, "batch": args.batch,
           "delay_ms": args.delay_ms,
           "cpu_count": os.cpu_count() or 1,
           "quick": r_quick, "fallback": r_fall, "planner": r_plan}
    if args.quick:
        row["value"] = None
    else:
        r = speedup_check(stages, params, count=args.count,
                          batch=args.batch, delay_ms=args.delay_ms,
                          min_speedup=args.min_speedup)
        row.update({"value": r["speedup"], **{
            k: v for k, v in r.items() if k != "speedup"}})
    print(json.dumps(row))
    log("shm fast-path smoke: OK")


if __name__ == "__main__":
    main()
