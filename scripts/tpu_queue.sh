#!/bin/bash
# Measurement queue fired when the axon tunnel recovers (see the nohup
# retry loop): decode bench -> BatchNorm-folding comparison rows.
set -u
cd "${1:-/root/repo}"

echo "[queue] $(date +%H:%M:%S) bench_decode" >&2
timeout 2400 python scripts/bench_decode.py > DECODE_r04.json \
    2> /tmp/decode_r04.err
echo "[queue] decode rc=$? $(date +%H:%M:%S)" >&2

echo "[queue] $(date +%H:%M:%S) fold-bn comparison (quick bench x2)" >&2
DEFER_BENCH_REQUIRE_TPU=1 timeout 1500 python bench.py --quick \
    > /tmp/bench_nofold.json 2> /tmp/bench_nofold.err
echo "[queue] nofold rc=$?" >&2
DEFER_BENCH_REQUIRE_TPU=1 timeout 1500 python bench.py --quick --fold-bn \
    > /tmp/bench_fold.json 2> /tmp/bench_fold.err
echo "[queue] fold rc=$? $(date +%H:%M:%S)" >&2
python - <<'EOF' > FOLDBN_r04.json
import json
rows = {}
for tag, path in (("baseline", "/tmp/bench_nofold.json"),
                  ("fold_bn", "/tmp/bench_fold.json")):
    try:
        with open(path) as f:
            d = json.loads(f.read().strip().splitlines()[-1])
        rows[tag] = {"pipeline_img_per_s": d["value"],
                     "single_chip_best_img_per_s":
                         d["single_chip_best_img_per_s"],
                     "flops_per_img": d["flops_per_img"]}
    except Exception as e:  # noqa: BLE001
        rows[tag] = {"error": repr(e)[:200]}
print(json.dumps({"metric": "resnet50_fold_bn_comparison", **rows}))
EOF
echo "[queue] done" >&2
