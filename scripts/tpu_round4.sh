#!/bin/bash
# One-shot round-4 TPU measurement session (single-client tunnel: strictly
# sequential).  Produces, at the repo root:
#   PROFILE_r04.json      per-phase dispatch/RTT/compute breakdown
#   BENCH_r04_builder.json  headline bench (driver runs its own BENCH_r04)
#   BENCHMARKS_r04.json   the five BASELINE configs (one JSON line each)
# Usage: bash scripts/tpu_round4.sh [repo_root]
set -u
cd "${1:-/root/repo}"

echo "[tpu_round4] $(date +%H:%M:%S) profile_dispatch" >&2
timeout 1800 python scripts/profile_dispatch.py > PROFILE_r04.json \
    2> /tmp/profile_r04.err
echo "[tpu_round4] profile rc=$? $(date +%H:%M:%S)" >&2
if [ -s PROFILE_r04.json ]; then
    if python scripts/render_profile.py PROFILE_r04.json > PROFILE_r04.md
    then
        echo "[tpu_round4] rendered PROFILE_r04.md" >&2
    else
        echo "[tpu_round4] render_profile FAILED (md left empty)" >&2
    fi
fi

echo "[tpu_round4] $(date +%H:%M:%S) bench.py (full sweep)" >&2
DEFER_BENCH_REQUIRE_TPU=1 DEFER_BENCH_TPU_ATTEMPTS=2 \
    timeout 2700 python bench.py \
    --chunks 32,128,512 --microbatches 1,8,32 \
    > BENCH_r04_builder.json 2> /tmp/bench_r04.err
echo "[tpu_round4] bench rc=$? $(date +%H:%M:%S)" >&2

echo "[tpu_round4] $(date +%H:%M:%S) benchmarks/run.py (5 configs)" >&2
timeout 3600 python benchmarks/run.py > BENCHMARKS_r04.json \
    2> /tmp/benchmarks_r04.err
echo "[tpu_round4] suite rc=$? $(date +%H:%M:%S)" >&2

echo "[tpu_round4] done; artifact sizes:" >&2
wc -c PROFILE_r04.json BENCH_r04_builder.json BENCHMARKS_r04.json >&2
