#!/bin/bash
# Round-5 TPU measurement queue — fired at the first healthy tunnel
# window (scripts/tpu_watch.sh touches /tmp/tpu_ok on recovery).
#
# Order is by VERDICT r4 priority: (2) decode + fold-bn re-measured on
# TPU as committed artifacts; (4) the MFU-ceiling hunt (batch >= 256,
# XLA flag sweep).  Every artifact is written to the repo root so a
# wedge mid-queue still leaves the earlier results committed.
set -u
cd "${1:-/root/repo}"

echo "[r5queue] $(date +%H:%M:%S) bench_decode -> DECODE_r05.json" >&2
timeout 2400 python scripts/bench_decode.py > DECODE_r05.json.tmp \
    2> /tmp/decode_r05.err \
  && mv DECODE_r05.json.tmp DECODE_r05.json
echo "[r5queue] decode rc=$? $(date +%H:%M:%S)" >&2

echo "[r5queue] $(date +%H:%M:%S) fold-bn comparison" >&2
DEFER_BENCH_REQUIRE_TPU=1 DEFER_BENCH_TPU_TIMEOUT_S=150 \
    timeout 1500 python bench.py --quick \
    > /tmp/bench_nofold.json 2> /tmp/bench_nofold.err
echo "[r5queue] nofold rc=$?" >&2
DEFER_BENCH_REQUIRE_TPU=1 DEFER_BENCH_TPU_TIMEOUT_S=150 \
    timeout 1500 python bench.py --quick --fold-bn \
    > /tmp/bench_fold.json 2> /tmp/bench_fold.err
echo "[r5queue] fold rc=$? $(date +%H:%M:%S)" >&2
python - <<'EOF' > FOLDBN_r05.json
import json
rows = {}
for tag, path in (("baseline", "/tmp/bench_nofold.json"),
                  ("fold_bn", "/tmp/bench_fold.json")):
    try:
        with open(path) as f:
            d = json.loads(f.read().strip().splitlines()[-1])
        rows[tag] = {"pipeline_img_per_s": d["value"],
                     "single_chip_best_img_per_s":
                         d["single_chip_best_img_per_s"],
                     "flops_per_img": d["flops_per_img"]}
    except Exception as e:  # noqa: BLE001
        rows[tag] = {"error": repr(e)[:200]}
print(json.dumps({"metric": "resnet50_fold_bn_comparison", **rows}))
EOF

echo "[r5queue] $(date +%H:%M:%S) MFU hunt (batch sweep to 512)" >&2
DEFER_BENCH_REQUIRE_TPU=1 DEFER_BENCH_TPU_TIMEOUT_S=150 \
    timeout 2400 python bench.py --batches 32,128,256,512 \
    --chunks 32,128 --microbatches 16,32 \
    > BENCH_r05_builder.json.tmp 2> /tmp/bench_r05.err \
  && mv BENCH_r05_builder.json.tmp BENCH_r05_builder.json
echo "[r5queue] mfu rc=$? $(date +%H:%M:%S)" >&2

echo "[r5queue] $(date +%H:%M:%S) per-op profile" >&2
timeout 1200 python scripts/profile_resnet_ops.py > PROFILE_OPS_r05.json.tmp \
    2> /tmp/profile_ops.err \
  && mv PROFILE_OPS_r05.json.tmp PROFILE_OPS_r05.json
echo "[r5queue] profile rc=$? $(date +%H:%M:%S)" >&2

echo "[r5queue] $(date +%H:%M:%S) five-config suite" >&2
timeout 2400 python benchmarks/run.py \
    --weights-dir "${DEFER_WEIGHTS_DIR:-/root/weights}" \
    > BENCHMARKS_r05.json.tmp \
    2> /tmp/benchmarks_r05.err \
  && mv BENCHMARKS_r05.json.tmp BENCHMARKS_r05.json
echo "[r5queue] done $(date +%H:%M:%S)" >&2
