#!/bin/bash
# Round-5 follow-up TPU queue — runs after tpu_round5.sh drains.
#
# 1. Decode benchmark re-run: the first run timed out at row 26/30 and
#    (pre-fix) left no artifact; bench_decode.py now rewrites
#    DECODE_r05.json after every row, so even a timeout keeps the rows.
# 2. XLA flag sweep for the MFU-ceiling hunt (VERDICT r4 #4).
set -u
cd "${1:-/root/repo}"

echo "[r5b] $(date +%H:%M:%S) bench_decode (incremental) -> DECODE_r05.json" >&2
DEFER_DECODE_OUT="$PWD/DECODE_r05.json" \
    timeout 3600 python scripts/bench_decode.py > /tmp/decode_r05b.out \
    2> /tmp/decode_r05b.err
echo "[r5b] decode rc=$? $(date +%H:%M:%S)" >&2

echo "[r5b] $(date +%H:%M:%S) xla flag sweep -> XLA_SWEEP_r05.json" >&2
DEFER_SWEEP_OUT="$PWD/XLA_SWEEP_r05.json" \
    timeout 7200 python scripts/xla_flag_sweep.py > /tmp/xla_sweep.out \
    2> /tmp/xla_sweep.err
echo "[r5b] sweep rc=$? $(date +%H:%M:%S)" >&2

echo "[r5b] $(date +%H:%M:%S) speculative decode bench -> SPEC_r05.json" >&2
DEFER_SPEC_OUT="$PWD/SPEC_r05.json" \
    timeout 2400 python scripts/bench_spec.py > /tmp/spec_r05.out \
    2> /tmp/spec_r05.err
echo "[r5b] spec rc=$? $(date +%H:%M:%S)" >&2

echo "[r5b] $(date +%H:%M:%S) fold-bn re-measure (device-committed params)" >&2
DEFER_BENCH_REQUIRE_TPU=1 DEFER_BENCH_TPU_TIMEOUT_S=150 \
    timeout 1500 python bench.py --quick \
    > /tmp/bench_nofold2.json 2> /tmp/bench_nofold2.err
echo "[r5b] nofold2 rc=$?" >&2
DEFER_BENCH_REQUIRE_TPU=1 DEFER_BENCH_TPU_TIMEOUT_S=150 \
    timeout 1500 python bench.py --quick --fold-bn \
    > /tmp/bench_fold2.json 2> /tmp/bench_fold2.err
echo "[r5b] fold2 rc=$? $(date +%H:%M:%S)" >&2
python - <<'PYEOF' > FOLDBN_r05.json
import json
rows = {}
for tag, path in (("baseline", "/tmp/bench_nofold2.json"),
                  ("fold_bn", "/tmp/bench_fold2.json")):
    try:
        with open(path) as f:
            d = json.loads(f.read().strip().splitlines()[-1])
        rows[tag] = {"pipeline_img_per_s": d["value"],
                     "single_chip_best_img_per_s":
                         d["single_chip_best_img_per_s"],
                     "flops_per_img": d["flops_per_img"]}
    except Exception as e:  # noqa: BLE001
        rows[tag] = {"error": repr(e)[:200]}
print(json.dumps({"metric": "resnet50_fold_bn_comparison",
                  "note": "re-measured after committing folded params "
                          "to device (first run shipped host numpy "
                          "weights through the tunnel per call)",
                  **rows}))
PYEOF
echo "[r5b] foldbn artifact rewritten $(date +%H:%M:%S)" >&2
