#!/bin/bash
# Poll the TPU tunnel; on success touch /tmp/tpu_ok and exit.
# Probe timeout is GENEROUS (300 s) and attempts are bounded: killing a
# probe that has just acquired the device grant can itself wedge the
# single-client tunnel, so err toward waiting, probe rarely, stop after
# ~6 h rather than looping forever.
for i in $(seq 1 36); do
  if timeout 300 python -c "import jax; ds=jax.devices(); assert ds[0].platform!='cpu'; print(ds[0].device_kind)" >/tmp/tpu_kind 2>/tmp/tpu_err; then
    date +%s > /tmp/tpu_ok
    echo "tpu healthy after probe $i: $(cat /tmp/tpu_kind)"
    exit 0
  fi
  echo "probe $i failed $(date -u +%H:%M:%S)"
  sleep 300
done
echo "gave up after 36 probes"
exit 1
