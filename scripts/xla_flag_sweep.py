"""XLA flag sweep for the MFU-ceiling hunt (VERDICT r4 #4).

Each flag set gets its own ``bench.py`` subprocess (focused config: the
best-known batch/chunk/microbatch).  Flags travel via
``DEFER_XLA_COMPILER_OPTS`` -> per-executable ``compiler_options``, NOT
``XLA_FLAGS``: this chip compiles through a remote relay whose LOCAL
client rejects TPU-only XLA_FLAGS at parse time (round-1 sweep failed
exactly so), while compiler_options are forwarded (probed).  Flags
probed are the documented TPU performance levers relevant to a
conv-dominated pipelined workload:

- ``scoped_vmem_limit_kib``: more VMEM headroom for fusions (less HBM
  spill between the conv and its fused elementwise epilogue);
- ``latency_hiding_scheduler``: overlaps the pipeline's ppermute
  collectives with stage compute;
- ``async collective_permute``: makes the stage->stage hop itself
  asynchronous.

Per-flag progress lines go to stderr; stdout gets ONE final JSON line
with the scoreboard ``value`` (best pipeline img/s over all flag sets)
and ``unit`` keys, like every other measurement script.  The combined
artifact is rewritten incrementally to ``DEFER_SWEEP_OUT`` (default
XLA_SWEEP.json in the repo root) — a timeout keeps completed rows,
same contract as bench_decode.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: async collective_permute is now the pipeline's TPU DEFAULT
#: (utils/xla_opts.RING_DEFAULTS, adopted off this sweep's r5 result),
#: so the control row must switch it OFF explicitly — a bare env no
#: longer isolates flags.
FLAG_SETS = {
    "no_async_cp": "xla_enable_async_collective_permute=false",
    "default": "",
    "default+vmem64m": "xla_tpu_scoped_vmem_limit_kib=65536",
    "default+lhs": "xla_tpu_enable_latency_hiding_scheduler=true",
    "default+lhs+vmem64m": ("xla_tpu_enable_latency_hiding_scheduler=true "
                            "xla_tpu_scoped_vmem_limit_kib=65536"),
}


def main():
    out_path = os.environ.get("DEFER_SWEEP_OUT",
                              os.path.join(REPO, "XLA_SWEEP.json"))
    per_run_s = float(os.environ.get("DEFER_SWEEP_RUN_TIMEOUT_S", "1200"))
    rows = {}

    from defer_tpu.utils.artifact import flush_artifact

    def flush():
        return flush_artifact(out_path,
                              {"metric": "resnet50_xla_flag_sweep",
                               "value": 0.0, "unit": "inferences/sec",
                               "rows": rows}, merge_key="rows",
                              value_key="pipeline_img_per_s")

    for name, flags in FLAG_SETS.items():
        p = None
        env = dict(os.environ)
        env["DEFER_XLA_COMPILER_OPTS"] = flags
        env["DEFER_BENCH_REQUIRE_TPU"] = "1"
        env.setdefault("DEFER_BENCH_TPU_TIMEOUT_S", "150")
        t0 = time.time()
        try:
            p = subprocess.run(
                [sys.executable, os.path.join(REPO, "bench.py"),
                 "--batches", "128", "--chunks", "32",
                 "--microbatches", "32"],
                capture_output=True, text=True, timeout=per_run_s, env=env,
                cwd=REPO)
            line = p.stdout.strip().splitlines()[-1] if p.stdout.strip() \
                else ""
            d = json.loads(line)
            rows[name] = {
                "flags": flags,
                "pipeline_img_per_s": d.get("value"),
                "single_chip_best_img_per_s":
                    d.get("single_chip_best_img_per_s"),
                "mfu_pipeline_best": d.get("mfu_pipeline_best"),
                "mfu_best": d.get("mfu_best"),
                "wall_s": round(time.time() - t0, 1),
            }
        except subprocess.TimeoutExpired:
            rows[name] = {"flags": flags, "error": "timeout",
                          "wall_s": round(time.time() - t0, 1)}
        except Exception as e:  # noqa: BLE001 — record and continue
            rows[name] = {"flags": flags, "error": repr(e)[:300],
                          "stderr": p.stderr[-500:] if p is not None else ""}
        print(json.dumps({name: rows[name]}), file=sys.stderr, flush=True)
        final = flush()
    print(json.dumps(final))


if __name__ == "__main__":
    main()
