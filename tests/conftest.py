"""Test configuration: 8 virtual CPU devices (the idiomatic JAX fake backend
for multi-device tests — SURVEY.md §4).

Note: this environment pre-registers a TPU PJRT plugin via sitecustomize
before pytest starts, so env vars alone are too late; we also force platform
selection through jax.config.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the one place the multi-device host mesh is forced (the flag must land
# before jax initializes its backends; the helper refuses with a reason
# when that window has closed)
from defer_tpu.utils.compat import force_host_device_count  # noqa: E402

_DEVICES_OK, _DEVICES_WHY = force_host_device_count(8)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests (subprocess CLI, big configs)")
    config.addinivalue_line(
        "markers", "timeout: per-test timeout (pytest-timeout compatible)")


@pytest.fixture(scope="session", autouse=True)
def _devices():
    assert len(jax.devices()) == 8, (jax.devices(), _DEVICES_WHY)
    yield


@pytest.fixture
def host_devices():
    """The forced multi-device host mesh, or a skip-with-reason when
    this process's jax initialized before the flag could land — the
    test vehicle for device-resident (ici) and sharding tests."""
    if not _DEVICES_OK:
        pytest.skip(_DEVICES_WHY)
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip(f"needs a multi-device host mesh, have {len(devs)}")
    return devs


#: per-test watchdog so one hung multi-process/socket test cannot eat the
#: whole 870 s tier-1 budget.  Generous: the slowest healthy tests (big
#: jit compiles on a 1-core host) finish well under 2 minutes.
PER_TEST_TIMEOUT_S = int(os.environ.get("DEFER_TEST_TIMEOUT_S", "300"))


def _pytest_timeout_active(config) -> bool:
    """True when the real pytest-timeout plugin is installed AND armed
    (``--timeout`` flag or ``timeout`` ini).  Merely having the plugin
    installed arms nothing — the fallback must still cover a plain
    ``pytest -m 'not slow'`` run, or one hung socket test eats the
    whole tier-1 budget."""
    if not config.pluginmanager.hasplugin("timeout"):
        return False
    for probe in (lambda: config.getoption("timeout"),
                  lambda: config.getini("timeout")):
        try:
            if probe():
                return True
        except (ValueError, KeyError):
            pass
    return False


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    """Fallback per-test timeout when pytest-timeout is not installed or
    not armed (CI installs and arms it; this container may not have
    it): a SIGALRM on the main thread aborts the test body with a
    TimeoutError.  Defers to the real plugin when it is active, and to
    a ``@pytest.mark.timeout(N)`` marker for per-test overrides."""
    import signal
    import threading

    if _pytest_timeout_active(item.config) \
            or not hasattr(signal, "SIGALRM") \
            or threading.current_thread() is not threading.main_thread():
        yield
        return
    marker = item.get_closest_marker("timeout")
    limit = int(marker.args[0]) if marker and marker.args \
        else PER_TEST_TIMEOUT_S
    if limit <= 0:
        yield
        return

    def on_alarm(signum, frame):  # noqa: ARG001 — signal signature
        raise TimeoutError(
            f"{item.nodeid} exceeded the {limit}s per-test timeout "
            f"(DEFER_TEST_TIMEOUT_S / @pytest.mark.timeout override)")

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(limit)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
