"""Test configuration: 8 virtual CPU devices (the idiomatic JAX fake backend
for multi-device tests — SURVEY.md §4).

Note: this environment pre-registers a TPU PJRT plugin via sitecustomize
before pytest starts, so env vars alone are too late; we also force platform
selection through jax.config.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests (subprocess CLI, big configs)")


@pytest.fixture(scope="session", autouse=True)
def _devices():
    assert len(jax.devices()) == 8, jax.devices()
    yield
