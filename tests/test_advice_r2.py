"""Regression tests for the round-2 advisor findings (ADVICE.md r2).

Each test fails on the pre-fix code:

1. serve_endpoint silently dropped samples when the staging ring stayed
   full past push's timeout (dispatcher.py — push return ignored).
2. serve_endpoint's reader thread died on a bad-size sample without closing
   the ring, wedging the serve loop and hanging the client to its cap.
3. ChainDispatcher.stream validated frames with bare asserts (stripped
   under ``python -O``); an early END mis-drained instead of raising.
4. SpmdPipeline treated any [C, microbatch, buf_elems] numpy array as
   pre-staged, bypassing input-size validation.
5. The watchdog's fixed 60 s default falsely declared legitimately slow
   deployments dead; it now scales to the slowest completed dispatch.
"""

import queue
import socket
import time

import numpy as np
import pytest

import jax

from defer_tpu import Defer, DeferConfig, END_OF_STREAM
from defer_tpu.models import resnet_tiny
from defer_tpu.partition.partitioner import partition
from defer_tpu.runtime.node import ChainDispatcher
from defer_tpu.runtime.spmd import SpmdPipeline
from defer_tpu.transport.framed import TensorClient, send_end
from defer_tpu.transport.staging import HostStagingRing


@pytest.fixture(scope="module")
def tiny():
    g = resnet_tiny()
    return g, g.init(jax.random.key(0))


def test_endpoint_ring_stall_fails_loudly(tiny, monkeypatch):
    """ADVICE r2 #1: a ring that never accepts (pipeline stalled) must abort
    the connection, not silently return fewer results than inputs."""
    g, params = tiny
    monkeypatch.setattr(HostStagingRing, "push",
                        lambda self, sample, timeout_s=30.0: False)
    defer = Defer(config=DeferConfig(microbatch=1, chunk=2))
    address, thread = defer.serve_endpoint(g, params, num_stages=2,
                                           stall_timeout_s=0.2)
    client = TensorClient(*address)
    x = np.zeros((1, 32, 32, 3), np.float32)
    with pytest.raises((OSError, ConnectionError)):
        client.infer_stream([x, x])
    thread.join(timeout=30)
    assert not thread.is_alive()
    assert any(isinstance(e, RuntimeError) for e in thread.errors)


def test_endpoint_bad_sample_aborts_connection(tiny):
    """ADVICE r2 #2: a wrong-size sample must kill the stream with an error
    in bounded time — the reader's ValueError used to leak, leaving the
    serve loop spinning and the client hanging to its 600 s cap."""
    g, params = tiny
    defer = Defer(config=DeferConfig(microbatch=1, chunk=2))
    address, thread = defer.serve_endpoint(g, params, num_stages=2)
    client = TensorClient(*address)
    t0 = time.monotonic()
    with pytest.raises((OSError, ConnectionError)):
        client.infer_stream([np.zeros((1, 7), np.float32)])
    assert time.monotonic() - t0 < 60
    thread.join(timeout=30)
    assert not thread.is_alive()
    assert any(isinstance(e, ValueError) for e in thread.errors)


def test_chain_stream_early_end_raises_connectionerror():
    """ADVICE r2 #3: an END frame where a result tensor is due (a stage node
    died and cascaded END) must raise ConnectionError — explicitly, not via
    an ``assert`` that ``python -O`` strips."""
    send_sock, chain_in = socket.socketpair()
    res_conn, chain_out = socket.socketpair()
    cd = ChainDispatcher.__new__(ChainDispatcher)
    cd._send_sock = send_sock
    cd._res_conn = res_conn
    cd.codec = "raw"
    cd.window = 1
    send_end(chain_out)  # the dead chain's cascaded END
    with pytest.raises(ConnectionError, match="still in flight"):
        cd.stream([np.zeros((1, 4), np.float32),
                   np.zeros((1, 4), np.float32)])
    for s in (send_sock, chain_in, res_conn, chain_out):
        s.close()


def test_unstaged_buffer_shaped_input_rejected(tiny):
    """ADVICE r2 #4: a numpy block that merely *looks* like the transfer
    buffer ([C, microbatch, buf_elems]) must still be size-validated; only
    the explicit ``staged=True`` opt-in (or a device block from
    ``stage_inputs``) may skip it."""
    from defer_tpu.models import vgg_tiny
    g = vgg_tiny()
    params = g.init(jax.random.key(0))
    pipe = SpmdPipeline(partition(g, num_stages=4), params,
                        microbatch=1, chunk=2)
    in_size = pipe.stages[0].in_spec.size
    assert pipe.buf_elems != in_size  # precondition: shapes distinguishable
    block = np.zeros((2, 1, pipe.buf_elems), np.float32)
    with pytest.raises(ValueError, match="stage-0 input"):
        pipe.push(block)
    pipe.reset()
    assert isinstance(pipe.push(block, staged=True), list)  # opt-in works
    with pytest.raises(ValueError, match="staged block"):
        pipe.push(np.zeros((2, 1, pipe.buf_elems + 1), np.float32),
                  staged=True)


def test_watchdog_scales_to_slow_dispatches(tiny, monkeypatch):
    """ADVICE r2 #5: dispatches legitimately slower than watchdog_s (big
    chunk on a slow host) must not be declared dead — the threshold scales
    to the slowest completed dispatch instead of a fixed cutoff."""
    g, params = tiny
    orig_push = SpmdPipeline.push

    def slow_push(self, *a, **kw):
        time.sleep(0.6)  # every dispatch ~3x the configured watchdog
        return orig_push(self, *a, **kw)

    monkeypatch.setattr(SpmdPipeline, "push", slow_push)
    defer = Defer(config=DeferConfig(microbatch=1, chunk=2, watchdog_s=0.2))
    in_q, out_q = queue.Queue(), queue.Queue()
    h = defer.run_defer(g, params, None, in_q, out_q, num_stages=2)
    x = np.zeros((1, 32, 32, 3), np.float32)
    in_q.put(x)
    in_q.put(x)
    in_q.put(END_OF_STREAM)
    h.join(timeout=120)  # raises RuntimeError if the watchdog misfired
    assert h.healthy
    outs = []
    while not out_q.empty():
        outs.append(out_q.get())
    assert len(outs) == 2 and all(o is not None for o in outs)
