"""Regression tests for bench.py's bounded TPU-probe budget.

BENCH_r02 and BENCH_r04 were lost (rc=124, no stdout) because the old
probe policy (3 x 600 s + backoff) could outlive the driver's capture
window when the tunnel wedged.  The contract now: with a wedged or absent
TPU, bench.py prints exactly ONE parseable JSON line (value null,
tpu_unavailable true, last_good attached) and exits 0 — fast.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run(env_extra, timeout=120):
    env = dict(os.environ)
    # the harness conftest forces JAX_PLATFORMS=cpu; the bench must not
    # inherit that decision — clear it so only the probe result matters
    env.pop("JAX_PLATFORMS", None)
    env.pop("DEFER_BENCH_CPU", None)
    env.update(env_extra)
    t0 = time.monotonic()
    r = subprocess.run([sys.executable, BENCH], capture_output=True,
                       text=True, timeout=timeout, env=env)
    return r, time.monotonic() - t0


def _parse_single_json_line(stdout):
    lines = [ln for ln in stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line: {lines!r}"
    return json.loads(lines[0])


def test_wedged_tunnel_yields_fallback_json_fast():
    """A probe that hangs (simulated) must degrade to the fallback line
    well inside the driver's window — this is the rc=124 regression."""
    r, dt = _run({
        "DEFER_BENCH_PROBE_CODE": "import time; time.sleep(60)",
        "DEFER_BENCH_TPU_TIMEOUT_S": "1",
        "DEFER_BENCH_TPU_ATTEMPTS": "2",
        "DEFER_BENCH_TPU_BACKOFF_S": "0",
    })
    assert r.returncode == 0, r.stderr[-2000:]
    assert dt < 60, f"fallback took {dt:.0f}s"
    out = _parse_single_json_line(r.stdout)
    assert out["value"] is None
    assert out["tpu_unavailable"] is True
    assert out["metric"].startswith("resnet50_")
    assert "timed out" in out["probe_diag"]
    # last known-good TPU number rides along for the scoreboard, and a
    # wrapper record without a real value must not be accepted as it
    if out["last_good"] is not None:
        assert out["last_good"]["value"] is not None
        assert out["metric"] == out["last_good"]["metric"]


def test_cpu_only_backend_yields_fallback_json():
    """A probe that finds only a CPU backend is 'no TPU', not a green
    light to benchmark the host."""
    r, dt = _run({
        "DEFER_BENCH_PROBE_CODE": "print('cpu | | 1')",
        "DEFER_BENCH_TPU_TIMEOUT_S": "30",
        "DEFER_BENCH_TPU_ATTEMPTS": "2",
    })
    assert r.returncode == 0, r.stderr[-2000:]
    out = _parse_single_json_line(r.stdout)
    assert out["value"] is None and out["tpu_unavailable"] is True
    assert "no TPU" in out["probe_diag"]


def test_require_tpu_exits_3():
    r, _ = _run({
        "DEFER_BENCH_PROBE_CODE": "print('cpu | | 1')",
        "DEFER_BENCH_REQUIRE_TPU": "1",
        "DEFER_BENCH_TPU_TIMEOUT_S": "30",
        "DEFER_BENCH_TPU_ATTEMPTS": "1",
    })
    assert r.returncode == 3
    assert not r.stdout.strip()


def test_total_budget_is_bounded():
    """Worst-case wall clock under default-shaped settings stays under
    the 6-minute cap demanded by the driver contract (scaled down here:
    2 x 2s probes + 1s backoff + overhead must come in near that sum,
    not at N x probe-timeout-unbounded)."""
    r, dt = _run({
        "DEFER_BENCH_PROBE_CODE": "import time; time.sleep(30)",
        "DEFER_BENCH_TPU_TIMEOUT_S": "2",
        "DEFER_BENCH_TPU_ATTEMPTS": "2",
        "DEFER_BENCH_TPU_BACKOFF_S": "1",
    })
    assert r.returncode == 0
    assert dt < 45, f"budget not bounded: {dt:.0f}s"
    out = _parse_single_json_line(r.stdout)
    assert out["tpu_unavailable"] is True
