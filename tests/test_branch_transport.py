"""Branch fan-out/join transport tests: the ``(path, seq)`` reorder
buffer's ordering, duplicate/stale/END-gap edges, backpressure liveness,
and failure propagation (docs/TRANSPORT.md)."""

import queue
import threading
import time

import pytest

from defer_tpu.transport.branch import BranchJoin, BroadcastSender
from defer_tpu.transport.framed import K_CTRL, K_END, K_TENSOR_SEQ


def drain(j, timeout=5.0):
    out = []
    while True:
        kind, value = j.get(timeout=timeout)
        out.append((kind, value))
        if kind == K_END:
            return out


def test_join_orders_across_racing_paths():
    j = BranchJoin(3)
    n = 20

    def feeder(path, order):
        j.attach(path)
        for seq in order:
            j.put(path, seq, (path, seq))
        j.end(path)

    rng_orders = [list(range(n)), list(range(n))[::-1],
                  sorted(range(n), key=lambda s: s % 4)]
    # path 0 in order; path 1 reversed; path 2 shuffled: the consumer
    # must still see 0..n-1 strictly in order, parts in path order
    threads = [threading.Thread(target=feeder, args=(p, o))
               for p, o in enumerate(rng_orders)]
    for t in threads:
        t.start()
    items = drain(j)
    for t in threads:
        t.join()
    tensors = [v for k, v in items if k == K_TENSOR_SEQ]
    assert [s for s, _ in tensors] == list(range(n))
    for s, parts in tensors:
        assert parts == [(0, s), (1, s), (2, s)]
    assert items[-1] == (K_END, None)


def test_join_duplicate_and_stale_raise():
    j = BranchJoin(2)
    j.attach(0)
    j.attach(1)
    j.put(0, 0, "a")
    with pytest.raises(ValueError, match="duplicate"):
        j.put(0, 0, "again")
    j.put(1, 0, "b")
    assert j.get() == (K_TENSOR_SEQ, (0, ["a", "b"]))
    with pytest.raises(ValueError, match="stale"):
        j.put(0, 0, "late")


def test_join_end_gap_raises():
    """All paths ended but a seq is missing a part: the gap names the
    missing (seq, paths) instead of silently truncating the stream."""
    j = BranchJoin(2)
    j.attach(0)
    j.attach(1)
    j.put(0, 0, "a")
    j.end(0)
    j.end(1)      # path 1 never delivered seq 0
    with pytest.raises(ConnectionError, match="missing"):
        j.get(timeout=1.0)


def test_join_double_end_and_double_attach_raise():
    j = BranchJoin(2)
    j.attach(0)
    with pytest.raises(ConnectionError, match="claimed"):
        j.attach(0)
    j.attach(1)
    j.end(0)
    j.end(0)      # poisoned: surfaced at the consumer
    with pytest.raises(ConnectionError, match="two END"):
        j.get(timeout=1.0)


def test_join_path_range_checked():
    j = BranchJoin(2)
    with pytest.raises(ValueError, match="out of range"):
        j.attach(2)
    with pytest.raises(ValueError, match="out of range"):
        j.put(5, 0, "x")
    with pytest.raises(ValueError):
        BranchJoin(1)


def test_join_backpressure_liveness():
    """A full buffer parks depositors EXCEPT for frames landing in an
    existing slot or opening the consumer's next needed seq — the frame
    everyone waits on is always admitted."""
    j = BranchJoin(2, capacity=2)
    j.attach(0)
    j.attach(1)
    j.put(0, 1, "b1")
    j.put(0, 2, "b2")          # two distinct seqs buffered: full
    with pytest.raises(TimeoutError, match="full"):
        j.put(0, 3, "b3", timeout=0.2)
    j.put(1, 1, "c1")          # existing slot: admitted while full
    j.put(1, 0, "c0")          # opens seq 0 — THE next needed: admitted
    j.put(0, 0, "b0")
    assert j.get(timeout=1.0) == (K_TENSOR_SEQ, (0, ["b0", "c0"]))
    assert j.get(timeout=1.0) == (K_TENSOR_SEQ, (1, ["b1", "c1"]))


def test_join_ctrl_rides_ahead_and_fail_propagates():
    j = BranchJoin(2)
    j.attach(0)
    j.put(0, 0, "x")
    j.put_ctrl({"cmd": "trace"})
    assert j.get(timeout=1.0) == (K_CTRL, {"cmd": "trace"})
    with pytest.raises(queue.Empty):
        j.get_nowait()         # seq 0 still missing path 1
    j.fail(ConnectionError("branch died"))
    with pytest.raises(ConnectionError, match="branch died"):
        j.get(timeout=1.0)
    # producers parked in put() wake up with the same failure
    with pytest.raises(ConnectionError, match="branch died"):
        j.put(0, 1, "y")


def test_join_get_timeout_reports_progress():
    j = BranchJoin(3)
    j.attach(0)
    j.put(0, 0, "only")
    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="1/3"):
        j.get(timeout=0.2)
    assert time.monotonic() - t0 < 2.0


def test_broadcast_sender_needs_two_channels():
    with pytest.raises(ValueError, match=">= 2"):
        BroadcastSender([object()])
