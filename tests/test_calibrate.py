"""Calibration tests: fits recover known constants from synthetic
telemetry, the artifact survives plan-JSON roundtrips, and degenerate
fits are rejected loudly."""

import json

import pytest

from defer_tpu import GraphBuilder
from defer_tpu.graph import ops
from defer_tpu.plan import (CalibratedConstants, CalibrationError,
                            CodecSpec, StageCostModel, evaluate_cuts,
                            fit_constants, hop_telemetry_from_stats,
                            plan_from_json, predict_stage_service_s)
from defer_tpu.plan.calibrate import SCHEMA, codec_only_parts
from defer_tpu.plan.replan import cost_model_from_plan


def dense_chain(widths, name="chain", in_width=8):
    b = GraphBuilder(name)
    x = b.input((in_width,))
    for i, w in enumerate(widths):
        x = b.add(ops.Dense(w), x, name=f"fc{i}")
    return b.build()


def summ(count, total):
    """A cumulative histogram summary as stats replies carry it."""
    return {"count": count, "sum": total, "p50": total / max(count, 1),
            "mean": total / max(count, 1)}


def hop(raw, codec, tier="tcp", *, n=32, enc_bw=None, dec_bw=None,
        hs_bw=None, link_bw=None, ratio=1.0, tx_s=None, cut="c0",
        stage=0):
    """One synthetic per-hop telemetry record generated from KNOWN
    constants — what the fit must recover.  ``ratio`` is the codec's
    wire-byte ratio (the link fit regresses over raw/ratio bytes)."""
    rec = {"cut": cut, "stage": stage, "raw_bytes": raw, "codec": codec,
           "tier": tier, "enc_s": {"count": 0}, "dec_s": {"count": 0},
           "host_sync_s": {"count": 0}, "tx_s": {"count": 0}}
    if enc_bw:
        rec["enc_s"] = summ(n, n * raw / enc_bw)
    if dec_bw:
        rec["dec_s"] = summ(n, n * raw / dec_bw)
    if hs_bw:
        rec["host_sync_s"] = summ(n, n * raw / hs_bw)
    if tx_s is not None:
        rec["tx_s"] = summ(n, tx_s)
    elif link_bw:
        # tx prices encode + send; the wire moves raw/ratio bytes
        enc_sum = rec["enc_s"].get("sum", 0.0)
        rec["tx_s"] = summ(n, enc_sum + n * (raw / ratio) / link_bw)
    return rec


# -- fitting -----------------------------------------------------------------


def test_fit_recovers_known_constants():
    raw = 1 << 20
    hops = [
        hop(raw, "lzb", enc_bw=2e9, dec_bw=1e9, hs_bw=5e9, link_bw=1e8,
            ratio=1.3, cut="c0", stage=0),
        hop(raw // 2, "lzb", enc_bw=2e9, dec_bw=1e9, hs_bw=5e9,
            link_bw=1e8, ratio=1.3, cut="c1", stage=1),
    ]
    cal = fit_constants(hops, gen="v5e", bench_memory=False)
    spec = cal.codecs["lzb"]
    assert spec.encode_bytes_per_s == pytest.approx(2e9, rel=1e-6)
    assert spec.decode_bytes_per_s == pytest.approx(1e9, rel=1e-6)
    assert cal.host_sync_bw_s == pytest.approx(5e9, rel=1e-6)
    assert cal.link_bw_s == pytest.approx(1e8, rel=1e-6)
    assert cal.gen == "v5e"
    assert cal.provenance["codec.lzb"]["method"] == "measured"
    assert cal.provenance["codec.lzb"]["samples"] == 128  # enc+dec, 2 hops
    # lzb is a known name: ratio carried from the default table
    from defer_tpu.plan import DEFAULT_CODECS
    assert spec.ratio == DEFAULT_CODECS["lzb"].ratio


def test_fit_recovers_ici_bandwidth():
    raw = 1 << 22
    want = 3.2e10
    hops = [hop(raw, "ici", tier="ici", tx_s=32 * raw / want)]
    cal = fit_constants(hops, bench_memory=False)
    assert cal.ici_bw_s == pytest.approx(want, rel=1e-6)
    assert cal.provenance["ici_bw_s"]["method"] == "measured"


def test_fit_keys_specs_by_deployed_name():
    """A codec name the analytic table never heard of (the dsleep/esleep
    delay vehicles) still calibrates — as a flat throughput spec under
    its deployed name."""
    raw = 1 << 20
    cal = fit_constants([hop(raw, "dsleep10+raw", enc_bw=4e9,
                             dec_bw=raw / 10e-3)], bench_memory=False)
    assert "dsleep10+raw" in cal.codecs
    assert cal.codecs["dsleep10+raw"].decode_bytes_per_s == pytest.approx(
        raw / 10e-3, rel=1e-6)
    assert not cal.codecs["dsleep10+raw"].lossy


def test_fit_keeps_prior_when_no_telemetry():
    prior = StageCostModel(dense_chain([8, 8]), gen="v4",
                           link_bw_s=7e8, ici_bw_s=9e9,
                           host_sync_bw_s=3e9)
    # a tcp hop with encode-only telemetry: no host_sync/tx samples
    cal = fit_constants([hop(1 << 20, "raw", enc_bw=1e9)],
                        prior=prior, bench_memory=False)
    assert cal.host_sync_bw_s == prior.host_sync_bw_s
    assert cal.ici_bw_s == prior.ici_bw_s
    assert cal.provenance["host_sync_bw_s"]["method"] == "prior"
    assert cal.provenance["ici_bw_s"]["method"] == "prior"


# -- degenerate rejection ----------------------------------------------------


def test_fit_rejects_zero_byte_hop():
    with pytest.raises(CalibrationError, match="zero-byte"):
        fit_constants([hop(0, "raw", enc_bw=1e9)], bench_memory=False)


def test_fit_rejects_undersampled_histogram():
    bad = hop(1 << 20, "raw", enc_bw=1e9, n=3)  # 0 < 3 < min_samples
    with pytest.raises(CalibrationError, match="only 3 sample"):
        fit_constants([bad], bench_memory=False)


def test_fit_rejects_empty():
    with pytest.raises(CalibrationError, match="no hop telemetry"):
        fit_constants([], bench_memory=False)


def test_zero_count_is_legitimate_absence():
    """count == 0 is a tier working as designed (an ici hop records no
    host_sync), NOT a degenerate fit — must not raise."""
    rec = hop(1 << 20, "ici", tier="ici", tx_s=32 * (1 << 20) / 4.5e10)
    assert rec["host_sync_s"] == {"count": 0}
    fit_constants([rec], bench_memory=False)  # no raise


# -- the artifact ------------------------------------------------------------


def test_artifact_roundtrip(tmp_path):
    cal = fit_constants([hop(1 << 20, "lzb", enc_bw=2e9, dec_bw=1e9,
                             hs_bw=5e9, link_bw=1e8)],
                        gen="v4", bench_memory=False)
    p = tmp_path / "cal.json"
    cal.save(str(p))
    back = CalibratedConstants.load(str(p))
    assert back.to_json() == cal.to_json()
    assert back.schema == SCHEMA
    assert isinstance(back.codecs["lzb"], CodecSpec)


def test_artifact_rejects_unknown_schema():
    with pytest.raises(CalibrationError, match="schema"):
        CalibratedConstants.from_json({"schema": "bogus.v9"})


def test_apply_overlays_without_mutating():
    g = dense_chain([8, 8, 8])
    cost = StageCostModel(g, gen="v4", link_bw_s=1e9)
    cal = CalibratedConstants(host_sync_bw_s=2e9, link_bw_s=5e7,
                              codecs={"weird": CodecSpec(
                                  name="weird", ratio=1.0,
                                  encode_bytes_per_s=1e9,
                                  decode_bytes_per_s=1e9, lossy=False)})
    out = cal.apply(cost)
    assert out is not cost
    assert out.host_sync_bw_s == 2e9 and out.link_bw_s == 5e7
    assert "weird" in out.codecs and "raw" in out.codecs  # merge
    assert cost.link_bw_s == 1e9 and "weird" not in cost.codecs
    # unfitted fields keep the model's own values
    assert out.local_bw_s == cost.local_bw_s


# -- plan-JSON roundtrip -----------------------------------------------------


def test_calibration_survives_plan_json_roundtrip():
    """Calibrated model -> evaluate_cuts (deployed-codec pin) -> to_json
    -> plan_from_json -> cost_model_from_plan must reproduce the same
    per-stage service predictions — including a codec name the default
    table has no row for, and the plan's batch."""
    g = dense_chain([8, 16, 8, 8])
    cuts = [g.topo_order[1], g.topo_order[2]]
    node_costs = {n: 1e-4 for n in g.topo_order}
    cost = StageCostModel(g, gen="v4", batch=4, link_bw_s=1e9,
                          node_costs=node_costs)
    raw = cost.cut_bytes(cuts[0])
    cal = fit_constants(
        [hop(raw, "dsleep5+raw", enc_bw=2e9, dec_bw=raw / 5e-3,
             cut=cuts[0])], bench_memory=False)
    cal_cost = cal.apply(cost)
    deployed = ["dsleep5+raw", "raw"]
    pred = predict_stage_service_s(g, cuts, deployed, cal_cost)

    plan = evaluate_cuts(g, cuts, cal_cost, hop_codecs=deployed)
    assert plan.codecs == deployed
    doc = json.loads(json.dumps(plan.to_json()))
    restored = cost_model_from_plan(g, plan_from_json(doc))
    assert restored.batch == 4
    assert "dsleep5+raw" in restored.codecs
    back = predict_stage_service_s(g, cuts, deployed, restored)
    for a, b in zip(back, pred):
        assert a == pytest.approx(b, rel=1e-3)


def test_evaluate_cuts_hop_codecs_validation():
    g = dense_chain([8, 8, 8, 8])
    cost = StageCostModel(g, gen="v4",
                          node_costs={n: 1e-4 for n in g.topo_order})
    cut = g.topo_order[2]
    with pytest.raises(ValueError, match="hop codecs"):
        evaluate_cuts(g, [cut], cost, hop_codecs=["raw", "raw"])
    with pytest.raises(ValueError, match="replicas"):
        evaluate_cuts(g, [cut], cost, hop_codecs=["raw"],
                      replicas=[1, 2])


# -- measurement-aligned prediction ------------------------------------------


def test_predict_stage_service_alignment():
    """Stage k = max(compute, inbound decode, outbound encode) with
    CODEC-ONLY parts; hop comm never lands on the wrong stage."""
    g = dense_chain([8, 8, 8])
    cuts = [g.topo_order[0], g.topo_order[1]]
    node_costs = {n: 1e-3 for n in g.topo_order}
    cost = StageCostModel(g, gen="v4", link_bw_s=1e9,
                          node_costs=node_costs)
    slow = CodecSpec(name="slowdec", ratio=1.0,
                     encode_bytes_per_s=1e12,
                     decode_bytes_per_s=10.0, lossy=False)
    cost.codecs = {**cost.codecs, "slowdec": slow}
    pred = predict_stage_service_s(g, cuts, ["slowdec", "raw"], cost)
    dec = cost.cut_bytes(cuts[0]) / 10.0
    # the expensive decode binds the RECEIVING stage (1), not stage 0
    assert pred[1] == pytest.approx(max(dec, pred[0]), rel=1e-9)
    assert pred[0] < dec
    # tier pseudo-codecs do no codec work: pure per-stage compute
    order = g.topo_order
    bounds = [0, order.index(cuts[0]) + 1, order.index(cuts[1]) + 1,
              len(order)]
    compute = [cost.compute_seconds(order[a:b])
               for a, b in zip(bounds, bounds[1:])]
    none = predict_stage_service_s(g, cuts, ["ici", "local"], cost)
    assert none == pytest.approx(compute, rel=1e-9)
    # length mismatch is loud
    with pytest.raises(ValueError, match="hop codecs"):
        predict_stage_service_s(g, cuts, ["raw"], cost)


def test_codec_only_parts_unknown_falls_back_to_raw():
    g = dense_chain([8, 8])
    cost = StageCostModel(g, gen="v4",
                          node_costs={n: 1e-4 for n in g.topo_order})
    cut = g.topo_order[1]
    assert codec_only_parts(cost, cut, "never-heard-of-it") == \
        codec_only_parts(cost, cut, "raw")
    assert codec_only_parts(cost, cut, "device") == (0.0, 0.0)


# -- stats reshaping ---------------------------------------------------------


def stats_row(stage, codec, *, enc=None, dec=None, hs=None, tx=None,
              replica=None, tier="tcp"):
    return {"stage": stage, "replica": replica, "codec": codec,
            "tier": tier,
            "encode_latency_s": enc or {"count": 0},
            "decode_latency_s": dec or {"count": 0},
            "host_sync_s": hs or {"count": 0},
            "tx_s": tx or {"count": 0}}


def test_hop_telemetry_from_stats_joins_sides():
    """Hop k joins stage k's encode/host-sync/send with stage k+1's
    decode (measured at the receiver); raw bytes come from the graph."""
    g = dense_chain([8, 8, 8])
    cuts = [g.topo_order[1]]
    stats = [
        stats_row(0, "lzb", enc=summ(16, 0.016), hs=summ(16, 0.008),
                  tx=summ(16, 0.032)),
        stats_row(1, "raw", dec=summ(16, 0.160)),
    ]
    hops = hop_telemetry_from_stats(g, cuts, stats, batch=2)
    assert len(hops) == 1
    h = hops[0]
    spec = g.out_spec(cuts[0])
    assert h["raw_bytes"] == spec.size * spec.dtype.itemsize * 2
    assert h["codec"] == "lzb"           # the SENDER's codec
    assert h["enc_s"]["sum"] == pytest.approx(0.016)
    assert h["dec_s"]["sum"] == pytest.approx(0.160)


def test_hop_telemetry_window_bounds_against_baseline():
    g = dense_chain([8, 8, 8])
    cuts = [g.topo_order[1]]
    base = [stats_row(0, "lzb", enc=summ(8, 0.8)),
            stats_row(1, "raw", dec=summ(8, 0.8))]
    now = [stats_row(0, "lzb", enc=summ(24, 0.96)),
           stats_row(1, "raw", dec=summ(24, 0.96))]
    h = hop_telemetry_from_stats(g, cuts, now, baseline=base)[0]
    # only the NEW 16 samples (sum 0.16) anchor the fit
    assert h["enc_s"] == {"count": 16, "sum": pytest.approx(0.16)}
    assert h["dec_s"] == {"count": 16, "sum": pytest.approx(0.16)}


def test_hop_telemetry_pools_replicas():
    g = dense_chain([8, 8, 8])
    cuts = [g.topo_order[1]]
    stats = [
        stats_row(0, "raw", enc=summ(8, 0.08), replica=0),
        stats_row(0, "raw", enc=summ(8, 0.24), replica=1),
        stats_row(1, "raw", dec=summ(16, 0.16)),
    ]
    h = hop_telemetry_from_stats(g, cuts, stats)[0]
    assert h["enc_s"] == {"count": 16, "sum": pytest.approx(0.32)}
