"""Capacity accounting and drift auditing (obs/capacity.py), plus the
window-bounded measurement forms they score against (obs/cluster.py
rolling delta-means, replan's baseline-windowed folds)."""

import pytest

from defer_tpu import GraphBuilder
from defer_tpu.graph import ops
from defer_tpu.obs import (CapacityModel, ClusterView, DriftAuditor,
                           achieved_mfu, stage_flops_bytes)
from defer_tpu.obs.capacity import stages_from_cuts
from defer_tpu.obs.cluster import _win_mean_ms
from defer_tpu.obs.events import recorder
from defer_tpu.plan import measured_stage_seconds
from defer_tpu.utils import hw


def dense_chain(widths, name="chain", in_width=8):
    b = GraphBuilder(name)
    x = b.input((in_width,))
    for i, w in enumerate(widths):
        x = b.add(ops.Dense(w), x, name=f"fc{i}")
    return b.build()


# -- analytic side -----------------------------------------------------------


def test_stage_flops_bytes_scales_with_batch():
    g = dense_chain([8, 8])
    f1, b1 = stage_flops_bytes(g, g.topo_order, batch=1)
    f4, b4 = stage_flops_bytes(g, g.topo_order, batch=4)
    assert f1 > 0 and b1 > 0
    assert f4 == pytest.approx(4 * f1) and b4 == pytest.approx(4 * b1)


def test_achieved_mfu_honest_denominator_policy():
    # no peak / no time / no flops: None, never a fabricated 0.0
    assert achieved_mfu(1e9, 1e-3, 0.0) is None
    assert achieved_mfu(1e9, 0.0, 1e12) is None
    assert achieved_mfu(0.0, 1e-3, 1e12) is None
    assert achieved_mfu(1e12, 1.0, 2e12) == pytest.approx(0.5)


def test_stages_from_cuts_partitions_topo_order():
    g = dense_chain([8, 8, 8, 8])
    order = g.topo_order
    stages = stages_from_cuts(g, [order[0], order[2]])
    assert stages == [order[:1], order[1:3], order[3:]]
    assert [n for s in stages for n in s] == order


def test_capacity_model_known_gen():
    g = dense_chain([8, 8, 8])
    cut = g.topo_order[1]
    cap = CapacityModel(g, [cut], batch=2, gen="v4")
    assert cap.num_stages == 2
    assert cap.peak_flops_s == hw.peak_flops("v4") > 0
    for k in range(2):
        assert cap.stage_flops[k] > 0
        assert cap.roofline_s(k) > 0
        # a measured time at exactly the compute bound -> MFU sanity
        t = cap.stage_flops[k] / cap.peak_flops_s
        assert cap.mfu(k, t) == pytest.approx(1.0)
        assert cap.mfu(k, 2 * t) == pytest.approx(0.5)
        assert 0 < cap.roofline_util(k, 2 * cap.roofline_s(k)) <= 0.5
    # chain MFU: both stages at the bottleneck for one interval
    bott = max(cap.stage_flops) / cap.peak_flops_s
    want = sum(cap.stage_flops) / (bott * cap.peak_flops_s * 2)
    assert cap.chain_mfu(bott) == pytest.approx(want)
    doc = cap.to_json()
    assert doc["gen"] == "v4"
    assert all(r is not None for r in doc["roofline_ms"])


def test_capacity_model_unknown_gen_yields_none_not_zero():
    g = dense_chain([8, 8])
    cut = g.topo_order[0]
    cap = CapacityModel(g, [cut], gen="tpu-v99")
    assert cap.peak_flops_s == 0.0  # no v5e fallback here
    assert cap.mfu(0, 1e-3) is None
    assert cap.roofline_s(0) is None
    assert cap.roofline_util(0, 1e-3) is None
    assert cap.chain_mfu(1e-3) is None
    assert cap.to_json()["roofline_ms"] == [None, None]
    # an explicit override restores the numbers
    over = CapacityModel(g, [cut], gen="tpu-v99", peak_flops_s=1e12,
                         hbm_bw_s=1e11)
    assert over.mfu(0, 1e-3) is not None


# -- drift auditor -----------------------------------------------------------


class FakeView:
    """Stands in for ClusterView: serves a scripted window-bounded
    measurement map."""

    def __init__(self):
        self.measured = {}
        self.windows = []

    def stage_service_ms(self, *, window=None):
        self.windows.append(window)
        return dict(self.measured)


def drift_events():
    return [e for e in recorder().snapshot()
            if e["kind"] == "model_drift"]


def test_drift_auditor_sustain_and_single_event_per_episode():
    recorder().clear()
    view = FakeView()
    aud = DriftAuditor([10.0, 20.0], threshold=0.25, sustain=2, window=6)
    view.measured = {0: 10.5, 1: 21.0}          # within threshold
    assert aud.observe(view) == []
    assert view.windows[-1] == 6                 # audits the window form
    view.measured = {0: 14.0, 1: 21.0}           # stage 0 over (+40%)
    assert aud.observe(view) == []               # 1 interval < sustain
    flags = aud.observe(view)                    # 2nd interval: flag
    assert [f.stage for f in flags] == [0]
    assert flags[0].intervals == 2
    assert flags[0].rel_err == pytest.approx(0.4)
    assert len(drift_events()) == 1
    flags = aud.observe(view)                    # sustained: flags again
    assert flags and flags[0].intervals == 3
    assert len(drift_events()) == 1              # but only ONE event
    ev = drift_events()[0]["data"]
    assert ev["stage"] == 0 and ev["predicted_ms"] == 10.0
    # recovery re-arms the episode
    view.measured = {0: 10.2, 1: 21.0}
    assert aud.observe(view) == []
    view.measured = {0: 30.0, 1: 21.0}
    aud.observe(view)
    assert aud.observe(view)
    assert len(drift_events()) == 2


def test_drift_audit_rows_need_both_numbers():
    recorder().clear()
    view = FakeView()
    aud = DriftAuditor([10.0, 20.0], threshold=0.1, sustain=1)
    view.measured = {0: 50.0}                    # stage 1 not measured yet
    flags = aud.observe(view)
    assert aud.last[1]["err"] is None            # no fabricated error
    assert aud.last[0]["err"] == pytest.approx(4.0)
    assert [f.stage for f in flags] == [0]       # only the measured stage
    # an unmeasured stage never drifts, no matter how long
    assert all(f.stage == 0 for f in aud.observe(view))


# -- window-bounded measurement (the numbers the auditor scores) -------------


def push(count, total, *, p50=None, stage=0, replica=0, phase="infer_s"):
    summ = {"count": count, "sum": total,
            "p50": p50 if p50 is not None else total / max(count, 1)}
    return {"node": {"stage": stage, "replica": replica},
            "latency": {phase: summ}}


def test_win_mean_ms_is_a_delta_not_a_fold():
    h = [(0.0, push(10, 0.010)), (1.0, push(20, 0.030)),
         (2.0, push(30, 0.110))]
    # window mean = (0.110 - 0.010) / (30 - 10) = 5 ms
    assert _win_mean_ms(h, "infer_s") == pytest.approx(5.0)
    # no new samples -> None (idle chain must not read as 0 ms)
    assert _win_mean_ms([h[0], h[0]], "infer_s") is None
    assert _win_mean_ms(h, "decode_s") is None


def test_stage_service_ms_windowed_tracks_regime_shift():
    view = ClusterView()
    # 10 pushes in a 1 ms/frame regime...
    n = s = 0
    for i in range(10):
        n, s = n + 8, s + 8 * 0.001
        view.ingest(push(n, s, p50=1e-3))
    # ...then 4 pushes at 5 ms/frame; the cumulative p50 stays ~1 ms
    for i in range(4):
        n, s = n + 8, s + 8 * 0.005
        view.ingest(push(n, s, p50=1e-3))
    lifetime = view.stage_service_ms()
    windowed = view.stage_service_ms(window=4)
    assert lifetime[0] == pytest.approx(1.0)
    assert windowed[0] == pytest.approx(5.0, rel=0.01)


def test_stage_service_ms_window_falls_back_to_lifetime():
    view = ClusterView()
    view.ingest(push(8, 0.016, p50=2e-3))        # a single push
    assert view.stage_service_ms(window=4)[0] == pytest.approx(2.0)
    view.ingest(push(8, 0.016, p50=2e-3))        # no new samples either
    assert view.stage_service_ms(window=4)[0] == pytest.approx(2.0)


def test_measured_stage_seconds_windowed_stats_list():
    base = [{"stage": 0, "replica": 0,
             "infer_latency_s": {"count": 10, "sum": 0.010, "p50": 1e-3}}]
    now = [{"stage": 0, "replica": 0,
            "infer_latency_s": {"count": 30, "sum": 0.060, "p50": 1e-3}}]
    # delta mean (0.05 / 20) beats the lifetime p50
    got = measured_stage_seconds(now, baseline=base)
    assert got[0] == pytest.approx(2.5e-3)
    # without a baseline: the lifetime quantile
    assert measured_stage_seconds(now)[0] == pytest.approx(1e-3)
    # baseline with no new samples: keep the lifetime figure
    assert measured_stage_seconds(base, baseline=base)[0] == \
        pytest.approx(1e-3)


def test_measured_stage_seconds_windowed_registry_form():
    base = {"pipe.stage0.latency_s": {"count": 4, "sum": 0.004,
                                      "p50": 1e-3}}
    now = {"pipe.stage0.latency_s": {"count": 12, "sum": 0.036,
                                     "p50": 1e-3}}
    assert measured_stage_seconds(now, baseline=base)[0] == \
        pytest.approx(4e-3)


# -- rows() carries the node-side capacity fields ----------------------------


def test_rows_surface_capacity_fields():
    view = ClusterView()
    p = push(8, 0.016, p50=2e-3)
    p["capacity"] = {"flops": 2.5e6, "mfu": 0.125,
                     "achieved_flops_s": 1.25e9}
    view.ingest(p)
    row = view.rows()[0]
    assert row["flops"] == 2.5e6
    assert row["mfu"] == 0.125
    assert row["achieved_flops_s"] == 1.25e9
