"""Async transport channel layer (transport/channel.py): backpressure,
error propagation, in-order delivery under load, and the overlapped node
loop producing byte-identical results vs the serial baseline."""

import socket
import threading
import time

import numpy as np
import pytest

import jax

from defer_tpu import partition
from defer_tpu.models import resnet_tiny
from defer_tpu.obs import REGISTRY
from defer_tpu.transport.channel import (AsyncReceiver, AsyncSender,
                                         ChannelError)
from defer_tpu.transport.framed import (K_END, K_TENSOR, recv_frame,
                                        send_end, send_frame)


@pytest.fixture(scope="module")
def tiny():
    g = resnet_tiny()
    return g, g.init(jax.random.key(0))


def test_receiver_bounded_queue_applies_backpressure():
    """A full rx queue parks the rx thread (it stops reading), but every
    frame still arrives, in order, once the consumer drains."""
    a, b = socket.socketpair()
    try:
        rx = AsyncReceiver(b, depth=2)
        for i in range(5):
            send_frame(a, np.full((4,), i, np.int32))
        send_end(a)
        time.sleep(0.3)
        # depth=2 in the queue + at most one frame in the thread's hand:
        # the receiver must NOT have slurped all 6 frames
        assert rx.qsize() <= 2
        got = []
        while True:
            kind, v = rx.get(timeout=5.0)
            if kind == K_END:
                break
            got.append(int(v[0]))
        assert got == list(range(5))
    finally:
        a.close()
        b.close()


def test_sender_bounded_queue_blocks_producer():
    """With the wire stalled (peer not reading, kernel buffer shrunk), a
    producer pushing past depth must block — bounded in-flight depth is
    the backpressure contract."""
    a, b = socket.socketpair()
    try:
        a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 16384)
        tx = AsyncSender(a, depth=2)
        big = np.zeros(1 << 18, np.float32)  # 1 MiB frames
        fed = []
        done = threading.Event()

        def feed():
            for i in range(6):
                tx.send(big)
                fed.append(i)
            done.set()

        t = threading.Thread(target=feed, daemon=True)
        t.start()
        time.sleep(0.4)
        assert not done.is_set()      # producer parked on the full queue
        assert len(fed) <= 4          # depth 2 + wire slack, not all 6
        for _ in range(6):            # drain the wire; producer unblocks
            kind, _ = recv_frame(b)
            assert kind == K_TENSOR
        t.join(timeout=10)
        assert done.is_set()
    finally:
        a.close()
        b.close()


def test_receiver_error_propagates_to_consumer():
    a, b = socket.socketpair()
    try:
        rx = AsyncReceiver(b, depth=4)
        a.sendall(b"\x01\x03")  # truncated header
        a.close()
        with pytest.raises(ConnectionError):
            rx.get(timeout=5.0)
    finally:
        b.close()


def test_sender_error_propagates_and_unblocks_producer():
    a, b = socket.socketpair()
    b.close()  # dead peer: sends fail with EPIPE
    try:
        tx = AsyncSender(a, depth=2)
        with pytest.raises((ChannelError, OSError)):
            for _ in range(200):
                tx.send(np.zeros(1024, np.float32))
                time.sleep(0.005)
        # flush after death raises too (never hangs)
        with pytest.raises((ChannelError, OSError)):
            tx.flush(timeout=5.0)
    finally:
        a.close()


def test_in_order_delivery_under_load():
    """Sender and receiver threads racing over one socket: frames come out
    exactly in send order (the channel adds no reordering)."""
    a, b = socket.socketpair()
    try:
        tx = AsyncSender(a, depth=4, codec="lzb")
        rx = AsyncReceiver(b, depth=4)
        n = 300

        def feed():
            for i in range(n):
                tx.send(np.full((16,), i, np.int32))
            tx.send_end()

        t = threading.Thread(target=feed, daemon=True)
        t.start()
        seqs = []
        while True:
            kind, v = rx.get(timeout=30.0)
            if kind == K_END:
                break
            seqs.append(int(v[0]))
        t.join(timeout=10)
        assert seqs == list(range(n))
    finally:
        a.close()
        b.close()


def test_sender_flush_completes_pending_writes():
    a, b = socket.socketpair()
    try:
        tx = AsyncSender(a, depth=8)
        for i in range(5):
            tx.send(np.full((8,), i, np.float32))
        got = []

        def drain():
            for _ in range(5):
                got.append(recv_frame(b)[1])

        t = threading.Thread(target=drain, daemon=True)
        t.start()
        tx.flush(timeout=10.0)
        t.join(timeout=10)
        assert tx.qsize() == 0 and len(got) == 5
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# overlapped node loop vs the serial baseline (in-process, 2 stages)
# ---------------------------------------------------------------------------

def _run_inproc_chain(stages, params, xs, *, overlap: bool, codec: str):
    """Two StageNode threads wired into a chain, driven by a dispatcher —
    the in-band deploy topology with the overlap mode under test."""
    from defer_tpu.runtime.node import ChainDispatcher, StageNode

    nodes = [StageNode(None, "127.0.0.1:0", None, overlap=overlap,
                       inflight=2)
             for _ in range(2)]
    addrs = [f"127.0.0.1:{n.address[1]}" for n in nodes]
    threads = [threading.Thread(target=n.serve, daemon=True) for n in nodes]
    for t in threads:
        t.start()
    disp = ChainDispatcher(addrs[0], codec=codec)
    try:
        disp.deploy(stages, params, addrs, batch=xs[0].shape[0])
        outs = disp.stream(xs)
    finally:
        disp.close()
    for t in threads:
        t.join(timeout=30)
    return outs


def test_overlapped_chain_byte_identical_to_serial(tiny):
    """The overlap is a scheduling change only: with the deterministic bf8
    codec, the overlapped chain must produce byte-identical outputs to the
    serial baseline, and the channel gauges must be registered."""
    g, params = tiny
    stages = partition(g, num_stages=2)
    rng = np.random.default_rng(11)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(6)]
    fast = _run_inproc_chain(stages, params, xs, overlap=True, codec="bf8")
    slow = _run_inproc_chain(stages, params, xs, overlap=False, codec="bf8")
    assert len(fast) == len(slow) == 6
    for y1, y2 in zip(fast, slow):
        np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
    snap = REGISTRY.snapshot()
    for name in ("node.rx_queue_depth", "node.tx_queue_depth",
                 "node.inflight", "chain.tx_queue_depth",
                 "chain.rx_queue_depth"):
        assert name in snap, f"gauge {name} missing from the registry"


@pytest.mark.slow
def test_three_process_chain_overlap_byte_identical(tiny):
    """Satellite: a real 3-process chain (one OS process per stage) run
    overlapped and serial over the same inputs — byte-identical outputs."""
    from defer_tpu.runtime.node import run_chain

    cpu_env = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
               "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    g, params = tiny
    stages = partition(g, num_stages=3)
    rng = np.random.default_rng(12)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(5)]
    fast = run_chain(stages, params, xs, env=cpu_env, codec="bf8",
                     overlap=True)
    slow = run_chain(stages, params, xs, env=cpu_env, codec="bf8",
                     overlap=False)
    assert len(fast) == len(slow) == 5
    for y1, y2 in zip(fast, slow):
        np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
