"""CLI surface: models / partition / bench commands."""

import json
import os
import subprocess
import sys

import pytest

ENV = {**os.environ,
       "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
       "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(*args, timeout=300):
    return subprocess.run(
        [sys.executable, "-m", "defer_tpu", *args], cwd=ROOT, env=ENV,
        capture_output=True, text=True, timeout=timeout)


@pytest.mark.slow
def test_cli_models():
    r = run_cli("models")
    assert r.returncode == 0
    assert "resnet50" in r.stdout and "bert_base" in r.stdout


@pytest.mark.slow
def test_cli_partition_and_dot(tmp_path):
    dot = str(tmp_path / "g.dot")
    r = run_cli("partition", "--model", "resnet_tiny", "--stages", "4",
                "--dot", dot)
    assert r.returncode == 0, r.stderr
    assert "valid cut points" in r.stdout
    assert "StageSpec(0" in r.stdout
    assert open(dot).read().startswith("digraph")


@pytest.mark.slow
def test_cli_bench_json():
    r = run_cli("bench", "--model", "resnet_tiny", "--stages", "2",
                "--chunk", "4", "--seconds", "1")
    assert r.returncode == 0, r.stderr
    line = r.stdout.strip().splitlines()[-1]
    d = json.loads(line)
    assert d["unit"] == "inferences/sec" and d["value"] > 0
